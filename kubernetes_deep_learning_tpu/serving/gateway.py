"""The serving gateway: the IO tier, same public API as the reference.

Reference behavior being reproduced (reference model_server.py:52-66):
``POST /predict`` with body ``{"url": "<image url>"}`` -> fetch the image,
preprocess, call the model tier, return ``{label: score}`` for every class.
The two-tier split and its rationale -- IO-bound gateway vs compute-bound
model server, keep the accelerator from idling on IO -- is the reference's
(guide.md:160-168) and is kept.

Differences, all TPU-first:

- preprocessing stops at resized **uint8**; normalization happens on the
  TPU where it fuses into the first conv (the reference ships float32
  TensorProtos, 3x the bytes);
- the model contract (input size, resize filter, labels) is **discovered**
  from the model server's /v1/models/<name> endpoint at startup instead of
  hardcoded (reference model_server.py:18,21-32,40-47);
- service discovery stays env-var based: ``KDLT_SERVING_HOST`` with a
  localhost default, exactly like the reference's ``TF_SERVING_HOST``
  (reference model_server.py:13, serving-gateway-deployment.yaml:22-24) --
  but the value may be a comma-separated REPLICA LIST (serving.upstream):
  per-replica health + circuit breakers, automatic failover on connect
  errors and 5xx, and deadline-budget-aware hedged requests
  (``KDLT_HEDGE_DELAY_MS``), so the gateway survives a model-tier replica
  dying instead of outsourcing all availability to the orchestrator.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import re
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_deep_learning_tpu.modelspec import ModelSpec
from kubernetes_deep_learning_tpu.ops import preprocess
from kubernetes_deep_learning_tpu.runtime import BatcherClosed, QueueFull
from kubernetes_deep_learning_tpu.serving import protocol
from kubernetes_deep_learning_tpu.serving.admission import (
    DEADLINE_HEADER,
    AdmissionController,
    BrownoutController,
    Deadline,
    Shed,
    install_sigterm_drain,
    retry_after_headers,
)
from kubernetes_deep_learning_tpu.serving import cache as cache_lib
from kubernetes_deep_learning_tpu.serving import faults as faults_lib
from kubernetes_deep_learning_tpu.serving.microbatch import UpstreamStall
from kubernetes_deep_learning_tpu.serving.tracing import (
    PARENT_SPAN_HEADER,
    REQUEST_ID_HEADER,
    TRACE_HEADER,
    ensure_request_id,
    log_request,
)
from kubernetes_deep_learning_tpu.serving.upstream import (
    UpstreamPool,
    resolve_serving_host,
)
from kubernetes_deep_learning_tpu.utils import flightrecorder as incident_lib
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib
from kubernetes_deep_learning_tpu.utils import slo as slo_lib
from kubernetes_deep_learning_tpu.utils import trace as trace_lib

DEFAULT_PORT = 9696          # reference gateway port (gateway.dockerfile:15-16)
DEFAULT_SERVING_HOST = "localhost:8500"  # reference model_server.py:13
SERVING_HOST_ENV = "KDLT_SERVING_HOST"
MODEL_ENV = "KDLT_MODEL"
DEFAULT_MODEL = "clothing-model"
# Multi-model routing: ``POST /predict`` keeps the reference's shape and
# serves the DEFAULT model ($KDLT_MODEL); ``POST /predict/<model>`` or the
# X-Kdlt-Model header route to any other model the tier's registry serves.
# Path wins over header (the more explicit signal).
MODEL_HEADER = protocol.MODEL_HEADER
WSGI_MODEL_KEY = "HTTP_X_KDLT_MODEL"
# Priority classes: bounded X-Kdlt-Priority values, parsed once at the
# transport edge (unknown/absent -> interactive) and propagated upstream.
PRIORITY_HEADER = protocol.PRIORITY_HEADER
WSGI_PRIORITY_KEY = "HTTP_X_KDLT_PRIORITY"
# How often the brownout control loop re-reads the burn signal.
BROWNOUT_EVAL_S = 1.0
# Model names are path/label material: constrain them before they touch
# URLs, metrics labels, or upstream requests.
_MODEL_NAME_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")
# Generative lane routing: ``POST /generate`` streams tokens from the
# decode model (``/generate/<model>`` routes explicitly).  The default
# model name mirrors the model tier's lane ($KDLT_DECODE_MODEL); the
# gateway holds only the NAME -- decode weights and the KV-cache live in
# the model tier, this tier proxies the event stream.
DECODE_MODEL_ENV = "KDLT_DECODE_MODEL"
DEFAULT_DECODE_MODEL = "gen-default"
# A token stream outlives any single-response deadline: connect fast,
# then read with a generous per-chunk idle timeout (each TOKEN resets
# it -- this bounds decode silence, not stream length).
GENERATE_CONNECT_TIMEOUT_S = 5.0
GENERATE_IDLE_TIMEOUT_S = 60.0
PREDICT_TIMEOUT_S = 20.0     # reference's gRPC deadline (model_server.py:55)
PER_IMAGE_TIMEOUT_S = 0.25   # extra upstream budget per batched image: a
                             # 256-image predict is one POST and must not be
                             # held to the single-image 20 s deadline
UPSTREAM_RETRY_BACKOFF_S = 0.05  # one retry on the model tier's 503 overload
MIN_RETRY_BUDGET_S = 0.05    # a 503 retry must leave at least this much
                             # deadline budget AFTER the backoff sleep, or
                             # the retry is skipped (it cannot finish anyway)
MAX_BATCH_FETCHERS = 8       # default concurrent image downloads per batch
                             # request; $KDLT_FETCH_CONCURRENCY overrides
                             # (GUIDE Appendix A) -- the constant stays as
                             # the documented default and back-compat alias
FETCH_CONCURRENCY_ENV = "KDLT_FETCH_CONCURRENCY"
MAX_URLS_PER_REQUEST = 256   # hard cap: bounds per-request image memory
MAX_PREDICT_BODY_BYTES = 4 * 1024 * 1024  # /predict bodies are JSON of up to
# 256 URLs -- a few KB each covers any sane client; checked against
# Content-Length BEFORE reading so an adversarial multi-GB body cannot
# exhaust gateway memory (the model tier has the equivalent pre-read cap).


def resolve_fetch_concurrency(explicit: int | None = None) -> int:
    """Explicit arg > $KDLT_FETCH_CONCURRENCY > MAX_BATCH_FETCHERS; >= 1."""
    if explicit is not None:
        return max(1, int(explicit))
    raw = os.environ.get(FETCH_CONCURRENCY_ENV, "")
    try:
        return max(1, int(raw)) if raw.strip() else MAX_BATCH_FETCHERS
    except ValueError:
        return MAX_BATCH_FETCHERS


class _BytesWireRejected(Exception):
    """A bytes-wire POST came back 400/415: the replica pool is mixed-version
    (stale negotiation) or the server was flipped to KDLT_INGEST=0 after
    discovery.  Internal signal only -- the caller decodes at the gateway
    and resends the SAME request on the tensor wire, so the client never
    sees the rollout seam."""


class UpstreamError(RuntimeError):
    """Model-tier failure; surfaces as a retryable 5xx, never a client 400.

    ``retry_after_s`` carries the model tier's own Retry-After hint (or the
    circuit breaker's remaining cool-down) through to the client response.
    """

    def __init__(
        self, msg: str, http_status: int = 502, retry_after_s: float | None = None
    ):
        super().__init__(msg)
        self.http_status = http_status
        self.retry_after_s = retry_after_s


class Gateway:
    def __init__(
        self,
        serving_host: str | None = None,
        model: str | None = None,
        port: int = DEFAULT_PORT,
        host: str = "0.0.0.0",
        bind: bool = True,
        request_log: bool = False,
        upstream_batch: int = 0,
        upstream_delay_ms: float = 2.0,
        admission: bool | None = None,
        failover: bool | None = None,
        hedge_delay_ms: float | None = None,
        probe_interval_s: float | None = None,
        slo: bool | None = None,
        slo_windows=None,
        cache: bool | None = None,
        cache_ttl_s: float | None = None,
        cache_max_mb: float | None = None,
        cache_neg_ttl_s: float | None = None,
        cache_swr_s: float | None = None,
        pool_resolve_s: float | None = None,
        brownout: bool | None = None,
        brownout_enter: float | None = None,
        brownout_exit: float | None = None,
        brownout_dwell_s: float | None = None,
        brownout_eval_s: float = BROWNOUT_EVAL_S,
        incident: bool | None = None,
        incident_dir: str | None = None,
        incident_triggers: str | None = None,
        incident_dedup_s: float | None = None,
        ingest: bool | None = None,
        fetch_concurrency: int | None = None,
    ):
        # request_log: print one traced line per /predict (rid, status,
        # duration).  Off by default for in-process use (tests, benches);
        # the CLI turns it on.  Errors are always logged, with the rid.
        self.request_log = request_log
        # upstream_batch > 0: coalesce concurrent single-image requests into
        # one upstream predict of up to this size (serving.microbatch) --
        # the model tier then sees few, fat requests.  0 = one upstream call
        # per request (the reference's shape, model_server.py:55).
        # Coalescing is PER MODEL (a batch must be one model's images);
        # non-default models get their batcher lazily on first request.
        self._upstream_batch = upstream_batch
        self._upstream_delay_ms = upstream_delay_ms
        self._microbatchers: dict[str, object] = {}
        self._microbatcher_lock = threading.Lock()
        self._microbatcher = None
        if upstream_batch > 0:
            self._microbatcher = self._make_microbatcher(None)
        # bind=False skips the in-tree HTTP server entirely: serving.wsgi
        # wraps this object under an external WSGI server (gunicorn) instead,
        # the reference's production-server arrangement.
        self.serving_host = serving_host or os.environ.get(
            SERVING_HOST_ENV, DEFAULT_SERVING_HOST
        )
        self.model = model or os.environ.get(MODEL_ENV, DEFAULT_MODEL)
        # The generative lane's default route target: /generate goes to
        # this model on the model tier's :generate route.  Purely a name
        # here -- the gateway never loads decode weights; it proxies the
        # token stream.
        self.decode_model = (
            os.environ.get(DECODE_MODEL_ENV, "").strip()
            or DEFAULT_DECODE_MODEL
        )
        self._session_obj = None
        self._session_lock = threading.Lock()
        self._spec_lock = threading.Lock()

        self.registry = metrics_lib.Registry()
        # Per-request span traces (utils.trace): the gateway half of the
        # cross-tier waterfall.  /debug/trace/<rid> on this tier MERGES the
        # model tier's spans in (fetched from the replica pool), so one GET
        # yields the full client-visible timeline.
        self.tracer = trace_lib.Tracer("gateway", registry=self.registry)
        # SLO engine (utils.slo): the CLIENT-OBSERVED per-model goodput/
        # burn-rate windows -- this tier sees what the user saw (including
        # failover/hedging saves the model tier's own view cannot know
        # about).  /debug/slo here also merges every replica's view.
        # slo_windows overrides the (label, seconds) window pair -- benches
        # compress hours of burn dynamics into seconds while keeping the
        # "5m" label contract the brownout ladder and dashboards key on.
        self.slo = slo_lib.SloEngine(
            self.registry, tier="gateway", enabled=slo,
            windows=slo_windows if slo_windows is not None else slo_lib.WINDOWS,
        )
        self._m_requests = self.registry.counter("kdlt_gateway_requests_total", "requests")
        self._m_errors = self.registry.counter("kdlt_gateway_errors_total", "errors")
        self._m_latency = self.registry.histogram(
            "kdlt_gateway_request_seconds", "end-to-end request latency"
        )
        self._m_fetch = self.registry.histogram(
            "kdlt_gateway_fetch_seconds", "image download+decode+resize latency"
        )
        # Admission control (serving.admission): deadline budgets, AIMD
        # concurrency limiting, shed accounting, graceful drain -- the
        # gateway-tier front door.  admission=None -> $KDLT_ADMISSION ->
        # enabled.  The breaker guards the upstream hop: a dead/saturated
        # model tier turns into fast local 503s instead of a thread-pinning
        # timeout per request.
        self.admission = AdmissionController(
            self.registry, tier="gateway", enabled=admission
        )
        # Incident flight recorder (utils.flightrecorder): the IO tier's
        # black box.  Every failure edge below (brownout ladder, burn
        # crossings, shed bursts, breaker opens, pool churn) records into
        # its timeline, and the trigger engine turns sustained signals
        # into /debug/incidents bundles.  Built BEFORE the brownout loop
        # thread (which feeds it) and the pool (which takes its hook).
        self.recorder = incident_lib.FlightRecorder(
            "gateway", self.registry, tracer=self.tracer,
            enabled=incident, incident_dir=incident_dir,
            triggers=incident_triggers, dedup_s=incident_dedup_s,
        )
        # Brownout (serving.admission.brownout): the slow loop.  When the
        # SLO burn rate stays unsustainable, the ladder degrades serving in
        # stages -- hedges off, stale cache serves, then shedding the lower
        # priority classes -- instead of every class failing together.  The
        # evaluate() loop runs on its own ~1 s daemon, never the hot path.
        self.brownout = BrownoutController(
            self.slo, registry=self.registry, enabled=brownout,
            burn_enter=brownout_enter, burn_exit=brownout_exit,
            dwell_s=brownout_dwell_s,
        )
        self._brownout_eval_s = max(0.05, brownout_eval_s)
        self._brownout_stop = threading.Event()
        self._brownout_thread: threading.Thread | None = None
        if self.brownout.enabled:
            self._brownout_thread = threading.Thread(
                target=self._brownout_loop, name="kdlt-brownout", daemon=True
            )
            self._brownout_thread.start()
        # Content-addressed response cache + singleflight coalescing
        # (serving.cache): checked AHEAD of admission, so a hit consumes no
        # AIMD concurrency slot, no preprocessing, and no upstream/device
        # work, while identical in-flight misses collapse into ONE upstream
        # flight (hedging fires once per flight, not per caller).
        # cache=None -> $KDLT_CACHE -> enabled; KDLT_CACHE=0 kills the
        # whole subsystem (cache AND coalescing) -- the exact legacy path.
        self.cache = (
            cache_lib.ResponseCache(
                self.registry, ttl_s=cache_ttl_s, max_mb=cache_max_mb,
                neg_ttl_s=cache_neg_ttl_s, swr_s=cache_swr_s,
            )
            if cache_lib.cache_enabled(cache)
            else None
        )
        self._singleflight = cache_lib.SingleFlight()
        # Raw-bytes ingest wire (GUIDE 10q): when enabled here (KDLT_INGEST,
        # default on; ``ingest`` arg overrides) AND the model tier
        # advertised the capability during spec discovery (X-Kdlt-Ingest),
        # fetched JPEG/PNG bytes travel upstream verbatim and the MODEL
        # tier decodes -- this tier's Python stops paying decode+resize
        # CPU per image.  Unsniffable blobs and mixed-version replicas
        # fall back per request to the legacy tensor wire (reason-labelled
        # counters below).  The decoded-uint8 cache serves the LEGACY
        # preprocess path here: a repeat image skips decode+resize.
        self._ingest_enabled = protocol.ingest_enabled(ingest)
        self._ingest_caps: dict[str, tuple] = {}
        self._fetch_concurrency = resolve_fetch_concurrency(fetch_concurrency)
        self.decoded_cache = cache_lib.DecodedCache(registry=self.registry)
        self._m_ingest = metrics_lib.ingest_gateway_metrics(self.registry)
        # Multi-replica upstream pool (serving.upstream): replica list from
        # the serving host, per-replica health + breaker, hedging policy.
        # With a single replica this degrades to exactly the PR 2 posture
        # (one breaker, no failover possible).  Dynamic membership: a
        # dns+srv:// serving host carries its own resolver; a plain list
        # re-resolves its DNS names when KDLT_POOL_RESOLVE_S /
        # --pool-resolve-s asks for it (the pool builds that resolver).
        hosts, resolver = resolve_serving_host(self.serving_host)
        self.pool = UpstreamPool(
            hosts,
            registry=self.registry,
            failover=failover,
            hedge_delay_ms=hedge_delay_ms,
            probe_interval_s=probe_interval_s,
            resolver=resolver,
            resolve_interval_s=pool_resolve_s,
            on_event=self.recorder.record,
        )
        self.pool.start_probing()
        # What a bundle snapshots: the same documents the /debug pages
        # serve, captured at fire time (the pages themselves only show
        # NOW; the bundle is the page as of the incident).
        self.recorder.add_snapshot_provider("slo", self.slo.debug_payload)
        self.recorder.add_snapshot_provider("brownout", self._brownout_debug)
        self.recorder.add_snapshot_provider("pool", self.pool.debug_payload)
        self.recorder.add_snapshot_provider("cache", self._cache_debug)
        # Fault injection (serving.faults): the gateway.upstream point;
        # None (zero-overhead) unless $KDLT_FAULTS configures rules.
        self._faults = faults_lib.from_env()
        if self._faults is not None:
            self._faults.attach(self.registry)

        self._httpd = None
        self.port = port
        if bind:
            self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
            self._httpd.daemon_threads = True
            self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    # --- brownout control loop ---------------------------------------------

    def _brownout_loop(self) -> None:
        while not self._brownout_stop.wait(self._brownout_eval_s):
            try:
                prev_stage = self.brownout.stage
                self.brownout.evaluate()
                stage = self.brownout.stage
                # Flight-recorder feed: the eval tick is the one place
                # that sees every slow-loop signal -- ladder moves, burn
                # crossings (edge-detected inside the recorder against
                # the burn-crossing trigger threshold), and shed bursts
                # (delta of the O(1) note_shed ticks from the hot path).
                burn = round(self.brownout.max_burn(), 4)
                if stage > prev_stage:
                    self.recorder.record(
                        "brownout.enter", stage=stage, burn=burn
                    )
                elif stage < prev_stage:
                    self.recorder.record(
                        "brownout.exit", stage=stage, burn=burn
                    )
                self.recorder.observe_burn(burn)
                self.recorder.tick_shed_burst()
            except Exception:  # noqa: BLE001 - the loop must outlive a blip
                continue

    def _brownout_shed(self, priority: str) -> Shed:
        """The 429 a brownout class-shed answers with.  429 (not 503) on
        purpose: slo.classify files 4xx as client-class, so the load the
        ladder sheds leaves the burn denominator and the signal can
        recover instead of latching the ladder at max stage.  Retry-After
        is the dwell (the soonest the stage can change), jittered so the
        shed class cannot come back as one synchronized retry storm."""
        dwell = max(self.brownout.dwell_s, 1.0)
        return Shed(
            "brownout",
            http_status=429,
            retry_after_s=dwell * random.uniform(0.75, 1.25),
            detail=(
                f"brownout stage {self.brownout.stage} sheds "
                f"{priority} requests"
            ),
        )

    # --- model routing -----------------------------------------------------

    def _make_microbatcher(self, model: str | None):
        from kubernetes_deep_learning_tpu.serving.microbatch import (
            UpstreamMicroBatcher,
        )

        return UpstreamMicroBatcher(
            lambda images, request_id, _m=model: self._predict_batch(
                images, request_id, model=_m
            ),
            max_batch=self._upstream_batch,
            max_delay_ms=self._upstream_delay_ms,
        )

    def _microbatcher_for(self, model: str | None):
        """The per-model upstream micro-batcher (None when coalescing is
        off).  One per model: a flush must be one model's images."""
        if self._upstream_batch <= 0:
            return None
        if model is None or model == self.model:
            return self._microbatcher
        with self._microbatcher_lock:
            mb = self._microbatchers.get(model)
            if mb is None:
                mb = self._make_microbatcher(model)
                self._microbatchers[model] = mb
            return mb

    def resolve_model(self, path: str, header: str | None) -> str | None:
        """Route a /predict request to a model name.

        ``/predict`` -> the default model (reference-compatible);
        ``/predict/<model>`` -> that model; the X-Kdlt-Model header applies
        when the path carries no model.  Returns None for a malformed name
        (the transports answer 404 without touching the upstream).
        """
        model: str | None = None
        if path.startswith("/predict/"):
            model = path[len("/predict/"):]
        elif header:
            model = str(header).strip()
        if model is None or model == self.model:
            return self.model
        if not _MODEL_NAME_RE.match(model):
            return None
        return model

    # --- model-server client ----------------------------------------------

    def _session(self):
        # One shared Session (thread-safe for plain requests): connections to
        # the model tier are pooled across handler threads instead of one
        # fresh TCP setup per short-lived client connection.
        import requests

        if self._session_obj is None:
            with self._session_lock:
                if self._session_obj is None:
                    s = requests.Session()
                    adapter = requests.adapters.HTTPAdapter(
                        pool_connections=4, pool_maxsize=64
                    )
                    s.mount("http://", adapter)
                    self._session_obj = s
        return self._session_obj

    @property
    def breaker(self):
        """The first replica's circuit breaker (back-compat: the PR 2
        single-upstream surface; per-replica breakers live on the pool)."""
        return self.pool.replicas[0].breaker

    @breaker.setter
    def breaker(self, value) -> None:
        self.pool.replicas[0].breaker = value

    def _fetch_spec(self, replica, model: str | None = None) -> ModelSpec:
        """GET one replica's /v1/models/<name> contract (RequestException
        propagates -- the caller decides whether that means failover)."""
        r = self._session().get(
            f"{replica.base}/v1/models/{model or self.model}", timeout=10
        )
        if r.status_code == 404:
            raise UpstreamError(
                f"model tier serves no model {model or self.model!r}", 404
            )
        r.raise_for_status()
        # Ingest negotiation rides spec discovery (GUIDE 10q): the header's
        # presence IS the capability; an old server never sends it and this
        # gateway stays on the tensor wire for that model.
        replica.ingest_caps = protocol.parse_ingest_caps(
            r.headers.get(protocol.INGEST_HEADER)
        )
        return ModelSpec.from_json(r.text)

    @property
    def spec(self) -> ModelSpec:
        """The DEFAULT model's contract, discovered from the model tier.

        Discovery sweeps the replica pool (healthy replicas first) and the
        first answer becomes the pool's ``reference_spec`` -- the contract
        every other replica is validated against before serving traffic
        (see _validate_replica_spec).
        """
        return self.spec_for(None)

    def spec_for(self, model: str | None) -> ModelSpec:
        """A model's reference contract, discovered on first use.

        The default model keeps the original pool.reference_spec slot
        (back-compat for everything built on the single-model surface);
        other models land in pool.reference_specs keyed by name.
        """
        pool = self.pool
        default = model is None or model == self.model
        cached = (
            pool.reference_spec if default else pool.reference_specs.get(model)
        )
        if cached is not None:
            return cached
        import requests

        with self._spec_lock:
            cached = (
                pool.reference_spec if default
                else pool.reference_specs.get(model)
            )
            if cached is not None:
                return cached
            last_exc: Exception | None = None
            for replica in pool.snapshot_ordered():
                try:
                    spec = self._fetch_spec(replica, None if default else model)
                except UpstreamError:
                    raise  # a 404 is an answer (unknown model), not an outage
                except requests.RequestException as e:
                    last_exc = e
                    continue
                if default:
                    replica.spec = spec
                    pool.reference_spec = spec
                else:
                    replica.specs[model] = spec
                    pool.reference_specs[model] = spec
                # The reference replica's advertised ingest caps become the
                # routed model's negotiation outcome; a stale answer on a
                # mixed pool is healed per request (_BytesWireRejected).
                self._ingest_caps["" if default else model] = getattr(
                    replica, "ingest_caps", ()
                )
                return spec
            raise UpstreamError(
                f"model spec discovery failed: {last_exc}"
            ) from last_exc

    def supports_ingest(self, cap: str, model: str | None = None) -> bool:
        """Negotiated ingest capability for the routed model: this gateway
        has KDLT_INGEST on AND the model tier advertised ``cap`` at spec
        discovery.  ``cap`` is a protocol.INGEST_CAPS member (kdlt-lint's
        closed-vocabulary registry covers call sites)."""
        if not self._ingest_enabled:
            return False
        default = model is None or model == self.model
        return cap in self._ingest_caps.get("" if default else model, ())

    def _fetch_one(self, url: str):
        """url -> resized uint8 HWC image (host-side half of the pipeline),
        sized for the DEFAULT model (the single-argument surface tests
        monkeypatch; _fetch_one_for is the model-aware variant)."""
        return self._fetch_one_for(url, None)

    def _fetch_one_for(self, url: str, model: str | None):
        spec = self.spec_for(model)
        t0 = time.perf_counter()
        data = preprocess.fetch_image_bytes(url)
        image = self._decode_cached(data, spec)
        self._m_fetch.observe(time.perf_counter() - t0)
        return image

    def _decode_cached(self, data: bytes, spec) -> "object":
        """Decode+resize through the decoded-uint8 cache: content-addressed
        by (payload hash, preprocess params), so a repeat image -- same
        bytes, any URL, any model sharing the resolution/filter -- skips
        the gateway's decode+resize CPU entirely."""
        cache = self.decoded_cache
        if not cache.enabled:
            return preprocess.preprocess_bytes(
                data, spec.input_shape[:2], filter=spec.resize_filter
            )
        key = cache_lib.decoded_key(
            data, cache_lib.decoded_params(spec.input_shape, spec.resize_filter)
        )
        hit = cache.get(key)
        if hit is not None:
            return hit
        image = preprocess.preprocess_bytes(
            data, spec.input_shape[:2], filter=spec.resize_filter
        )
        cache.put(key, image)
        return image

    def _fetch_one_bytes(self, url: str, trace=None, model: str | None = None):
        """Raw-bytes ingest fetch: download only -- no decode, no resize
        (that CPU moves to the model tier).  Returns the encoded payload;
        the caller sniffs it before committing to the bytes wire."""
        self.spec_for(model)  # contract discovery still gates serving
        if trace is None:
            t0 = time.perf_counter()
            data = preprocess.fetch_image_bytes(url)
            self._m_fetch.observe(time.perf_counter() - t0)
            return data
        with trace.span(trace_lib.SPAN_GATEWAY_PREPROCESS):
            t0 = time.perf_counter()
            data = preprocess.fetch_image_bytes(url)
            self._m_fetch.observe(time.perf_counter() - t0)
            return data

    def _fetch_one_traced(self, url: str, trace=None, model: str | None = None):
        """_fetch_one under a ``gateway.preprocess`` span.  Kept separate so
        _fetch_one's single-argument surface (which tests monkeypatch) stays
        stable whether or not the request is traced."""
        if model is None:
            fetch = self._fetch_one
        else:
            def fetch(u):
                return self._fetch_one_for(u, model)
        if trace is None:
            return fetch(url)
        with trace.span(trace_lib.SPAN_GATEWAY_PREPROCESS):
            return fetch(url)

    def _validate_replica_spec(self, replica, model: str | None = None) -> None:
        """Failover spec re-validation: before a replica other than the
        reference source serves traffic, its contract must match the pool's
        reference -- a replica left serving a different model version
        surfaces as an explicit 502, never silently mixed responses.

        Only runs once a reference exists and only until the replica's spec
        is cached (it is re-cleared when the replica rejoins after being
        unhealthy).  RequestException propagates: an unreachable replica is
        a connect failure, which the failover loop routes around.  Checked
        PER MODEL: each routed model's contract is validated independently.
        """
        default = model is None or model == self.model
        reference = (
            self.pool.reference_spec if default
            else self.pool.reference_specs.get(model)
        )
        if reference is None:
            return
        if default:
            if replica.spec is None:
                replica.spec = self._fetch_spec(replica)
            cached = replica.spec
        else:
            cached = replica.specs.get(model)
            if cached is None:
                cached = replica.specs[model] = self._fetch_spec(replica, model)
        if cached.to_json() != reference.to_json():
            self.pool.mark_spec_mismatch(replica)
            raise UpstreamError(
                f"model-tier replica {replica.host} serves a different "
                f"model contract ({model or self.model!r}) than the pool "
                "reference", 502,
            )

    def _post_once(self, replica, body, request_id, deadline, timeout,
                   span_id: str = "", model: str | None = None,
                   priority: str | None = None, content_type: str | None = None):
        """One upstream POST to one replica (headers re-measured now)."""
        if self._faults is not None:
            self._faults.fire("gateway.upstream")
        headers = {"Content-Type": content_type or protocol.MSGPACK_CONTENT_TYPE}
        if request_id:  # cross-tier trace propagation
            headers[REQUEST_ID_HEADER] = request_id
        if span_id:  # this attempt's span: the model tier's root parent
            headers[PARENT_SPAN_HEADER] = span_id
        if deadline is not None:  # remaining budget, re-measured now
            headers[DEADLINE_HEADER] = deadline.header_value()
        if priority:  # class propagation: the model tier sheds by class too
            headers[PRIORITY_HEADER] = priority
        return self._session().post(
            f"{replica.base}/v1/models/{model or self.model}:predict",
            data=body,
            headers=headers,
            timeout=timeout,
        )

    def _attempt_traced(self, replica, body, request_id, deadline, timeout,
                        trace, role: str, model: str | None = None,
                        priority: str | None = None,
                        content_type: str | None = None):
        """One upstream POST recorded as a ``gateway.upstream`` span.

        Returns ``(response, span)``; on failure records the span with the
        error tag and re-raises.  The span id travels upstream as
        X-Kdlt-Parent-Span, so the model tier's subtree hangs off THIS
        attempt -- which is what makes a hedged request's waterfall show
        two distinguishable model-tier executions.
        """
        if trace is None:
            return self._post_once(
                replica, body, request_id, deadline, timeout, model=model,
                priority=priority, content_type=content_type,
            ), None
        sid = trace_lib.new_span_id()
        w0 = trace_lib.now_s()
        try:
            r = self._post_once(
                replica, body, request_id, deadline, timeout, span_id=sid,
                model=model, priority=priority, content_type=content_type,
            )
        except Exception as e:
            trace.tracer.record(
                trace.trace_id, trace_lib.SPAN_GATEWAY_UPSTREAM, w0,
                trace_lib.now_s() - w0, parent_id=trace.span_id, span_id=sid,
                replica=replica.host, role=role, error=str(e)[:120],
            )
            raise
        span = trace.tracer.record(
            trace.trace_id, trace_lib.SPAN_GATEWAY_UPSTREAM, w0, trace_lib.now_s() - w0,
            parent_id=trace.span_id, span_id=sid,
            replica=replica.host, role=role, status=r.status_code,
        )
        return r, span

    def _post_hedged(
        self, primary, body, request_id, deadline, timeout, tried,
        trace=None, role: str = "primary", model: str | None = None,
        priority: str | None = None, content_type: str | None = None,
    ):
        """POST with a deadline-budget-aware hedged second attempt.

        If the primary has not answered within the pool's hedge delay AND
        another healthy replica exists AND the remaining budget can still
        cover a useful attempt, a second request fires against that
        replica; the first usable answer wins and the loser is abandoned
        (its daemon thread drains the response into the connection pool --
        plain HTTP/1.1 has no cancel).  Tail-at-scale hedging: the hedge
        only ever duplicates the slowest requests, so the added load is
        bounded by the hedge-delay percentile.

        Returns ``(winning_replica, response)``.  If every attempt raised,
        failures are recorded for the hedge replica (the caller records the
        primary's), the hedge replica is appended to ``tried``, and the
        primary's exception re-raises.
        """
        pool = self.pool
        delay = pool.hedge_delay_s
        hedgeable = (
            pool.failover
            and delay > 0
            # Brownout stage >= 1: hedges duplicate work exactly when the
            # tier can least afford it, so they are the first thing to go.
            and not self.brownout.hedging_disabled
            and pool.has_healthy_candidate(exclude=[primary, *tried])
            and (
                deadline is None
                or deadline.remaining_s() > delay + MIN_RETRY_BUDGET_S
            )
        )
        if not hedgeable:
            r, span = self._attempt_traced(
                primary, body, request_id, deadline, timeout, trace, role,
                model=model, priority=priority, content_type=content_type,
            )
            if span is not None:
                span.tags["winner"] = True
            return primary, r
        import queue as queue_lib

        results: queue_lib.Queue = queue_lib.Queue()

        def attempt(rep, rep_role):
            try:
                r, span = self._attempt_traced(
                    rep, body, request_id, deadline, timeout, trace, rep_role,
                    model=model, priority=priority, content_type=content_type,
                )
                results.put((rep, r, None, span))
            except Exception as e:  # noqa: BLE001 - reported via the queue
                results.put((rep, None, e, None))

        threading.Thread(
            target=attempt, args=(primary, role), name="kdlt-upstream-primary",
            daemon=True,
        ).start()
        try:
            first = results.get(timeout=delay)
        except queue_lib.Empty:
            first = None
        hedge = None
        if first is None:
            # Primary is slow past the hedge delay: fire the hedge.
            hedge = pool.choose(
                exclude=[primary, *tried],
                gate_breaker=self.admission.enabled,
            )
            if hedge is None:
                first = results.get()
            else:
                if pool.m_hedge_fired is not None:
                    pool.m_hedge_fired.inc()
                threading.Thread(
                    target=attempt, args=(hedge, "hedge"),
                    name="kdlt-upstream-hedge", daemon=True,
                ).start()
                first = results.get()
        outcomes = [first]
        if hedge is not None and not self._usable(first):
            # The faster attempt failed; the slower one may still win.
            outcomes.append(results.get())
        winner = next((o for o in outcomes if self._usable(o)), None)
        if winner is None:
            # No usable answer; prefer returning a 5xx response (the
            # caller's 503/failover policy applies) over raising.
            winner = next((o for o in outcomes if o[1] is not None), None)
        if winner is not None:
            rep, r, _exc, span = winner
            if span is not None:
                # The used attempt is marked on the trace: a hedged
                # request's waterfall shows BOTH attempt spans and which
                # one's response the client actually got.
                span.tags["winner"] = True
            for lrep, lr, lexc, _lspan in outcomes:
                if lrep is rep:
                    continue  # the caller accounts the winner's outcome
                if lexc is not None or (lr is not None and lr.status_code >= 500):
                    pool.record_failure(lrep)
                    if lr is not None and lr.headers.get(
                        protocol.STALLED_HEADER
                    ):
                        pool.mark_stalled(lrep)  # declared stall: out now
                    if lrep not in tried:
                        tried.append(lrep)  # a known-bad failover target
            if hedge is not None and rep is hedge and pool.m_hedge_won is not None:
                pool.m_hedge_won.inc()
            return rep, r
        # Every observed attempt raised: account the hedge's failure here
        # (the caller only knows the primary) and re-raise the primary's.
        primary_exc = None
        for lrep, _lr, lexc, _lspan in outcomes:
            if lrep is primary:
                primary_exc = lexc
                continue
            pool.record_failure(lrep)
            if lrep not in tried:
                tried.append(lrep)
        raise primary_exc if primary_exc is not None else outcomes[-1][2]

    @staticmethod
    def _usable(outcome) -> bool:
        """A hedged attempt outcome worth returning: a response that is not
        a server-side failure (2xx-4xx means the tier is up and judged the
        request on its merits)."""
        _rep, r, exc, _span = outcome
        return exc is None and r is not None and r.status_code < 500

    @staticmethod
    def _status_error(r) -> UpstreamError:
        """Map a non-200 upstream response to the client-facing error.
        A 404 passes through: "no such model" is the caller's mistake
        (bad route), not a tier outage dressed up as a 502."""
        if r.status_code == 404:
            return UpstreamError(
                f"model server error 404: {r.text[:200]}", 404
            )
        status = 503 if r.status_code == 503 else 502
        retry_after = None
        if status == 503:
            try:
                retry_after = float(r.headers.get("Retry-After", ""))
            except (TypeError, ValueError):
                retry_after = None
        return UpstreamError(
            f"model server error {r.status_code}: {r.text[:200]}",
            status,
            retry_after_s=retry_after,
        )

    def _predict_batch(
        self,
        images,
        request_id: str = "",
        deadline: Deadline | None = None,
        trace=None,
        model: str | None = None,
        priority: str | None = None,
    ) -> tuple[list, list[str]]:
        """uint8 (N,H,W,C) -> (logit rows, labels) via the legacy tensor
        wire (msgpack uint8)."""
        return self._predict_wire(
            protocol.encode_predict_request(images), images.shape[0],
            request_id, deadline, trace, model, priority,
        )

    def _predict_bytes(
        self,
        blobs: list[bytes],
        request_id: str = "",
        deadline: Deadline | None = None,
        trace=None,
        model: str | None = None,
        priority: str | None = None,
    ) -> tuple[list, list[str]]:
        """Encoded JPEG/PNG blobs -> (logit rows, labels) via the raw-bytes
        ingest wire (GUIDE 10q): the model tier decodes.  Raises
        _BytesWireRejected on an upstream 400/415 so the caller can decode
        locally and resend on the tensor wire (mixed-pool rollout)."""
        body = protocol.encode_bytes_predict_request(blobs)
        self._m_ingest["bytes_requests"].inc()
        self._m_ingest["wire_bytes"].inc(len(body))
        return self._predict_wire(
            body, len(blobs), request_id, deadline, trace, model, priority,
            content_type=protocol.BYTES_CONTENT_TYPE,
        )

    def _predict_wire(
        self,
        body: bytes,
        n_images: int,
        request_id: str = "",
        deadline: Deadline | None = None,
        trace=None,
        model: str | None = None,
        priority: str | None = None,
        content_type: str | None = None,
    ) -> tuple[list, list[str]]:
        """One encoded request body -> (logit rows, labels) via the model
        tier; the shared upstream engine for both wire formats.

        Failure policy over the replica pool (serving.upstream):

        - a connect error / injected fault fails over to the next replica
          (passive health + breaker bookkeeping per replica) until the
          pool or the deadline budget is exhausted;
        - a 503 (the tier's explicit transient overload signal) fails over
          immediately when another HEALTHY replica exists; otherwise it
          keeps PR 2's single-upstream shape -- one brief backoff retry
          against the same replica, budget permitting;
        - slow responses are hedged to a second replica after the hedge
          delay (_post_hedged), budget permitting;
        - when every replica is refused up front (breakers open), the
          request sheds locally as breaker_open, Retry-After = the
          soonest any replica might recover.

        Deadline-aware throughout: the read timeout is clamped to the
        request's remaining budget (a caller that will give up in 800 ms
        must not hold this thread for 20 s) and the REMAINING budget
        travels upstream in the deadline header.
        """
        import requests

        pool = self.pool
        gate = self.admission.enabled
        # (connect, read) pair: only the READ budget scales with batch size;
        # an unreachable model tier should still fail fast at connect.
        base_read = (
            PREDICT_TIMEOUT_S + PER_IMAGE_TIMEOUT_S * max(0, n_images - 1)
        )
        tried: list = []
        retried_503 = False
        last_exc: UpstreamError | None = None
        r = None
        while True:
            replica = pool.choose(exclude=tried, gate_breaker=gate)
            if replica is None:
                if not tried and gate:
                    # Every replica refused up front: fast local shed
                    # instead of a thread-pinning timeout per request.
                    self.admission.count_shed("breaker_open")
                    self.recorder.note_shed()
                    self.recorder.record("breaker.open", rid=request_id or None)
                    raise UpstreamError(
                        "model tier circuit breaker is open",
                        503,
                        retry_after_s=pool.min_retry_after_s() or 0.5,
                    )
                if last_exc is not None:
                    raise last_exc
                if r is not None:
                    raise self._status_error(r)
                raise UpstreamError(
                    "no model-tier replica available", 503, retry_after_s=0.5
                )
            if tried and pool.m_failover is not None:
                pool.m_failover.inc()
            read_timeout = base_read
            if deadline is not None:
                read_timeout = deadline.clamp(read_timeout, floor_s=0.05)
            timeout = (
                min(PREDICT_TIMEOUT_S, max(read_timeout, 0.05)), read_timeout
            )
            try:
                self._validate_replica_spec(replica, model)
                replica, r = self._post_hedged(
                    replica, body, request_id, deadline, timeout, tried,
                    trace=trace,
                    role="failover" if tried else "primary",
                    model=model, priority=priority, content_type=content_type,
                )
            except (
                requests.RequestException,
                faults_lib.InjectedFault,
                ConnectionError,
            ) as e:
                pool.record_failure(replica)
                if replica not in tried:
                    tried.append(replica)
                last_exc = UpstreamError(f"model server unreachable: {e}")
                last_exc.__cause__ = e
                if not pool.failover:
                    # Blind mode (KDLT_FAILOVER=0, the chaos-A/B baseline
                    # arm): one attempt, the failure surfaces as-is.
                    raise last_exc
                if deadline is not None and (
                    deadline.remaining_s() < MIN_RETRY_BUDGET_S
                ):
                    raise last_exc  # no budget left to try anyone else
                continue
            # Breaker/health bookkeeping per attempt: any 5xx (including
            # the tier's 503 shed) is evidence of an unhealthy/saturated
            # replica; 2xx-4xx means it is up and judging requests on
            # their merits.
            if r.status_code >= 500:
                pool.record_failure(replica)
                if r.headers.get(protocol.STALLED_HEADER):
                    # A DECLARED dispatch stall (the replica's watchdog
                    # fired; only a restart recovers it) is not transient
                    # overload: take the replica out of rotation NOW
                    # instead of feeding it UNHEALTHY_AFTER more requests
                    # -- a stalled cross-host leader would otherwise keep
                    # stranding every coalesced flight that dials it.
                    pool.mark_stalled(replica)
            else:
                # Feed the replica's latency EWMA (the power-of-two-choices
                # ranking signal) from the winning response's own timing.
                elapsed = getattr(r, "elapsed", None)
                pool.record_success(
                    replica,
                    latency_s=(
                        elapsed.total_seconds() if elapsed is not None else None
                    ),
                )
            if r.status_code != 503:
                break
            last_exc = None
            if replica not in tried:
                tried.append(replica)
            if pool.has_healthy_candidate(exclude=tried):
                continue  # overloaded here; another healthy replica may not be
            if retried_503:
                break
            if deadline is not None and deadline.remaining_s() < (
                UPSTREAM_RETRY_BACKOFF_S + MIN_RETRY_BUDGET_S
            ):
                # A nearly-expired request must not burn its last budget
                # sleeping out the backoff and re-posting work that cannot
                # finish in time; surface the 503 to the client now.
                break
            retried_503 = True
            time.sleep(UPSTREAM_RETRY_BACKOFF_S)
            tried.remove(replica)  # the backoff retry re-targets this replica
        if r.status_code != 200:
            if (
                content_type == protocol.BYTES_CONTENT_TYPE
                and r.status_code in (400, 415)
            ):
                # The bytes wire was negotiated but THIS replica rejected
                # it (old code, or KDLT_INGEST flipped off after
                # discovery).  Signal the caller to decode locally and
                # resend on the tensor wire -- a rollout seam, never a
                # client-visible error.
                raise _BytesWireRejected(r.text[:200])
            raise self._status_error(r)
        if self.cache is not None:
            # Learn the serving artifact's identity from the response: a
            # CHANGED hash is a hot reload with different bytes, which
            # drops that model's cached entries (a byte-identical
            # re-export under a higher version keeps them).
            self.cache.note_artifact_hash(
                model or self.model,
                r.headers.get(protocol.ARTIFACT_HASH_HEADER, ""),
            )
        try:
            logits, labels = protocol.decode_predict_response(
                r.content, r.headers.get("Content-Type", "")
            )
        except Exception as e:
            # A 200 with an undecodable body is the model tier's fault
            # (truncated response, content-type mismatch), never the client's.
            raise UpstreamError(f"malformed model server response: {e}") from e
        return logits, labels

    def apply_model(
        self,
        url: str,
        request_id: str = "",
        deadline: Deadline | None = None,
        trace=None,
        model: str | None = None,
        priority: str | None = None,
    ) -> dict[str, float]:
        """url -> {label: score}; the reference's apply_model
        (reference model_server.py:52-56).  ``model`` routes to a
        non-default served model (multi-model registry).  ``priority``
        travels upstream on the direct path; micro-batched flushes mix
        classes, so a coalesced upstream POST carries none."""
        if self._ingest_enabled:
            self.spec_for(model)  # negotiation rides spec discovery
            if self.supports_ingest(protocol.INGEST_BYTES_CAP, model):
                # Raw-bytes wire (GUIDE 10q).  Bypasses the microbatcher:
                # the upstream POST already carries compact encoded bytes,
                # so coalescing would only add queueing delay.
                return self._apply_model_bytes(
                    url, request_id, deadline, trace, model, priority
                )
            self._m_ingest["fallbacks"]["negotiation"].inc()
        image = self._fetch_one_traced(url, trace, model=model)
        microbatcher = self._microbatcher_for(model)
        if microbatcher is not None:
            # Micro-batched flushes coalesce MANY requests' upstream hop
            # into one POST; the upstream attempt is not attributable to a
            # single request's subtree, so the trace records the wait as
            # one span instead.
            if trace is None:
                row, labels = microbatcher.predict(
                    image,
                    request_id,
                    timeout=None if deadline is None else deadline.remaining_s(),
                )
            else:
                with trace.span(trace_lib.SPAN_GATEWAY_MICROBATCH):
                    row, labels = microbatcher.predict(
                        image,
                        request_id,
                        timeout=None if deadline is None else deadline.remaining_s(),
                    )
            return dict(zip(labels, map(float, row)))
        logits, labels = self._predict_batch(
            image[None], request_id, deadline, trace, model=model,
            priority=priority,
        )
        return dict(zip(labels, map(float, logits[0])))

    def _apply_model_bytes(
        self, url, request_id, deadline, trace, model, priority,
    ) -> dict[str, float]:
        """apply_model over the raw-bytes ingest wire, with the per-request
        fallbacks (GUIDE 10q): an unsniffable blob (reason "format") or a
        replica that rejects the wire (reason "rejected") decodes at the
        gateway and resends the SAME fetched bytes on the tensor wire --
        never a second download, never a client-visible seam."""
        import numpy as np

        spec = self.spec_for(model)
        blob = self._fetch_one_bytes(url, trace, model)
        if protocol.sniff_image_format(blob) is not None:
            try:
                logits, labels = self._predict_bytes(
                    [blob], request_id, deadline, trace, model=model,
                    priority=priority,
                )
                return dict(zip(labels, map(float, logits[0])))
            except _BytesWireRejected:
                self._m_ingest["fallbacks"]["rejected"].inc()
        else:
            self._m_ingest["fallbacks"]["format"].inc()
        image = self._decode_cached(blob, spec)
        logits, labels = self._predict_batch(
            np.asarray(image)[None], request_id, deadline, trace, model=model,
            priority=priority,
        )
        return dict(zip(labels, map(float, logits[0])))

    def apply_model_batch(
        self,
        urls: list[str],
        request_id: str = "",
        deadline: Deadline | None = None,
        trace=None,
        model: str | None = None,
        priority: str | None = None,
    ) -> list[dict]:
        """urls -> per-url {label: score} or {"error": ...}, order-preserving.

        Beyond-reference extension: fetches run concurrently (IO-bound) and
        every successfully fetched image travels to the model tier as ONE
        predict (the tier splits oversize batches over its own bucket
        ladder, ServedModel.predict -- chunking policy lives in one place).
        A bad URL fails only its own entry; a model-tier failure fails the
        whole request (UpstreamError propagates, not a per-URL condition).
        """
        from concurrent.futures import ThreadPoolExecutor

        if not urls:
            return []
        if len(urls) > MAX_URLS_PER_REQUEST:
            raise ValueError(
                f"{len(urls)} urls exceeds the {MAX_URLS_PER_REQUEST}-url limit"
            )
        self.spec_for(model)  # discover contract FIRST: outage => 502, not 200
        if self._ingest_enabled:
            if self.supports_ingest(protocol.INGEST_BYTES_CAP, model):
                return self._apply_model_batch_bytes(
                    urls, request_id, deadline, trace, model, priority
                )
            self._m_ingest["fallbacks"]["negotiation"].inc()
        with ThreadPoolExecutor(
            max_workers=min(len(urls), self._fetch_concurrency)
        ) as ex:
            fetched = list(
                ex.map(lambda u: self._fetch_one_safe(u, trace, model), urls)
            )
        good = [(i, img) for i, (img, _) in enumerate(fetched) if img is not None]
        results: list[dict] = [
            {"error": err} if err is not None else {} for _, err in fetched
        ]
        if good:
            import numpy as np

            logits, labels = self._predict_batch(
                np.stack([img for _, img in good]), request_id, deadline,
                trace, model=model, priority=priority,
            )
            for row, (i, _) in enumerate(good):
                results[i] = dict(zip(labels, map(float, logits[row])))
        return results

    def _apply_model_batch_bytes(
        self, urls, request_id, deadline, trace, model, priority,
    ) -> list[dict]:
        """apply_model_batch over the raw-bytes ingest wire.

        Wire choice is per REQUEST: all sniffable blobs -> one bytes POST;
        any exotic blob drops the whole request to the tensor wire (reason
        "format") so the batch stays one upstream flight either way, and a
        _BytesWireRejected replica gets the tensor resend (reason
        "rejected").  Per-URL failure semantics match the legacy path: a
        bad download or undecodable blob fails only its own entry."""
        from concurrent.futures import ThreadPoolExecutor

        import numpy as np

        spec = self.spec_for(model)

        def fetch(u):
            try:
                return self._fetch_one_bytes(u, trace, model), None
            except UpstreamError:
                raise  # model-tier trouble fails the request, not the URL
            except Exception as e:  # noqa: BLE001 - per-URL failure
                return None, str(e)

        with ThreadPoolExecutor(
            max_workers=min(len(urls), self._fetch_concurrency)
        ) as ex:
            fetched = list(ex.map(fetch, urls))
        good = [(i, blob) for i, (blob, _) in enumerate(fetched) if blob is not None]
        results: list[dict] = [
            {"error": err} if err is not None else {} for _, err in fetched
        ]
        if not good:
            return results
        logits = labels = None
        if all(protocol.sniff_image_format(b) is not None for _, b in good):
            try:
                logits, labels = self._predict_bytes(
                    [b for _, b in good], request_id, deadline, trace,
                    model=model, priority=priority,
                )
            except _BytesWireRejected:
                self._m_ingest["fallbacks"]["rejected"].inc()
        else:
            self._m_ingest["fallbacks"]["format"].inc()
        if logits is None:
            # Tensor-wire fallback: decode the already-fetched bytes here
            # (through the decoded cache); a blob that fails to decode
            # fails only its own entry, like a bad URL.
            keep, images = [], []
            for i, blob in good:
                try:
                    images.append(self._decode_cached(blob, spec))
                    keep.append(i)
                except Exception as e:  # noqa: BLE001 - per-URL failure
                    results[i] = {"error": str(e)}
            if not keep:
                return results
            good = [(i, None) for i in keep]
            logits, labels = self._predict_batch(
                np.stack(images), request_id, deadline, trace, model=model,
                priority=priority,
            )
        for row, (i, _) in enumerate(good):
            results[i] = dict(zip(labels, map(float, logits[row])))
        return results

    def _fetch_one_safe(self, url: str, trace=None, model: str | None = None):
        try:
            return self._fetch_one_traced(url, trace, model=model), None
        except UpstreamError:
            raise  # model-tier trouble is the request's failure, not the URL's
        except Exception as e:
            return None, str(e)

    # --- transport-neutral request handling --------------------------------
    # One implementation of routing, error mapping, and metrics policy,
    # shared by the in-tree threaded server below and serving.wsgi (gunicorn)
    # so the two deployment postures can never diverge.

    def handle_get(self, path: str) -> tuple[int, bytes, str]:
        """Route a GET; returns (status, body, content_type)."""
        if path == "/healthz":
            return 200, b"ok", "text/plain"
        if path == "/readyz":
            if self.admission.draining:
                # Drain flips readiness FIRST so the Service/LB stops
                # routing here while in-flight work completes.
                return 503, b"draining", "text/plain"
            try:
                self.spec  # reachable + spec discoverable => ready
                return 200, b"ready", "text/plain"
            except Exception as e:
                return 503, str(e).encode(), "text/plain"
        if path == "/metrics":
            # Pull-model freshness: SLO window gauges recompute at scrape.
            self.slo.refresh()
            return 200, self.registry.render().encode(), "text/plain"
        if path == "/debug/slo":
            return (
                200, json.dumps(self.handle_slo()).encode(), "application/json"
            )
        if path == "/debug/cache":
            # The response cache's operator surface: sizing, hit ratio,
            # per-model residency, resolved artifact hashes, and the
            # singleflight's live flight count.
            return (
                200, json.dumps(self._cache_debug()).encode(),
                "application/json",
            )
        if path == "/debug/brownout":
            # The degradation ladder's operator surface: live stage, burn
            # vs the enter/exit thresholds, transition history, per-class
            # admitted/shed counts, and the limiter's per-model shares.
            return (
                200, json.dumps(self._brownout_debug()).encode(),
                "application/json",
            )
        if path == "/debug/pool":
            # The replica pool's operator surface: membership, per-replica
            # health/quarantine/drain state, picks, and the latency EWMA
            # driving power-of-two-choices (kdlt-client --stats renders
            # the per-replica rows from this).
            return (
                200,
                json.dumps(self.pool.debug_payload()).encode(),
                "application/json",
            )
        if path.split("?", 1)[0] == "/debug/profile":
            # Bucket-shape audit, merged across the fleet: each replica's
            # per-bucket padding waste and compiled FLOPs/img (the numbers
            # that say whether the bucket ladder fits the traffic).
            return (
                200, json.dumps(self.handle_profile()).encode(),
                "application/json",
            )
        if path in ("/debug", "/debug/"):
            # The debug INDEX: every debug surface this tier serves, with
            # a one-line description -- so operators (and kdlt-client
            # --stats) need not memorize the route list.
            return (
                200, json.dumps(self.debug_index()).encode(),
                "application/json",
            )
        if path in ("/debug/incidents", "/debug/incidents/"):
            return (
                200, json.dumps(self.handle_incidents()).encode(),
                "application/json",
            )
        if path.startswith("/debug/incidents/"):
            return self.handle_incident(path.rsplit("/", 1)[-1])
        if path.startswith("/debug/trace/"):
            return self.handle_trace(path.rsplit("/", 1)[-1])
        return 404, b'{"error": "not found"}', "application/json"

    def _cache_debug(self) -> dict:
        # "decoded" is the decoded-uint8 tier (content-addressed, GUIDE
        # 10q) -- independent of the response cache, so it reports even
        # when KDLT_CACHE=0 disables the response tier.
        decoded = {"decoded": self.decoded_cache.stats()}
        if self.cache is None:
            return {"enabled": False, **decoded}
        return {
            "enabled": True,
            **self.cache.stats(),
            **self._singleflight.stats(),
            **decoded,
        }

    def _brownout_debug(self) -> dict:
        payload = self.brownout.debug_payload()
        payload["classes"] = self.admission.class_stats()
        limiter = self.admission.limiter
        payload["shares"] = limiter.shares() if limiter is not None else {}
        return payload

    def debug_index(self) -> dict:
        """GET /debug/: this tier's debug routes, one line each."""
        return {
            "tier": "gateway",
            "routes": {
                "/debug/slo": "merged fleet SLO view: gateway-observed + "
                "every replica's goodput and burn windows",
                "/debug/cache": "response cache sizing, hit ratio, "
                "per-model residency, live singleflight count",
                "/debug/brownout": "degradation ladder stage, burn vs "
                "thresholds, transitions, per-class shed accounting",
                "/debug/pool": "upstream membership and per-replica "
                "health/quarantine/drain, picks, latency EWMA",
                "/debug/profile?audit=buckets": "merged bucket-shape "
                "audit: per-replica padding waste and FLOPs/img per bucket",
                "/debug/incidents": "flight-recorder bundles (own + "
                "replicas'), merged into causal windows",
                "/debug/incidents/<id>": "one full incident bundle "
                "(timeline, pinned traces, snapshots, metrics delta)",
                "/debug/trace/<rid>": "merged cross-tier span waterfall "
                "for one request id",
            },
        }

    def handle_incidents(self) -> dict:
        """GET /debug/incidents: this tier's bundles plus every model-tier
        replica's, merged into causal windows (one failure fires triggers
        on several processes within seconds; the window groups them).
        Unreachable replicas degrade to error entries, never a failed
        response -- incident review must work during the incident."""
        payload = self.recorder.debug_payload()
        own = payload["incidents"]
        for e in own:
            e["origin"] = "gateway"
        entries = list(own)
        replicas: dict[str, object] = {}
        for replica in self.pool.replicas:
            try:
                r = self._session().get(
                    f"{replica.base}/debug/incidents", timeout=2.0
                )
                if r.status_code != 200:
                    replicas[replica.host] = {
                        "error": f"status {r.status_code}"
                    }
                    continue
                body = r.json()
                remote = body.get("incidents", [])
                for e in remote:
                    e["origin"] = replica.host
                replicas[replica.host] = remote
                entries.extend(remote)
            except Exception as e:  # noqa: BLE001 - partial views beat none
                replicas[replica.host] = {"error": str(e)[:200]}
        payload["replicas"] = replicas
        payload["windows"] = incident_lib.merge_windows(entries)
        return payload

    def handle_incident(self, bundle_id: str) -> tuple[int, bytes, str]:
        """GET /debug/incidents/<id>: the full bundle -- own first, then
        each replica is asked (the id encodes nothing about its origin;
        the gateway is the tier that knows the replica list)."""
        bundle = self.recorder.get(bundle_id)
        if bundle is None:
            for replica in self.pool.replicas:
                try:
                    r = self._session().get(
                        f"{replica.base}/debug/incidents/{bundle_id}",
                        timeout=2.0,
                    )
                    if r.status_code == 200:
                        bundle = r.json()
                        break
                except Exception:  # noqa: BLE001 - try the next replica
                    continue
        if bundle is None:
            return (
                404,
                json.dumps(
                    {"error": f"no incident bundle {bundle_id!r} on any tier"}
                ).encode(),
                "application/json",
            )
        return 200, json.dumps(bundle).encode(), "application/json"

    def handle_slo(self) -> dict:
        """GET /debug/slo: the MERGED fleet SLO view.

        Three sections: ``gateway`` is this tier's own accounting (what
        clients experienced, failover/hedging included), ``replicas`` is
        each model-tier replica's /debug/slo verbatim, and ``merged`` sums
        the replicas' raw counts per (model, window) and re-derives
        goodput/burn -- the per-model fleet truth an autoscaler reads.  An
        unreachable replica degrades to an error entry, never a failed
        response: like /debug/trace, this surface must work best when the
        serving path is misbehaving.
        """
        payload = self.slo.debug_payload()
        payload["gateway"] = payload.pop("models", {})
        replicas: dict[str, dict] = {}
        for replica in self.pool.replicas:
            try:
                r = self._session().get(
                    f"{replica.base}/debug/slo", timeout=2.0
                )
                replicas[replica.host] = (
                    r.json() if r.status_code == 200
                    else {"error": f"status {r.status_code}"}
                )
            except Exception as e:  # noqa: BLE001 - partial views beat none
                replicas[replica.host] = {"error": str(e)[:200]}
        payload["replicas"] = replicas
        payload["merged"] = slo_lib.merge_model_views(
            [v.get("models") for v in replicas.values() if isinstance(v, dict)],
            self.slo.target,
        )
        return payload

    def handle_profile(self) -> dict:
        """GET /debug/profile?audit=buckets: the merged bucket-shape audit.

        Each model-tier replica's per-bucket padding-waste ratio and
        compiled FLOPs/img, keyed by replica host -- the fleet view of
        whether the bucket ladder fits the traffic shape.  An unreachable
        replica degrades to an error entry, never a failed response.
        """
        replicas: dict[str, dict] = {}
        for replica in self.pool.replicas:
            try:
                r = self._session().get(
                    f"{replica.base}/debug/profile?audit=buckets", timeout=2.0
                )
                replicas[replica.host] = (
                    r.json() if r.status_code == 200
                    else {"error": f"status {r.status_code}"}
                )
            except Exception as e:  # noqa: BLE001 - partial views beat none
                replicas[replica.host] = {"error": str(e)[:200]}
        return {"tier": "gateway", "replicas": replicas}

    def handle_trace(self, raw_rid: str) -> tuple[int, bytes, str]:
        """GET /debug/trace/<rid>: the MERGED cross-tier waterfall.

        This tier's spans plus every model-tier replica's spans for the
        same trace id (fetched from their /debug/trace endpoints -- the
        gateway is the only tier that knows the replica list), sorted on
        the shared timeline.  An unreachable replica degrades to a partial
        trace, never an error: the debug surface must work best exactly
        when the serving path is misbehaving.
        """
        rid = ensure_request_id(raw_rid)
        info = self.tracer.trace_info(rid)
        spans = list(info["spans"]) if info is not None else []
        # Truncation accounting rides along: a merged waterfall missing its
        # pipeline stages with spans_dropped > 0 was CAPPED, not
        # un-instrumented (the silent-drop bug this field fixes).
        spans_dropped = info["spans_dropped"] if info is not None else 0
        retention = info["retention_class"] if info is not None else None
        for replica in self.pool.replicas:
            try:
                r = self._session().get(
                    f"{replica.base}/debug/trace/{rid}", timeout=2.0
                )
                if r.status_code == 200:
                    body = r.json()
                    spans.extend(body.get("spans", []))
                    spans_dropped += int(body.get("spans_dropped", 0) or 0)
            except Exception:  # noqa: BLE001 - partial traces beat no traces
                continue
        if not spans:
            return 404, json.dumps(
                {"error": f"no trace for {rid!r} on any tier",
                 "ring": self.tracer.stats()}
            ).encode(), "application/json"
        return 200, json.dumps(
            {"trace_id": rid, "spans": trace_lib.sort_spans(spans),
             "spans_dropped": spans_dropped, "retention_class": retention}
        ).encode(), "application/json"

    def reject_oversize(self, length: int) -> tuple[int, bytes, str] | None:
        """Pre-read Content-Length check shared by both transports; returns
        the 413 response when the declared body exceeds the cap, else None.
        Negative lengths are rejected too: rfile.read(-1) would read until
        connection close, which is exactly the unbounded buffering the cap
        exists to prevent."""
        if length < 0 or length > MAX_PREDICT_BODY_BYTES:
            self._m_errors.inc()
            return (
                413,
                json.dumps({
                    "error": f"request body {length} bytes exceeds the "
                    f"{MAX_PREDICT_BODY_BYTES}-byte limit"
                }).encode(),
                "application/json",
            )
        return None

    def _cache_key(self, routed: str, url: str, salt: str) -> str:
        """The content hash of one canonicalized single-url request:
        model name + resolved artifact hash + preprocessing params (from
        the model's cached contract; a never-discovered spec contributes
        the empty string, which only splits the very first pre-discovery
        flight) + the URL payload + the client's cache-bust salt."""
        default = routed == self.model
        spec = (
            self.pool.reference_spec if default
            else self.pool.reference_specs.get(routed)
        )
        params = (
            "" if spec is None
            else f"{tuple(spec.input_shape)}|{spec.resize_filter}"
        )
        return cache_lib.content_key(
            routed, self.cache.resolved_hash(routed), params, url, salt=salt
        )

    def _predict_coalesced(
        self,
        body: bytes,
        req: dict,
        rid: str,
        deadline: Deadline | None,
        rt,
        model: str | None,
        routed: str,
        salt: str,
        priority: str | None = None,
    ) -> tuple[int, bytes, str, dict[str, str]]:
        """The cache + singleflight front door for one single-url request.

        Hit: served straight from the cache -- no admission slot, no
        preprocessing, no upstream.  Miss: the first arrival leads the
        flight through the normal path (admission included) and fans its
        finished response out; concurrent identical arrivals become
        followers, counted admitted-but-not-dispatched, each waiting under
        its OWN deadline (a follower's 504 never cancels the leader).
        Only 200s are cached, so an injected/real upstream failure is
        never served back; salted (cache-bust) requests coalesce but are
        never stored.
        """
        key = self._cache_key(routed, str(req.get("url", "")), salt)
        w0 = trace_lib.now_s()
        # Brownout stage >= 2: TTL-expired 200s within the SWR window are
        # served immediately (marked "stale") instead of paying the full
        # fetch path -- bounded staleness traded for shed load.
        cached = self.cache.lookup_swr(
            key, stale_ok=self.brownout.serve_stale
        )
        if cached is not None:
            # Positive (200) or negative (recent 404/400 under the short
            # KDLT_CACHE_NEG_TTL_S) -- either way the full fetch path is
            # skipped; a negative hit still answers with ITS error status
            # and counts as this client's error.
            hit_status, out, ctype, stale = cached
            disposition = "stale" if stale else "hit"
            if hit_status != 200:
                self._m_errors.inc()
            self.tracer.record(
                rid, trace_lib.SPAN_GATEWAY_CACHE, w0, trace_lib.now_s() - w0,
                parent_id=rt.span_id, result=disposition, status=hit_status,
            )
            return hit_status, out, ctype, {
                cache_lib.CACHE_STATUS_HEADER: disposition
            }
        flight, leader = self._singleflight.begin(key)
        if not leader:
            self.cache.count_coalesced()
            # Admitted-but-not-dispatched: the follower IS served (via the
            # leader's flight) without consuming a concurrency slot.
            self.admission.count_coalesced(routed)
            timeout = (
                deadline.remaining_s() if deadline is not None
                else PREDICT_TIMEOUT_S + 10.0
            )
            try:
                status, out, ctype, extra = flight.wait(max(0.0, timeout))
            except cache_lib.FlightTimeout:
                # This waiter's own budget expired; the leader flies on for
                # the others.
                self._m_errors.inc()
                self.admission.count_shed("deadline_exhausted", priority)
                self.tracer.record(
                    rid, trace_lib.SPAN_GATEWAY_CACHE, w0, trace_lib.now_s() - w0,
                    parent_id=rt.span_id, result="coalesced", outcome="timeout",
                )
                return 504, json.dumps(
                    {"error": "deadline budget exhausted waiting on the "
                     "coalesced upstream flight"}
                ).encode(), "application/json", {
                    cache_lib.CACHE_STATUS_HEADER: "coalesced"
                }
            except BaseException as e:  # noqa: BLE001 - leader died unmapped
                self._m_errors.inc()
                self.tracer.record(
                    rid, trace_lib.SPAN_GATEWAY_CACHE, w0, trace_lib.now_s() - w0,
                    parent_id=rt.span_id, result="coalesced",
                    error=str(e)[:120],
                )
                return 502, json.dumps(
                    {"error": f"coalesced flight failed: {e}"}
                ).encode(), "application/json", {
                    cache_lib.CACHE_STATUS_HEADER: "coalesced"
                }
            if status >= 400:
                self._m_errors.inc()  # every follower answers its own client
            self.tracer.record(
                rid, trace_lib.SPAN_GATEWAY_CACHE, w0, trace_lib.now_s() - w0,
                parent_id=rt.span_id, result="coalesced", status=status,
            )
            return status, out, ctype, {
                **extra, cache_lib.CACHE_STATUS_HEADER: "coalesced"
            }
        # Leader: record the miss decision as its own (short) span, then
        # run the normal path -- its sub-spans (admission, preprocess,
        # upstream attempts) follow in this same trace.
        self.cache.count_miss()
        self.tracer.record(
            rid, trace_lib.SPAN_GATEWAY_CACHE, w0, trace_lib.now_s() - w0,
            parent_id=rt.span_id, result="miss",
        )
        try:
            status, out, ctype, extra, _n = self._predict_response(
                body, req, rid, deadline, rt, model, routed,
                priority=priority,
            )
        except BaseException as e:
            # _predict_response maps every Exception; only process-fatal
            # escapes land here.  Fail the flight so followers never hang.
            self._singleflight.finish(key, flight)
            flight.fail(e)
            raise
        if not salt and self.cache.storable_response(status, ctype):
            # Store BEFORE detaching the flight: an arrival in between
            # hits the cache instead of starting a duplicate flight.
            # Salted requests are deliberate cache opt-outs: they
            # coalesce (same salt = same stampede) but are never stored.
            # The key is RE-canonicalized: this flight may just have
            # learned the model's artifact hash / contract (the first
            # request of a model, or the first after a reload), and the
            # entry must live under the key every future lookup computes.
            # storable_response: 200 always; 404/400 only under the short
            # negative TTL (a hammered bad URL stops paying the fetch
            # path); 5xx never -- upstream failures are not replayable;
            # text/event-stream never -- a token stream is a live
            # connection, not a replayable value.
            self.cache.put(
                self._cache_key(routed, str(req.get("url", "")), salt),
                out, ctype, routed, self.cache.resolved_hash(routed),
                status=status,
            )
        self._singleflight.finish(key, flight)
        flight.resolve((status, out, ctype, extra))
        return status, out, ctype, {
            **extra, cache_lib.CACHE_STATUS_HEADER: "miss"
        }

    def _predict_response(
        self,
        body: bytes,
        req: dict | None,
        rid: str,
        deadline: Deadline | None,
        rt,
        model: str | None,
        routed: str,
        priority: str | None = None,
    ) -> tuple[int, bytes, str, dict[str, str], int]:
        """The admission -> parse -> preprocess -> upstream core of one
        /predict, every failure mapped to its client-facing response;
        returns (status, body, content_type, extra_headers, n_urls).

        Called once per upstream flight: cache hits never reach it, and
        coalesced followers receive its return tuple through the flight
        instead of calling it.  ``req`` is the already-parsed body when
        the cache front door ran (None re-parses here so bad JSON keeps
        its 400 mapping AFTER admission, the historical precedence).
        """
        ticket = None
        n_urls = 1
        try:
            try:
                with rt.span(trace_lib.SPAN_GATEWAY_ADMISSION):
                    ticket = self.admission.admit(
                        deadline, model=routed,
                        priority=priority or protocol.DEFAULT_PRIORITY,
                    )
            except Shed as e:
                self._m_errors.inc()
                self.recorder.note_shed()
                return e.http_status, json.dumps(
                    {"error": str(e), "shed_reason": e.reason}
                ).encode(), "application/json", e.headers(), n_urls
            if req is None:
                req = json.loads(body)
            if "urls" in req:  # batch extension; {"url": ...} is the
                # reference's schema (reference test.py:15) and unchanged
                urls = list(req["urls"])
                n_urls = len(urls)
                preds = self.apply_model_batch(
                    urls, rid, deadline, trace=rt, model=model,
                    priority=priority,
                )
                return 200, json.dumps(
                    {"predictions": preds}
                ).encode(), "application/json", {}, n_urls
            scores = self.apply_model(
                req["url"], rid, deadline, trace=rt, model=model,
                priority=priority,
            )
            return 200, json.dumps(scores).encode(), "application/json", {}, n_urls
        except UpstreamError as e:
            self._m_errors.inc()
            if ticket is not None and e.http_status == 503:
                ticket.mark_overloaded()  # AIMD: the tier below is saturated
            return e.http_status, json.dumps(
                {"error": str(e)}
            ).encode(), "application/json", retry_after_headers(
                e.retry_after_s
            ), n_urls
        except (QueueFull, BatcherClosed, UpstreamStall) as e:
            # Transient server-side conditions from the upstream
            # micro-batcher (overload, shutdown race, hung upstream): a
            # retryable 503, exactly like the model tier's own mapping --
            # NOT a 400, which clients would treat as a permanent error.
            # (UpstreamStall is typed precisely so this clause does not
            # have to catch TimeoutError, which would also swallow
            # client-side image-fetch timeouts on Python >= 3.11.)
            self._m_errors.inc()
            if ticket is not None:
                ticket.mark_overloaded()
            return 503, json.dumps(
                {"error": f"upstream unavailable: {e}"}
            ).encode(), "application/json", retry_after_headers(
                self.admission.retry_after_s()
            ), n_urls
        except Exception as e:
            # Bad JSON, missing "url", unfetchable/undecodable image:
            # genuinely the caller's fault.
            self._m_errors.inc()
            return 400, json.dumps(
                {"error": str(e)}
            ).encode(), "application/json", {}, n_urls
        finally:
            if ticket is not None:
                ticket.release()

    def handle_predict(
        self,
        body: bytes,
        request_id: str | None = None,
        deadline: Deadline | None = None,
        model: str | None = None,
        cache_bust: str | None = None,
        priority: str | None = None,
    ) -> tuple[int, bytes, str, dict[str, str]]:
        """POST /predict body -> (status, body, content_type, extra_headers).

        ``request_id`` is the (already-sanitized) cross-tier trace id; both
        transports mint/sanitize it via tracing.ensure_request_id before
        calling here so the id in the response header, the upstream call,
        and the log line is the same one.  ``deadline`` is the request's
        parsed deadline budget (transports build it from the
        X-Request-Deadline-Ms header when admission is enabled); the extra
        headers carry Retry-After on shed/overload responses.  ``model``
        is the transports' resolved route target (resolve_model); None
        keeps the default model and the exact single-model code path.
        ``cache_bust`` is the client's X-Kdlt-Cache-Bust salt (hashed into
        the content key; never stored).

        Single-url requests ride the content-addressed cache + singleflight
        front door (serving.cache) AHEAD of admission; batch requests and
        the cache-disabled posture take the legacy path unchanged.  Every
        disposition -- hit, miss, coalesced -- lands in the SAME
        latency/SLO/trace accounting below, at the same handler boundary.
        """
        t0 = time.perf_counter()
        rid = request_id or ensure_request_id(None)
        # Normalize: the default model rides the legacy (model=None) path
        # end to end, so single-model deployments are bit-for-bit the old
        # gateway; only genuinely non-default routes carry a name.
        if model is not None and model == self.model:
            model = None
        routed = model or self.model
        priority = protocol.parse_priority(priority)
        # This request's trace (trace id = rid): the root span carrier every
        # child span -- admission, preprocess, upstream attempts -- nests
        # under, and the key /debug/trace/<rid> serves the waterfall by.
        rt = self.tracer.request_trace(rid)
        w_start = trace_lib.now_s()
        self._m_requests.inc()
        # Per-model request count (bounded `model` label, minted centrally):
        # the route is sanitized by resolve_model before it reaches here.
        metrics_lib.model_request_counter(self.registry, routed).inc()
        status = 500
        n_urls = 1
        try:
            if self.brownout.sheds(priority):
                # Stage 3/4 class shed, ahead of cache AND admission: the
                # shed class's traffic must stop consuming anything --
                # that is the capacity being handed back to interactive.
                self._m_errors.inc()
                self.admission.count_shed("brownout", priority)
                self.recorder.note_shed()
                e = self._brownout_shed(priority)
                status = e.http_status
                return status, json.dumps(
                    {"error": str(e), "shed_reason": e.reason}
                ).encode(), "application/json", e.headers()
            if deadline is None and self.admission.enabled:
                deadline = Deadline.default()
            req = None
            if self.cache is not None:
                try:
                    parsed = json.loads(body)
                except Exception:  # noqa: BLE001 - core path maps the 400
                    parsed = None
                if (
                    isinstance(parsed, dict)
                    and "url" in parsed
                    and "urls" not in parsed
                ):
                    req = parsed
            if req is not None:
                status, out, ctype, extra = self._predict_coalesced(
                    body, req, rid, deadline, rt, model, routed,
                    str(cache_bust or ""), priority=priority,
                )
            else:
                status, out, ctype, extra, n_urls = self._predict_response(
                    body, None, rid, deadline, rt, model, routed,
                    priority=priority,
                )
            return status, out, ctype, extra
        finally:
            dt = time.perf_counter() - t0
            slow = (
                self._m_latency.count >= 100
                and dt >= self._m_latency.percentile(0.99)
            )
            self._m_latency.observe(
                dt,
                exemplar=rid if metrics_lib.exemplars_enabled() else None,
            )
            deadline_exceeded = deadline is not None and deadline.expired
            # Client-observed SLO accounting, per routed model -- the same
            # boundary as kdlt_gateway_request_seconds.
            self.slo.record(
                routed, status, dt, deadline_exceeded=deadline_exceeded
            )
            # Root span last (it covers the whole handler); the transports
            # build the X-Kdlt-Trace header AFTER handle_predict returns,
            # so the header summary includes it.
            self.tracer.record(
                rid, trace_lib.SPAN_GATEWAY_REQUEST, w_start, trace_lib.now_s() - w_start,
                span_id=rt.span_id, status=status, urls=n_urls,
            )
            self.tracer.classify(
                rid, trace_lib.retention_class(status, deadline_exceeded, slow)
            )
            # Sheds (503/504) skip the always-log rule: rejection must stay
            # cheap under overload; kdlt_admission_shed_total counts them.
            if self.request_log or (status >= 500 and status not in (503, 504)):
                log_request(
                    "gateway predict", rid, status=status, t0=t0,
                    span_id=rt.span_id, urls=n_urls,
                )

    def handle_generate(
        self,
        body: bytes,
        request_id: str | None = None,
        deadline: Deadline | None = None,
        model: str | None = None,
        priority: str | None = None,
    ):
        """POST /generate -> (status, payload, content_type, extra_headers).

        ``payload`` is complete bytes for every error response; for a 200
        event-stream it is an ITERATOR of raw chunk bytes proxied from the
        model tier as they arrive (both transports write it chunked, one
        flush per chunk, so tokens reach the client at decode speed).

        Deliberately NOT on the cache/singleflight/hedging path: a token
        stream is a stateful live connection.  Caching one replays a dead
        transcript (the cache's store predicate refuses the content type
        as a backstop), coalescing would fan one client's generation out
        to strangers, and a hedge would run the SAME generation twice on
        two replicas -- paying double decode for a stream you can only
        deliver once.  Failover is therefore connect-time only: once the
        stream starts, a mid-stream replica death truncates (the client
        sees a missing done event and retries).

        Brownout and admission still apply, ahead of any upstream work:
        the admission ticket is held for the LIFE of the stream, so an
        active generation occupies gateway concurrency exactly like an
        in-flight predict.  SLO accounting happens at stream end -- the
        done event's finish_reason (the model tier already judged the
        per-token TTFT/TPOT budgets there) plus stream truncation decide
        deadline_exceeded, so a decode-lane burn drives this tier's
        brownout ladder like any other burn.
        """
        import requests

        t0 = time.perf_counter()
        rid = request_id or ensure_request_id(None)
        routed = model or self.decode_model
        priority = protocol.parse_priority(priority)
        rt = self.tracer.request_trace(rid)
        w_start = trace_lib.now_s()
        self._m_requests.inc()
        metrics_lib.model_request_counter(self.registry, routed).inc()

        def account(status: int, *, deadline_exceeded: bool = False) -> None:
            dt = time.perf_counter() - t0
            self._m_latency.observe(
                dt,
                exemplar=rid if metrics_lib.exemplars_enabled() else None,
            )
            late = deadline_exceeded or (
                deadline is not None and deadline.expired
            )
            self.slo.record(routed, status, dt, deadline_exceeded=late)
            self.tracer.record(
                rid, trace_lib.SPAN_GATEWAY_GENERATE, w_start,
                trace_lib.now_s() - w_start,
                span_id=rt.span_id, status=status,
            )
            self.tracer.classify(
                rid, trace_lib.retention_class(status, late, False)
            )
            if self.request_log or (
                status >= 500 and status not in (503, 504)
            ):
                log_request(
                    "gateway generate", rid, status=status, t0=t0,
                    span_id=rt.span_id,
                )

        def error(status: int, msg: str, extra: dict | None = None):
            self._m_errors.inc()
            account(status)
            return status, json.dumps(
                {"error": msg}
            ).encode(), "application/json", dict(extra or {})

        if self.brownout.sheds(priority):
            # Same class shed as /predict, ahead of admission AND any
            # upstream connection: a shed best-effort generation costs
            # zero decode slots anywhere.
            self.admission.count_shed("brownout", priority)
            self.recorder.note_shed()
            e = self._brownout_shed(priority)
            self._m_errors.inc()
            account(e.http_status)
            return e.http_status, json.dumps(
                {"error": str(e), "shed_reason": e.reason}
            ).encode(), "application/json", e.headers()
        if deadline is None and self.admission.enabled:
            deadline = Deadline.default()
        ticket = None
        try:
            with rt.span(trace_lib.SPAN_GATEWAY_ADMISSION):
                ticket = self.admission.admit(
                    deadline, model=routed,
                    priority=priority or protocol.DEFAULT_PRIORITY,
                )
        except Shed as e:
            self.recorder.note_shed()
            self._m_errors.inc()
            account(e.http_status)
            return e.http_status, json.dumps(
                {"error": str(e), "shed_reason": e.reason}
            ).encode(), "application/json", e.headers()

        headers = {"Content-Type": protocol.JSON_CONTENT_TYPE}
        headers[REQUEST_ID_HEADER] = rid
        if deadline is not None:
            headers[DEADLINE_HEADER] = deadline.header_value()
        if priority:
            headers[PRIORITY_HEADER] = priority
        read_timeout = GENERATE_IDLE_TIMEOUT_S
        if deadline is not None:
            read_timeout = deadline.clamp(read_timeout)
        tried: list = []
        r = None
        replica = None
        last_err: Exception | None = None
        # Connect-time failover only: up to two replicas, first stream
        # wins.  Each pool.choose consumed a breaker allow(), so every
        # pick is settled with record_success/record_failure.
        for _ in range(2):
            replica = self.pool.choose(exclude=tried)
            if replica is None:
                break
            tried.append(replica)
            sid = trace_lib.new_span_id()
            headers[PARENT_SPAN_HEADER] = sid
            w0 = trace_lib.now_s()
            try:
                r = self._session().post(
                    f"{replica.base}/v1/models/{routed}:generate",
                    data=body, headers=headers,
                    timeout=(GENERATE_CONNECT_TIMEOUT_S, read_timeout),
                    stream=True,
                )
            except requests.RequestException as e:
                self.pool.record_failure(replica)
                self.tracer.record(
                    rid, trace_lib.SPAN_GATEWAY_UPSTREAM, w0,
                    trace_lib.now_s() - w0, parent_id=rt.span_id,
                    span_id=sid, replica=replica.host, role="generate",
                    error=str(e)[:120],
                )
                last_err = e
                r = None
                continue
            # Headers arrived: the replica is alive and answered (even a
            # 4xx/503 is an answer; breaker accounting is about reachability).
            self.pool.record_success(replica, trace_lib.now_s() - w0)
            self.tracer.record(
                rid, trace_lib.SPAN_GATEWAY_UPSTREAM, w0,
                trace_lib.now_s() - w0, parent_id=rt.span_id, span_id=sid,
                replica=replica.host, role="generate", status=r.status_code,
            )
            break
        if r is None:
            ticket.release()
            return error(
                502,
                f"no upstream replica reachable for generate: {last_err}",
                retry_after_headers(self.pool.min_retry_after_s()),
            )
        ctype = r.headers.get("Content-Type", "application/json")
        if r.status_code != 200 or not ctype.startswith(
            protocol.EVENT_STREAM_CONTENT_TYPE
        ):
            # Complete (non-streamed) answer: JSON mode, or any error --
            # pass the upstream's status and body through verbatim.
            out = r.content
            r.close()
            if r.status_code == 503:
                # AIMD congestion signal before release: the tier below
                # is saturated, so this tier's concurrency limit is high.
                ticket.mark_overloaded()
                extra = retry_after_headers(self.admission.retry_after_s())
            else:
                extra = {}
            ticket.release()
            if r.status_code >= 400:
                self._m_errors.inc()
            account(r.status_code)
            return r.status_code, out, ctype, extra

        def stream():
            """Pass-through chunk relay.  A small rolling tail keeps the
            terminal done event parseable without buffering the stream;
            the finally releases the admission ticket and closes the SLO
            loop whether the stream completed, truncated, or the CLIENT
            disconnected (GeneratorExit from the transport closes the
            upstream response, which cancels the generation server-side)."""
            tail = b""
            truncated = True
            try:
                for chunk in r.iter_content(chunk_size=None):
                    if not chunk:
                        continue
                    tail = (tail + chunk)[-4096:]
                    yield chunk
                truncated = False
            except requests.RequestException:
                pass  # upstream died mid-stream; the client sees truncation
            finally:
                r.close()
                ticket.release()
                done = None
                for ev in protocol.parse_sse_events(tail):
                    if ev.get("done"):
                        done = ev
                late = (
                    truncated
                    or done is None
                    or done.get("finish_reason") == "deadline"
                )
                if truncated:
                    self._m_errors.inc()
                account(200, deadline_exceeded=late)

        return 200, stream(), protocol.EVENT_STREAM_CONTENT_TYPE, {
            "Cache-Control": "no-store"
        }

    # --- HTTP plumbing ----------------------------------------------------

    def _make_handler(self):
        gw = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # TCP_NODELAY: http.server writes a response as two send()s
            # (header buffer, then body); with Nagle on, the body segment
            # waits out the peer's delayed ACK of the header segment -- a
            # flat ~40 ms added to every response on Linux.  Found by the
            # span tracer: client wall minus the gateway.request root span
            # was a constant ~40 ms that belonged to no stage.
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                pass

            def _send(
                self, code: int, body: bytes, ctype: str, rid: str = "",
                extra: dict[str, str] | None = None,
            ):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if rid:
                    self.send_header(REQUEST_ID_HEADER, rid)
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_stream(self, chunks, ctype: str, rid: str = "",
                             extra: dict[str, str] | None = None):
                """Write an iterator of chunk bytes as one HTTP/1.1
                chunked-transfer response, flushing per chunk (tokens must
                reach the client as they decode).  On client disconnect
                the iterator is closed, which propagates cancellation all
                the way to the decode slot."""
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Transfer-Encoding", "chunked")
                if rid:
                    self.send_header(REQUEST_ID_HEADER, rid)
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                try:
                    for chunk in chunks:
                        if not chunk:
                            continue
                        self.wfile.write(
                            f"{len(chunk):X}\r\n".encode() + chunk + b"\r\n"
                        )
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:
                    self.close_connection = True
                finally:
                    closer = getattr(chunks, "close", None)
                    if closer is not None:
                        closer()

            def do_GET(self):
                self._send(*gw.handle_get(self.path))

            def _generate(self, path: str, rid: str):
                """POST /generate[/<model>]: proxy one token stream."""
                model = None
                if path.startswith("/generate/"):
                    model = path[len("/generate/"):]
                    if not _MODEL_NAME_RE.match(model):
                        return self._send(
                            404, b'{"error": "malformed model name"}',
                            "application/json", rid,
                        )
                length = int(self.headers.get("Content-Length", 0) or 0)
                rejected = gw.reject_oversize(length)
                if rejected is not None:
                    self.close_connection = True
                    return self._send(*rejected, rid)
                deadline = (
                    Deadline.from_header(self.headers.get(DEADLINE_HEADER))
                    if gw.admission.enabled
                    else None
                )
                status, payload, ctype, extra = gw.handle_generate(
                    self.rfile.read(length), rid, deadline, model=model,
                    priority=self.headers.get(PRIORITY_HEADER),
                )
                if status == 200 and not isinstance(
                    payload, (bytes, bytearray)
                ):
                    return self._send_stream(payload, ctype, rid, extra)
                summary = gw.tracer.summary(rid)
                if summary:
                    extra = {**extra, TRACE_HEADER: summary}
                self._send(status, payload, ctype, rid, extra)

            def do_POST(self):
                rid = ensure_request_id(self.headers.get(REQUEST_ID_HEADER))
                path = self.path.split("?", 1)[0]
                if path == "/generate" or path.startswith("/generate/"):
                    return self._generate(path, rid)
                if path != "/predict" and not path.startswith("/predict/"):
                    return self._send(
                        404, b'{"error": "not found"}', "application/json", rid
                    )
                # Model routing: /predict/<model> or X-Kdlt-Model; the bare
                # /predict keeps the reference's shape (default model).
                model = gw.resolve_model(path, self.headers.get(MODEL_HEADER))
                if model is None:
                    return self._send(
                        404, b'{"error": "malformed model name"}',
                        "application/json", rid,
                    )
                length = int(self.headers.get("Content-Length", 0))
                rejected = gw.reject_oversize(length)
                if rejected is not None:
                    # The unread body is still in the socket; close rather
                    # than let keep-alive parse gigabytes as a next request.
                    self.close_connection = True
                    return self._send(*rejected, rid)
                deadline = (
                    Deadline.from_header(self.headers.get(DEADLINE_HEADER))
                    if gw.admission.enabled
                    else None
                )
                status, out, ctype, extra = gw.handle_predict(
                    self.rfile.read(length), rid, deadline, model=model,
                    cache_bust=self.headers.get(cache_lib.CACHE_BUST_HEADER),
                    priority=self.headers.get(PRIORITY_HEADER),
                )
                # Server-Timing-style span summary; handle_predict has
                # recorded the full trace (root included) by return time.
                summary = gw.tracer.summary(rid)
                if summary:
                    extra = {**extra, TRACE_HEADER: summary}
                self._send(status, out, ctype, rid, extra)

        return Handler

    def start(self, block: bool = False) -> None:
        if self._httpd is None:
            raise RuntimeError("gateway built with bind=False; serve it via WSGI")
        self._serving = True
        if block:
            self._httpd.serve_forever()
        else:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="kdlt-gateway", daemon=True
            )
            self._thread.start()

    def begin_drain(self) -> None:
        """Graceful-drain entry: /readyz goes 503 and admission sheds new
        work with reason "draining" while in-flight requests complete
        (admission.wait_idle observes them).  The CLI wires SIGTERM here."""
        self.admission.begin_drain()

    def shutdown(self) -> None:
        self._brownout_stop.set()
        self.recorder.close()
        if self._microbatcher is not None:
            self._microbatcher.close()
        with self._microbatcher_lock:
            for mb in self._microbatchers.values():
                mb.close()
            self._microbatchers.clear()
        self.pool.close()
        if self._httpd is None:
            return
        # See ModelServer.shutdown: BaseServer.shutdown() hangs if
        # serve_forever never ran.
        if getattr(self, "_serving", False):
            self._httpd.shutdown()
        self._httpd.server_close()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description="serving gateway")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    p.add_argument("--serving-host", default=None, help=f"overrides ${SERVING_HOST_ENV}")
    p.add_argument("--model", default=None, help=f"overrides ${MODEL_ENV}")
    p.add_argument(
        "--no-request-log",
        action="store_true",
        help="disable the per-request traced log line (rid, status, duration)",
    )
    p.add_argument(
        "--upstream-batch",
        type=int,
        default=0,
        help="coalesce concurrent single-image requests into one upstream "
        "predict of up to this size (0 = off, one upstream call per request)",
    )
    p.add_argument("--upstream-delay-ms", type=float, default=2.0)
    p.add_argument(
        "--no-admission",
        action="store_true",
        help="disable admission control (deadline rejection, AIMD "
        "concurrency limiting, circuit breaking); graceful drain stays on",
    )
    p.add_argument(
        "--no-failover",
        action="store_true",
        help="disable upstream failover/health tracking/hedging: the "
        "replica list becomes a blind round-robin (overrides $KDLT_FAILOVER)",
    )
    p.add_argument(
        "--hedge-delay-ms",
        type=float,
        default=None,
        help="fire a hedged upstream attempt against a second healthy "
        "replica after this many ms without a response (default "
        "$KDLT_HEDGE_DELAY_MS; 0 = off)",
    )
    p.add_argument(
        "--probe-interval-s",
        type=float,
        default=None,
        help="seconds between /healthz probes of unhealthy upstream "
        "replicas (default $KDLT_PROBE_INTERVAL_S or 1.0)",
    )
    p.add_argument(
        "--pool-resolve-s",
        type=float,
        default=None,
        help="re-resolve the serving host's DNS name(s) every this many "
        "seconds and apply membership deltas live (joiners quarantined "
        "until ready, leavers drained); default $KDLT_POOL_RESOLVE_S or "
        "off.  KDLT_SERVING_HOST=dns+srv://name resolves SRV records "
        "instead",
    )
    p.add_argument(
        "--no-slo",
        action="store_true",
        help="disable the SLO engine (per-model goodput/burn-rate windows, "
        "kdlt_slo_* gauges, /debug/slo); default $KDLT_SLO or enabled",
    )
    p.add_argument(
        "--no-cache",
        action="store_true",
        help="disable the content-addressed response cache AND singleflight "
        "request coalescing (serving.cache); default $KDLT_CACHE or enabled",
    )
    p.add_argument(
        "--cache-swr-s",
        type=float,
        default=None,
        help="stale-while-revalidate window: TTL-expired 200s stay servable "
        "(marked X-Kdlt-Cache: stale) for this many extra seconds under "
        "brownout stage >= 2 (default $KDLT_CACHE_SWR_S or 0 = off)",
    )
    p.add_argument(
        "--no-brownout",
        action="store_true",
        help="disable the SLO-burn-driven brownout ladder (hedges off -> "
        "stale serves -> shed best-effort -> shed batch); default "
        "$KDLT_BROWNOUT or enabled",
    )
    p.add_argument(
        "--brownout-enter",
        type=float,
        default=None,
        help="burn-rate multiple entering brownout stage s at enter*s "
        "(default $KDLT_BROWNOUT_BURN_ENTER or 2.0)",
    )
    p.add_argument(
        "--brownout-exit",
        type=float,
        default=None,
        help="burn-rate multiple leaving brownout stage s below exit*s; "
        "must stay under --brownout-enter for hysteresis (default "
        "$KDLT_BROWNOUT_BURN_EXIT or 1.0)",
    )
    p.add_argument(
        "--brownout-dwell-s",
        type=float,
        default=None,
        help="minimum seconds between brownout stage transitions (default "
        "$KDLT_BROWNOUT_DWELL_S or 10)",
    )
    args = p.parse_args(argv)
    gw = Gateway(
        serving_host=args.serving_host,
        model=args.model,
        port=args.port,
        request_log=not args.no_request_log,
        upstream_batch=args.upstream_batch,
        upstream_delay_ms=args.upstream_delay_ms,
        admission=False if args.no_admission else None,
        failover=False if args.no_failover else None,
        hedge_delay_ms=args.hedge_delay_ms,
        probe_interval_s=args.probe_interval_s,
        slo=False if args.no_slo else None,
        cache=False if args.no_cache else None,
        cache_swr_s=args.cache_swr_s,
        pool_resolve_s=args.pool_resolve_s,
        brownout=False if args.no_brownout else None,
        brownout_enter=args.brownout_enter,
        brownout_exit=args.brownout_exit,
        brownout_dwell_s=args.brownout_dwell_s,
    )
    # SIGTERM -> flip /readyz, shed new work, finish in-flight, then stop;
    # pairs with the k8s terminationGracePeriodSeconds/preStop settings.
    install_sigterm_drain(gw.admission, gw.shutdown)
    print(f"gateway listening on :{gw.port}, model tier at {gw.serving_host}")
    gw.start(block=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
