"""kdlt-doctor: read the incident flight recorder like a flight recorder.

The serving tiers capture trigger-driven diagnostic bundles
(utils/flightrecorder.py) and surface them at /debug/incidents, with the
gateway merging every replica's bundles into causal windows.  This tool is
the operator's reader:

    kdlt-doctor                          # list incidents (merged windows)
    kdlt-doctor inc-...-dispatch-stall   # render one bundle's causal
                                         # timeline, traces interleaved
    kdlt-doctor --file bundle.json       # same, from a kubectl-cp'd file

The timeline render is the point: the bundle's events in monotonic order,
offset-stamped relative to the first, with each implicated trace's span
waterfall (utils/trace.py render_waterfall) inlined right under the event
that referenced it -- what happened, in what order, and what each affected
request was doing while it happened.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from kubernetes_deep_learning_tpu.utils.trace import render_waterfall


def fetch_json(url: str, timeout: float = 5.0):
    import requests

    r = requests.get(url, timeout=timeout)
    r.raise_for_status()
    return r.json()


def _fmt_wall(t: float | None) -> str:
    if not isinstance(t, (int, float)):
        return "-"
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))


def _fmt_attrs(ev: dict) -> str:
    parts = []
    if ev.get("rid"):
        parts.append(f"rid={ev['rid']}")
    for k, v in (ev.get("attrs") or {}).items():
        parts.append(f"{k}={v}")
    return " ".join(parts)


def render_list(payload: dict) -> str:
    """The /debug/incidents document as an operator table + windows."""
    out = []
    incidents = list(payload.get("incidents", []))
    for host, remote in (payload.get("replicas") or {}).items():
        if isinstance(remote, list):
            incidents.extend(remote)
        else:
            out.append(f"# replica {host}: {remote.get('error', 'error')}")
    if not incidents:
        out.append("no incident bundles captured")
        return "\n".join(out)
    out.append(
        f"{'id':<42s} {'trigger':<18s} {'tier':<13s} "
        f"{'fired':<21s} {'lat_s':>6s} {'ev':>4s}"
    )
    for e in sorted(
        incidents,
        key=lambda e: e.get("fired_at_s") or 0.0, reverse=True,
    ):
        lat = e.get("capture_latency_s")
        out.append(
            f"{e.get('id', '-'):<42s} {e.get('trigger', '-'):<18s} "
            f"{e.get('tier', '-'):<13s} {_fmt_wall(e.get('fired_at_s')):<21s} "
            f"{lat if lat is not None else '-':>6} {e.get('events', 0):>4d}"
        )
    windows = payload.get("windows") or []
    if windows:
        out.append("")
        out.append("causal windows (incidents within 30 s merge):")
        for i, w in enumerate(windows):
            ids = ", ".join(
                f"{ref.get('id')}@{ref.get('origin', 'local')}"
                for ref in w.get("incidents", [])
            )
            out.append(
                f"  [{i}] {_fmt_wall(w.get('start_s'))} "
                f"+{max(0.0, (w.get('end_s') or 0) - (w.get('start_s') or 0)):.1f}s "
                f"triggers={','.join(w.get('triggers', []))}: {ids}"
            )
    return "\n".join(out)


def render_bundle(bundle: dict) -> str:
    """One bundle as an ASCII causal timeline, traces interleaved."""
    out = []
    out.append(
        f"incident {bundle.get('id')}  "
        f"(tier {bundle.get('tier')}, trigger {bundle.get('trigger')})"
    )
    out.append(
        f"fired    {_fmt_wall(bundle.get('fired_at_s'))}   "
        f"captured {_fmt_wall(bundle.get('captured_at_s'))}   "
        f"capture latency {bundle.get('capture_latency_s', '-')}s"
    )
    snaps = sorted((bundle.get("snapshots") or {}).keys())
    delta = bundle.get("metrics_delta") or {}
    out.append(
        f"snapshots: {', '.join(snaps) or '-'}   "
        f"metrics moved: {len(delta)} series   "
        f"traces pinned: {len(bundle.get('traces') or {})}"
    )
    profile = bundle.get("profile")
    if profile:
        out.append(f"device profile: {json.dumps(profile)}")
    events = bundle.get("events") or []
    out.append("")
    out.append(f"timeline ({len(events)} events, offsets from the first):")
    t0 = events[0].get("m", 0.0) if events else 0.0
    traces = dict(bundle.get("traces") or {})
    rendered: set = set()
    for ev in events:
        rel = (ev.get("m", t0) or t0) - t0
        marker = ">" if ev is bundle.get("event") or (
            ev.get("m") == (bundle.get("event") or {}).get("m")
            and ev.get("kind") == (bundle.get("event") or {}).get("kind")
        ) else " "
        out.append(
            f" {marker}+{rel:8.3f}s  [{ev.get('tier', '?')}] "
            f"{ev.get('kind', '?'):<18s} {_fmt_attrs(ev)}"
        )
        rid = ev.get("rid")
        if rid and rid in traces and rid not in rendered:
            rendered.add(rid)
            info = traces[rid] or {}
            out.append(
                f"            trace {rid} "
                f"(retention {info.get('retention_class', '?')}):"
            )
            try:
                water = render_waterfall(info.get("spans") or [])
            except Exception as e:  # noqa: BLE001 - render what we can
                water = f"(waterfall unavailable: {e})"
            for line in water.splitlines():
                out.append("              " + line)
    leftover = [r for r in traces if r not in rendered]
    for rid in leftover:
        info = traces[rid] or {}
        out.append("")
        out.append(
            f"trace {rid} (retention {info.get('retention_class', '?')}):"
        )
        try:
            water = render_waterfall(info.get("spans") or [])
        except Exception as e:  # noqa: BLE001
            water = f"(waterfall unavailable: {e})"
        for line in water.splitlines():
            out.append("  " + line)
    if delta:
        out.append("")
        out.append("metrics delta since previous capture (top movers):")
        movers = sorted(
            delta.items(), key=lambda kv: abs(kv[1]), reverse=True
        )[:20]
        for series, d in movers:
            out.append(f"  {d:+12.3f}  {series}")
        if len(delta) > 20:
            out.append(f"  ... {len(delta) - 20} more series")
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="list and render incident flight-recorder bundles"
    )
    p.add_argument(
        "incident", nargs="?", default=None,
        help="bundle id to render (default: list all incidents)",
    )
    p.add_argument(
        "--gateway", default="http://localhost:9696",
        help="gateway base URL; its /debug/incidents merges every "
        "replica's bundles into causal windows",
    )
    p.add_argument(
        "--file", default=None,
        help="render a bundle JSON file instead of fetching (for bundles "
        "kubectl-cp'd out of KDLT_INCIDENT_DIR)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the raw JSON document instead of the ASCII render",
    )
    args = p.parse_args(argv)
    if args.file:
        with open(args.file, encoding="utf-8") as f:
            bundle = json.load(f)
        print(json.dumps(bundle, indent=2) if args.json
              else render_bundle(bundle))
        return 0
    base = args.gateway.rstrip("/")
    try:
        if args.incident:
            doc = fetch_json(f"{base}/debug/incidents/{args.incident}")
            print(json.dumps(doc, indent=2) if args.json
                  else render_bundle(doc))
        else:
            doc = fetch_json(f"{base}/debug/incidents")
            print(json.dumps(doc, indent=2) if args.json
                  else render_list(doc))
    except Exception as e:  # noqa: BLE001 - CLI surface
        print(f"kdlt-doctor: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
