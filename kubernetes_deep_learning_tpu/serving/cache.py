"""Gateway content-addressed response cache + singleflight coalescing.

At millions-of-users scale the same public image URLs recur heavily, yet
until now every duplicate request rode the full gateway -> admission ->
preprocess -> model-tier path.  This module is the classic serving-layer
answer (Clipper's prediction cache, NSDI '17; Go's singleflight), hosted
where the paper's two-tier split wants it -- the IO tier:

- **content addressing**: a request is identified by the sha256 of its
  canonicalized form -- model name + the model's *resolved artifact hash*
  (the registry's sha256 identity, learned from the model tier's
  ``X-Kdlt-Artifact-Hash`` response header) + preprocessing parameters
  (input shape, resize filter) + the payload (the image URL) + an optional
  client salt (``X-Kdlt-Cache-Bust``).  Keying on the artifact hash, not
  the version number, is what makes hot-reload semantics exact: a version
  bump with byte-identical content keeps every entry; changed bytes change
  the hash and drop that model's entries (:meth:`ResponseCache.note_artifact_hash`).

- **singleflight coalescing** (:class:`SingleFlight`): identical in-flight
  requests collapse into ONE upstream call whose result fans out to every
  waiter.  Deadline semantics are per-waiter: a follower whose own budget
  expires gets its own 504 without cancelling the leader, and hedging/
  failover fire once per *flight* (only the leader talks upstream), not
  once per caller.

- **bounded LRU response cache** (:class:`ResponseCache`): successful
  responses only, TTL'd (``KDLT_CACHE_TTL_S``), capped by byte budget
  (``KDLT_CACHE_MAX_MB``), with ``KDLT_CACHE=0`` as the subsystem kill
  switch (no cache, no coalescing -- the exact legacy gateway).

A hit avoids admission, preprocessing, and all device work, so it raises
goodput under overload *and* cuts p50 at idle; the gateway therefore
checks the cache AHEAD of admission (hits never consume AIMD concurrency
slots; coalesced followers are counted admitted-but-not-dispatched).
All ``kdlt_cache_*`` series are minted centrally in utils/metrics.py
(tools/check_metrics.py confines the prefix there).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict

from kubernetes_deep_learning_tpu.serving.protocol import (  # noqa: F401 - re-exported wire surface
    ARTIFACT_HASH_HEADER,
    CACHE_BUST_HEADER,
    CACHE_STATUS_HEADER,
    EVENT_STREAM_CONTENT_TYPE,
)
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

CACHE_ENV = "KDLT_CACHE"
TTL_ENV = "KDLT_CACHE_TTL_S"
MAX_MB_ENV = "KDLT_CACHE_MAX_MB"
NEG_TTL_ENV = "KDLT_CACHE_NEG_TTL_S"
SWR_ENV = "KDLT_CACHE_SWR_S"
# Decoded-uint8 tier byte budget (DecodedCache below); 0 disables the tier.
DECODED_MB_ENV = "KDLT_CACHE_DECODED_MB"
DEFAULT_DECODED_MB = 32.0

# Staleness ceiling between an artifact reload and the first miss that
# teaches the gateway the new hash; 60 s matches the version watcher's
# default poll cadence (one watcher period of bounded staleness).
DEFAULT_TTL_S = 60.0
DEFAULT_MAX_MB = 64.0
# Negative caching: a hammered bad URL (404/400) answers from the cache
# for this long instead of paying the full fetch path per request.  Short
# by design -- a 404 can become a 200 the moment the object is uploaded --
# and 0 disables it.  5xx are NEVER negative-cached: they are the
# upstream's transient state, not the request's.
DEFAULT_NEG_TTL_S = 5.0
NEGATIVE_STATUSES = (400, 404)

# Stale-while-revalidate window: TTL-expired 200s stay resident for this
# many extra seconds and can be served (marked stale) when the caller
# opts in -- the brownout controller's stage-2 degradation.  0 disables
# retention entirely, so the default cache behaves exactly as before.
DEFAULT_SWR_S = 0.0

# A client salt is hashed, never echoed, but still bound it: a multi-KB
# header must not become free amplification of the hash input.
MAX_BUST_SALT_LEN = 128

# The artifact-hash slot of a key before any upstream response has taught
# the gateway the real one (process start, or a model never yet served).
UNRESOLVED_HASH = "unresolved"

WSGI_CACHE_BUST_KEY = "HTTP_X_KDLT_CACHE_BUST"


def cache_enabled(explicit: bool | None = None) -> bool:
    """Explicit arg > $KDLT_CACHE > enabled-by-default (the kill switch
    disables the whole subsystem: response cache AND coalescing)."""
    if explicit is not None:
        return bool(explicit)
    raw = os.environ.get(CACHE_ENV, "").strip().lower()
    return raw not in ("0", "false", "off", "no")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw.strip() else default
    except ValueError:
        return default


def content_key(
    model: str,
    artifact_hash: str,
    preprocess_params: str,
    payload: str | bytes,
    salt: str = "",
) -> str:
    """sha256 over the canonicalized request, length-prefixed per field.

    Length prefixes keep the concatenation unambiguous (``("a", "bc")``
    and ``("ab", "c")`` must not collide); the fields are exactly the
    ISSUE's canonical form: model name, resolved artifact hash,
    preprocessing params, payload bytes, plus the cache-bust salt.
    """
    h = hashlib.sha256()
    for field in (model, artifact_hash, preprocess_params, payload,
                  salt[:MAX_BUST_SALT_LEN]):
        data = field.encode() if isinstance(field, str) else bytes(field)
        h.update(str(len(data)).encode())
        h.update(b":")
        h.update(data)
    return h.hexdigest()


class FlightTimeout(TimeoutError):
    """A coalesced follower's own deadline expired before the flight
    resolved; the follower 504s, the leader keeps flying."""


class Flight:
    """One in-flight upstream computation; followers block on :meth:`wait`.

    The leader resolves it exactly once with the finished response (or
    fails it with the leader's escaped exception); every waiter observes
    the same outcome, each bounded by its OWN timeout.
    """

    __slots__ = ("_done", "_value", "_error", "followers", "started_s")

    def __init__(self):
        self._done = threading.Event()
        self._value = None
        self._error: BaseException | None = None
        self.followers = 0
        self.started_s = time.monotonic()

    def resolve(self, value) -> None:
        self._value = value
        self._done.set()

    def fail(self, error: BaseException) -> None:
        self._error = error
        self._done.set()

    def wait(self, timeout_s: float | None):
        if not self._done.wait(timeout_s):
            raise FlightTimeout(
                "deadline expired waiting on the coalesced flight"
            )
        if self._error is not None:
            raise self._error
        return self._value


class SingleFlight:
    """Key -> at most one live Flight; later arrivals join as followers.

    The leader MUST call :meth:`finish` before resolving/failing its
    flight (pop-then-resolve): a request arriving after the pop starts a
    fresh flight instead of receiving a result computed under a deadline
    that is not its own.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._flights: dict[str, Flight] = {}  # guarded-by: _lock

    def begin(self, key: str) -> tuple[Flight, bool]:
        """Join or start the key's flight; returns (flight, is_leader)."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is not None:
                flight.followers += 1
                return flight, False
            flight = Flight()
            self._flights[key] = flight
            return flight, True

    def finish(self, key: str, flight: Flight) -> None:
        """Detach a completed flight (leader-only; identity-checked so a
        raced replacement flight is never evicted by a stale leader)."""
        with self._lock:
            if self._flights.get(key) is flight:
                del self._flights[key]

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight_flights": len(self._flights),
                "waiting_followers": sum(
                    f.followers for f in self._flights.values()
                ),
            }


class _Entry:
    __slots__ = ("body", "ctype", "nbytes", "model", "artifact_hash",
                 "expires_s", "stored_s", "hits", "status")

    def __init__(self, body, ctype, model, artifact_hash, expires_s,
                 status=200):
        self.body = body
        self.ctype = ctype
        self.nbytes = len(body)
        self.model = model
        self.artifact_hash = artifact_hash
        self.expires_s = expires_s
        self.stored_s = time.monotonic()
        self.hits = 0
        self.status = status


class ResponseCache:
    """Bounded, TTL'd, artifact-hash-invalidated LRU of 200 responses.

    Stores ``(body, ctype)`` keyed by content hash.  Thread-safe; all
    sizing is by response-body bytes against the ``KDLT_CACHE_MAX_MB``
    budget.  Invalidation is two-layered: the content key already embeds
    the resolved artifact hash (a reload changes future keys), and
    :meth:`note_artifact_hash` eagerly drops the superseded entries so the
    byte budget is not squatted by unreachable stale data.
    """

    def __init__(
        self,
        registry: metrics_lib.Registry | None = None,
        ttl_s: float | None = None,
        max_mb: float | None = None,
        neg_ttl_s: float | None = None,
        swr_s: float | None = None,
    ):
        self.ttl_s = ttl_s if ttl_s is not None else _env_float(
            TTL_ENV, DEFAULT_TTL_S
        )
        # Negative-entry TTL (404/400): $KDLT_CACHE_NEG_TTL_S, 0 disables
        # negative caching entirely (only 200s are stored).
        self.neg_ttl_s = neg_ttl_s if neg_ttl_s is not None else _env_float(
            NEG_TTL_ENV, DEFAULT_NEG_TTL_S
        )
        # Stale-while-revalidate retention past TTL for 200s only;
        # servable exclusively through stale_ok lookups (brownout stage 2).
        self.swr_s = max(0.0, swr_s if swr_s is not None else _env_float(
            SWR_ENV, DEFAULT_SWR_S
        ))
        max_mb = max_mb if max_mb is not None else _env_float(
            MAX_MB_ENV, DEFAULT_MAX_MB
        )
        self.max_bytes = int(max_mb * 1024 * 1024)
        self._entries: OrderedDict[str, _Entry] = OrderedDict()  # guarded-by: _lock
        self._bytes = 0              # guarded-by: _lock
        self._hashes: dict[str, str] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # Plain-int mirrors of the counters so /debug/cache works with or
        # without a registry (tests construct bare caches).
        self.hits = 0                # guarded-by: _lock
        self.misses = 0              # guarded-by: _lock
        self.coalesced = 0           # guarded-by: _lock
        self.negative_hits = 0       # guarded-by: _lock
        self.stale_hits = 0          # guarded-by: _lock
        self.evictions: dict[str, int] = {  # guarded-by: _lock
            reason: 0 for reason, _ in metrics_lib.CACHE_EVICTION_REASONS
        }
        self._m = (
            metrics_lib.cache_metrics(registry) if registry is not None
            else None
        )

    # --- artifact-hash identity ---------------------------------------------

    def resolved_hash(self, model: str) -> str:
        """The model's last-learned artifact hash (key material); a model
        the gateway has never seen answer resolves to a sentinel, so the
        first flight per process is simply an unmergeable one-off key."""
        with self._lock:
            return self._hashes.get(model, UNRESOLVED_HASH)

    def note_artifact_hash(self, model: str, artifact_hash: str) -> None:
        """Learn/refresh a model's artifact identity from an upstream
        response.  A CHANGED hash is a hot reload with different bytes:
        every entry stored under the old hash is dropped (reason
        "reload").  An unchanged hash -- including a version bump that
        re-exported identical bytes -- keeps all entries."""
        if not artifact_hash:
            return
        with self._lock:
            prev = self._hashes.get(model)
            if prev == artifact_hash:
                return
            self._hashes[model] = artifact_hash
            if prev is None:
                return
            stale = [
                k for k, e in self._entries.items()
                if e.model == model and e.artifact_hash != artifact_hash
            ]
            for k in stale:
                self._evict_locked(k, "reload")
            self._refresh_gauges_locked()

    def count_coalesced(self) -> None:
        """One singleflight follower rode an identical request's flight
        (the gateway counts these here so /debug/cache and the metric
        stay one source)."""
        with self._lock:
            self.coalesced += 1
        self._count("coalesced")

    # --- lookup / store -----------------------------------------------------

    def count_miss(self) -> None:
        """One lookup miss that went on to LEAD its own upstream flight
        (followers of an existing flight count as ``coalesced`` instead,
        so hits + misses + coalesced partitions the cacheable traffic and
        hit_ratio compares flights avoided vs flights flown)."""
        with self._lock:
            self.misses += 1
            self._count("misses")
            self._refresh_gauges_locked()

    def storable_status(self, status: int) -> bool:
        """Whether a response with this status may enter the cache: 200
        always; 400/404 only while negative caching is on (neg_ttl_s > 0).
        5xx (and everything else) never -- an upstream's transient failure
        must not be replayed to innocent followers."""
        if status == 200:
            return True
        return status in NEGATIVE_STATUSES and self.neg_ttl_s > 0

    def storable_response(self, status: int, ctype: str | None) -> bool:
        """storable_status plus the content-type guard: a
        ``text/event-stream`` body is a live connection's transcript, not
        a value.  Caching one -- or letting singleflight fan it out --
        would replay the first client's token stream to a second client
        as a dead recording, with the first stream's TTFT/TPOT stamped in
        its done event.  The generative lane never routes through the
        cache front door, but the store predicate refuses the content
        type outright so no future route can wire a stream into the
        cache by accident."""
        if ctype and ctype.strip().lower().startswith(
            EVENT_STREAM_CONTENT_TYPE
        ):
            return False
        return self.storable_status(status)

    def lookup(self, key: str) -> tuple[int, bytes, str] | None:
        """Hit -> (status, body, ctype) and LRU-touch; miss/expired ->
        None (the caller decides whether the miss leads a flight or
        coalesces, and counts it via count_miss / count_coalesced).
        Negative entries (status != 200) count as hits AND as
        negative_hits."""
        got = self.lookup_swr(key, stale_ok=False)
        return None if got is None else got[:3]

    def lookup_swr(
        self, key: str, stale_ok: bool = False,
    ) -> tuple[int, bytes, str, bool] | None:
        """lookup() plus the stale-while-revalidate window: a TTL-expired
        200 stays resident for ``swr_s`` extra seconds and is served (with
        the final tuple element True) ONLY when the caller passes
        ``stale_ok`` -- the brownout controller's stage-2 degradation.
        Without ``stale_ok`` an in-window entry answers None (the caller
        leads a revalidating flight) but is NOT evicted, so a later
        brownout can still use it.  Past ``expires + swr_s`` the entry is
        gone regardless -- a stale serve can never outlive the window.
        Negative entries never get SWR: a replayed 404 is pure harm."""
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            stale = False
            if entry is not None and entry.expires_s <= now:
                swr = self.swr_s if entry.status == 200 else 0.0
                if now >= entry.expires_s + swr:
                    self._evict_locked(key, "ttl")
                    entry = None
                elif stale_ok and entry.status == 200:
                    stale = True
                else:
                    self._refresh_gauges_locked()
                    return None
            if entry is None:
                self._refresh_gauges_locked()
                return None
            self._entries.move_to_end(key)
            entry.hits += 1
            self.hits += 1
            self._count("hits")
            if stale:
                self.stale_hits += 1
                self._count("stale_hits")
            if entry.status != 200:
                self.negative_hits += 1
                self._count("neg_hits")
            self._refresh_gauges_locked()
            return entry.status, entry.body, entry.ctype, stale

    def get(self, key: str) -> tuple[bytes, str] | None:
        """lookup() without the status (the original surface)."""
        got = self.lookup(key)
        return None if got is None else (got[1], got[2])

    def put(
        self, key: str, body: bytes, ctype: str, model: str,
        artifact_hash: str, status: int = 200,
    ) -> bool:
        """Store one cacheable response; returns False when the body alone
        exceeds the whole byte budget, or the status is not storable.
        Negative entries (400/404) live under the short neg_ttl_s."""
        if len(body) > self.max_bytes or not self.storable_response(
            status, ctype
        ):
            return False
        ttl = self.ttl_s if status == 200 else self.neg_ttl_s
        expires = time.monotonic() + ttl if ttl > 0 else float("inf")
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            entry = _Entry(body, ctype, model, artifact_hash, expires,
                           status=status)
            self._entries[key] = entry
            self._bytes += entry.nbytes
            if self._m is not None:
                self._m["bytes"].inc(entry.nbytes)
            while self._bytes > self.max_bytes and self._entries:
                oldest = next(iter(self._entries))
                if oldest == key:
                    break  # never evict the entry being inserted
                self._evict_locked(oldest, "lru")
            self._refresh_gauges_locked()
        return True

    def invalidate_model(self, model: str) -> int:
        """Drop every entry of one model (operator surface); returns the
        count dropped."""
        with self._lock:
            stale = [
                k for k, e in self._entries.items() if e.model == model
            ]
            for k in stale:
                self._evict_locked(k, "reload")
            self._refresh_gauges_locked()
            return len(stale)

    # --- internals ----------------------------------------------------------

    def _evict_locked(self, key: str, reason: str) -> None:
        entry = self._entries.pop(key, None)
        if entry is None:
            return
        self._bytes -= entry.nbytes
        self.evictions[reason] = self.evictions.get(reason, 0) + 1
        if self._m is not None:
            counter = self._m["evictions"].get(reason)
            if counter is not None:
                counter.inc()

    def _count(self, name: str) -> None:
        if self._m is not None:
            self._m[name].inc()

    def _refresh_gauges_locked(self) -> None:
        if self._m is None:
            return
        self._m["resident"].set(float(self._bytes))
        self._m["entries"].set(float(len(self._entries)))
        total = self.hits + self.misses
        self._m["hit_ratio"].set(self.hits / total if total else 0.0)

    def stats(self) -> dict:
        """The /debug/cache payload body (everything but the flights)."""
        with self._lock:
            total = self.hits + self.misses
            per_model: dict[str, int] = {}
            negative = 0
            for e in self._entries.values():
                per_model[e.model] = per_model.get(e.model, 0) + 1
                negative += e.status != 200
            return {
                "entries": len(self._entries),
                "negative_entries": negative,
                "resident_bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "ttl_s": self.ttl_s,
                "neg_ttl_s": self.neg_ttl_s,
                "swr_s": self.swr_s,
                "hits": self.hits,
                "misses": self.misses,
                "coalesced": self.coalesced,
                "negative_hits": self.negative_hits,
                "stale_hits": self.stale_hits,
                "hit_ratio": round(self.hits / total, 4) if total else 0.0,
                "evictions": dict(self.evictions),
                "entries_by_model": per_model,
                "artifact_hashes": dict(self._hashes),
            }


# --- decoded-uint8 tier (cache carry-over #2) ------------------------------

def decoded_params(input_shape, resize_filter: str) -> str:
    """The canonical preprocess-params half of a decoded-tier key.  Both
    tiers spell it through this one function: a gateway and a model server
    disagreeing on the params string would silently never share entries."""
    return f"{tuple(input_shape)}|{resize_filter}"


def decoded_key(payload: bytes, params: str) -> str:
    """(content bytes, resolved preprocess params) -> decoded-tier key.

    Deliberately EXCLUDES the model name: two models with the same input
    contract decode the same image to the same pixels, so a cross-model
    hit skips the decode+resize entirely.  Content-addressed keys make
    entries immutable -- no TTL, no artifact invalidation."""
    h = hashlib.sha256()
    h.update(payload)
    h.update(b"|")
    h.update(params.encode())
    return h.hexdigest()


class DecodedCache:
    """Bounded LRU of decoded+resized uint8 image tensors.

    The decode stage's memo (GUIDE 10q): keyed by
    :func:`decoded_key` so identical image content requested for ANY
    model with the same input contract skips JPEG/PNG decode and resize.
    Lives on both tiers -- the gateway's legacy preprocess path and the
    model tier's bytes-wire decode stage consult one instance each.

    Entries are immutable by contract: callers must never mutate a
    returned array (get() marks it read-only to enforce that cheaply).
    KDLT_CACHE_DECODED_MB=0 disables the tier (get/put become no-ops).
    All kdlt_cache_decoded_* series are minted centrally in
    utils/metrics.py.
    """

    def __init__(
        self,
        registry: metrics_lib.Registry | None = None,
        max_mb: float | None = None,
    ):
        max_mb = max_mb if max_mb is not None else _env_float(
            DECODED_MB_ENV, DEFAULT_DECODED_MB
        )
        self.max_bytes = int(max_mb * 1024 * 1024)
        self._entries: "OrderedDict[str, object]" = OrderedDict()  # guarded-by: _lock
        self._bytes = 0              # guarded-by: _lock
        self._lock = threading.Lock()
        self.hits = 0                # guarded-by: _lock
        self.misses = 0              # guarded-by: _lock
        self.evictions = 0           # guarded-by: _lock
        self._m = (
            metrics_lib.cache_decoded_metrics(registry)
            if registry is not None else None
        )

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    def get(self, key: str):
        """Hit -> the decoded uint8 array (read-only view) + LRU touch;
        miss -> None."""
        if not self.enabled:
            return None
        with self._lock:
            arr = self._entries.get(key)
            if arr is None:
                self.misses += 1
                if self._m is not None:
                    self._m["misses"].inc()
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            if self._m is not None:
                self._m["hits"].inc()
            return arr

    def put(self, key: str, arr) -> bool:
        """Store one decoded tensor; returns False when disabled or the
        tensor alone exceeds the whole byte budget."""
        if not self.enabled or arr.nbytes > self.max_bytes:
            return False
        stored = arr.copy() if not arr.flags.c_contiguous else arr
        stored.setflags(write=False)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = stored
            self._bytes += stored.nbytes
            while self._bytes > self.max_bytes and self._entries:
                oldest = next(iter(self._entries))
                if oldest == key:
                    break  # never evict the entry being inserted
                victim = self._entries.pop(oldest)
                self._bytes -= victim.nbytes
                self.evictions += 1
                if self._m is not None:
                    self._m["evictions"].inc()
            self._refresh_gauges_locked()
        return True

    def _refresh_gauges_locked(self) -> None:
        if self._m is None:
            return
        self._m["resident"].set(float(self._bytes))
        self._m["entries"].set(float(len(self._entries)))

    def stats(self) -> dict:
        """The /debug/cache "decoded" section."""
        with self._lock:
            total = self.hits + self.misses
            return {
                "enabled": self.enabled,
                "entries": len(self._entries),
                "resident_bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_ratio": round(self.hits / total, 4) if total else 0.0,
                "evictions": self.evictions,
            }
