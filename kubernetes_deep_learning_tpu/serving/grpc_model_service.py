"""gRPC ModelService frontend: TF-Serving's management surface.

The reference's model tier carries these RPCs in the TF-Serving binary
(reference tf-serving.dockerfile:2); this closes the last of its gRPC
management surface in-tree (VERDICT r3 "missing" #4):

- ``GetModelStatus`` -- which version of a model is loaded and whether it
  is AVAILABLE (readiness-gated: a model still in warmup reports LOADING),
  in the binary's exact response shape (ModelVersionStatus with the
  ManagerState enum values).
- ``HandleReloadConfigRequest`` -- TF-Serving's config-reload API.  This
  server's model set is its ``--models`` root (one base path for every
  model -- the same layout the reference bakes into its image), so the
  accepted subset is: a model_config_list naming served (or
  newly-droppable-into-the-root) models triggers an immediate version
  rescan (the version watcher's poll, synchronously).  Configs that try
  to point a model OUTSIDE the root, or an empty list (TF-Serving
  semantics: unload everything), are refused loudly with
  FAILED_PRECONDITION rather than half-honored.

Like grpc_predict, the wire comes from hand-written wire-compatible
protos (tfs_protos/, protoc output in tfs_gen/ -- no TensorFlow
dependency); routing is by literal method path, so stock
``tensorflow_serving.apis.model_service_pb2_grpc`` client stubs work
unmodified.
"""

from __future__ import annotations

import os

import grpc

from kubernetes_deep_learning_tpu.serving.tfs_gen.tensorflow_serving.apis import (
    get_model_status_pb2,
    model_management_pb2,
)

MODEL_SERVICE_NAME = "tensorflow.serving.ModelService"

_STATE = get_model_status_pb2.ModelVersionStatus


class ModelServicer:
    """Implements ModelService over a ModelServer's models."""

    def __init__(self, model_server):
        self._server = model_server

    def GetModelStatus(self, request, context):
        name = request.model_spec.name
        model = self._server.models.get(name)
        if model is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"Could not find any versions of model {name}",
            )
        want = (
            int(request.model_spec.version.value)
            if request.model_spec.HasField("version")
            else None
        )
        if want is not None and want != model.version:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"Could not find version {want} of model {name}",
            )
        resp = get_model_status_pb2.GetModelStatusResponse()
        st = resp.model_version_status.add()
        st.version = model.version
        ready = getattr(model.engine, "ready", True)
        st.state = _STATE.AVAILABLE if ready else _STATE.LOADING
        st.status.error_code = 0  # OK
        return resp

    def HandleReloadConfigRequest(self, request, context):
        cfg = request.config
        if cfg.WhichOneof("config") != "model_config_list":
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "only model_config_list configs are supported",
            )
        configs = list(cfg.model_config_list.config)
        if not configs:
            # TF-Serving would unload every model; a serving pod emptying
            # itself on a malformed request is an outage, not a feature.
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "refusing an empty model_config_list (would unload all "
                "models); this server's model set is its --models root",
            )
        root = os.path.abspath(self._server.model_root)
        for mc in configs:
            # The hand-written ModelConfig models fields 1/2/4 only; a
            # stock client setting e.g. model_version_policy (field 7)
            # parses into unknown fields.  Refuse rather than return OK
            # while silently ignoring the pin ("refused loudly" contract).
            # Detection via discard-and-compare: serialization preserves
            # unknown fields, and the UnknownFields() accessor is
            # NotImplementedError on the upb protobuf backend.
            clean = model_management_pb2.ModelConfig()
            clean.CopyFrom(mc)
            clean.DiscardUnknownFields()
            if clean.SerializeToString() != mc.SerializeToString():
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"model {mc.name!r}: config carries unsupported "
                    "ModelConfig fields (e.g. model_version_policy); this "
                    "server always serves the highest version under its "
                    "--models root",
                )
            if mc.base_path:
                base = os.path.abspath(mc.base_path)
                if base != os.path.join(root, mc.name) and base != root:
                    context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION,
                        f"model {mc.name!r}: base_path {mc.base_path!r} is "
                        f"outside this server's --models root {root!r}; "
                        "place version dirs under the root instead",
                    )
        # Synchronous version-watcher scan: picks up new models and higher
        # versions dropped under the root (the managed-reload analog of
        # TF-Serving applying a new config).
        updated = self._server.poll_versions()
        missing = [mc.name for mc in configs if mc.name not in self._server.models]
        resp = model_management_pb2.ReloadConfigResponse()
        if missing:
            resp.status.error_code = 5  # NOT_FOUND
            resp.status.error_message = (
                f"no versions of {missing} under the model root"
                + (f"; reload applied {updated}" if updated else "")
            )
        else:
            resp.status.error_code = 0
            resp.status.error_message = ""
        return resp


def add_model_service_to_server(servicer: ModelServicer, grpc_server) -> None:
    """Register by literal method path (same approach as grpc_predict)."""
    handlers = {
        "GetModelStatus": grpc.unary_unary_rpc_method_handler(
            servicer.GetModelStatus,
            request_deserializer=get_model_status_pb2.GetModelStatusRequest.FromString,
            response_serializer=get_model_status_pb2.GetModelStatusResponse.SerializeToString,
        ),
        "HandleReloadConfigRequest": grpc.unary_unary_rpc_method_handler(
            servicer.HandleReloadConfigRequest,
            request_deserializer=model_management_pb2.ReloadConfigRequest.FromString,
            response_serializer=model_management_pb2.ReloadConfigResponse.SerializeToString,
        ),
    }
    grpc_server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(MODEL_SERVICE_NAME, handlers),)
    )
