"""Shed: the typed rejection every admission decision point raises.

A shed is not an error in the request (4xx) and not a server fault (500):
it is the tier protecting its goodput by refusing work it cannot finish
usefully -- DAGOR-style overload control (SoCC '18).  Each shed carries a
machine-readable ``reason`` (one of utils.metrics.ADMISSION_SHED_REASONS),
the HTTP status to map it to (503 for retryable overload, 504 for an
already-exhausted deadline budget), and an optional ``retry_after_s`` hint
surfaced as a ``Retry-After`` response header so well-behaved clients
(serving.client) back off instead of hammering a saturated tier.
"""

from __future__ import annotations

RETRY_AFTER_HEADER = "Retry-After"


class Shed(RuntimeError):
    """The request was refused by admission control, not failed by it."""

    def __init__(
        self,
        reason: str,
        http_status: int = 503,
        retry_after_s: float | None = None,
        detail: str = "",
    ):
        super().__init__(detail or f"request shed ({reason})")
        self.reason = reason
        self.http_status = http_status
        self.retry_after_s = retry_after_s

    def headers(self) -> dict[str, str]:
        """The extra response headers this shed mandates."""
        return retry_after_headers(self.retry_after_s)


def retry_after_headers(retry_after_s: float | None) -> dict[str, str]:
    """``Retry-After`` as decimal seconds (fractional; our client parses
    float, and proxies that insist on integers still read the magnitude)."""
    if retry_after_s is None:
        return {}
    return {RETRY_AFTER_HEADER: f"{max(0.0, retry_after_s):.3f}"}
