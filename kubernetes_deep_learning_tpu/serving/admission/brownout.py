"""Brownout: SLO-burn-driven staged graceful degradation (gateway tier).

Admission control (limiter.py) protects the tier from *instantaneous*
overload -- queue depth and concurrency.  This module closes the slower
loop: when the fleet is persistently missing its SLO (the PR 7 burn rate
over the fast window stays above sustainable), the gateway walks a ladder
of progressively cheaper serving modes instead of letting every class of
traffic degrade equally:

====== ===================================================================
stage  degradation (cumulative -- stage 3 includes 1 and 2)
====== ===================================================================
1      hedged retries disabled (hedges add load exactly when the tier
       can least afford duplicate work)
2      stale-while-revalidate cache serves: TTL-expired 200s within the
       ``KDLT_CACHE_SWR_S`` window answer immediately, marked
       ``X-Kdlt-Cache: stale``
3      ``best-effort`` requests shed at the gateway (429, reason
       ``brownout``)
4      ``batch`` requests shed too -- only ``interactive`` still served
====== ===================================================================

The controller is a hysteresis state machine, never a thermostat that
flaps: stage ``s`` is entered only when burn >= ``enter * s`` and left
only when burn < ``exit * s`` (``exit`` strictly below ``enter`` leaves a
dead band), it moves at most ONE stage per evaluation, and any two
transitions are separated by ``KDLT_BROWNOUT_DWELL_S`` seconds of dwell.
Class sheds use 429 (a *client*-class outcome in slo.classify), so the
load the brownout sheds leaves the SLO denominator and the burn signal
can actually recover -- shedding with 503 would keep burn pinned high and
latch the ladder at max stage.

Metrics (``kdlt_brownout_stage``, ``kdlt_brownout_transitions_total``)
are minted centrally in utils.metrics; ``/debug/brownout`` on the gateway
exposes the live stage, thresholds, and transition history.
"""

from __future__ import annotations

import os
import threading
import time

from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

BROWNOUT_ENV = "KDLT_BROWNOUT"
BURN_ENTER_ENV = "KDLT_BROWNOUT_BURN_ENTER"
BURN_EXIT_ENV = "KDLT_BROWNOUT_BURN_EXIT"
DWELL_ENV = "KDLT_BROWNOUT_DWELL_S"

# Stage s enters at burn >= DEFAULT_BURN_ENTER * s: 2/4/6/8 with the
# defaults.  Burn 2.0 over 5 m means the error budget is draining at twice
# the sustainable rate -- degrading hedges is cheap insurance there, while
# shedding whole classes (6x/8x) is reserved for genuine incidents.
DEFAULT_BURN_ENTER = 2.0
# Stage s exits below DEFAULT_BURN_EXIT * s; strictly below enter so the
# [exit*s, enter*(s+1)) band is where a stage holds steady.
DEFAULT_BURN_EXIT = 1.0
DEFAULT_DWELL_S = 10.0
MAX_STAGE = 4

# Which SloEngine window feeds the ladder: the fast (reaction-time) one.
BURN_WINDOW = "5m"

STAGE_ACTIONS = {
    1: "hedging disabled",
    2: "stale cache serves",
    3: "shed best-effort",
    4: "shed batch",
}

_HISTORY_CAP = 64


def brownout_enabled(explicit: bool | None = None) -> bool:
    """Explicit arg > $KDLT_BROWNOUT > enabled-by-default (the ladder only
    acts when burn is already well past sustainable, so the default-on
    posture matches the other serving subsystems' kill switches)."""
    if explicit is not None:
        return bool(explicit)
    raw = os.environ.get(BROWNOUT_ENV, "").strip().lower()
    return raw not in ("0", "false", "off", "no")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw.strip() else default
    except ValueError:
        return default


class BrownoutController:
    """The gateway's degradation ladder; evaluate() is called off the hot
    path (a ~1 s daemon loop), the read properties are lock-cheap hot-path
    gates.  ``clock`` is injectable for the fake-clock hysteresis tests.
    """

    def __init__(
        self,
        slo,
        registry: metrics_lib.Registry | None = None,
        enabled: bool | None = None,
        burn_enter: float | None = None,
        burn_exit: float | None = None,
        dwell_s: float | None = None,
        window: str = BURN_WINDOW,
        clock=time.monotonic,
    ):
        slo_on = slo is not None and getattr(slo, "enabled", False)
        self.enabled = brownout_enabled(enabled) and slo_on
        self.slo = slo
        self.window = window
        self.burn_enter = max(1e-6, (
            burn_enter if burn_enter is not None
            else _env_float(BURN_ENTER_ENV, DEFAULT_BURN_ENTER)
        ))
        exit_ = (
            burn_exit if burn_exit is not None
            else _env_float(BURN_EXIT_ENV, DEFAULT_BURN_EXIT)
        )
        # Hysteresis requires exit strictly under enter; a misconfigured
        # pair degrades to a half-band rather than a flapping ladder.
        if not 0.0 < exit_ < self.burn_enter:
            exit_ = self.burn_enter / 2.0
        self.burn_exit = exit_
        self.dwell_s = max(0.0, (
            dwell_s if dwell_s is not None
            else _env_float(DWELL_ENV, DEFAULT_DWELL_S)
        ))
        self._clock = clock
        self._lock = threading.Lock()
        # _stage is written only by the control loop (under _lock) and
        # read lock-free by the hot-path gates: a single-int read racing
        # one stage transition is equivalently ordered either way.
        self._stage = 0
        self._last_burn = 0.0        # guarded-by: _lock
        self._last_transition_t: float | None = None  # guarded-by: _lock
        self.transitions: list[dict] = []  # guarded-by: _lock
        self._m = (
            metrics_lib.brownout_metrics(registry)
            if registry is not None else None
        )
        if self._m is not None:
            self._m["stage"].set(0.0)

    # --- hot-path gates -----------------------------------------------------

    @property
    def stage(self) -> int:
        return self._stage

    @property
    def hedging_disabled(self) -> bool:
        return self._stage >= 1

    @property
    def serve_stale(self) -> bool:
        return self._stage >= 2

    def sheds(self, priority: str) -> bool:
        """Whether the current stage sheds this priority class at the
        door.  ``interactive`` is never brownout-shed -- protecting it is
        the point of the ladder."""
        stage = self._stage
        if priority == "best-effort":
            return stage >= 3
        if priority == "batch":
            return stage >= 4
        return False

    # --- control loop -------------------------------------------------------

    def max_burn(self) -> float:
        """The signal: the worst per-model burn rate over the fast window
        (max, not mean -- one tenant's incident must not be averaged away
        by a healthy fleet)."""
        if self.slo is None or not getattr(self.slo, "enabled", False):
            return 0.0
        worst = 0.0
        for windows in self.slo.model_windows().values():
            row = windows.get(self.window)
            if row:
                worst = max(worst, float(row.get("burn_rate", 0.0)))
        return worst

    def evaluate(self) -> int:
        """One control-loop tick: move at most one stage, respecting the
        thresholds and the dwell; returns the (possibly new) stage."""
        if not self.enabled:
            return self._stage
        burn = self.max_burn()
        now = self._clock()
        with self._lock:
            self._last_burn = burn
            stage = self._stage
            next_stage = stage
            if stage < MAX_STAGE and burn >= self.burn_enter * (stage + 1):
                next_stage = stage + 1
            elif stage > 0 and burn < self.burn_exit * stage:
                next_stage = stage - 1
            if next_stage == stage:
                return stage
            if (
                self._last_transition_t is not None
                and now - self._last_transition_t < self.dwell_s
            ):
                return stage  # dwell: hold the current stage
            direction = "up" if next_stage > stage else "down"
            # The label is the boundary stage crossed: entering s is
            # (s, up); leaving s is (s, down) -- max(old, new) either way.
            boundary = max(stage, next_stage)
            self._stage = next_stage
            self._last_transition_t = now
            self.transitions.append({
                "t": round(now, 3),
                "from": stage,
                "to": next_stage,
                "burn": round(burn, 4),
            })
            del self.transitions[:-_HISTORY_CAP]
            if self._m is not None:
                self._m["stage"].set(float(next_stage))
                counter = self._m["transitions"].get((boundary, direction))
                if counter is not None:
                    counter.inc()
            return next_stage

    # --- observability ------------------------------------------------------

    def debug_payload(self) -> dict:
        """The /debug/brownout JSON body."""
        with self._lock:
            stage = self._stage
            return {
                "enabled": self.enabled,
                "stage": stage,
                "burn": round(self._last_burn, 4),
                "window": self.window,
                "burn_enter": self.burn_enter,
                "burn_exit": self.burn_exit,
                "dwell_s": self.dwell_s,
                "actions": [
                    STAGE_ACTIONS[s] for s in range(1, stage + 1)
                ],
                "transitions": list(self.transitions),
            }
