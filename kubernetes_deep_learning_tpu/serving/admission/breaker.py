"""Circuit breaker for the gateway -> model-tier hop, with half-open probing.

When the model tier is down or persistently shedding, every gateway request
otherwise pays a full connect/read timeout against a dead upstream before
failing -- tying up gateway threads exactly when the system most needs them
free.  The breaker converts that into a fast local 503: after
``failure_threshold`` consecutive upstream failures it OPENs (all calls
refused with a Retry-After equal to the remaining cool-down), after
``reset_timeout_s`` it goes HALF_OPEN and lets ``half_open_probes`` real
requests through as probes; a probe failure re-opens, a full set of probe
successes closes.

Deliberately consecutive-failure-triggered (not a windowed error rate): the
gateway's per-request 503 retry already absorbs one-off shed responses, so
N consecutive failures genuinely means the tier is unhealthy, and the
counter resets on any success.
"""

from __future__ import annotations

import os
import threading
import time

FAILURES_ENV = "KDLT_BREAKER_FAILURES"
RESET_S_ENV = "KDLT_BREAKER_RESET_S"
PROBES_ENV = "KDLT_BREAKER_HALF_OPEN_PROBES"

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw.strip() else default
    except ValueError:
        return default


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int | None = None,
        reset_timeout_s: float | None = None,
        half_open_probes: int | None = None,
        clock=time.monotonic,
    ):
        # ``clock`` is injectable so state-machine tests don't sleep.
        self.failure_threshold = int(
            failure_threshold if failure_threshold is not None
            else _env_float(FAILURES_ENV, 5)
        )
        self.reset_timeout_s = (
            reset_timeout_s if reset_timeout_s is not None
            else _env_float(RESET_S_ENV, 2.0)
        )
        self.half_open_probes = int(
            half_open_probes if half_open_probes is not None
            else _env_float(PROBES_ENV, 1)
        )
        self._clock = clock
        self._lock = threading.Lock()
        self.state = CLOSED          # guarded-by: _lock
        self._consecutive_failures = 0  # guarded-by: _lock
        self._opened_at = 0.0        # guarded-by: _lock
        self._probes_issued = 0      # guarded-by: _lock
        self._probe_successes = 0    # guarded-by: _lock

    def allow(self) -> bool:
        """May a request go upstream right now?  HALF_OPEN consumes a probe
        slot per True, so callers must follow up with record_success/
        record_failure for the probe accounting to close the loop."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if self._clock() - self._opened_at < self.reset_timeout_s:
                    return False
                self.state = HALF_OPEN
                self._probes_issued = 0
                self._probe_successes = 0
            # HALF_OPEN: a bounded number of live probes, everyone else sheds.
            if self._probes_issued < self.half_open_probes:
                self._probes_issued += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self.state == HALF_OPEN:
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_probes:
                    self.state = CLOSED
                    self._consecutive_failures = 0
            else:
                self._consecutive_failures = 0

    def record_failure(self) -> None:
        with self._lock:
            if self.state == HALF_OPEN:
                self._trip_locked()
                return
            self._consecutive_failures += 1
            if self.state == CLOSED and (
                self._consecutive_failures >= self.failure_threshold
            ):
                self._trip_locked()

    def _trip_locked(self) -> None:
        self.state = OPEN
        self._opened_at = self._clock()
        self._consecutive_failures = 0

    def reset(self) -> None:
        """Force CLOSED with clean counters: out-of-band recovery evidence
        (the upstream pool's active /healthz probe succeeding) supersedes
        the time-based cool-down -- failover recovery must not wait out an
        OPEN window on a replica already proven healthy."""
        with self._lock:
            self.state = CLOSED
            self._consecutive_failures = 0
            self._probes_issued = 0
            self._probe_successes = 0

    def retry_after_s(self) -> float:
        """Remaining cool-down before half-open probing (0 when not OPEN)."""
        with self._lock:
            if self.state != OPEN:
                return 0.0
            return max(0.0, self._opened_at + self.reset_timeout_s - self._clock())
