"""Propagated deadline budgets: one clock from client edge to batcher.

The reference's only latency control is a fixed 20 s gRPC deadline at the
gateway (reference model_server.py:55); every queue and upstream call below
it waits on its own unrelated constant, so a request can keep consuming
gateway threads, batcher slots, and TPU time long after its caller has
given up.  Here a request carries its REMAINING budget in the
``X-Request-Deadline-Ms`` header: the client states a total budget, the
gateway converts it to an absolute monotonic deadline, and every hop down
(upstream HTTP call, model-tier admission, batcher future wait) re-derives
its timeout from what is left -- Clockwork-style (OSDI '20): work that
cannot finish inside its deadline is rejected as early as possible instead
of executed uselessly.

Absent or unparsable headers fall back to the reference-compatible default
budget (``KDLT_ADMISSION_DEFAULT_DEADLINE_MS``, 20 s), so deadline-unaware
clients see exactly the legacy behavior; client-supplied values are capped
(``KDLT_ADMISSION_MAX_DEADLINE_MS``) so a hostile header cannot pin server
resources for an hour.
"""

from __future__ import annotations

import math
import os
import time

DEADLINE_HEADER = "X-Request-Deadline-Ms"
WSGI_DEADLINE_KEY = "HTTP_X_REQUEST_DEADLINE_MS"

DEFAULT_DEADLINE_MS_ENV = "KDLT_ADMISSION_DEFAULT_DEADLINE_MS"
MAX_DEADLINE_MS_ENV = "KDLT_ADMISSION_MAX_DEADLINE_MS"
DEFAULT_DEADLINE_MS = 20_000.0  # the reference's 20 s deadline, as a budget
MAX_DEADLINE_MS = 300_000.0


def _env_ms(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw.strip() else default
    except ValueError:
        return default


class Deadline:
    """An absolute monotonic deadline, created from a remaining-ms budget.

    Absolute internally (so elapsed time anywhere in the pipeline is
    automatically charged against it), relative on the wire (clock skew
    between tiers must not corrupt the budget -- the header always carries
    remaining milliseconds, re-measured at send time).
    """

    __slots__ = ("budget_s", "_deadline")

    def __init__(self, budget_s: float, now: float | None = None):
        self.budget_s = budget_s
        self._deadline = (time.monotonic() if now is None else now) + budget_s

    @classmethod
    def default(cls) -> "Deadline":
        return cls(_env_ms(DEFAULT_DEADLINE_MS_ENV, DEFAULT_DEADLINE_MS) / 1e3)

    @classmethod
    def from_header(cls, raw: str | None) -> "Deadline":
        """Parse ``X-Request-Deadline-Ms``; absent/garbage -> the default
        budget, oversized values capped, and a non-positive value becomes an
        already-exhausted deadline (the sender spent the budget upstream;
        admission rejects it before it touches the TPU)."""
        if raw is None or not str(raw).strip():
            return cls.default()
        try:
            ms = float(raw)
        except (TypeError, ValueError):
            return cls.default()
        if not math.isfinite(ms):
            # "nan" parses but slides through min()/max() unchanged -- a
            # never-expiring deadline that defeats the hostile-header cap
            # and re-propagates as "nan" downstream.  Garbage -> default.
            return cls.default()
        ms = min(ms, _env_ms(MAX_DEADLINE_MS_ENV, MAX_DEADLINE_MS))
        return cls(max(ms, 0.0) / 1e3)

    def remaining_s(self) -> float:
        return self._deadline - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1e3

    @property
    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def header_value(self) -> str:
        """The remaining budget, as the wire header value (re-measured now)."""
        return f"{max(self.remaining_ms(), 0.0):.1f}"

    def clamp(self, timeout_s: float, floor_s: float = 0.001) -> float:
        """``timeout_s`` shrunk to the remaining budget (never below
        ``floor_s``: a zero/negative socket timeout means 'wait forever' or
        raises, neither of which is 'fail fast')."""
        return max(floor_s, min(timeout_s, self.remaining_s()))
