"""End-to-end admission control & overload management for the serving path.

The reference leans entirely on Kubernetes for overload behavior (replica
scaling + a cloud LoadBalancer); in-process it has a single fixed 20 s
deadline and no shedding, so under 2x load every request degrades together.
This package makes the tiers themselves predictable under overload, in the
spirit of Clockwork (OSDI '20) and DAGOR (SoCC '18):

- ``deadline``: a per-request deadline budget propagated in the
  ``X-Request-Deadline-Ms`` header, so every queue wait and upstream
  timeout is computed from the REMAINING budget and exhausted requests are
  rejected before touching the TPU;
- ``limiter``: an AIMD adaptive concurrency limiter with a bounded
  admission queue (503 + Retry-After with a distinct shed reason);
- ``breaker``: a gateway-side circuit breaker on the model tier with
  half-open probing;
- ``controller``: the per-tier front door combining the above, the
  ``kdlt_admission_*`` metrics, and graceful drain (SIGTERM flips /readyz,
  stops admission, lets in-flight work finish).

bench.py --overload-ab is the acceptance harness: goodput (in-deadline
completions/s) under 2x offered load with admission on vs off.
"""

from kubernetes_deep_learning_tpu.serving.admission.breaker import CircuitBreaker
from kubernetes_deep_learning_tpu.serving.admission.brownout import (
    BrownoutController,
    brownout_enabled,
)
from kubernetes_deep_learning_tpu.serving.admission.controller import (
    AdmissionController,
    Ticket,
    admission_enabled,
    drain_timeout_s,
    install_sigterm_drain,
)
from kubernetes_deep_learning_tpu.serving.admission.deadline import (
    DEADLINE_HEADER,
    WSGI_DEADLINE_KEY,
    Deadline,
)
from kubernetes_deep_learning_tpu.serving.admission.limiter import (
    AdaptiveLimiter,
    env_budgets,
    parse_budgets,
)
from kubernetes_deep_learning_tpu.serving.admission.shed import (
    RETRY_AFTER_HEADER,
    Shed,
    retry_after_headers,
)

__all__ = [
    "AdaptiveLimiter",
    "AdmissionController",
    "BrownoutController",
    "CircuitBreaker",
    "DEADLINE_HEADER",
    "Deadline",
    "RETRY_AFTER_HEADER",
    "Shed",
    "Ticket",
    "WSGI_DEADLINE_KEY",
    "admission_enabled",
    "brownout_enabled",
    "drain_timeout_s",
    "env_budgets",
    "install_sigterm_drain",
    "parse_budgets",
    "retry_after_headers",
]
