"""Adaptive concurrency limiter + bounded admission queue (AIMD).

The static alternative -- a fixed thread/queue cap -- is wrong in both
directions on a serving tier whose per-request cost varies with batch
shape, model, and device health.  This limiter learns the sustainable
concurrency the way TCP learns a path's bandwidth:

- **Additive increase**: every clean completion grows the limit by
  ``1/limit`` (≈ +1 per round of in-flight completions).
- **Multiplicative decrease**: an observed-latency overload signal -- the
  caller saw a deadline miss, a full downstream queue, or an upstream 503
  while holding the slot (Ticket.mark_overloaded), or the admission-queue
  wait exceeded an explicit target (``KDLT_ADMISSION_TARGET_QUEUE_MS``,
  off by default: on a device-bound tier queueing is where waiting
  BELONGS, so only budget-relative misses are unambiguous congestion) --
  shrinks the limit by ``decrease`` (default x0.9), at most once per
  ``cooldown_s`` so one burst's worth of misses counts as ONE congestion
  event, not thirty.

Requests beyond the limit wait in a bounded queue -- but never for their
whole deadline: the wait is capped at ``queue_wait_fraction`` (default a
quarter) of the remaining budget, so an admitted request always keeps the
bulk of its budget for actual execution (one that burned its budget
queueing would be admitted only to miss its deadline on the device, the
worst of both worlds).  Beyond ``queue_cap`` waiters, or past the wait
bound, the request sheds with a distinct reason so dashboards can tell
"queue overflowed" from "queue too slow".
"""

from __future__ import annotations

import os
import threading
import time

from kubernetes_deep_learning_tpu.serving.admission.shed import Shed

MAX_CONCURRENCY_ENV = "KDLT_ADMISSION_MAX_CONCURRENCY"
MIN_CONCURRENCY_ENV = "KDLT_ADMISSION_MIN_CONCURRENCY"
INITIAL_CONCURRENCY_ENV = "KDLT_ADMISSION_INITIAL_CONCURRENCY"
QUEUE_CAP_ENV = "KDLT_ADMISSION_QUEUE_CAP"
TARGET_QUEUE_MS_ENV = "KDLT_ADMISSION_TARGET_QUEUE_MS"
MAX_QUEUE_WAIT_MS_ENV = "KDLT_ADMISSION_MAX_QUEUE_WAIT_MS"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw.strip() else default
    except ValueError:
        return default


def env_max_limit(default: float = 64.0) -> float:
    """The operator's concurrency-ceiling override, for callers that
    reconcile it with a tier-specific floor before constructing the
    limiter (e.g. the model server's batch-formation floor)."""
    return _env_float(MAX_CONCURRENCY_ENV, default)


class AdaptiveLimiter:
    def __init__(
        self,
        min_limit: float | None = None,
        max_limit: float | None = None,
        initial: float | None = None,
        target_wait_s: float | None = None,
        queue_cap: int | None = None,
        max_queue_wait_s: float | None = None,
        queue_wait_fraction: float = 0.25,
        decrease: float = 0.9,
        cooldown_s: float = 0.1,
    ):
        self.min_limit = min_limit if min_limit is not None else max(
            1.0, _env_float(MIN_CONCURRENCY_ENV, 1.0)
        )
        self.max_limit = max_limit if max_limit is not None else _env_float(
            MAX_CONCURRENCY_ENV, 64.0
        )
        # An inverted pair (floor above ceiling, e.g. a tier's explicit
        # batch-formation floor vs the default env ceiling) must never
        # reach the AIMD update: release() would clamp decreases UP to
        # min_limit -- raising admitted concurrency on congestion -- while
        # acquire() clamps the working limit down to max_limit, oscillating
        # between the two.  The explicit floor wins.
        self.max_limit = max(self.max_limit, self.min_limit)
        assert self.min_limit <= self.max_limit
        self._limit = float(
            initial if initial is not None
            else _env_float(INITIAL_CONCURRENCY_ENV, 8.0)
        )
        self._limit = min(max(self._limit, self.min_limit), self.max_limit)
        # 0 disables the absolute-target decrease signal (the default): the
        # budget-relative signals (queue_wait_fraction bound + the caller's
        # mark_overloaded) adapt to each request's own deadline instead of
        # a one-size constant.
        self.target_wait_s = (
            target_wait_s if target_wait_s is not None
            else _env_float(TARGET_QUEUE_MS_ENV, 0.0) / 1e3
        )
        self.queue_cap = int(
            queue_cap if queue_cap is not None else _env_float(QUEUE_CAP_ENV, 128)
        )
        # The absolute ceiling exists so a request with NO deadline (legacy
        # client, admission-on server) cannot park forever; deadline-carrying
        # requests are bounded tighter by queue_wait_fraction of their budget.
        self.max_queue_wait_s = (
            max_queue_wait_s if max_queue_wait_s is not None
            else _env_float(MAX_QUEUE_WAIT_MS_ENV, 10_000.0) / 1e3
        )
        self.queue_wait_fraction = queue_wait_fraction
        self._decrease = decrease
        self._cooldown_s = cooldown_s
        self._last_decrease = 0.0
        self._inflight = 0
        self._waiters = 0
        self._cond = threading.Condition()

    @property
    def limit(self) -> float:
        return self._limit

    @property
    def inflight(self) -> int:
        return self._inflight

    def _slots_full(self) -> bool:
        return self._inflight >= max(1, int(self._limit))

    def acquire(self, budget_s: float | None = None) -> float:
        """Take a concurrency slot; returns the queue wait in seconds.

        ``budget_s`` is the request's remaining deadline; the wait is
        bounded by ``queue_wait_fraction`` of it (and the absolute
        ``max_queue_wait_s``) so a queued request keeps enough budget to
        actually execute.  Raises Shed("queue_full") when the waiter cap is
        hit, Shed("queue_timeout") when no slot frees inside the bound.
        """
        with self._cond:
            if not self._slots_full():
                self._inflight += 1
                return 0.0
            if self._waiters >= self.queue_cap:
                raise Shed(
                    "queue_full",
                    retry_after_s=max(self.target_wait_s, 0.05),
                    detail=f"admission queue at its {self.queue_cap}-waiter cap",
                )
            bound = self.max_queue_wait_s
            if budget_s is not None:
                bound = min(bound, max(0.0, budget_s) * self.queue_wait_fraction)
            t0 = time.monotonic()
            giveup = t0 + bound
            self._waiters += 1
            try:
                while self._slots_full():
                    remaining = giveup - time.monotonic()
                    if remaining <= 0:
                        # release() hands out a SINGLE notify; if it landed
                        # on this waiter just as the bound expired, pass it
                        # on -- otherwise the freed slot idles while the
                        # remaining waiters sleep out their full bound and
                        # shed despite available capacity.
                        self._cond.notify()
                        raise Shed(
                            "queue_timeout",
                            retry_after_s=max(self.target_wait_s, 0.05),
                            detail=(
                                f"no concurrency slot freed within "
                                f"{bound * 1e3:.0f}ms (limit {self._limit:.1f})"
                            ),
                        )
                    self._cond.wait(remaining)
            finally:
                self._waiters -= 1
            self._inflight += 1
            return time.monotonic() - t0

    def release(
        self,
        queue_wait_s: float = 0.0,
        overloaded: bool = False,
        headroom: bool = True,
    ) -> None:
        """Free the slot and feed the AIMD controller.

        ``overloaded`` is the caller's downstream congestion signal
        (deadline miss / queue full / upstream 503); a queue wait above the
        explicit target is the local one.  ``headroom=False`` marks a
        completion that made it but without comfortable budget to spare:
        it neither grows nor shrinks the limit.  The hold band between
        "fast enough to grow" and "slow enough to shrink" is what keeps the
        equilibrium stable -- grow-on-every-success alone ratchets the
        limit up between cooldown-capped decreases until every completion
        rides the deadline ceiling.
        """
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            now = time.monotonic()
            if overloaded or (
                self.target_wait_s > 0 and queue_wait_s > self.target_wait_s
            ):
                if now - self._last_decrease >= self._cooldown_s:
                    self._limit = max(self.min_limit, self._limit * self._decrease)
                    self._last_decrease = now
            elif headroom:
                self._limit = min(self.max_limit, self._limit + 1.0 / max(self._limit, 1.0))
            self._cond.notify()
