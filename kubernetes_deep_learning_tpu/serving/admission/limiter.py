"""Adaptive concurrency limiter + bounded admission queue (AIMD) with
per-model budgets and priority classes.

The static alternative -- a fixed thread/queue cap -- is wrong in both
directions on a serving tier whose per-request cost varies with batch
shape, model, and device health.  This limiter learns the sustainable
concurrency the way TCP learns a path's bandwidth:

- **Additive increase**: every clean completion grows the limit by
  ``1/limit`` (≈ +1 per round of in-flight completions).
- **Multiplicative decrease**: an observed-latency overload signal -- the
  caller saw a deadline miss, a full downstream queue, or an upstream 503
  while holding the slot (Ticket.mark_overloaded), or the admission-queue
  wait exceeded an explicit target (``KDLT_ADMISSION_TARGET_QUEUE_MS``,
  off by default: on a device-bound tier queueing is where waiting
  BELONGS, so only budget-relative misses are unambiguous congestion) --
  shrinks the limit by ``decrease`` (default x0.9), at most once per
  ``cooldown_s`` so one burst's worth of misses counts as ONE congestion
  event, not thirty.

The tier-wide AIMD limit is then PARTITIONED into per-model budgets
(``KDLT_ADMIT_BUDGETS``; weights default to ``KDLT_SCHED_WEIGHTS`` so the
admission split and the scheduler split agree).  Each model's share is
``limit * w_m / sum(w of ACTIVE models)`` -- active meaning in-flight or
queued -- so a single-model tier keeps the exact legacy behavior (its
share IS the limit) and idle capacity is never wasted: a model past its
share may still run on slots nobody else wants (work-conserving
borrowing).  The teeth are at the queue: grants go to under-share waiters
first (then higher priority class, then FIFO), and when the waiter cap is
hit the evicted victim is the most over-share waiter first (borrowed
slots preempt-shed first), then the lowest class, then the youngest.  A
noisy neighbor therefore exhausts ITS budget, not the tier's.

Requests beyond the limit wait in a bounded queue -- but never for their
whole deadline: the wait is capped at ``queue_wait_fraction`` (default a
quarter) of the remaining budget, so an admitted request always keeps the
bulk of its budget for actual execution (one that burned its budget
queueing would be admitted only to miss its deadline on the device, the
worst of both worlds).  Beyond ``queue_cap`` waiters, or past the wait
bound, the request sheds with a distinct reason so dashboards can tell
"queue overflowed" from "budget exhausted" from "queue too slow".

Shed ``Retry-After`` hints are derived from live state -- queued waiters
ahead of a retry times the observed slot-hold EWMA over the limit -- with
±25% jitter, so a synchronized thundering herd of retriers decorrelates
instead of re-arriving as one wave (the retry-storm failure mode a
constant hint invites).
"""

from __future__ import annotations

import os
import random
import threading
import time

from kubernetes_deep_learning_tpu.serving.admission.shed import Shed
from kubernetes_deep_learning_tpu.serving.protocol import (
    DEFAULT_PRIORITY,
    PRIORITY_RANK,
)

MAX_CONCURRENCY_ENV = "KDLT_ADMISSION_MAX_CONCURRENCY"
MIN_CONCURRENCY_ENV = "KDLT_ADMISSION_MIN_CONCURRENCY"
INITIAL_CONCURRENCY_ENV = "KDLT_ADMISSION_INITIAL_CONCURRENCY"
QUEUE_CAP_ENV = "KDLT_ADMISSION_QUEUE_CAP"
TARGET_QUEUE_MS_ENV = "KDLT_ADMISSION_TARGET_QUEUE_MS"
MAX_QUEUE_WAIT_MS_ENV = "KDLT_ADMISSION_MAX_QUEUE_WAIT_MS"
# Per-model budget weights: "model=weight,..." enables explicit weights,
# "0"/"off" disables partitioning (the legacy shared limiter), anything
# else -- including unset -- enables budgets with the scheduler's
# KDLT_SCHED_WEIGHTS weights (default weight 1.0 per model), so the
# admission split and the device-time split agree by default.
BUDGETS_ENV = "KDLT_ADMIT_BUDGETS"
# Spelled locally (not imported from runtime.scheduler, which sits above
# this layer) -- the grammar below matches scheduler.resolve_weights.
SCHED_WEIGHTS_ENV = "KDLT_SCHED_WEIGHTS"

_FALSY = {"0", "off", "false", "no"}
_TRUTHY = {"", "1", "on", "true", "yes", "auto"}
# Retry-After derivation bounds: never under 50ms (a tight loop of instant
# retries), never over 10s (a confused EWMA must not park clients).
RETRY_AFTER_MIN_S = 0.05
RETRY_AFTER_MAX_S = 10.0
RETRY_AFTER_JITTER = 0.25
_HOLD_EWMA_ALPHA = 0.2

_ENV_SENTINEL = object()


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw.strip() else default
    except ValueError:
        return default


def env_max_limit(default: float = 64.0) -> float:
    """The operator's concurrency-ceiling override, for callers that
    reconcile it with a tier-specific floor before constructing the
    limiter (e.g. the model server's batch-formation floor)."""
    return _env_float(MAX_CONCURRENCY_ENV, default)


def parse_budgets(raw: str | None) -> dict[str, float]:
    """"model=weight,..." -> weight map (scheduler.resolve_weights grammar:
    malformed entries are skipped, weights floored at 1e-3)."""
    out: dict[str, float] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, w = part.partition("=")
        name = name.strip()
        if not name:
            continue
        try:
            out[name] = max(float(w), 1e-3)
        except ValueError:
            continue
    return out


def env_budgets() -> dict[str, float] | None:
    """Resolve KDLT_ADMIT_BUDGETS: None disables partitioning (legacy
    shared limiter); a dict -- possibly empty, every model then weighing
    1.0 -- enables it."""
    raw = os.environ.get(BUDGETS_ENV, "").strip()
    if raw.lower() in _FALSY:
        return None
    if raw.lower() in _TRUTHY:
        raw = os.environ.get(SCHED_WEIGHTS_ENV, "")
    return parse_budgets(raw)


class _Waiter:
    """One queued request: who it is (model, class), when it arrived, and
    how it left the queue (granted a slot, or shed by an evictor)."""

    __slots__ = ("model", "priority", "rank", "enq_t", "granted", "shed")

    def __init__(self, model: str | None, priority: str, enq_t: float):
        self.model = model
        self.priority = priority
        self.rank = PRIORITY_RANK.get(priority, 0)
        self.enq_t = enq_t
        self.granted = False
        self.shed: Shed | None = None


class AdaptiveLimiter:
    def __init__(
        self,
        min_limit: float | None = None,
        max_limit: float | None = None,
        initial: float | None = None,
        target_wait_s: float | None = None,
        queue_cap: int | None = None,
        max_queue_wait_s: float | None = None,
        queue_wait_fraction: float = 0.25,
        decrease: float = 0.9,
        cooldown_s: float = 0.1,
        budgets: dict[str, float] | None = _ENV_SENTINEL,  # type: ignore[assignment]
    ):
        self.min_limit = min_limit if min_limit is not None else max(
            1.0, _env_float(MIN_CONCURRENCY_ENV, 1.0)
        )
        self.max_limit = max_limit if max_limit is not None else _env_float(
            MAX_CONCURRENCY_ENV, 64.0
        )
        # An inverted pair (floor above ceiling, e.g. a tier's explicit
        # batch-formation floor vs the default env ceiling) must never
        # reach the AIMD update: release() would clamp decreases UP to
        # min_limit -- raising admitted concurrency on congestion -- while
        # acquire() clamps the working limit down to max_limit, oscillating
        # between the two.  The explicit floor wins.
        self.max_limit = max(self.max_limit, self.min_limit)
        assert self.min_limit <= self.max_limit
        self._limit = float(  # guarded-by: _cond
            initial if initial is not None
            else _env_float(INITIAL_CONCURRENCY_ENV, 8.0)
        )
        self._limit = min(max(self._limit, self.min_limit), self.max_limit)
        # 0 disables the absolute-target decrease signal (the default): the
        # budget-relative signals (queue_wait_fraction bound + the caller's
        # mark_overloaded) adapt to each request's own deadline instead of
        # a one-size constant.
        self.target_wait_s = (
            target_wait_s if target_wait_s is not None
            else _env_float(TARGET_QUEUE_MS_ENV, 0.0) / 1e3
        )
        self.queue_cap = int(
            queue_cap if queue_cap is not None else _env_float(QUEUE_CAP_ENV, 128)
        )
        # The absolute ceiling exists so a request with NO deadline (legacy
        # client, admission-on server) cannot park forever; deadline-carrying
        # requests are bounded tighter by queue_wait_fraction of their budget.
        self.max_queue_wait_s = (
            max_queue_wait_s if max_queue_wait_s is not None
            else _env_float(MAX_QUEUE_WAIT_MS_ENV, 10_000.0) / 1e3
        )
        self.queue_wait_fraction = queue_wait_fraction
        self._decrease = decrease
        self._cooldown_s = cooldown_s
        self._last_decrease = 0.0    # guarded-by: _cond
        self._inflight = 0           # guarded-by: _cond
        self._inflight_by: dict[str, int] = {}  # guarded-by: _cond
        self._waiters: list[_Waiter] = []  # guarded-by: _cond
        self._cond = threading.Condition()
        # Observed slot-hold EWMA (seconds held from admit to release), the
        # live backlog-drain estimate behind derived Retry-After hints.
        self._hold_ewma_s = 0.0      # guarded-by: _cond
        self.budgets: dict[str, float] | None = (
            env_budgets() if budgets is _ENV_SENTINEL else budgets
        )

    @property
    def limit(self) -> float:
        with self._cond:
            return self._limit

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._waiters)

    def _slots_full_locked(self) -> bool:
        return self._inflight >= max(1, int(self._limit))

    # --- per-model budget partitioning ---------------------------------

    def _weight(self, model: str | None) -> float:
        if self.budgets is None or model is None:
            return 1.0
        return self.budgets.get(model, 1.0)

    def _share_locked(self, model: str | None) -> float:
        """``model``'s budget: its weighted slice of the current limit over
        the ACTIVE model set (in-flight or queued, plus itself).  With one
        active model the share is the whole limit -- single-tenant tiers
        keep the exact legacy AIMD behavior."""
        if self.budgets is None or model is None:
            return self._limit
        active = set(self._inflight_by)
        active.update(w.model for w in self._waiters if w.model is not None)
        active.add(model)
        total = sum(self._weight(m) for m in active)
        if total <= 0:
            return self._limit
        return self._limit * self._weight(model) / total

    def _over_share_locked(self, model: str | None) -> bool:
        if self.budgets is None or model is None:
            return False
        return self._inflight_by.get(model, 0) >= self._share_locked(model)

    def _take_slot_locked(self, model: str | None) -> None:
        self._inflight += 1
        if model is not None:
            self._inflight_by[model] = self._inflight_by.get(model, 0) + 1

    def shares(self) -> dict[str, float]:
        """Current per-model budget shares (debug surface)."""
        with self._cond:
            if self.budgets is None:
                return {}
            active = set(self._inflight_by)
            active.update(w.model for w in self._waiters if w.model is not None)
            return {m: self._share_locked(m) for m in sorted(active)}

    # --- derived Retry-After -------------------------------------------

    def _retry_after_locked(self) -> float:
        """Backlog-drain estimate: waiters ahead of a retry, served
        ``limit`` at a time, each holding a slot for the observed EWMA.
        Jittered ±25% so herds decorrelate; clamped so neither a cold
        EWMA nor a deep queue produces a degenerate hint."""
        hold = self._hold_ewma_s if self._hold_ewma_s > 0 else max(
            self.target_wait_s, 0.1
        )
        base = (len(self._waiters) + 1) / max(self._limit, 1.0) * hold
        base = min(max(base, RETRY_AFTER_MIN_S), RETRY_AFTER_MAX_S)
        return base * random.uniform(
            1.0 - RETRY_AFTER_JITTER, 1.0 + RETRY_AFTER_JITTER
        )

    def retry_after_s(self) -> float:
        with self._cond:
            return self._retry_after_locked()

    # --- queue arbitration ---------------------------------------------

    def _grant_key_locked(self, w: _Waiter) -> tuple:
        # Under-share waiters first (the budget guarantee), then higher
        # class (lower rank), then FIFO.
        return (self._over_share_locked(w.model), w.rank, w.enq_t)

    def _grant_waiters_locked(self) -> None:
        """Hand free slots to the best waiters; wakes every waiter whose
        state changed (granted or shed elsewhere)."""
        woke = False
        while self._waiters and not self._slots_full_locked():
            w = min(self._waiters, key=self._grant_key_locked)
            self._waiters.remove(w)
            w.granted = True
            self._take_slot_locked(w.model)
            woke = True
        if woke:
            self._cond.notify_all()

    def _evict_for_locked(self, model: str | None, rank: int) -> bool:
        """Make room at the waiter cap for a (model, rank) arrival by
        shedding the WORST queued waiter -- most over-share first (borrowed
        slots preempt-shed first), then lowest class, then youngest -- but
        only one strictly worse than the newcomer.  Returns False when the
        newcomer itself is the worst (it should shed queue_full)."""
        if not self._waiters:
            return False

        def victim_key(w: _Waiter) -> tuple:
            return (self._over_share_locked(w.model), w.rank, w.enq_t)

        victim = max(self._waiters, key=victim_key)
        newcomer_key = (self._over_share_locked(model), rank, time.monotonic())
        if victim_key(victim) <= newcomer_key:
            return False
        reason = (
            "budget_exhausted" if self._over_share_locked(victim.model)
            else "preempted"
        )
        victim.shed = Shed(
            reason,
            retry_after_s=self._retry_after_locked(),
            detail=(
                f"evicted from the admission queue by a "
                f"{'under-budget' if reason == 'budget_exhausted' else 'higher-class'} "
                f"arrival (model={victim.model!r}, class={victim.priority})"
            ),
        )
        self._waiters.remove(victim)
        self._cond.notify_all()
        return True

    def acquire(
        self,
        budget_s: float | None = None,
        model: str | None = None,
        priority: str = DEFAULT_PRIORITY,
    ) -> float:
        """Take a concurrency slot; returns the queue wait in seconds.

        ``budget_s`` is the request's remaining deadline; the wait is
        bounded by ``queue_wait_fraction`` of it (and the absolute
        ``max_queue_wait_s``) so a queued request keeps enough budget to
        actually execute.  ``model`` keys the per-model budget and
        ``priority`` the class-ordered arbitration.  Raises
        Shed("queue_full") when the waiter cap is hit and nobody worse can
        be evicted, Shed("budget_exhausted"/"preempted") on eviction, and
        Shed("queue_timeout") when no slot frees inside the bound.
        """
        rank = PRIORITY_RANK.get(priority, 0)
        with self._cond:
            if not self._slots_full_locked() and not self._waiters:
                # Free slot, empty queue: take it.  Work-conserving
                # borrowing happens exactly here -- an over-share model may
                # run on capacity nobody is waiting for; the budget bites
                # only once there IS contention (a queue).
                self._take_slot_locked(model)
                return 0.0
            if len(self._waiters) >= self.queue_cap:
                if not self._evict_for_locked(model, rank):
                    raise Shed(
                        "queue_full",
                        retry_after_s=self._retry_after_locked(),
                        detail=(
                            f"admission queue at its {self.queue_cap}-waiter "
                            f"cap with no lower-class or over-budget waiter "
                            f"to evict"
                        ),
                    )
            bound = self.max_queue_wait_s
            if budget_s is not None:
                bound = min(bound, max(0.0, budget_s) * self.queue_wait_fraction)
            t0 = time.monotonic()
            giveup = t0 + bound
            w = _Waiter(model, priority, t0)
            self._waiters.append(w)
            # A slot may be free right now (transiently, between a grant
            # sweep and this arrival): sweep so the newcomer -- or a better
            # waiter -- takes it immediately instead of on the next release.
            self._grant_waiters_locked()
            while True:
                if w.granted:
                    return time.monotonic() - t0
                if w.shed is not None:
                    raise w.shed
                remaining = giveup - time.monotonic()
                if remaining <= 0:
                    self._waiters.remove(w)
                    raise Shed(
                        "queue_timeout",
                        retry_after_s=self._retry_after_locked(),
                        detail=(
                            f"no concurrency slot freed within "
                            f"{bound * 1e3:.0f}ms (limit {self._limit:.1f})"
                        ),
                    )
                self._cond.wait(remaining)

    def release(
        self,
        queue_wait_s: float = 0.0,
        overloaded: bool = False,
        headroom: bool = True,
        model: str | None = None,
        held_s: float | None = None,
    ) -> None:
        """Free the slot and feed the AIMD controller.

        ``overloaded`` is the caller's downstream congestion signal
        (deadline miss / queue full / upstream 503); a queue wait above the
        explicit target is the local one.  ``headroom=False`` marks a
        completion that made it but without comfortable budget to spare:
        it neither grows nor shrinks the limit.  The hold band between
        "fast enough to grow" and "slow enough to shrink" is what keeps the
        equilibrium stable -- grow-on-every-success alone ratchets the
        limit up between cooldown-capped decreases until every completion
        rides the deadline ceiling.  ``model`` mirrors acquire()'s and
        ``held_s`` (admit -> release) feeds the Retry-After hold EWMA.
        """
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            if model is not None and model in self._inflight_by:
                left = self._inflight_by[model] - 1
                if left > 0:
                    self._inflight_by[model] = left
                else:
                    del self._inflight_by[model]
            if held_s is not None and held_s >= 0:
                self._hold_ewma_s = (
                    held_s if self._hold_ewma_s <= 0
                    else (1 - _HOLD_EWMA_ALPHA) * self._hold_ewma_s
                    + _HOLD_EWMA_ALPHA * held_s
                )
            now = time.monotonic()
            if overloaded or (
                self.target_wait_s > 0 and queue_wait_s > self.target_wait_s
            ):
                if now - self._last_decrease >= self._cooldown_s:
                    self._limit = max(self.min_limit, self._limit * self._decrease)
                    self._last_decrease = now
            elif headroom:
                self._limit = min(self.max_limit, self._limit + 1.0 / max(self._limit, 1.0))
            self._grant_waiters_locked()
            # Even when nobody was granted (e.g. only over-bound waiters
            # remain mid-timeout), wake the queue so timing loops re-check.
            self._cond.notify_all()
