"""AdmissionController: a tier's front door, plus graceful drain.

One controller sits in front of each serving tier's request handling
(gateway /predict, model-server :predict).  Per request it applies, in
order: drain refusal, deadline-exhausted rejection, and the adaptive
concurrency limiter's bounded queue -- raising a typed Shed for the
transport to map to 503/504 + Retry-After -- and tracks the in-flight
count that graceful drain waits on.  All decisions land in the
``kdlt_admission_*`` series (utils.metrics.admission_metrics) under the
tier's label.

``enabled=False`` (or KDLT_ADMISSION=0) keeps the controller as a pure
in-flight tracker: no limiter, no deadline rejection -- the exact legacy
behavior, which is what bench.py --overload-ab's baseline arm measures --
but drain still works (shutdown semantics are not load policy).
"""

from __future__ import annotations

import os
import signal
import threading
import time

from kubernetes_deep_learning_tpu.serving.admission.deadline import Deadline
from kubernetes_deep_learning_tpu.serving.admission.limiter import AdaptiveLimiter
from kubernetes_deep_learning_tpu.serving.admission.shed import Shed
from kubernetes_deep_learning_tpu.serving.protocol import DEFAULT_PRIORITY
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

ADMISSION_ENV = "KDLT_ADMISSION"
DRAIN_TIMEOUT_ENV = "KDLT_DRAIN_TIMEOUT_S"
# Inside the k8s terminationGracePeriodSeconds (30 gateway / 60 model tier)
# minus the preStop sleep, so the drain always finishes before the kill.
DEFAULT_DRAIN_TIMEOUT_S = 25.0
DRAIN_RETRY_AFTER_S = 1.0  # "come back via a replica that is not dying"


def admission_enabled(explicit: bool | None = None) -> bool:
    """Explicit arg > $KDLT_ADMISSION > enabled-by-default."""
    if explicit is not None:
        return bool(explicit)
    raw = os.environ.get(ADMISSION_ENV, "").strip().lower()
    return raw not in ("0", "false", "off", "no")


# Observed-latency AIMD bands, as fractions of the deadline budget spent by
# the time the ticket is released.  Above CONGESTION the completion counts
# as a congestion signal even though it technically made it (the NEXT
# request one queue-slot further back will not); below HEADROOM it earns an
# additive increase; between the two the limit holds.  The hold band keeps
# the equilibrium below the everything-finishes-exactly-at-the-deadline
# regime.
LATENCY_CONGESTION_FRACTION = 0.5
LATENCY_HEADROOM_FRACTION = 0.25


class Ticket:
    """Proof of admission; must be released exactly once (finally block).

    ``mark_overloaded()`` before release feeds the limiter's multiplicative
    decrease: the handler observed downstream congestion (deadline miss,
    full batcher queue, upstream 503) while holding this slot.  A release
    that finds more than LATENCY_CONGESTION_FRACTION of the deadline budget
    spent is treated the same way.
    """

    __slots__ = (
        "_controller", "queue_wait_s", "_deadline", "_overloaded", "_released",
        "model", "_t0",
    )

    def __init__(
        self,
        controller: "AdmissionController",
        queue_wait_s: float,
        deadline: Deadline | None = None,
        model: str | None = None,
    ):
        self._controller = controller
        self.queue_wait_s = queue_wait_s
        self._deadline = deadline
        self._overloaded = False
        self._released = False
        self.model = model
        self._t0 = time.monotonic()

    def mark_overloaded(self) -> None:
        self._overloaded = True

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        overloaded = self._overloaded
        headroom = True
        if self._deadline is not None:
            spent_fraction = 1.0 - (
                self._deadline.remaining_s() / max(self._deadline.budget_s, 1e-9)
            )
            overloaded = overloaded or spent_fraction > LATENCY_CONGESTION_FRACTION
            headroom = spent_fraction < LATENCY_HEADROOM_FRACTION
        self._controller._release(
            self.queue_wait_s, overloaded, headroom,
            model=self.model, held_s=time.monotonic() - self._t0,
        )


class AdmissionController:
    def __init__(
        self,
        registry: metrics_lib.Registry,
        tier: str,
        enabled: bool | None = None,
        limiter: AdaptiveLimiter | None = None,
    ):
        self.tier = tier
        self.enabled = admission_enabled(enabled)
        self._limiter = (
            limiter if limiter is not None
            else (AdaptiveLimiter() if self.enabled else None)
        )
        self._tier_registry = registry.with_labels(tier=tier)
        self._m = metrics_lib.admission_metrics(self._tier_registry)
        # Per-priority-class admitted/shed (bounded `class` label, minted
        # centrally): which class pays for an overload is the question the
        # brownout gates and --tenant-ab read.
        self._class_m = metrics_lib.admission_class_metrics(self._tier_registry)
        # Per-model kdlt_admission_* slices (bounded `model` label, minted
        # centrally): lazily created per model name the handlers pass in.
        self._model_m: dict[str, dict] = {}  # guarded-by: _model_m_lock
        self._model_m_lock = threading.Lock()
        if self._limiter is not None:
            self._m["limit"].set(self._limiter.limit)
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight = 0           # guarded-by: _lock
        # Monotonic one-way flag (False -> True, never back): admit()
        # reads it lock-free; a request racing the flip is equivalently
        # ordered either way, so no lock is needed.
        self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def limit(self) -> float | None:
        return self._limiter.limit if self._limiter is not None else None

    @property
    def limiter(self) -> AdaptiveLimiter | None:
        return self._limiter

    def retry_after_s(self, fallback: float = 0.05) -> float:
        """A live Retry-After for sheds decided outside the limiter: the
        limiter's queue-depth/hold-time derivation (jittered) when one
        exists, else the caller's fallback."""
        if self._limiter is not None:
            return self._limiter.retry_after_s()
        return fallback

    def _model_metrics(self, model: str | None) -> dict | None:
        if model is None:
            return None
        with self._model_m_lock:
            mm = self._model_m.get(model)
            if mm is None:
                if len(self._model_m) >= 2 * metrics_lib.MODEL_LABEL_CAP:
                    # Memo cap: past it, unmemoized names go straight to
                    # the overflow bucket so a hostile stream of distinct
                    # names cannot grow this dict (the label itself is
                    # already capped by the central mint).
                    return metrics_lib.admission_model_metrics(
                        self._tier_registry, metrics_lib.MODEL_LABEL_OVERFLOW
                    )
                mm = metrics_lib.admission_model_metrics(
                    self._tier_registry, model
                )
                self._model_m[model] = mm
            return mm

    def admit(
        self,
        deadline: Deadline | None = None,
        model: str | None = None,
        priority: str = DEFAULT_PRIORITY,
    ) -> Ticket:
        """Admit or raise Shed.  Order: drain, deadline, concurrency.

        ``model`` attributes the decision to the per-model
        kdlt_admission_* slice (the bounded ``model`` label) AND keys the
        limiter's per-model budget; callers pass it once routing has
        resolved a REGISTERED model name, which is what keeps the label's
        value set bounded by the model registry.  ``priority`` (a
        protocol.PRIORITY_CLASSES member, already normalized by
        parse_priority) orders queue grants and eviction: the lowest class
        sheds first.
        """
        mm = self._model_metrics(model)
        self._m["requests"].inc()
        if mm is not None:
            mm["requests"].inc()
        if self._draining:
            self._shed(Shed(
                "draining", 503, retry_after_s=DRAIN_RETRY_AFTER_S,
                detail=f"{self.tier} is draining for shutdown",
            ), priority=priority)
        if self.enabled and deadline is not None and deadline.expired:
            self._shed(Shed(
                "deadline_exhausted", 504,
                detail=(
                    f"deadline budget exhausted before execution "
                    f"({deadline.budget_s * 1e3:.0f}ms budget)"
                ),
            ), priority=priority)
        queue_wait = 0.0
        if self._limiter is not None:
            budget = deadline.remaining_s() if deadline is not None else None
            try:
                queue_wait = self._limiter.acquire(
                    budget, model=model, priority=priority
                )
            except Shed as e:
                self._shed(e, priority=priority)
            self._m["limit"].set(self._limiter.limit)
        self._m["queue_wait"].observe(queue_wait)
        if deadline is not None:
            self._m["deadline_remaining_ms"].observe(max(deadline.remaining_ms(), 0.0))
        self._m["admitted"].inc()
        if mm is not None:
            mm["admitted"].inc()
        cm = self._class_m.get(priority)
        if cm is not None:
            cm["admitted"].inc()
        with self._lock:
            self._inflight += 1
            self._m["inflight"].set(float(self._inflight))
        return Ticket(
            self, queue_wait, deadline if self.enabled else None, model=model
        )

    def _shed(self, e: Shed, priority: str | None = None) -> None:
        counter = self._m["shed"].get(e.reason)
        if counter is not None:
            counter.inc()
        if priority is not None:
            cm = self._class_m.get(priority)
            if cm is not None:
                cm["shed"].inc()
        raise e

    def count_shed(self, reason: str, priority: str | None = None) -> None:
        """Record a shed decided OUTSIDE admit() (e.g. the gateway's circuit
        breaker refusing the upstream call mid-request, or a brownout class
        shed ahead of admission)."""
        counter = self._m["shed"].get(reason)
        if counter is not None:
            counter.inc()
        if priority is not None:
            cm = self._class_m.get(priority)
            if cm is not None:
                cm["shed"].inc()

    def class_stats(self) -> dict:
        """Per-priority-class admitted/shed counts (the /debug/brownout and
        kdlt-client --stats surface)."""
        return {
            cls: {
                "admitted": m["admitted"].value,
                "shed": m["shed"].value,
            }
            for cls, m in self._class_m.items()
        }

    def count_coalesced(self, model: str | None = None) -> None:
        """Record a cache-coalesced singleflight follower: admitted-but-
        not-dispatched.  It IS served (through the leader's flight), so it
        counts as seen + admitted -- but it consumes no limiter slot and
        no in-flight ledger entry, because exactly one request (the
        leader) holds real gateway capacity for the whole flight.
        kdlt_cache_coalesced_total carries the distinction."""
        mm = self._model_metrics(model)
        self._m["requests"].inc()
        self._m["admitted"].inc()
        if mm is not None:
            mm["requests"].inc()
            mm["admitted"].inc()

    def _release(
        self,
        queue_wait_s: float,
        overloaded: bool,
        headroom: bool,
        model: str | None = None,
        held_s: float | None = None,
    ) -> None:
        if self._limiter is not None:
            self._limiter.release(
                queue_wait_s, overloaded=overloaded, headroom=headroom,
                model=model, held_s=held_s,
            )
            self._m["limit"].set(self._limiter.limit)
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            self._m["inflight"].set(float(self._inflight))
            self._idle.notify_all()

    # --- graceful drain -----------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admitting (every new request sheds "draining", /readyz goes
        503 so the endpoint pool stops routing here); in-flight work keeps
        running to completion."""
        self._draining = True
        self._m["draining"].set(1.0)

    def wait_idle(self, timeout_s: float | None = None) -> bool:
        """Block until every admitted request has released (True) or the
        timeout passes (False)."""
        if timeout_s is None:
            timeout_s = drain_timeout_s()
        giveup = time.monotonic() + timeout_s
        with self._lock:
            while self._inflight > 0:
                remaining = giveup - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True


def drain_timeout_s() -> float:
    raw = os.environ.get(DRAIN_TIMEOUT_ENV, "")
    try:
        return float(raw) if raw.strip() else DEFAULT_DRAIN_TIMEOUT_S
    except ValueError:
        return DEFAULT_DRAIN_TIMEOUT_S


def install_sigterm_drain(controller: AdmissionController, stop, timeout_s=None):
    """SIGTERM -> graceful drain -> ``stop()``.

    The handler flips drain immediately (readiness fails, admission sheds)
    and hands the bounded wait-for-idle plus the final ``stop()`` (e.g.
    httpd shutdown) to a daemon thread -- signal handlers run between
    bytecodes of the serve_forever thread and must not block there.  Pairs
    with the k8s manifests' terminationGracePeriodSeconds/preStop settings:
    kubelet sends SIGTERM after preStop, and the drain budget
    ($KDLT_DRAIN_TIMEOUT_S, default 25 s) fits inside the grace period.
    """

    def _finish():
        controller.wait_idle(timeout_s)
        stop()

    def _handler(signum, frame):  # noqa: ARG001 - signal signature
        controller.begin_drain()
        threading.Thread(target=_finish, name="kdlt-drain", daemon=True).start()

    signal.signal(signal.SIGTERM, _handler)
