"""Cross-tier request tracing: one id from client edge to model server.

The reference has no tracing at all (SURVEY.md section 5: the only latency
control is a 20 s deadline).  Here every request carries an ``X-Request-Id``:
the gateway accepts a client-supplied id or mints one, forwards it to the
model tier on the upstream call (HTTP header / gRPC metadata), and both
tiers echo it in the response and stamp it on their log lines -- so one
``kubectl logs`` grep over both pods reconstructs a request's path.

The request id doubles as the Dapper-style **trace id** (utils.trace): each
tier records per-request spans keyed by it, the active span id crosses the
tier boundary in ``X-Kdlt-Parent-Span``, and ``/debug/trace/<rid>`` serves
the waterfall.  This module re-exports the propagation constants so serving
code has one import site for the whole trace surface.

Ids are sanitized to a conservative charset before logging or forwarding:
a client-chosen id must not be able to inject log lines or header structure.

``KDLT_LOG_FORMAT=json`` switches log_request to one JSON object per line
(machine-parseable structured logs for k8s log pipelines); the default
stays the human ``[rid=...]`` format.
"""

from __future__ import annotations

import json
import os
import re
import time
import uuid

from kubernetes_deep_learning_tpu.utils.trace import (  # noqa: F401 - re-exports
    GRPC_PARENT_SPAN_KEY,
    PARENT_SPAN_HEADER,
    TRACE_HEADER,
    ensure_span_id,
)

REQUEST_ID_HEADER = "X-Request-Id"
GRPC_METADATA_KEY = "x-request-id"  # gRPC metadata keys are lowercase

LOG_FORMAT_ENV = "KDLT_LOG_FORMAT"

_RID_SAFE_RE = re.compile(r"[^A-Za-z0-9_.\-]")


def ensure_request_id(raw: str | None) -> str:
    """Sanitized client-supplied id, or a fresh 16-hex-char one."""
    if raw:
        rid = _RID_SAFE_RE.sub("", raw)[:64]
        if rid:
            return rid
    return uuid.uuid4().hex[:16]


def log_json() -> bool:
    return os.environ.get(LOG_FORMAT_ENV, "").strip().lower() == "json"


def log_request(
    tier: str,
    rid: str,
    *,
    status: int | str,
    t0: float,
    span_id: str | None = None,
    **fields,
) -> None:
    """One stdout line per request, kubectl-logs-greppable by rid.

    ``fields`` are extra key=value pairs (model name, batch size, ...).
    Values are str()'d in the default format; callers pass only values
    they control.  With ``KDLT_LOG_FORMAT=json`` the line is one JSON
    object carrying the same data plus the trace/span ids, so a log
    pipeline can join log lines to ``/debug/trace/<rid>`` waterfalls
    without parsing the human format.
    """
    dur_ms = (time.perf_counter() - t0) * 1e3
    if log_json():
        rec = {
            "rid": rid,
            "trace_id": rid,  # the request id IS the trace id
            "tier": tier,
            "status": status,
            "dur_ms": round(dur_ms, 1),
        }
        if span_id:
            rec["span_id"] = span_id
        rec.update(fields)
        print(json.dumps(rec, default=str), flush=True)
        return
    extra = "".join(f" {k}={v}" for k, v in fields.items())
    print(f"[rid={rid}] {tier} status={status} dur_ms={dur_ms:.1f}{extra}", flush=True)
