"""Cross-tier request tracing: one id from client edge to model server.

The reference has no tracing at all (SURVEY.md section 5: the only latency
control is a 20 s deadline).  Here every request carries an ``X-Request-Id``:
the gateway accepts a client-supplied id or mints one, forwards it to the
model tier on the upstream call (HTTP header / gRPC metadata), and both
tiers echo it in the response and stamp it on their log lines -- so one
``kubectl logs`` grep over both pods reconstructs a request's path.

Ids are sanitized to a conservative charset before logging or forwarding:
a client-chosen id must not be able to inject log lines or header structure.
"""

from __future__ import annotations

import re
import time
import uuid

REQUEST_ID_HEADER = "X-Request-Id"
GRPC_METADATA_KEY = "x-request-id"  # gRPC metadata keys are lowercase

_RID_SAFE_RE = re.compile(r"[^A-Za-z0-9_.\-]")


def ensure_request_id(raw: str | None) -> str:
    """Sanitized client-supplied id, or a fresh 16-hex-char one."""
    if raw:
        rid = _RID_SAFE_RE.sub("", raw)[:64]
        if rid:
            return rid
    return uuid.uuid4().hex[:16]


def log_request(
    tier: str, rid: str, *, status: int | str, t0: float, **fields
) -> None:
    """One stdout line per request, kubectl-logs-greppable by rid.

    ``fields`` are extra key=value pairs (model name, batch size, ...).
    Values are str()'d; callers pass only values they control.
    """
    extra = "".join(f" {k}={v}" for k, v in fields.items())
    dur_ms = (time.perf_counter() - t0) * 1e3
    print(f"[rid={rid}] {tier} status={status} dur_ms={dur_ms:.1f}{extra}", flush=True)
