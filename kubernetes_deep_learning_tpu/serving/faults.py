"""Deterministic fault injection for the serving hot path.

The reference (and PRs 0-2 here) can only *hope* the serving path recovers
from failure: nothing in the system can deliberately break a component, so
"failover works" was an untested belief.  Chaos-engineering practice
(Basiri et al., "Chaos Engineering", IEEE Software '16) says recovery code
that is never exercised is broken by default; this module makes breaking a
component a one-env-var operation, deterministic enough to assert on in
tests and the bench.py --chaos-ab harness.

Configuration: ``KDLT_FAULTS=point:kind:rate[:arg][,point:kind:rate[:arg]]``
with ``KDLT_FAULTS_SEED`` (default 0) seeding the per-(point, kind) random
streams, so the exact same request sequence sees the exact same faults on
every run regardless of thread interleaving across points.

Fault points are the ``FAULT_POINTS`` registry below -- the closed
vocabulary of names compiled into the serving path (the fault matrix,
GUIDE.md section 10e):

==================  =====================================================
point               where it fires
==================  =====================================================
``gateway.upstream``  the gateway's upstream POST to a model-tier replica
                      (before the socket is touched; an injected error is
                      indistinguishable from a dead replica)
``server.predict``    the model server's /predict handler, after routing
                      and admission (corrupt applies to the response bytes)
``dispatch.submit``   InFlightDispatcher.submit, before predict_async
``dispatch.complete`` the dispatcher's completion thread, before the
                      blocking device sync (a ``hang`` here is a wedged
                      device handle -- the watchdog's prey)
``grpc.predict``      the gRPC PredictionService unary shell
``crosshost.broadcast`` the cross-host input broadcast, before the
                      collective is issued
``crosshost.collective`` the cross-host collective compute step
==================  =====================================================

Kinds:

- ``error``      raise :class:`InjectedFault` (a server-side 5xx-shaped
                 failure, never a client 400)
- ``latency``    sleep ``arg`` milliseconds (default 100)
- ``hang``       sleep ``arg`` SECONDS (default 300) -- a wedged component,
                 not a slow one; pair with the dispatcher watchdog
- ``disconnect`` raise :class:`InjectedDisconnect` (a ConnectionError; HTTP
                 handlers translate it into an abrupt socket close with no
                 response bytes)
- ``corrupt``    garble the payload handed to :meth:`FaultInjector.corrupt`
                 (response-body corruption; decoders must fail loudly)

Inertness contract: when ``KDLT_FAULTS`` is unset/empty, :func:`from_env`
returns ``None`` and every call site is a single ``is not None`` check --
the production hot path pays nothing.  Components each build their OWN
injector at construction time (no process-global mutable state), so tests
can run faulted and clean servers side by side in one process.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
import zlib

FAULTS_ENV = "KDLT_FAULTS"
SEED_ENV = "KDLT_FAULTS_SEED"

KINDS = ("error", "latency", "hang", "disconnect", "corrupt")

# The closed vocabulary of fault points (see the module docstring's matrix
# for where each fires).  Production ``fire()``/``corrupt()`` call sites
# use these exact strings; kdlt-lint's closed-vocab pass enforces
# membership statically, so a chaos experiment against a typo'd point
# cannot silently "pass" by testing nothing.  parse_rules itself stays
# permissive (tests inject at synthetic points).
FAULT_POINTS = frozenset({
    "gateway.upstream",
    "server.predict",
    "dispatch.submit",
    "dispatch.complete",
    "grpc.predict",
    "crosshost.broadcast",
    "crosshost.collective",
})

DEFAULT_LATENCY_MS = 100.0
DEFAULT_HANG_S = 300.0


class InjectedFault(RuntimeError):
    """A deliberately injected component failure (server-fault-shaped)."""


class InjectedDisconnect(ConnectionError):
    """A deliberately injected abrupt connection loss."""


@dataclasses.dataclass(frozen=True)
class FaultRule:
    point: str
    kind: str
    rate: float       # firing probability per arrival at the point, [0, 1]
    arg: float | None  # latency: ms; hang: seconds; others: unused


def parse_rules(spec: str) -> tuple[FaultRule, ...]:
    """``point:kind:rate[:arg]``, comma-separated -> validated rules.

    Raises ValueError on malformed entries: a typo'd chaos experiment must
    fail the boot loudly, not silently run the healthy configuration and
    "pass" the recovery test.
    """
    rules = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"fault entry {entry!r} is not point:kind:rate[:arg]"
            )
        point, kind, rate_s = parts[0], parts[1], parts[2]
        if not point:
            raise ValueError(f"fault entry {entry!r} has an empty point")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (one of {KINDS})")
        rate = float(rate_s)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"fault rate {rate!r} outside [0, 1] in {entry!r}")
        arg = float(parts[3]) if len(parts) == 4 else None
        rules.append(FaultRule(point, kind, rate, arg))
    return tuple(rules)


class FaultInjector:
    """Applies configured fault rules at named points, deterministically.

    Each (point, kind) pair draws from its own seeded random stream, so
    which arrivals fault depends only on (seed, point, kind, arrival
    index at that point) -- never on thread scheduling across points.
    """

    def __init__(self, rules: tuple[FaultRule, ...], seed: int = 0):
        self.rules = rules
        self.seed = seed
        self._by_point: dict[str, list[FaultRule]] = {}
        for r in rules:
            self._by_point.setdefault(r.point, []).append(r)
        self._rngs = {
            (r.point, r.kind): random.Random(
                zlib.crc32(f"{seed}/{r.point}/{r.kind}".encode())
            )
            for r in rules
        }
        self.counts: dict[tuple[str, str], int] = {
            (r.point, r.kind): 0 for r in rules
        }
        self._lock = threading.Lock()
        # kdlt_fault_injected_total{point,kind} counters per attached
        # registry, pre-created at attach so the series are visible at 0.
        self._counters: list[dict[tuple[str, str], object]] = []

    @classmethod
    def from_env(cls, environ=None) -> "FaultInjector | None":
        """The env-configured injector, or None (the inert fast path)."""
        environ = os.environ if environ is None else environ
        spec = environ.get(FAULTS_ENV, "").strip()
        if not spec:
            return None
        raw_seed = environ.get(SEED_ENV, "").strip()
        try:
            seed = int(raw_seed) if raw_seed else 0
        except ValueError:
            seed = 0
        rules = parse_rules(spec)
        return cls(rules, seed=seed) if rules else None

    def attach(self, registry) -> None:
        """Export kdlt_fault_injected_total{point,kind} on ``registry``."""
        counters = {
            (r.point, r.kind): registry.with_labels(
                point=r.point, kind=r.kind
            ).counter(
                "kdlt_fault_injected_total",
                "faults injected by the KDLT_FAULTS framework",
            )
            for r in self.rules
        }
        with self._lock:
            self._counters.append(counters)

    def _roll(self, rule: FaultRule) -> bool:
        with self._lock:
            fired = self._rngs[(rule.point, rule.kind)].random() < rule.rate
            if fired:
                self.counts[(rule.point, rule.kind)] += 1
                for counters in self._counters:
                    counters[(rule.point, rule.kind)].inc()
        return fired

    def fire(self, point: str) -> None:
        """Apply the control-flow kinds configured at ``point`` (in rule
        order): latency/hang sleep on the calling thread, error/disconnect
        raise.  ``corrupt`` rules are ignored here (see :meth:`corrupt`)."""
        for rule in self._by_point.get(point, ()):
            if rule.kind == "corrupt" or not self._roll(rule):
                continue
            if rule.kind == "latency":
                time.sleep((rule.arg if rule.arg is not None else DEFAULT_LATENCY_MS) / 1e3)
            elif rule.kind == "hang":
                time.sleep(rule.arg if rule.arg is not None else DEFAULT_HANG_S)
            elif rule.kind == "error":
                raise InjectedFault(f"injected fault at {point}")
            elif rule.kind == "disconnect":
                raise InjectedDisconnect(f"injected disconnect at {point}")

    def corrupt(self, point: str, data: bytes) -> bytes:
        """Apply any firing ``corrupt`` rule at ``point`` to ``data``.

        Garbles a prefix (XOR) so decoders fail structurally instead of
        returning shifted-but-plausible values -- a corrupt response must
        surface as a loud 502-class decode error, never silent bad data.
        """
        for rule in self._by_point.get(point, ()):
            if rule.kind == "corrupt" and self._roll(rule):
                head = bytes(b ^ 0x5A for b in data[:64])
                return head + data[64:]
        return data


def from_env(environ=None) -> FaultInjector | None:
    """Module-level convenience mirror of FaultInjector.from_env."""
    return FaultInjector.from_env(environ)
