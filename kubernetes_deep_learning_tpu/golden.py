"""Golden-logit verification: the reference's correctness baseline as a tool.

The reference's only correctness artifact is a human-checked score dict for
one test image ("score for pants is the highest", reference guide.md:623-629)
-- the expected logits below are transcribed from reference guide.md:623-625
(see BASELINE.md).  This CLI makes that check executable: given the
transfer-learned Keras weights (``xception_v4_large_08_0.894.h5``, obtained
out-of-band per reference guide.md:176 -- this environment has no egress) and
the pants test image, it imports the weights, runs the in-tree engine, and
asserts every logit within tolerance.

Run against a live stack instead with ``--gateway`` to check the full
HTTP path (gateway -> model server) rather than the engine in-process.

CLI::

    kdlt-verify-golden --weights xception_v4_large_08_0.894.h5 --image pants.jpg
    kdlt-verify-golden --image pants.jpg --gateway http://localhost:9696 --image-url <url>
"""

from __future__ import annotations

import argparse
import sys

# Transcribed from reference guide.md:623-625 (and BASELINE.md).
GOLDEN_LOGITS = {
    "dress": -1.868,
    "hat": -4.761,
    "longsleeve": -2.316,
    "outwear": -1.062,
    "pants": 9.887,
    "shirt": -2.812,
    "shoes": -3.666,
    "shorts": 3.200,
    "skirt": -2.602,
    "t-shirt": -4.835,
}


def check_scores(scores: dict, atol: float) -> list[str]:
    """Compare a {label: logit} dict to the golden values; return failures."""
    failures = []
    for label, want in GOLDEN_LOGITS.items():
        got = scores.get(label)
        if got is None:
            failures.append(f"{label}: missing from response")
        elif abs(got - want) > atol:
            failures.append(f"{label}: got {got:.3f}, want {want:.3f} (atol {atol})")
    top = max(scores, key=scores.get) if scores else None
    if top != "pants":
        failures.append(f"top-1 is {top!r}, want 'pants' (reference guide.md:628)")
    return failures


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description="verify the reference golden logits")
    p.add_argument("--image", help="local path to the pants test image")
    p.add_argument("--weights", help="Keras .h5 weights (engine-level check)")
    p.add_argument("--gateway", help="gateway URL (full-stack check instead)")
    p.add_argument("--image-url", help="image URL for the gateway check")
    p.add_argument("--atol", type=float, default=0.05,
                   help="per-logit absolute tolerance (bf16 serving: try 0.2)")
    p.add_argument("--served-atol", type=float, default=0.2,
                   help="tolerance for the served-configuration check "
                        "(bf16 + fused fast path where available)")
    p.add_argument("--skip-served", action="store_true",
                   help="only check the exact f32 flax graph (round-2 behavior)")
    p.add_argument("--platform", default=None, help="jax platform override")
    args = p.parse_args(argv)

    if args.gateway:
        if not args.image_url:
            p.error("--gateway needs --image-url")
        from kubernetes_deep_learning_tpu.serving.client import predict_url

        scores = predict_url(args.gateway, args.image_url)
    else:
        if not (args.weights and args.image):
            p.error("engine check needs --weights and --image")
        from kubernetes_deep_learning_tpu.utils.platform import force_platform

        force_platform(args.platform)

        from kubernetes_deep_learning_tpu.export import artifact as art
        from kubernetes_deep_learning_tpu.modelspec import get_spec
        from kubernetes_deep_learning_tpu.models.keras_import import load_keras_h5
        from kubernetes_deep_learning_tpu.ops import preprocess
        from kubernetes_deep_learning_tpu.runtime.engine import InferenceEngine

        spec = get_spec("clothing-model")
        variables = load_keras_h5(spec, args.weights)
        with open(args.image, "rb") as f:
            image = preprocess.preprocess_bytes(
                f.read(), spec.input_shape[:2], filter=spec.resize_filter
            )
        artifact = art.ModelArtifact(
            spec, variables, None, {"compute_dtype": "float32"}, path="<in-memory>/1"
        )
        # fast=False: golden parity checks the exact flax graph first (the
        # reference-parity gate proper)...
        engine = InferenceEngine(
            artifact, buckets=(1,), use_exported=False, fast=False
        )
        scores = engine.predict_scores(image[None])[0]

    print("scores:", {k: round(v, 3) for k, v in sorted(scores.items())})
    failures = check_scores(scores, args.atol)
    if failures:
        for f in failures:
            print("FAIL", f, file=sys.stderr)
        return 1
    print(f"OK: all {len(GOLDEN_LOGITS)} logits within atol={args.atol}, top-1 pants")

    if not args.gateway and not args.skip_served:
        # ...and then the configuration actually SERVED: bf16 compute with
        # fast="auto", which on TPU is the fused Pallas path.  Without this
        # the numeric gate never exercises the program serving runs
        # (ADVICE r2: engine.prefer_live serves the fused path while golden
        # pinned fast=False), so real-weight drift on the fast path went
        # unvalidated.
        served = InferenceEngine(
            art.ModelArtifact(
                spec, variables, None,
                {"compute_dtype": "bfloat16"}, path="<in-memory>/1",
            ),
            buckets=(1,), use_exported=False, fast="auto",
        )
        served_scores = served.predict_scores(image[None])[0]
        print(
            "served-config scores:",
            {k: round(v, 3) for k, v in sorted(served_scores.items())},
        )
        served_failures = check_scores(served_scores, args.served_atol)
        if served_failures:
            for f in served_failures:
                print("FAIL (served config)", f, file=sys.stderr)
            return 1
        print(
            f"OK: served config (bf16, fast=auto) within atol={args.served_atol}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
