"""ModelSpec: the single source of truth for a served model.

The reference system splits the model contract across four places that must be
kept in sync by hand: the exporter output inspected with ``saved_model_cli``
(reference guide.md:199-236), hardcoded tensor/signature names in the gateway
(reference model_server.py:40-47), a hardcoded label list
(reference model_server.py:21-32), and a hardcoded preprocessor config
(reference model_server.py:18).  Here all of that lives in one dataclass that
the exporter, model server, and gateway all consume.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Everything needed to export, serve, and query one model."""

    name: str                       # served model name, e.g. "clothing-model"
    family: str                     # architecture family key in models.registry
    input_shape: tuple[int, int, int]   # (H, W, C), batch dim excluded
    labels: tuple[str, ...]         # output class labels, index-aligned
    preprocessing: str = "tf"       # "tf" | "caffe" | "torch" | "none"
    resize_filter: str = "bilinear"  # "bilinear" | "nearest" (host resize filter)
    input_dtype: str = "uint8"      # wire dtype gateway -> server (normalize on device)
    input_name: str = "image"       # request tensor key
    output_name: str = "scores"     # response tensor key
    head_hidden: tuple[int, ...] = ()   # hidden Dense sizes between pool and logits
    description: str = ""
    # Legacy tensor names from the reference's SavedModel signature
    # (reference guide.md:220-231: input_8/dense_7), accepted/emitted by the
    # gRPC PredictionService frontend so reference-era gRPC clients
    # (reference model_server.py:35-49) work against this server unmodified.
    compat_input_name: str = ""
    compat_output_name: str = ""

    @property
    def num_classes(self) -> int:
        return len(self.labels)

    @property
    def batched_shape(self) -> tuple[int, ...]:
        return (-1, *self.input_shape)

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        return json.dumps(d, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "ModelSpec":
        d: dict[str, Any] = json.loads(s)
        d["input_shape"] = tuple(d["input_shape"])
        d["labels"] = tuple(d["labels"])
        d["head_hidden"] = tuple(d.get("head_hidden", ()))
        return cls(**d)


_REGISTRY: dict[str, ModelSpec] = {}


def register_spec(spec: ModelSpec) -> ModelSpec:
    _REGISTRY[spec.name] = spec
    return spec


def get_spec(name: str) -> ModelSpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown model spec {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


def list_specs() -> list[str]:
    return sorted(_REGISTRY)


# The flagship model: the reference's 10-class clothing classifier
# (labels from reference model_server.py:21-32, input contract from
# reference guide.md:220-231: (-1, 299, 299, 3) f32 -> (-1, 10) f32).
# head_hidden=(100,) mirrors the bookcamp transfer-learning head that
# produced xception_v4_large_08_0.894.h5 (reference guide.md:176).
CLOTHING_MODEL = register_spec(
    ModelSpec(
        name="clothing-model",
        family="xception",
        input_shape=(299, 299, 3),
        labels=(
            "dress",
            "hat",
            "longsleeve",
            "outwear",
            "pants",
            "shirt",
            "shoes",
            "shorts",
            "skirt",
            "t-shirt",
        ),
        preprocessing="tf",
        # keras-image-helper (the reference gateway's preprocessor,
        # reference model_server.py:18) resizes with NEAREST; match it so the
        # reference's expected logits (guide.md:623-625) reproduce exactly.
        resize_filter="nearest",
        head_hidden=(100,),
        description="Xception clothing classifier (reference flagship model)",
        compat_input_name="input_8",
        compat_output_name="dense_7",
    )
)

_IMAGENET_LABELS = tuple(f"class_{i}" for i in range(1000))

# BASELINE.json config 3: ResNet50/ImageNet served via the same gateway path.
RESNET50_IMAGENET = register_spec(
    ModelSpec(
        name="resnet50-imagenet",
        family="resnet50",
        input_shape=(224, 224, 3),
        labels=_IMAGENET_LABELS,
        preprocessing="caffe",
        description="ResNet50 ImageNet classifier",
    )
)

# BASELINE.json config 4: EfficientNet-B3 with server-side dynamic batching.
EFFICIENTNET_B3_IMAGENET = register_spec(
    ModelSpec(
        name="efficientnet-b3-imagenet",
        family="efficientnet-b3",
        input_shape=(300, 300, 3),
        labels=_IMAGENET_LABELS,
        preprocessing="torch",
        description="EfficientNet-B3 ImageNet classifier",
    )
)

# Transformer classifier: the serving-path consumer of the in-tree flash
# attention kernel (ops.attention) -- 256x256/16 gives a 256-token sequence,
# an exact multiple of the kernel's 128-wide MXU tiles.  Inception-style
# [-1, 1] scaling per the original ViT recipe.
VIT_B16_IMAGENET = register_spec(
    ModelSpec(
        name="vit-b16-imagenet",
        family="vit-b16",
        input_shape=(256, 256, 3),
        labels=_IMAGENET_LABELS,
        preprocessing="tf",
        description="ViT-B/16 ImageNet classifier (Pallas flash attention)",
    )
)
