"""Fused Xception entry segment: conv2 + block2 in one Pallas kernel.

The entry flow is the fast path's remaining bottleneck (BENCH.md round 2:
~30 ms of the batch-256 forward; 4.4 ms of the 16.6 ms batch-64 forward,
running at only 10-28% MFU in XLA's fusions).  This kernel fuses the
segment the trace attributes most of that to:

    block1_conv2 3x3 VALID (C_IN->C_B) + BN + relu
    block2 residual 1x1 stride-2 conv + BN
    block2 sepconv1 (C_B->C_OUT) + BN + relu
    block2 sepconv2 (C_OUT->C_OUT) + BN
    maxpool 3x3/2 SAME + residual add

so the 147x147 intermediates (2.8-5.5 MB/image each) never round-trip
through HBM.  Reference analog: the whole entry flow happens inside the
TF-Serving binary's fused GPU graph (reference tf-serving.dockerfile:1);
here the hot segment is the framework's own kernel.

Design (same layout discipline as ops.fused_sepconv, see the round-2
lessons there):

- Layout (rows, W, bt, C): batch on sublanes, channels on lanes -- the
  layout XLA itself picks for these tensors.  Depthwise shifts and
  stride-2 selections move only along untiled outer dims.
- conv2 as in-kernel im2col: 9 lane-concatenated shifted slices make one
  (M, 9*C_IN) @ (9*C_IN, C_B) GEMM -- 9 accumulated K=32 GEMMs would
  waste 3/4 of every MXU pass.
- Spatial tiling with halos: output rows are tiled by ``rt``; overlapping
  input windows are not expressible in BlockSpec units, so the input is
  pre-gathered into per-tile slabs in XLA-land (~20-35% extra *input*
  traffic depending on rt -- input is the smallest tensor in the segment,
  so this trade wins over manual DMA complexity).
- Row-validity masks re-zero rows the BN affines contaminate in the halo
  region, and invalid rows are sent to -1e9 before the max-pool so they
  cannot win a window.

Geometry is parameterized (h_in, c_in, c_b, c_out) so tests exercise the
same code at small shapes in interpret mode; serving uses the Xception
numbers (149, 32, 64, 128).
"""

from __future__ import annotations

import functools

from kubernetes_deep_learning_tpu.ops.fused_sepconv import _legal_bt


@functools.cache
def _entry_compiler_params():
    from jax.experimental.pallas import tpu as pltpu

    params_cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    # The physical cap is 128 MiB on v5e; rt=13/bt=8 at the Xception shape
    # peaks just under 110 MiB.
    return params_cls(vmem_limit_bytes=110 * 1024 * 1024)


def entry_block_reference(a, w):
    """Plain-jnp semantics, NHWC (B, h, h, c_in) -> (B, h_out, h_out, c_out).

    Mirrors models.xception's conv2+block2 ops with BN folded to f32
    affines (the kernel's numerics); used by tests and as documentation of
    the contract.
    """
    import jax
    import jax.numpy as jnp

    def conv(x, k, stride=1, padding="VALID", fgc=1):
        return jax.lax.conv_general_dilated(
            x, k.astype(x.dtype), (stride, stride), padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=fgc,
        )

    c_b = w["conv2"].shape[-1]
    c_out = w["pw1"].shape[-1]
    b = conv(a, w["conv2"])
    b = jnp.maximum(
        b.astype(jnp.float32) * w["conv2_s"] + w["conv2_b"], 0
    ).astype(jnp.bfloat16)
    r = jnp.einsum("bhwc,cd->bhwd", b[:, ::2, ::2, :], w["res"].astype(jnp.bfloat16))
    r = (r.astype(jnp.float32) * w["res_s"] + w["res_b"]).astype(jnp.bfloat16)
    c = conv(b, w["dw1"][:, :, None, :].astype(jnp.bfloat16), padding="SAME", fgc=c_b)
    c = jnp.einsum("bhwc,cd->bhwd", c, w["pw1"].astype(jnp.bfloat16))
    c = jnp.maximum(
        c.astype(jnp.float32) * w["bn1_s"] + w["bn1_b"], 0
    ).astype(jnp.bfloat16)
    d = conv(c, w["dw2"][:, :, None, :].astype(jnp.bfloat16), padding="SAME", fgc=c_out)
    d = jnp.einsum("bhwc,cd->bhwd", d, w["pw2"].astype(jnp.bfloat16))
    d = (d.astype(jnp.float32) * w["bn2_s"] + w["bn2_b"]).astype(jnp.bfloat16)
    pooled = jax.lax.reduce_window(
        d, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    return pooled + r


def fused_entry_block_t(a_t, w, *, bt: int = 8, rt: int = 13, interpret: bool = False):
    """The kernel, on (h_in, h_in, B, c_in) bf16; returns (h_out, h_out, B, c_out).

    ``w`` is a dict of f32 weights: conv2 (3,3,c_in,c_b), res (c_b,c_out),
    dw1 (3,3,c_b), pw1 (c_b,c_out), dw2 (3,3,c_out), pw2 (c_out,c_out),
    plus folded-BN affine pairs conv2_s/conv2_b, res_s/res_b, bn1_s/bn1_b,
    bn2_s/bn2_b (see ops.fused_sepconv.fold_bn).

    B must be a multiple of 8 (callers pad, as for the sepconv kernels);
    ``rt`` is output rows per grid step (13 measured best at batch 64 --
    fewer tiles means less halo re-read, larger tiles blow scoped VMEM).

    The overlapping input row windows are staged as a SINGLE row-gather
    (one XLA op): the round-2 prototype stacked per-tile slices, which XLA
    compiled to six ~0.24 ms staging fusions (~1.7 ms total at batch 64,
    more than the kernel saved).  Manual HBM->VMEM DMA would avoid staging
    entirely but is impossible here: Mosaic requires sliced-DMA lane dims
    to be 128-aligned and the input has 32 channels (probed on v5e,
    "Slice shape along dimension 3 must be aligned to tiling (128)").
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    h_in, _, B, c_in = a_t.shape
    c_b = w["conv2"].shape[-1]
    c_out = w["pw1"].shape[-1]
    h_b = h_in - 2           # conv2 VALID
    h_out = -(-h_b // 2)     # pool stride 2 SAME
    assert B % 8 == 0, f"pad the batch to a multiple of 8 first (got {B})"
    bt = _legal_bt(bt, B)
    n_tiles = -(-h_out // rt)
    nb = B // bt
    ht_b = 2 * rt + 5        # b rows a tile needs (pool +-1, two dws +-1 each)
    ht_a = ht_b + 2          # conv2 VALID consumes 2 more
    # Top pad 3 (tile g starts at global a row 2*rt*g - 3), bottom pad to
    # cover the last slab.  No W pad: conv2's VALID column reach tops out
    # at h_in - 1.
    bottom = max(0, 2 * rt * (n_tiles - 1) + ht_a - (h_in + 3))
    a_pad = jnp.pad(a_t, ((3, bottom), (0, 0), (0, 0), (0, 0)))
    wp = h_in

    def compute_tile(a, g_r, refs, o_ref):
        """The fused segment for one (row-tile, batch-tile) step.
        ``a``: (ht_a, wp, bt, c_in) bf16 value; writes o_ref[0]."""
        (cv_ref, cvs_ref, cvb_ref, res_ref, ress_ref, resb_ref,
         dw1_ref, pw1_ref, s1_ref, b1_ref, dw2_ref, pw2_ref, s2_ref,
         b2_ref) = refs

        # --- conv2 3x3 VALID: im2col on lanes -> ONE K=9*c_in GEMM --------
        patches = jnp.concatenate(
            [
                a[dh : dh + ht_b, dwc : dwc + h_b, :, :]
                for dh in range(3)
                for dwc in range(3)
            ],
            axis=-1,
        )  # (ht_b, h_b, bt, 9*c_in), taps (dh, dwc)-major like cv's reshape
        z = jax.lax.dot_general(
            patches.reshape(ht_b * h_b * bt, 9 * c_in),
            cv_ref[...].reshape(9 * c_in, c_b).astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        b = jnp.maximum(z * cvs_ref[...] + cvb_ref[...], 0).astype(
            jnp.bfloat16
        ).reshape(ht_b, h_b, bt, c_b)

        # Validity of local b rows (global row = 2*rt*g - 3 + L).  Masks
        # carry full (bt, C) extent: Mosaic cannot broadcast one value over
        # sublanes AND lanes at once; int compares only (no bf16 compare).
        row0_b = 2 * rt * g_r - 3

        def row_mask(c):
            rows = (
                jax.lax.broadcasted_iota(jnp.int32, (ht_b, 1, bt, c), 0)
                + row0_b
            )
            return (rows >= 0) & (rows < h_b)

        valid_b = row_mask(c_b)
        b = b * valid_b.astype(jnp.bfloat16)

        # --- stride-2 selection: slice+reshape on OUTER dims (a
        # double-strided slice lowers to an unsupported Mosaic gather) ----
        def every_other(x, start, count, axis):
            idx = [slice(None)] * x.ndim
            idx[axis] = slice(start, start + 2 * count)
            x = x[tuple(idx)]
            shape = list(x.shape)
            shape[axis : axis + 1] = [count, 2]
            x = x.reshape(shape)
            idx = [slice(None)] * x.ndim
            idx[axis + 1] = 0
            out = x[tuple(idx)]
            return out.reshape(
                [s for i, s in enumerate(x.shape) if i != axis + 1]
            )

        # Residual 1x1/2 on b: row0_b is odd, so local rows 3,5,... are the
        # global even rows 2*rt*g, 2*rt*g + 2, ...
        b_rows = every_other(b, 3, rt + 1, 0)
        b_rows = jnp.pad(b_rows, ((0, 0), (0, 1), (0, 0), (0, 0)))
        b_even = every_other(b_rows, 0, (h_b + 1) // 2, 1)
        hr, wr = b_even.shape[0], b_even.shape[1]
        r = jax.lax.dot_general(
            b_even.reshape(hr * wr * bt, c_b),
            res_ref[...].astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        r = (r * ress_ref[...] + resb_ref[...]).astype(jnp.bfloat16).reshape(
            hr, wr, bt, c_out
        )

        # --- the two sepconvs --------------------------------------------
        def dw(x, dwk):
            xp = jnp.pad(x, ((1, 1), (1, 1), (0, 0), (0, 0)))
            acc = jnp.zeros(x.shape, jnp.float32)
            for dh in range(3):
                for dwc in range(3):
                    acc = acc + (
                        xp[dh : dh + x.shape[0], dwc : dwc + x.shape[1], :, :]
                        .astype(jnp.float32) * dwk[dh, dwc, :]
                    )
            return acc

        c = dw(b, dw1_ref[...])
        c = jax.lax.dot_general(
            c.astype(jnp.bfloat16).reshape(ht_b * h_b * bt, c_b),
            pw1_ref[...].astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        c = jnp.maximum(c * s1_ref[...] + b1_ref[...], 0).astype(
            jnp.bfloat16
        ).reshape(ht_b, h_b, bt, c_out)
        valid_out = row_mask(c_out)
        c = c * valid_out.astype(jnp.bfloat16)  # re-zero contaminated rows

        d = dw(c, dw2_ref[...])
        d = jax.lax.dot_general(
            d.astype(jnp.bfloat16).reshape(ht_b * h_b * bt, c_out),
            pw2_ref[...].astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        d = (d * s2_ref[...] + b2_ref[...]).reshape(ht_b, h_b, bt, c_out)
        # Invalid rows must lose the max-pool, not win it.
        d = jnp.where(valid_out, d, -1e9).astype(jnp.bfloat16)
        # SAME pool (1,1) col pad; one spare row/col keeps the stride-2
        # selections of the last window in range.
        d = jnp.pad(d, ((0, 0), (1, 1), (0, 0), (0, 0)), constant_values=-1e9)
        d = jnp.pad(d, ((0, 1), (0, 1), (0, 0), (0, 0)), constant_values=-1e9)

        # --- maxpool 3x3/2 + residual ------------------------------------
        # Out row j: window d rows 2*(rt*g+j)-1..+1 = local rows 2j+2..2j+4.
        pooled = None
        for dh in range(3):
            for dwc in range(3):
                sl = every_other(d, 2 + dh, rt, 0)
                sl = every_other(sl, dwc, h_out, 1)
                pooled = sl if pooled is None else jnp.maximum(pooled, sl)
        o_ref[0] = pooled + r[:rt, :h_out, :, :]

    weight_args = (
        w["conv2"], w["conv2_s"], w["conv2_b"], w["res"], w["res_s"],
        w["res_b"], w["dw1"], w["pw1"], w["bn1_s"], w["bn1_b"], w["dw2"],
        w["pw2"], w["bn2_s"], w["bn2_b"],
    )
    weight_shapes = tuple(tuple(x.shape) for x in weight_args)
    out_shape = jax.ShapeDtypeStruct((n_tiles, rt, h_out, B, c_out), jnp.bfloat16)

    # One row-gather stages every tile's overlapping window; the reshape to
    # the 5D slab stack is free (contiguous rows).
    import numpy as np

    row_idx = np.concatenate(
        [np.arange(2 * rt * g, 2 * rt * g + ht_a) for g in range(n_tiles)]
    )
    slabs = a_pad[row_idx].reshape(n_tiles, ht_a, wp, B, c_in)

    def kernel_slab(a_ref, *rest):
        compute_tile(a_ref[0], pl.program_id(0), rest[:14], rest[14])

    out = pl.pallas_call(
        kernel_slab,
        grid=(n_tiles, nb),
        in_specs=[
            pl.BlockSpec(
                (1, ht_a, wp, bt, c_in), lambda gr, gb: (gr, 0, 0, gb, 0)
            ),
            *(
                pl.BlockSpec(shp, functools.partial(lambda n, *_: (0,) * n, len(shp)))
                for shp in weight_shapes
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, rt, h_out, bt, c_out), lambda gr, gb: (gr, 0, 0, gb, 0)
        ),
        out_shape=out_shape,
        compiler_params=_entry_compiler_params(),
        interpret=interpret,
    )(slabs, *weight_args)
    # (n_tiles, rt, h_out, B, c_out) -> (h_out(+crop), h_out, B, c_out)
    return out.reshape(n_tiles * rt, h_out, B, c_out)[:h_out]


def entry_block_weights(params: dict, stats: dict):
    """Assemble the kernel's weight dict from the Xception flax tree
    (conv2 = block1_conv2 + bn; block2 residual + sepconv1/2 + bns),
    BN folded to f32 affines (ops.fused_sepconv.fold_bn)."""
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.ops.fused_sepconv import fold_bn

    cv_s, cv_b = fold_bn(params["block1_conv2_bn"], stats["block1_conv2_bn"])
    res_s, res_b = fold_bn(params["block2_res_bn"], stats["block2_res_bn"])
    bn1_s, bn1_b = fold_bn(params["block2_sepconv1_bn"], stats["block2_sepconv1_bn"])
    bn2_s, bn2_b = fold_bn(params["block2_sepconv2_bn"], stats["block2_sepconv2_bn"])
    f32 = lambda x: jnp.asarray(x, jnp.float32)  # noqa: E731
    return {
        "conv2": f32(params["block1_conv2"]["kernel"]),
        "conv2_s": cv_s, "conv2_b": cv_b,
        "res": f32(params["block2_res_conv"]["kernel"])[0, 0],
        "res_s": res_s, "res_b": res_b,
        "dw1": f32(params["block2_sepconv1"]["depthwise"]["kernel"])[:, :, 0, :],
        "pw1": f32(params["block2_sepconv1"]["pointwise"]["kernel"])[0, 0],
        "bn1_s": bn1_s, "bn1_b": bn1_b,
        "dw2": f32(params["block2_sepconv2"]["depthwise"]["kernel"])[:, :, 0, :],
        "pw2": f32(params["block2_sepconv2"]["pointwise"]["kernel"])[0, 0],
        "bn2_s": bn2_s, "bn2_b": bn2_b,
    }
