"""int8 quantization for serving artifacts: weight-only and full w8a8.

The reference's only performance lever is swapping the TF-Serving image for
the GPU build (reference tf-serving.dockerfile:1-2).  This module adds two
real ones, as two artifact schemes the engine dispatches on:

**``int8-weight-only``** (round 1): weights stored and carried in HBM as
symmetric per-output-channel int8 (scale = max|w| / 127), dequantized
inline inside the jitted forward.  Buys artifact bytes, weight HBM
residency, and small-batch latency (the big pointwise convs are
weight-bandwidth-bound at batch ~1-8).  Its stated limitation -- "bf16
-activation matmuls do not hit the MXU's 2x int8 path (that needs int8
activations too -- a calibration problem left for a later round)" -- is
what the second scheme closes.

**``int8-w8a8``** (this round): offline *activation calibration* runs N
representative uint8 images through the float graph and records, per
quantized conv/dense layer, the absmax of that layer's input under a
percentile clip; the resulting static per-tensor activation scale is
stored in the artifact next to the ``_q8`` weight leaves.  The quantized
forward (:func:`build_w8a8_forward`) then executes every calibrated
conv/dense matmul as **int8 x int8 -> int32** (``preferred_element_type=
jnp.int32``), which is the operand form the MXU's 2x int8 path consumes,
and requantizes on the way out: ``y = acc_i32 * (s_act * s_w) + bias``.
BatchNorm, biases, residual adds, pooling, and softmax/logits stay float32
-- only the matmul operands are quantized, symmetric (zero-point 0, so
'SAME' padding needs no zero-point correction).

Serving safety: the engine gates ``int8-w8a8`` activation behind a
golden-logits tolerance check at warmup ($KDLT_QUANT_TOL, top-1 agreement
+ max-abs bound); a mis-calibrated artifact refuses the int8-activation
program and serves weight-only instead, loudly (runtime.engine).

Wire format: each quantized kernel leaf is a dict in the same tree
position -- ``{"_q8": int8, "_q8_scale": f32[out]}``, plus
``"_q8_act_scale": f32[]`` once calibrated -- so the msgpack artifact
round-trips unchanged; ``metadata["quantization"]`` carries the scheme
tag the engine (and the registry's hot reload) dispatch on.
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np

QUANT_KEY = "_q8"
SCALE_KEY = "_q8_scale"
ACT_SCALE_KEY = "_q8_act_scale"
SCHEME = "int8-weight-only"
SCHEME_W8A8 = "int8-w8a8"
SCHEMES = (SCHEME, SCHEME_W8A8)

# The warmup tolerance gate (runtime.engine._run_quant_gate): max-abs logit
# drift of the w8a8 program vs the weight-only float reference, relative to
# the reference's max-abs logit, must stay within $KDLT_QUANT_TOL, AND
# top-1 agreement must reach GATE_TOP1.  Failing either refuses w8a8.
QUANT_TOL_ENV = "KDLT_QUANT_TOL"
DEFAULT_QUANT_TOL = 0.1
GATE_TOP1 = 0.99

# Operator scheme override: "auto" serves what the artifact says (gated);
# "weight-only" refuses int8 activations fleet-wide without re-exporting
# (the fast rollback knob when a calibrated model misbehaves in prod).
QUANT_SCHEME_ENV = "KDLT_QUANT_SCHEME"

# Calibration defaults: the percentile clip trades worst-case outlier
# coverage for resolution everywhere else (absmax calibration lets ONE
# outlier activation stretch the scale until typical values collapse into
# a few int8 codes -- tests/test_quantize.py shows the effect on a
# synthetic outlier stream).  99.9 is the classic post-training default.
DEFAULT_CALIB_PERCENTILE = 99.9
DEFAULT_CALIB_IMAGES = 32
# Scale floor: a layer whose calibration stream is identically zero (dead
# ReLU channel stack, all-black calibration set) must still get a finite,
# positive scale -- quantizing by 0 would be a NaN factory.
SCALE_FLOOR = 1e-6

# Leaves eligible for quantization: conv/dense kernels. Everything else
# (BN scale/bias/mean/var, biases) is tiny and precision-critical.
_KERNEL_NAMES = ("kernel",)


def resolve_quant_tol(explicit: float | None = None) -> float:
    """Explicit arg > $KDLT_QUANT_TOL > 0.1 (relative max-abs logit drift)."""
    if explicit is not None:
        return float(explicit)
    raw = os.environ.get(QUANT_TOL_ENV, "")
    try:
        return float(raw) if raw.strip() else DEFAULT_QUANT_TOL
    except ValueError:
        return DEFAULT_QUANT_TOL


def resolve_scheme_override(explicit: str | None = None) -> str:
    """$KDLT_QUANT_SCHEME: "auto" (default) or "weight-only" (refuse w8a8)."""
    raw = (explicit if explicit is not None
           else os.environ.get(QUANT_SCHEME_ENV, "")).strip().lower()
    return "weight-only" if raw in ("weight-only", "weight_only", "w8") else "auto"


def _is_quantized_leaf(v: Any) -> bool:
    return isinstance(v, dict) and QUANT_KEY in v and SCALE_KEY in v


def quantize_variables(
    variables: Any, min_size: int = 4096, skip: tuple[str, ...] = ("head",)
) -> Any:
    """float tree -> tree with int8-quantized kernel leaves.

    ``min_size``: kernels smaller than this many elements stay float;
    ``skip``: subtree names left untouched entirely -- by default the
    classifier head, whose logits-facing precision matters most and whose
    cost is negligible.  Scales are per OUTPUT channel (last axis),
    symmetric; an all-zero channel gets scale 1 to avoid 0/0.
    """

    def walk(tree):
        if not isinstance(tree, dict):
            return tree
        out = {}
        for k, v in tree.items():
            if k in skip:
                out[k] = v
                continue
            if (
                k in _KERNEL_NAMES
                and hasattr(v, "ndim")
                and v.ndim >= 2
                and v.size >= min_size
            ):
                w = np.asarray(v, np.float32)
                absmax = np.abs(w).max(axis=tuple(range(w.ndim - 1)))
                scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
                q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
                out[k] = {QUANT_KEY: q, SCALE_KEY: scale}
            elif isinstance(v, dict):
                out[k] = walk(v)
            else:
                out[k] = v
        return out

    return walk(variables)


def dequantize_variables(variables: Any, dtype: Any = None) -> Any:
    """Quantized tree -> float tree (jnp ops: usable on tracers, so the
    engine keeps int8 weights in HBM and dequantizes inside the jit)."""
    import jax.numpy as jnp

    target = jnp.float32 if dtype is None else dtype

    def walk(tree):
        if _is_quantized_leaf(tree):
            q = jnp.asarray(tree[QUANT_KEY])
            scale = jnp.asarray(tree[SCALE_KEY])
            return (q.astype(jnp.float32) * scale).astype(target)
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(variables)


def dequantize_variables_host(variables: Any) -> Any:
    """Host-side (numpy) dequantization to float32.

    For load-time consumers (mesh sharding, cross-host setup) that must not
    round-trip the full f32 tree through a device -- the jnp variant would
    briefly materialize 4x the int8 footprint on one chip at startup.
    """
    import numpy as np

    def walk(tree):
        if _is_quantized_leaf(tree):
            return np.asarray(tree[QUANT_KEY], np.float32) * np.asarray(
                tree[SCALE_KEY], np.float32
            )
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(variables)


def is_quantized(variables: Any) -> bool:
    found = False

    def walk(tree):
        nonlocal found
        if _is_quantized_leaf(tree):
            found = True
            return
        if isinstance(tree, dict):
            for v in tree.values():
                walk(v)

    walk(variables)
    return found


# --- activation calibration (the w8a8 half) ---------------------------------


def clip_scale(abs_values, percentile: float = DEFAULT_CALIB_PERCENTILE) -> np.float32:
    """One layer's static activation scale from observed |activation| samples.

    ``percentile`` 100 is plain absmax; below 100 clips the tail so a rare
    outlier cannot stretch the scale until typical values collapse into a
    few int8 codes.  Floored (SCALE_FLOOR) so a zero-range stream -- a dead
    layer, an all-black calibration set -- still yields a finite positive
    scale instead of a divide-by-zero.
    """
    a = np.asarray(abs_values, np.float32).ravel()
    amax = float(np.percentile(a, percentile)) if a.size else 0.0
    return np.float32(max(amax, SCALE_FLOOR) / 127.0)


def _leaf_for(variables: Any, module) -> dict | None:
    """The quantized kernel leaf a flax module owns, or None."""
    node = variables.get("params") if isinstance(variables, dict) else None
    for name in module.path:
        node = node.get(name) if isinstance(node, dict) else None
        if node is None:
            return None
    if not isinstance(node, dict):
        return None
    kernel = node.get("kernel")
    return kernel if _is_quantized_leaf(kernel) else None


def calibrate_activation_scales(
    spec,
    variables: Any,
    qvars: Any,
    images: np.ndarray,
    percentile: float = DEFAULT_CALIB_PERCENTILE,
    batch_size: int = 8,
) -> dict[tuple, np.float32]:
    """Run representative uint8 images through the FLOAT graph; return
    {module path -> static per-tensor activation scale} for every layer
    whose kernel ``qvars`` quantized.

    Runs the un-jitted flax forward so activations are concrete: the
    interceptor observes each quantized conv/dense layer's INPUT, takes the
    |x| percentile per batch, and keeps the max across batches.  Offline-
    only by design (artifact build time, never the serving path).
    """
    import flax.linen as nn
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import create_model
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    model = create_model(spec, dtype=None)
    observed: dict[tuple, float] = {}

    def interceptor(next_fun, args, kwargs, context):
        m = context.module
        if (
            isinstance(m, (nn.Conv, nn.Dense))
            and context.method_name == "__call__"
            and _leaf_for(qvars, m) is not None
        ):
            x = np.abs(np.asarray(args[0], np.float32))
            amax = float(np.percentile(x, percentile)) if x.size else 0.0
            key = tuple(m.path)
            observed[key] = max(observed.get(key, 0.0), amax)
        return next_fun(*args, **kwargs)

    images = np.asarray(images)
    for i in range(0, max(1, images.shape[0]), batch_size):
        chunk = images[i : i + batch_size]
        if chunk.shape[0] == 0:
            break
        if chunk.dtype == np.uint8:
            x = normalize(jnp.asarray(chunk), spec.preprocessing)
        else:
            x = jnp.asarray(chunk, jnp.float32)
        with nn.intercept_methods(interceptor):
            model.apply(variables, x, train=False)
    return {
        k: np.float32(max(v, SCALE_FLOOR) / 127.0) for k, v in observed.items()
    }


def attach_activation_scales(qvars: Any, scales: dict[tuple, Any]) -> Any:
    """Store calibrated per-tensor activation scales next to their ``_q8``
    weight leaves (``_q8_act_scale``, a 0-d float32 -- msgpack-safe)."""

    def walk(tree, path):
        if _is_quantized_leaf(tree):
            s = scales.get(path[:-1])  # path ends with the kernel name
            if s is not None:
                return {**tree, ACT_SCALE_KEY: np.asarray(s, np.float32)}
            return tree
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        return tree

    return walk(qvars, ())


def activation_scales(variables: Any) -> dict[tuple, np.float32]:
    """{module path -> stored activation scale} of a calibrated tree."""
    out: dict[tuple, np.float32] = {}

    def walk(tree, path):
        if _is_quantized_leaf(tree):
            if ACT_SCALE_KEY in tree:
                out[path[:-1]] = np.float32(np.asarray(tree[ACT_SCALE_KEY]))
            return
        if isinstance(tree, dict):
            for k, v in tree.items():
                walk(v, path + (k,))

    walk(variables.get("params", variables), ())
    return out


def is_calibrated(variables: Any) -> bool:
    """True when at least one quantized leaf carries an activation scale."""
    return bool(activation_scales(variables))


# --- the w8a8 forward --------------------------------------------------------


def _pair(v) -> tuple[int, int]:
    if v is None:
        return (1, 1)
    if isinstance(v, int):
        return (v, v)
    t = tuple(int(x) for x in v)
    return t if len(t) == 2 else (t[0], t[0])


def _conv_padding(pad):
    """flax Conv padding -> the lax conv form.  'CIRCULAR' is flax-side
    pre-padding the rewrite does not replicate: refuse at trace time
    (warmup fails loudly; the version watcher skips the artifact) rather
    than silently compute a different convolution."""
    if isinstance(pad, str):
        if pad.upper() == "CIRCULAR":
            raise NotImplementedError(
                "int8-w8a8 does not support CIRCULAR conv padding"
            )
        return pad
    if isinstance(pad, int):
        return [(pad, pad), (pad, pad)]
    out = []
    for p in tuple(pad):
        out.append((p, p) if isinstance(p, int) else tuple(int(x) for x in p))
    return out


def build_w8a8_forward(spec):
    """``f(variables, images) -> float32 logits`` executing every calibrated
    conv/dense as int8 x int8 -> int32.

    ``variables`` is the calibrated quantized tree.  Inside the jit:

    - the input's per-tensor activation scale and the kernel's per-channel
      weight scales are static constants, so quantize-in (``round(x/s_a)``
      clipped to [-127, 127]) and requantize-out (``acc * (s_a * s_w)``)
      are elementwise ops XLA fuses into the surrounding graph;
    - the matmul itself runs with int8 operands and
      ``preferred_element_type=jnp.int32`` -- on TPU that is the MXU's 2x
      int8 path; on CPU it is a (slow but exact) reference lowering, which
      is what the tests pin numerics against;
    - everything else -- normalization, BN, bias adds, residuals, pooling,
      the classifier head, the float logits -- runs float32, exactly the
      flax graph (the fused Pallas fast path is deliberately bypassed:
      int8 operand layouts are a different kernel contract).

    Quantized-but-uncalibrated leaves (defensive: a layer the calibration
    stream never reached) dequantize inline and run float, i.e. degrade to
    the weight-only semantics for that layer only.
    """
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import create_model
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    model = create_model(spec, dtype=None)

    def _dequant(leaf):
        return jnp.asarray(leaf[QUANT_KEY]).astype(jnp.float32) * jnp.asarray(
            leaf[SCALE_KEY], jnp.float32
        )

    def forward(variables, images):
        if images.dtype == jnp.uint8:
            x = normalize(images, spec.preprocessing)
        else:
            x = images.astype(jnp.float32)

        def interceptor(next_fun, args, kwargs, context):
            m = context.module
            if not (
                isinstance(m, (nn.Conv, nn.Dense))
                and context.method_name == "__call__"
            ):
                return next_fun(*args, **kwargs)
            leaf = _leaf_for(variables, m)
            if leaf is None:
                return next_fun(*args, **kwargs)
            xin = args[0].astype(jnp.float32)
            sw = jnp.asarray(leaf[SCALE_KEY], jnp.float32)
            if ACT_SCALE_KEY in leaf:
                s_act = jnp.asarray(leaf[ACT_SCALE_KEY], jnp.float32)
                lhs = jnp.clip(jnp.round(xin / s_act), -127, 127).astype(
                    jnp.int8
                )
                rhs = jnp.asarray(leaf[QUANT_KEY])
                out_scale = s_act * sw
                acc_dtype = jnp.int32
            else:  # uncalibrated: weight-only semantics for this layer
                lhs, rhs, out_scale, acc_dtype = (
                    xin, _dequant(leaf), None, jnp.float32
                )
            if isinstance(m, nn.Dense):
                acc = jax.lax.dot_general(
                    lhs, rhs, (((xin.ndim - 1,), (0,)), ((), ())),
                    preferred_element_type=acc_dtype,
                )
            else:
                # Dilation is exact under symmetric int8: inserted zeros
                # are the quantized zero (zero-point 0), same as padding.
                acc = jax.lax.conv_general_dilated(
                    lhs, rhs,
                    window_strides=_pair(m.strides),
                    padding=_conv_padding(m.padding),
                    lhs_dilation=_pair(getattr(m, "input_dilation", None)),
                    rhs_dilation=_pair(getattr(m, "kernel_dilation", None)),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    feature_group_count=m.feature_group_count,
                    preferred_element_type=acc_dtype,
                )
            y = acc.astype(jnp.float32)
            if out_scale is not None:
                y = y * out_scale
            if m.use_bias:
                node = variables["params"]
                for name in m.path:
                    node = node[name]
                y = y + jnp.asarray(node["bias"], jnp.float32)
            return y

        with nn.intercept_methods(interceptor):
            out = model.apply(variables, x, train=False)
        return out.astype(jnp.float32)

    return forward


# --- artifact build ----------------------------------------------------------


def representative_images(
    spec, n: int, seed: int = 0, image_dir: str | None = None
) -> np.ndarray:
    """N uint8 calibration images at the spec's input shape.

    ``image_dir``: real sample images (the production posture -- calibrate
    on traffic-like data), loaded and resized with the spec's resize
    filter, cycled if fewer than ``n``.  Without it, seeded uniform noise:
    sufficient for the repro harness and for exercising the full pipeline,
    but real deployments should calibrate on real images (GUIDE 9d).
    """
    h, w, c = spec.input_shape
    if image_dir:
        from PIL import Image

        resample = (
            Image.NEAREST if spec.resize_filter == "nearest" else Image.BILINEAR
        )
        files = sorted(
            os.path.join(image_dir, f)
            for f in os.listdir(image_dir)
            if f.lower().endswith((".png", ".jpg", ".jpeg", ".bmp", ".webp"))
        )
        if not files:
            raise FileNotFoundError(f"no images under {image_dir!r}")
        out = []
        for i in range(n):
            img = Image.open(files[i % len(files)]).convert("RGB")
            out.append(
                np.asarray(img.resize((w, h), resample), np.uint8)
            )
        return np.stack(out)
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, h, w, c), dtype=np.uint8)


def write_quantized_version(
    root: str,
    name: str,
    scheme: str = SCHEME,
    calib_images: np.ndarray | None = None,
    percentile: float = DEFAULT_CALIB_PERCENTILE,
    min_size: int = 4096,
    from_version: int | None = None,
) -> str:
    """Quantize <root>/<name>'s latest (or ``from_version``) float version
    into the NEXT version dir, under ``scheme``.

    ``int8-w8a8`` additionally calibrates activation scales from
    ``calib_images`` (uint8 NHWC; see :func:`representative_images`) --
    calibration happens HERE, at artifact build, never at serving time.
    The model server's version watcher then hot-loads the result exactly
    like any other new version (TF-Serving's own convention for rolling a
    model).  No StableHLO is emitted: quantized artifacts serve through
    the live-jit path (the exported-module format stays float-only and
    portable).
    """
    from kubernetes_deep_learning_tpu.export import artifact as art

    if scheme not in SCHEMES:
        raise ValueError(f"unknown quantization scheme {scheme!r}; known: {SCHEMES}")
    latest = art.latest_version(root, name)
    if latest is None:
        raise FileNotFoundError(f"no versions of {name!r} under {root!r}")
    version = latest if from_version is None else from_version
    src = art.load_artifact(art.version_dir(root, name, version))
    if src.metadata.get("quantization"):
        raise ValueError(
            f"{name} v{version} is already quantized "
            f"({src.metadata['quantization']}); quantize from a float version"
            + ("" if from_version is not None else " via from_version")
        )
    # Quantized artifacts drop the exported StableHLO (module=None below):
    # they can only serve through the live-jit in-tree forward.  A family
    # with no in-tree model would produce an unservable version that the
    # version watcher warm-up-fails on every scan (ADVICE r2) -- fail HERE,
    # at quantize time, instead.
    from kubernetes_deep_learning_tpu.models import create_model

    try:
        create_model(src.spec)
    except KeyError as e:
        raise ValueError(
            f"cannot quantize {name!r}: family {src.spec.family!r} has no "
            "in-tree forward, and quantized artifacts (module=None) can "
            "only serve via live jit"
        ) from e
    qvars = quantize_variables(src.variables, min_size=min_size)
    meta = {
        **src.metadata,
        "quantization": scheme,
        "quantized_from_version": version,
    }
    if scheme == SCHEME_W8A8:
        if calib_images is None:
            calib_images = representative_images(src.spec, DEFAULT_CALIB_IMAGES)
        scales = calibrate_activation_scales(
            src.spec, src.variables, qvars, calib_images, percentile=percentile
        )
        qvars = {
            **qvars,
            "params": attach_activation_scales(qvars["params"], scales),
        }
        meta["calibration"] = {
            "images": int(np.asarray(calib_images).shape[0]),
            "percentile": float(percentile),
            "layers": len(scales),
        }
    dst = art.version_dir(root, name, latest + 1)
    return art.save_artifact(dst, src.spec, qvars, None, meta)


def main(argv: list[str] | None = None) -> int:
    """CLI: kdlt-quantize --models <root> --model <name> [--scheme int8-w8a8]."""
    import argparse

    p = argparse.ArgumentParser(description="int8 artifact quantization")
    p.add_argument("--models", required=True, help="artifact root")
    p.add_argument("--model", required=True, help="model name under the root")
    p.add_argument(
        "--scheme", default=SCHEME, choices=list(SCHEMES),
        help="int8-weight-only (weights dequantize inline; no calibration) "
        "or int8-w8a8 (calibrated activation scales; matmuls run int8xint8 "
        "on the MXU's 2x path, gated at warmup by KDLT_QUANT_TOL)",
    )
    p.add_argument(
        "--calibrate-images", type=int, default=DEFAULT_CALIB_IMAGES,
        help="calibration batch size for --scheme int8-w8a8",
    )
    p.add_argument(
        "--calibrate-percentile", type=float, default=DEFAULT_CALIB_PERCENTILE,
        help="percentile clip on |activation| (100 = absmax)",
    )
    p.add_argument(
        "--calibrate-dir", default=None,
        help="directory of representative images (default: seeded noise; "
        "calibrate on real traffic samples in production)",
    )
    p.add_argument("--calibrate-seed", type=int, default=0)
    p.add_argument(
        "--from-version", type=int, default=None,
        help="quantize this (float) version instead of the latest",
    )
    args = p.parse_args(argv)
    calib = None
    if args.scheme == SCHEME_W8A8:
        from kubernetes_deep_learning_tpu.export import artifact as art

        version = (
            args.from_version
            if args.from_version is not None
            else art.latest_version(args.models, args.model)
        )
        if version is None:
            raise SystemExit(f"no versions of {args.model!r} under {args.models!r}")
        spec = art.load_artifact(
            art.version_dir(args.models, args.model, version)
        ).spec
        calib = representative_images(
            spec, args.calibrate_images, seed=args.calibrate_seed,
            image_dir=args.calibrate_dir,
        )
    path = write_quantized_version(
        args.models, args.model, scheme=args.scheme, calib_images=calib,
        percentile=args.calibrate_percentile, from_version=args.from_version,
    )
    print(f"wrote quantized artifact ({args.scheme}): {path}")
    return 0
