"""int8 weight-only quantization for serving artifacts.

The reference's only performance lever is swapping the TF-Serving image for
the GPU build (reference tf-serving.dockerfile:1-2).  This module adds a
real one: weights stored and carried in HBM as symmetric per-output-channel
int8 (scale = max|w| / 127), dequantized inline inside the jitted forward.

What this buys, honestly stated:

- artifact bytes and weight HBM residency: 4x smaller than f32;
- small-batch serving latency: at batch ~1-8 the big pointwise convs are
  weight-bandwidth-bound, so int8 weight reads help exactly where the p50
  target bites (the dequant multiply fuses into the conv's operand path);
- logit drift: bounded and test-asserted (tests/test_quantize.py) --
  per-channel symmetric int8 on conv/dense kernels only, BN and biases
  stay f32.

What it does NOT claim: bf16-activation matmuls do not hit the MXU's 2x
int8 path (that needs int8 activations too -- a calibration problem left
for a later round and recorded in ROADMAP.md).

Wire format: each quantized kernel leaf becomes a dict
``{"_q8": int8, "_q8_scale": f32}`` in the same tree position, so the
msgpack artifact round-trips unchanged; ``metadata["quantization"]``
carries the scheme tag the engine dispatches on.
"""

from __future__ import annotations

from typing import Any

import numpy as np

QUANT_KEY = "_q8"
SCALE_KEY = "_q8_scale"
SCHEME = "int8-weight-only"
# Leaves eligible for quantization: conv/dense kernels. Everything else
# (BN scale/bias/mean/var, biases) is tiny and precision-critical.
_KERNEL_NAMES = ("kernel",)


def _is_quantized_leaf(v: Any) -> bool:
    return isinstance(v, dict) and QUANT_KEY in v and SCALE_KEY in v


def quantize_variables(
    variables: Any, min_size: int = 4096, skip: tuple[str, ...] = ("head",)
) -> Any:
    """float tree -> tree with int8-quantized kernel leaves.

    ``min_size``: kernels smaller than this many elements stay float;
    ``skip``: subtree names left untouched entirely -- by default the
    classifier head, whose logits-facing precision matters most and whose
    cost is negligible.  Scales are per OUTPUT channel (last axis),
    symmetric; an all-zero channel gets scale 1 to avoid 0/0.
    """

    def walk(tree):
        if not isinstance(tree, dict):
            return tree
        out = {}
        for k, v in tree.items():
            if k in skip:
                out[k] = v
                continue
            if (
                k in _KERNEL_NAMES
                and hasattr(v, "ndim")
                and v.ndim >= 2
                and v.size >= min_size
            ):
                w = np.asarray(v, np.float32)
                absmax = np.abs(w).max(axis=tuple(range(w.ndim - 1)))
                scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
                q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
                out[k] = {QUANT_KEY: q, SCALE_KEY: scale}
            elif isinstance(v, dict):
                out[k] = walk(v)
            else:
                out[k] = v
        return out

    return walk(variables)


def dequantize_variables(variables: Any, dtype: Any = None) -> Any:
    """Quantized tree -> float tree (jnp ops: usable on tracers, so the
    engine keeps int8 weights in HBM and dequantizes inside the jit)."""
    import jax.numpy as jnp

    target = jnp.float32 if dtype is None else dtype

    def walk(tree):
        if _is_quantized_leaf(tree):
            q = jnp.asarray(tree[QUANT_KEY])
            scale = jnp.asarray(tree[SCALE_KEY])
            return (q.astype(jnp.float32) * scale).astype(target)
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(variables)


def dequantize_variables_host(variables: Any) -> Any:
    """Host-side (numpy) dequantization to float32.

    For load-time consumers (mesh sharding, cross-host setup) that must not
    round-trip the full f32 tree through a device -- the jnp variant would
    briefly materialize 4x the int8 footprint on one chip at startup.
    """
    import numpy as np

    def walk(tree):
        if _is_quantized_leaf(tree):
            return np.asarray(tree[QUANT_KEY], np.float32) * np.asarray(
                tree[SCALE_KEY], np.float32
            )
        if isinstance(tree, dict):
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(variables)


def is_quantized(variables: Any) -> bool:
    found = False

    def walk(tree):
        nonlocal found
        if _is_quantized_leaf(tree):
            found = True
            return
        if isinstance(tree, dict):
            for v in tree.values():
                walk(v)

    walk(variables)
    return found


def write_quantized_version(root: str, name: str) -> str:
    """Quantize <root>/<name>'s latest version into the NEXT version dir.

    The model server's version watcher then hot-loads it exactly like any
    other new version (TF-Serving's own convention for rolling a model).
    No StableHLO is emitted: quantized artifacts serve through the live-jit
    path (the exported-module format stays float-only and portable).
    """
    from kubernetes_deep_learning_tpu.export import artifact as art

    version = art.latest_version(root, name)
    if version is None:
        raise FileNotFoundError(f"no versions of {name!r} under {root!r}")
    src = art.load_artifact(art.version_dir(root, name, version))
    if src.metadata.get("quantization"):
        raise ValueError(f"{name} v{version} is already quantized")
    # Quantized artifacts drop the exported StableHLO (module=None below):
    # they can only serve through the live-jit in-tree forward.  A family
    # with no in-tree model would produce an unservable version that the
    # version watcher warm-up-fails on every scan (ADVICE r2) -- fail HERE,
    # at quantize time, instead.
    from kubernetes_deep_learning_tpu.models import create_model

    try:
        create_model(src.spec)
    except KeyError as e:
        raise ValueError(
            f"cannot quantize {name!r}: family {src.spec.family!r} has no "
            "in-tree forward, and quantized artifacts (module=None) can "
            "only serve via live jit"
        ) from e
    qvars = quantize_variables(src.variables)
    meta = {
        **src.metadata,
        "quantization": SCHEME,
        "quantized_from_version": version,
    }
    dst = art.version_dir(root, name, version + 1)
    return art.save_artifact(dst, src.spec, qvars, None, meta)


def main(argv: list[str] | None = None) -> int:
    """CLI: kdlt-quantize --models <root> --model <name>."""
    import argparse

    p = argparse.ArgumentParser(description="int8 weight-only quantization")
    p.add_argument("--models", required=True, help="artifact root")
    p.add_argument("--model", required=True, help="model name under the root")
    args = p.parse_args(argv)
    path = write_quantized_version(args.models, args.model)
    print(f"wrote quantized artifact: {path}")
    return 0
