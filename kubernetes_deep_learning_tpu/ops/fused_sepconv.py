"""Fused separable-conv residual block: one Pallas kernel per Xception
middle block.

What the XLA graph does for one middle block is 3 sepconv fusions, each a
round trip through HBM (trace evidence in BENCH.md): relu -> depthwise 3x3
-> pointwise GEMM -> BN affine, x3, + residual.  This kernel keeps the whole
(H, W) extent of a tile of images resident in VMEM across all three
sepconvs, eliminating the intermediate HBM traffic, and arranges the data
so TPU units are used on their terms (measured 83 -> 69 ms on the full
batch-256 Xception forward, exp/fused_middle.py progression):

- **Layout (H, W, B, C)** -- batch on sublanes, channels on lanes (the same
  layout XLA itself picks for these tensors: ``{0,3,2,1:T(8,128)}``).  The
  depthwise conv's 9 shifted reads then move only along OUTER dims -- no
  sublane/lane relayout (a naive (rows, C) layout spends more time in
  Mosaic relayouts than the GEMMs take).
- **Depthwise on the VPU** as 9 shifted multiply-adds over a zero-padded
  copy; zero halos give exact SAME-conv behavior with no masks.
- **Pointwise on the MXU**: (H*W*bt, C) @ (C, C) with f32 accumulation;
  the collapse is tile-aligned because bt is a multiple of 8 (or the whole
  batch) and C rides the lane dim.
- **BN folded**: inference-mode BatchNorm arrives as per-channel
  scale/shift (see ``fold_bn``), applied in f32 before the cast back.

The reference's analog of all of this is "use the TF-Serving GPU image"
(reference tf-serving.dockerfile:1); here the hot block IS the framework's
own kernel.
"""

from __future__ import annotations

import functools
from typing import Any

from kubernetes_deep_learning_tpu.models.layers import KERAS_BN_EPS


def fold_bn(bn_params: dict, bn_stats: dict, eps: float = KERAS_BN_EPS):
    """Inference BN -> (scale, shift): y = x * scale + shift, float32.

    jnp ops so it works on tracers (inside a jitted forward) as well as
    concrete arrays.  eps defaults to the model zoo's Keras-parity epsilon
    (models.layers.KERAS_BN_EPS) -- NOT flax's 1e-5 default.
    """
    import jax
    import jax.numpy as jnp

    gamma = jnp.asarray(bn_params["scale"], jnp.float32)
    beta = jnp.asarray(bn_params["bias"], jnp.float32)
    mean = jnp.asarray(bn_stats["mean"], jnp.float32)
    var = jnp.asarray(bn_stats["var"], jnp.float32)
    scale = gamma * jax.lax.rsqrt(var + eps)
    return scale, beta - mean * scale


def middle_block_weights(params: dict, stats: dict, block: str):
    """Stack one Xception middle block's 3 sepconvs for the fused kernel.

    Returns (dw (3,3,3,C) f32, pw (3,C,C) bf16, scale (3,C) f32,
    shift (3,C) f32) from the framework's flax variable tree (the layout
    models.keras_import produces and models.xception consumes).
    """
    import jax.numpy as jnp

    dws, pws, ss, bs = [], [], [], []
    for j in (1, 2, 3):
        sep = params[f"{block}_sepconv{j}"]
        dw = jnp.asarray(sep["depthwise"]["kernel"], jnp.float32)  # (3,3,1,C)
        pw = jnp.asarray(sep["pointwise"]["kernel"], jnp.float32)  # (1,1,C,C)
        scale, shift = fold_bn(
            params[f"{block}_sepconv{j}_bn"], stats[f"{block}_sepconv{j}_bn"]
        )
        dws.append(dw[:, :, 0, :])
        pws.append(pw[0, 0])
        ss.append(scale)
        bs.append(shift)
    return (
        jnp.stack(dws),
        jnp.stack(pws).astype(jnp.bfloat16),
        jnp.stack(ss),
        jnp.stack(bs),
    )


def pick_batch_tile(batch: int, h: int, w: int, c: int, budget_bytes: int = 9 << 20) -> int:
    """Largest bt in {16, 8} whose bf16 tile fits the budget (bt=16 at the
    Xception middle shape measured fastest); 8 otherwise.

    Only 8-multiples are ever returned: the kernel collapses (H, W, bt) into
    MXU rows, and Mosaic rejects that reshape unless the sublane-adjacent
    dim is 8-aligned (BENCH_r02: ``(361,728)->(19,19,1,728)`` at bt=1 failed
    to compile).  Callers with ``batch % 8 != 0`` must pad the batch axis up
    to a multiple of 8 first -- ``fused_sepconv_block_t`` and
    ``fused_sepconv_chain_t`` do this internally.
    """
    for bt in (16, 8):
        if batch % bt == 0 and h * w * bt * c * 2 <= budget_bytes:
            return bt
    return 8


def sepconv_block_reference(x, dw, pw, scale, shift):
    """Plain-jnp semantics of the fused kernel (NHWC), for tests and CPU."""
    import jax.numpy as jnp

    y = x
    for i in range(3):
        y = jnp.maximum(y, 0)
        yp = jnp.pad(y, ((0, 0), (1, 1), (1, 1), (0, 0)))
        acc = jnp.zeros(y.shape, jnp.float32)
        for a in range(3):
            for b in range(3):
                acc = acc + (
                    yp[:, a : a + y.shape[1], b : b + y.shape[2], :].astype(jnp.float32)
                    * dw[i, a, b, :].astype(jnp.float32)
                )
        z = jnp.einsum(
            "bhwc,cd->bhwd",
            acc.astype(jnp.bfloat16),
            pw[i],
            preferred_element_type=jnp.float32,
        )
        y = (z * scale[i] + shift[i]).astype(x.dtype)
    return x + y


def _pad_batch_to_8(xt):
    """Pad the (H, W, B, C) batch axis up to a multiple of 8 (min 8).

    The kernels collapse (H, W, bt) rows for the MXU; Mosaic only accepts
    that reshape when bt is 8-aligned, so any other batch is served by
    padding the sublane axis with zeros and slicing the result.  Returns
    (padded, original_B).  At small batches the waste is latency-trivial:
    the middle-flow tile is weight-bandwidth-bound, not row-bound.
    """
    import jax.numpy as jnp

    B = xt.shape[2]
    pad = (-B) % 8
    if pad:
        xt = jnp.pad(xt, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return xt, B


def _legal_bt(bt: int, B: int) -> int:
    """Clamp a (possibly caller-supplied) batch tile to a Mosaic-legal one:
    a multiple of 8 that divides the (already 8-aligned) padded batch."""
    bt = min(-(-bt // 8) * 8, B)
    while B % bt:
        bt -= 8
    return bt


def fused_sepconv_block_t(xt, dw, pw, scale, shift, *, bt: int = 0, interpret: bool = False):
    """The kernel, on (H, W, B, C) bf16 input; returns the same layout.

    Chain middle blocks in this transposed layout and pay the NHWC
    transpose once per flow (see models.xception_fast).  ``bt`` 0 = auto.
    Any batch size is legal: non-8-aligned batches are zero-padded on the
    sublane axis around the kernel (see _pad_batch_to_8).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    xt, B_orig = _pad_batch_to_8(xt)
    H, W, B, C = xt.shape
    bt = _legal_bt(bt or pick_batch_tile(B, H, W, C), B)

    def kernel(x_ref, dw_ref, pw_ref, s_ref, b_ref, o_ref):
        y = x_ref[...]  # (H, W, bt, C) bf16
        for i in range(3):
            y = jnp.maximum(y, 0)
            yp = jnp.pad(y, ((1, 1), (1, 1), (0, 0), (0, 0)))
            acc = jnp.zeros((H, W, bt, C), jnp.float32)
            for dh in range(3):
                for dwc in range(3):
                    tap = dw_ref[i, dh, dwc, :].astype(jnp.float32)
                    acc = acc + (
                        yp[dh : dh + H, dwc : dwc + W, :, :].astype(jnp.float32) * tap
                    )
            z = jax.lax.dot_general(
                acc.astype(jnp.bfloat16).reshape(H * W * bt, C),
                pw_ref[i],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            y = (z * s_ref[i] + b_ref[i]).astype(jnp.bfloat16).reshape(H, W, bt, C)
        o_ref[...] = x_ref[...] + y

    out = pl.pallas_call(
        kernel,
        grid=(B // bt,),
        in_specs=[
            pl.BlockSpec((H, W, bt, C), lambda g: (0, 0, g, 0)),
            pl.BlockSpec((3, 3, 3, C), lambda g: (0, 0, 0, 0)),
            pl.BlockSpec((3, C, C), lambda g: (0, 0, 0)),
            pl.BlockSpec((3, C), lambda g: (0, 0)),
            pl.BlockSpec((3, C), lambda g: (0, 0)),
        ],
        out_specs=pl.BlockSpec((H, W, bt, C), lambda g: (0, 0, g, 0)),
        out_shape=jax.ShapeDtypeStruct(xt.shape, xt.dtype),
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(xt, dw, pw, scale, shift)
    return out if B_orig == B else out[:, :, :B_orig, :]


@functools.cache
def _compiler_params(limit_bytes: int = 96 * 1024 * 1024) -> Any:
    from jax.experimental.pallas import tpu as pltpu

    # The default 16 MiB scoped-vmem cap rejects the bt=16 tile; v5e has
    # 128 MiB physical VMEM.  Default 96 MiB: the serving path's largest
    # tile needs far less, the measured speed at 96 vs 110 MiB is
    # identical (exp/worker_fault_probe.py scan-long-96m), and round 3-4's
    # recurring TPU worker faults make VMEM headroom cheap insurance.
    # Only the experimental entry path's block3 chain (74x74, 128->256
    # channels, peaks ~107 MiB at bt=8) requests 110 explicitly.
    # (CompilerParams was TPUCompilerParams in older jax releases.)
    params_cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return params_cls(vmem_limit_bytes=limit_bytes)


def fused_sepconv_block(x, dw, pw, scale, shift, *, bt: int = 0, interpret: bool = False):
    """NHWC convenience wrapper (transposes in and out; for single use)."""
    xt = x.transpose(1, 2, 0, 3)
    out = fused_sepconv_block_t(xt, dw, pw, scale, shift, bt=bt, interpret=interpret)
    return out.transpose(2, 0, 1, 3)


def fused_sepconv_chain_t(
    xt,
    stages,
    *,
    bt: int = 0,
    interpret: bool = False,
    vmem_limit_bytes: int = 0,
):
    """A chain of sepconv+BN stages in one kernel, (H, W, B, C) layout.

    ``stages``: sequence of dicts with keys ``dw`` (3,3,C_in) f32, ``pw``
    (C_in, C_out) bf16, ``scale``/``shift`` (C_out,) f32, ``pre_relu`` /
    ``post_relu`` bools -- covering both Xception exit patterns
    (block13: relu -> sep -> bn; block14: sep -> bn -> relu).  No residual,
    no pooling: those stay in XLA around the call.  Channel widths may grow
    along the chain (728 -> 1024 -> 1536 -> 2048 in the exit flow).

    Same layout argument as fused_sepconv_block_t: depthwise shifts move
    only along untiled outer dims; each pointwise GEMM takes the whole
    (H*W*bt) row extent.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    xt, B_orig = _pad_batch_to_8(xt)
    H, W, B, C0 = xt.shape
    bt = _legal_bt(
        bt or pick_batch_tile(B, H, W, max(s["pw"].shape[1] for s in stages)), B
    )
    c_out_final = stages[-1]["pw"].shape[1]
    pre = tuple(bool(s["pre_relu"]) for s in stages)
    post = tuple(bool(s["post_relu"]) for s in stages)

    def kernel(x_ref, *refs):
        o_ref = refs[-1]
        stage_refs = [refs[i * 4 : i * 4 + 4] for i in range(len(stages))]
        y = x_ref[...]
        for i, (dw_ref, pw_ref, s_ref, b_ref) in enumerate(stage_refs):
            c_in = y.shape[-1]
            if pre[i]:
                y = jnp.maximum(y, 0)
            yp = jnp.pad(y, ((1, 1), (1, 1), (0, 0), (0, 0)))
            acc = jnp.zeros((H, W, bt, c_in), jnp.float32)
            for dh in range(3):
                for dwc in range(3):
                    tap = dw_ref[dh, dwc, :].astype(jnp.float32)
                    acc = acc + (
                        yp[dh : dh + H, dwc : dwc + W, :, :].astype(jnp.float32) * tap
                    )
            z = jax.lax.dot_general(
                acc.astype(jnp.bfloat16).reshape(H * W * bt, c_in),
                pw_ref[...],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            z = z * s_ref[...] + b_ref[...]
            if post[i]:
                z = jnp.maximum(z, 0)
            y = z.astype(jnp.bfloat16).reshape(H, W, bt, pw_ref.shape[1])
        o_ref[...] = y

    in_specs = [pl.BlockSpec((H, W, bt, C0), lambda g: (0, 0, g, 0))]
    args = [xt]
    for s in stages:
        c_in, c_out = s["pw"].shape
        in_specs += [
            pl.BlockSpec((3, 3, c_in), lambda g: (0, 0, 0)),
            pl.BlockSpec((c_in, c_out), lambda g: (0, 0)),
            pl.BlockSpec((c_out,), lambda g: (0,)),
            pl.BlockSpec((c_out,), lambda g: (0,)),
        ]
        args += [s["dw"], s["pw"], s["scale"], s["shift"]]

    out = pl.pallas_call(
        kernel,
        grid=(B // bt,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((H, W, bt, c_out_final), lambda g: (0, 0, g, 0)),
        out_shape=jax.ShapeDtypeStruct((H, W, B, c_out_final), xt.dtype),
        compiler_params=(
            _compiler_params(vmem_limit_bytes) if vmem_limit_bytes
            else _compiler_params()
        ),
        interpret=interpret,
    )(*args)
    return out if B_orig == B else out[:, :, :B_orig, :]


def sepconv_stage_weights(params: dict, stats: dict, sep_name: str, bn_name: str,
                          pre_relu: bool, post_relu: bool):
    """One chain stage from the flax tree (see middle_block_weights)."""
    import jax.numpy as jnp

    sep = params[sep_name]
    scale, shift = fold_bn(params[bn_name], stats[bn_name])
    return {
        "dw": jnp.asarray(sep["depthwise"]["kernel"], jnp.float32)[:, :, 0, :],
        "pw": jnp.asarray(sep["pointwise"]["kernel"], jnp.float32)[0, 0].astype(
            jnp.bfloat16
        ),
        "scale": scale,
        "shift": shift,
        "pre_relu": pre_relu,
        "post_relu": post_relu,
    }
