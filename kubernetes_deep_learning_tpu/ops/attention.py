"""Attention ops: fused Pallas flash attention (TPU) + reference softmax path.

The reference system serves a fixed-shape CNN and has no attention anywhere
(SURVEY.md section 5: long-context "absent and inapplicable"); this module
exists because long-context support is first-class in this framework: it is
the single-device building block under ``parallel.ring`` (ring attention /
context parallelism over a device mesh).

Design (TPU-first):

- **Online softmax** (flash attention): the (S, S) score matrix is never
  materialized in HBM.  The Pallas kernel keeps one (block_q, d) query tile
  in VMEM and streams key/value tiles through a fori_loop, carrying the
  running row-max m, normalizer l, and unnormalized accumulator in f32.
- **MXU-shaped blocks**: default 128x128 score tiles, f32 accumulation via
  ``preferred_element_type`` so bf16 inputs still reduce exactly.
- **Partial outputs for ring composition**: ``attend_block`` returns
  (acc, m, l) so callers (ring attention) can combine partial attentions
  over KV shards with the standard log-sum-exp merge; ``flash_attention``
  is the fused single-shot form.
- ``interpret=True`` (auto on CPU) runs the same kernel through the Pallas
  interpreter, so tests exercise the real kernel logic without a TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-but-finite: -inf breaks exp(m - m_new) when a row is fully masked


def _causal_mask(q_offset: int, k_offset, block_q: int, block_k: int):
    """(block_q, block_k) bool mask: query global index >= key global index."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + q_offset
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + k_offset
    return rows >= cols


def pick_block(seq: int) -> int | None:
    """Largest MXU-friendly flash block (<=256, 8-aligned) dividing ``seq``.

    None means no legal tiling exists for ``seq`` AS IS; callers should go
    through ``flash_attention_padded`` (pad + kv_len masking) rather than
    falling back to the einsum path.  Single source of the kernel's tiling
    rule -- consumed by flash_attention_padded and parallel.ring.

    256 leads: fewer, fatter grid steps and k-iterations measured
    2.2-2.5x faster than 128x128 blocks at every swept S -- fast enough
    to beat even the einsum path at S=1024 (the kernel is
    per-step-overhead-bound at D=64; exp/vit_attn_variants.py, round 4).
    """
    for block in (256, 128, 64, 32, 16, 8):
        if seq % block == 0:
            return block
    return None


def mha_reference(q, k, v, *, causal: bool = False, k_offset: int = 0):
    """Plain softmax attention, (..., S, D) layout.  Ground truth for tests.

    ``k_offset`` is the global position of k[0] relative to q[0] (used when
    the KV block is a remote shard in ring attention).
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = _causal_mask(0, k_offset, q.shape[-2], k.shape[-2])
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)


def attend_block(q, k, v, *, causal: bool = False, k_offset: int = 0):
    """Unnormalized attention partials of q against one KV block.

    Returns ``(acc, m, l)`` with acc: (..., S_q, D) f32 unnormalized output,
    m: (..., S_q) f32 row max, l: (..., S_q) f32 row sum of exp(s - m).
    Partials over different KV blocks combine with ``combine_partials``;
    ``acc / l`` recovers the softmax-attention output.  This is the ring
    attention inner step (parallel.ring).
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = _causal_mask(0, k_offset, q.shape[-2], k.shape[-2])
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("...qk,...kd->...qd", p, v.astype(jnp.float32))
    return acc, m, l


def combine_partials(a, b):
    """Merge two (acc, m, l) partials (log-sum-exp over the KV axis)."""
    acc_a, m_a, l_a = a
    acc_b, m_b, l_b = b
    m = jnp.maximum(m_a, m_b)
    alpha = jnp.exp(m_a - m)
    beta = jnp.exp(m_b - m)
    return (
        acc_a * alpha[..., None] + acc_b * beta[..., None],
        m,
        l_a * alpha + l_b * beta,
    )


def finalize_partials(partial):
    """(acc, m, l) -> normalized attention output.

    Rows that attended nothing (l == 0, e.g. a flash partial over a fully
    causal-masked shard) are defined as zeros rather than 0/0 NaN, matching
    the fused kernel's empty-softmax convention.
    """
    acc, _, l = partial
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return jnp.where((l == 0.0)[..., None], 0.0, acc / safe_l[..., None])


# --- Pallas fused kernel ---------------------------------------------------


def _flash_body(q_ref, k_ref, v_ref, *, block_k, causal, k_offset, kv_len=None):
    """One (1, block_q, d) query tile vs the local KV, online softmax.

    Returns the running ``(acc, m, l)`` carried state: unnormalized output,
    row max, and normalizer, each f32 with m/l shaped (block_q, 1).

    ``kv_len``: number of VALID local kv rows (ragged sequences padded up
    to a block multiple -- e.g. ViT's 257 tokens padded to 264); columns at
    or beyond it are masked to -inf so pad keys never enter the softmax.
    """
    # Dots run on the INPUT dtype with f32 accumulation
    # (preferred_element_type): for bf16 serving inputs that's the MXU's
    # full bf16 rate -- upcasting operands to f32 ran the dots as multi-pass
    # f32 MXU ops at ~1/4 rate, which made this kernel 46% of ViT-B's
    # device time at ~5% MFU (exp/batch_dip_trace.py --model
    # vit-b16-imagenet, round 4).  Softmax statistics stay f32 throughout;
    # f32 inputs keep exact f32 dots (tests, exact paths).
    q = q_ref[0]                              # (block_q, d), input dtype
    in_dtype = q.dtype
    block_q, d = q.shape
    seq_k = k_ref.shape[1]
    num_k = seq_k // block_k
    scale = 1.0 / math.sqrt(d)
    q_start = pl.program_id(1) * block_q

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                              # (block_q, block_k) f32
        if kv_len is not None:
            cols = (
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
                + j * block_k
            )
            s = jnp.where(cols < kv_len, s, NEG_INF)
        if causal:
            mask = _causal_mask(q_start, j * block_k + k_offset, block_q, block_k)
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # p in [0, 1] cast to the input dtype for the PV dot (bf16 MXU
        # rate; standard flash practice), f32 accumulate.
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(in_dtype), v_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l

    if causal:
        # KV blocks whose first key lies beyond this tile's last query are
        # fully in the causal future: stop the stream at the diagonal block
        # instead of computing-then-masking them (~2x FLOPs/bandwidth saved
        # on average; the diagonal tile itself still masks elementwise).
        hi = (q_start + block_q - k_offset + block_k - 1) // block_k
        hi = jnp.clip(hi, 0, num_k)
    else:
        hi = num_k

    acc = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    return jax.lax.fori_loop(0, hi, body, (acc, m, l))


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, k_offset,
                  kv_len=None):
    """Fused form: normalize in-kernel, write the attention output tile."""
    acc, m, l = _flash_body(
        q_ref, k_ref, v_ref, block_k=block_k, causal=causal, k_offset=k_offset,
        kv_len=kv_len,
    )
    # A row masked across EVERY key (causal with k_offset pushing the whole
    # block into the future) ends with m still at NEG_INF and p=exp(0)=1
    # everywhere, i.e. acc/l = mean(v); define empty-softmax as zeros instead.
    masked = m <= NEG_INF * 0.5
    o_ref[0] = jnp.where(masked, 0.0, acc / jnp.where(masked, 1.0, l)).astype(
        o_ref.dtype
    )


def _flash_kernel_partials(
    q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *, block_k, causal, k_offset,
    kv_len=None,
):
    """Partial form: write raw (acc, m, l) for cross-shard lse merging."""
    acc, m, l = _flash_body(
        q_ref, k_ref, v_ref, block_k=block_k, causal=causal, k_offset=k_offset,
        kv_len=kv_len,
    )
    acc_ref[0] = acc
    m_ref[0] = m  # (block_q, 1): trailing singleton keeps Mosaic tiling legal
    l_ref[0] = l


try:  # pallas needs a recent jaxlib; keep the module importable without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU lowering)

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    k_offset: int = 0,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
    return_partials: bool = False,
    kv_len: int | None = None,
):
    """Fused flash attention.  q, k, v: (B, H, S, D) -> (B, H, S, D).

    ``kv_len``: valid kv rows when the sequences are PADDED to a block
    multiple (ragged lengths, e.g. ViT's 257 tokens); pad keys are masked
    out of the softmax.  See ``flash_attention_padded`` for the wrapper
    that does the padding/slicing.

    The full local KV for one (batch, head) lives in VMEM while query tiles
    stream over it, so S_local * D must fit VMEM (~16 MB/core) -- e.g.
    S=8192 at D=128 bf16 is 2 MB/tensor.  Longer sequences shard S over the
    mesh and wrap this kernel with parallel.ring.ring_attention, which is
    exactly the regime ring attention exists for.

    With ``return_partials=True`` the kernel skips in-kernel normalization
    and returns ``(acc, m, l)`` in ``attend_block``'s layout (acc f32
    (B,H,S,D); m, l f32 (B,H,S)) so ring attention can lse-merge partial
    attentions over KV shards while keeping O(S*D) memory -- attend_block's
    einsum would materialize the (S_local, S_local) score matrix per shard.

    ``interpret`` defaults to True off-TPU so the identical kernel logic is
    testable on CPU.
    """
    if not _HAVE_PALLAS:
        raise NotImplementedError("pallas unavailable; use mha_reference")
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq}, {sk}) must be multiples of blocks "
            f"({block_q}, {block_k}); pad the sequence"
        )
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)

    # Inside shard_map, outputs must declare which mesh axes they vary over
    # (check_vma); propagate the query's vma so the kernel composes with
    # parallel.ring.  Outside shard_map (or on a pre-vma JAX) this is the
    # empty set / None.
    from kubernetes_deep_learning_tpu.utils.jaxcompat import (
        shape_dtype_struct,
        typeof,
    )

    vma = getattr(typeof(qf), "vma", None)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda g, i: (g, i, 0)),
        pl.BlockSpec((1, sk, d), lambda g, i: (g, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda g, i: (g, 0, 0)),
    ]
    grid = (b * h, sq // block_q)

    if return_partials:
        kernel = functools.partial(
            _flash_kernel_partials, block_k=block_k, causal=causal,
            k_offset=k_offset, kv_len=kv_len,
        )
        # (B*H, S, 1) with trailing singleton: Mosaic requires the last two
        # block dims be (8k, 128k)-divisible or equal to the array dims; a
        # plain (1, block_q) row block violates that on TPU.
        row_spec = pl.BlockSpec((1, block_q, 1), lambda g, i: (g, i, 0))
        acc, m, l = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda g, i: (g, i, 0)),
                row_spec,
                row_spec,
            ],
            out_shape=[
                shape_dtype_struct((b * h, sq, d), jnp.float32, vma=vma),
                shape_dtype_struct((b * h, sq, 1), jnp.float32, vma=vma),
                shape_dtype_struct((b * h, sq, 1), jnp.float32, vma=vma),
            ],
            interpret=interpret,
        )(qf, kf, vf)
        return (
            acc.reshape(b, h, sq, d),
            m.reshape(b, h, sq),
            l.reshape(b, h, sq),
        )

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, k_offset=k_offset,
        kv_len=kv_len,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda g, i: (g, i, 0)),
        out_shape=shape_dtype_struct((b * h, sq, d), q.dtype, vma=vma),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d)


def flash_attention_padded(q, k, v, *, causal: bool = False,
                           interpret: bool | None = None):
    """Flash attention for ANY sequence length: pads S up to the nearest
    block multiple, masks the pad keys via ``kv_len``, slices the output.

    Without this, a sequence with no 8-aligned divisor (ViT-B/16 at 256
    squared has 257 tokens -- prime) silently fell back to the einsum
    reference and materialized the (S, S) score matrix in HBM.  Pad-query
    rows are zeros; their outputs are garbage-free (finite) and sliced off.
    """
    sq, sk = q.shape[2], k.shape[2]
    block_q, block_k = pick_block(sq), pick_block(sk)
    if block_q is not None and block_k is not None:
        return flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )
    # Pad to a multiple of 128, NOT the minimal 8: pick_block(next-8-
    # multiple) would tile the MXU at 8x8 for most ragged lengths (e.g.
    # 257 -> 264 -> block 8), wasting ~15/16 of every pass.  The extra pad
    # rows are masked by kv_len and cost <=127 rows of FLOPs.  Query and
    # KV pad INDEPENDENTLY: cross-attention arrives with sq != sk, and a
    # q-derived pad on k either misaligns or crashes the kernel's
    # divisibility check.
    sqp = -(-sq // 128) * 128
    skp = -(-sk // 128) * 128
    pad_q = ((0, 0), (0, 0), (0, sqp - sq), (0, 0))
    pad_k = ((0, 0), (0, 0), (0, skp - sk), (0, 0))
    out = flash_attention(
        jnp.pad(q, pad_q), jnp.pad(k, pad_k), jnp.pad(v, pad_k),
        causal=causal, block_q=pick_block(sqp), block_k=pick_block(skp),
        interpret=interpret, kv_len=sk if skp != sk else None,
    )
    return out[:, :, :sq, :]


# Sequence length up to which inference routes to the einsum path.  Not a
# perf crossover -- einsum never lost to the kernel in the round-4 sweep
# (6.5x faster at ViT-B's (32,12,256,64), still 1.4x at S=1024, because
# D=64 heads give each flash grid step only ~4 MFLOP of work against
# ~1.7 us of fixed per-step cost) -- but an HBM-comfort bound on the
# (B, H, S, S) f32 scores it materializes: <=1.6 GiB at the largest
# default bucket (128) for ViT-B.  Sequence-only (not batch) so the rule
# stays decidable under the exporter's SYMBOLIC batch dimension and every
# bucket of one artifact routes identically.
EINSUM_MAX_SEQ = 512


def use_einsum_attention(sq: int, sk: int) -> bool:
    """Trace-time routing rule for ``attention_serving`` (pure, testable)."""
    return sq <= EINSUM_MAX_SEQ and sk <= EINSUM_MAX_SEQ


def attention_serving(q, k, v, *, causal: bool = False):
    """Inference MHA with measured shape routing (round 4).

    Short/serving-scale sequences take the einsum path: materializing the
    f32 score matrix in HBM costs far less than the flash kernel's
    per-grid-step overhead (see ``EINSUM_MAX_SEQ``).  Beyond
    the sequence budget -- long-context, ring-attention shards -- the
    fused kernel takes over: that memory wall is what it exists for.  The
    kernel branch resolves per LOWERING platform (the exporter traces one
    module for cpu and tpu; a trace-time backend check would bake the
    wrong mode into one of them), while the einsum branch is
    platform-portable as-is.
    """
    sq, sk = q.shape[2], k.shape[2]
    if use_einsum_attention(sq, sk) or not _HAVE_PALLAS:
        return mha_reference(q, k, v, causal=causal)
    from kubernetes_deep_learning_tpu.utils.jaxcompat import platform_dependent

    return platform_dependent(
        q, k, v,
        tpu=functools.partial(
            flash_attention_padded, causal=causal, interpret=False
        ),
        default=functools.partial(mha_reference, causal=causal),
    )


# --- trainable memory-efficient attention ----------------------------------
# The Pallas kernel defines no VJP, so training previously fell back to the
# full einsum reference, materializing the (S, S) score matrix in HBM --
# exactly what flash attention exists to avoid, and the memory wall for
# long-context fine-tuning.  attention_trainable closes the gap with a
# custom_vjp: the primal is the fused kernel (per lowering platform, like
# models.vit), and the backward is the standard FlashAttention recomputation
# -- a lax.scan over KV blocks that rebuilds each score block from q, k and
# the saved logsumexp, so backward memory is O(S * block) instead of O(S^2).


def _finalize_with_lse(partials, dtype):
    """(acc, m, l) -> (normalized out, lse = m + log l), shared epilogue."""
    _, m, l = partials
    out = finalize_partials(partials).astype(dtype)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return out, m + jnp.log(safe_l)


def _forward_with_lse(q, k, v, causal: bool):
    """(out, lse) with lse the softmax log-normalizer per row."""
    # Cross-attention (sq != sk) tiles each side independently.
    block_q = pick_block(q.shape[2])
    block_k = pick_block(k.shape[2])

    def via_flash(q, k, v):
        partials = flash_attention(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            interpret=False, return_partials=True,
        )
        return _finalize_with_lse(partials, q.dtype)

    def via_reference(q, k, v):
        return _finalize_with_lse(attend_block(q, k, v, causal=causal), q.dtype)

    if block_q is None or block_k is None or not _HAVE_PALLAS:
        return via_reference(q, k, v)
    from kubernetes_deep_learning_tpu.utils.jaxcompat import platform_dependent

    return platform_dependent(
        q, k, v, tpu=via_flash, default=via_reference
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def attention_trainable(q, k, v, causal: bool = False):
    """Differentiable attention, (B, H, S, D), O(S * block) activation memory.

    Forward runs the fused flash kernel in TPU lowerings (einsum reference
    elsewhere); backward recomputes score blocks from (q, k, lse) in a scan
    over KV blocks.  The building block for long-context *training* --
    inference-only callers can keep using flash_attention directly.
    """
    out, _ = _forward_with_lse(q, k, v, causal)
    return out


def _attn_fwd(q, k, v, causal: bool):
    out, lse = _forward_with_lse(q, k, v, causal)
    return out, (q, k, v, out, lse)


def block_grads(q32, k32, v32, lse_q, delta_q, do32_q, scale, mask=None):
    """One (q-block, kv-block) backward pair from the saved logsumexp.

    THE single implementation of the FlashAttention-2 recomputation body --
    shared by the non-causal scan, the 2D-tiled causal backward, and the
    trainable ring's per-shard gradients (parallel.ring), so the score/p/ds
    algebra can never drift between them.  All inputs f32; ``mask`` is an
    optional (sq_blk, sk_blk) bool visibility mask.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse_q[..., None])
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, do32_q)
    dp = jnp.einsum("bhqd,bhkd->bhqk", do32_q, v32)
    ds = p * (dp - delta_q[..., None])
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k32) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q32) * scale
    return dq, dk, dv


def _attn_bwd(causal: bool, res, dout):
    q, k, v, out, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    block = pick_block(sk) or sk
    nk = sk // block

    do32 = dout.astype(jnp.float32)
    q32 = q.astype(jnp.float32)
    # D_i = sum_d dO_i * O_i, the softmax-backward row correction.
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)  # (B,H,Sq)

    if causal:
        return _attn_bwd_2d(q32, k, v, do32, lse, delta, scale, block, q.dtype)

    # Bidirectional: every (q, kv) pair contributes, so there is nothing to
    # skip and the single-level KV scan has the least loop overhead.
    def body(dq_acc, j):
        k32 = jax.lax.dynamic_slice_in_dim(k, j * block, block, axis=2).astype(
            jnp.float32
        )
        v32 = jax.lax.dynamic_slice_in_dim(v, j * block, block, axis=2).astype(
            jnp.float32
        )
        dq_j, dk_j, dv_j = block_grads(q32, k32, v32, lse, delta, do32, scale)
        return dq_acc + dq_j, (dk_j, dv_j)

    dq, (dks, dvs) = jax.lax.scan(
        body, jnp.zeros(q.shape, jnp.float32), jnp.arange(nk)
    )
    # scan stacks per-block grads as (nk, B, H, block, D); reorder the block
    # axis next to its intra-block dim before flattening to (B, H, Sk, D).
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, sk, d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, sk, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _attn_bwd_2d(q32, k, v, do32, lse, delta, scale, block, q_dtype):
    """Causal backward, 2D-tiled: (q block x kv block) pairs strictly above
    the diagonal are SKIPPED via lax.cond, reclaiming the triangular FLOPs
    the round-1 backward paid (its single-level KV scan had no q tiling, so
    no block was ever fully masked).  Memory stays O(S * block)."""
    b, h, sq, d = q32.shape
    sk = k.shape[2]
    block_q = pick_block(sq) or sq
    nq, nk = sq // block_q, sk // block

    def kv_body(dq_full, j):
        k32 = jax.lax.dynamic_slice_in_dim(k, j * block, block, axis=2).astype(
            jnp.float32
        )
        v32 = jax.lax.dynamic_slice_in_dim(v, j * block, block, axis=2).astype(
            jnp.float32
        )

        def q_body(carry, i):
            dq_full, dk_acc, dv_acc = carry
            q_i = jax.lax.dynamic_slice_in_dim(q32, i * block_q, block_q, axis=2)
            do_i = jax.lax.dynamic_slice_in_dim(do32, i * block_q, block_q, axis=2)
            lse_i = jax.lax.dynamic_slice_in_dim(lse, i * block_q, block_q, axis=2)
            dl_i = jax.lax.dynamic_slice_in_dim(delta, i * block_q, block_q, axis=2)

            def compute(args):
                dq_full, dk_acc, dv_acc = args
                rows = (
                    jax.lax.broadcasted_iota(jnp.int32, (block_q, block), 0)
                    + i * block_q
                )
                cols = (
                    jax.lax.broadcasted_iota(jnp.int32, (block_q, block), 1)
                    + j * block
                )
                dq_i, dk_i, dv_i = block_grads(
                    q_i, k32, v32, lse_i, dl_i, do_i, scale, mask=rows >= cols
                )
                dq_full = jax.lax.dynamic_update_slice_in_dim(
                    dq_full,
                    jax.lax.dynamic_slice_in_dim(
                        dq_full, i * block_q, block_q, axis=2
                    )
                    + dq_i,
                    i * block_q,
                    axis=2,
                )
                return dq_full, dk_acc + dk_i, dv_acc + dv_i

            # Skip pairs strictly above the diagonal: the last row of q
            # block i is i*bq + bq - 1; it sees no key >= that + 1.
            visible = (i + 1) * block_q > j * block
            return jax.lax.cond(visible, compute, lambda a: a, carry), None

        (dq_full, dk_j, dv_j), _ = jax.lax.scan(
            q_body,
            (
                dq_full,
                jnp.zeros((b, h, block, d), jnp.float32),
                jnp.zeros((b, h, block, d), jnp.float32),
            ),
            jnp.arange(nq),
        )
        return dq_full, (dk_j, dv_j)

    dq, (dks, dvs) = jax.lax.scan(
        kv_body, jnp.zeros(q32.shape, jnp.float32), jnp.arange(nk)
    )
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, sk, d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, sk, d)
    return dq.astype(q_dtype), dk.astype(k.dtype), dv.astype(v.dtype)


attention_trainable.defvjp(_attn_fwd, _attn_bwd)
