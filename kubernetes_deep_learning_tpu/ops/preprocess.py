"""Image preprocessing: host-side decode/resize, device-side normalization.

The reference delegates all of this to the ``keras-image-helper`` package
(reference model_server.py:8,18,53: ``create_preprocessor('xception',
target_size=(299, 299)).from_url(url)``), which downloads the image, resizes
with PIL, and normalizes on the *host*.  TPU-first redesign:

- host side does only what must be on host: HTTP fetch, JPEG/PNG decode, and
  resize to the model's input resolution, staying in **uint8** (3x smaller on
  the gateway->server wire than f32);
- normalization (the elementwise scale/shift) runs **on device**, where XLA
  fuses it into the first convolution -- it never costs a separate HBM pass.

A C++ fast path for resize lives in native/ (see ``_native.resize`` below);
PIL is the fallback so the package works without the compiled library.
"""

from __future__ import annotations

import io
import os
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np

try:  # optional C++ fast path (native/preprocess.cc)
    from kubernetes_deep_learning_tpu.ops import _native
except Exception:  # pragma: no cover - native lib not built
    _native = None

# Normalization constants, index-aligned with `modelspec.ModelSpec.preprocessing`.
#   tf    : x / 127.5 - 1            (Keras "tf" mode; Xception, reference
#           keras-image-helper behavior for create_preprocessor('xception'))
#   caffe : BGR, subtract ImageNet channel means (Keras "caffe" mode; ResNet50)
#   torch : x / 255, ImageNet mean/std (EfficientNet via torchvision convention)
_CAFFE_MEAN_BGR = np.array([103.939, 116.779, 123.68], np.float32)
_TORCH_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
_TORCH_STD = np.array([0.229, 0.224, 0.225], np.float32)

USER_AGENT = "kdlt-gateway/0.1"
FETCH_TIMEOUT_S = 10.0
MAX_FETCH_BYTES = 32 * 1024 * 1024  # reject pathological/streaming URLs

# Decode-pool sizing for the model tier's raw-bytes ingest stage (GUIDE
# 10q): threads running PIL/native decode+resize with the GIL released.
# Sized to the host's cores but capped -- decode work overlaps device
# execution, and an unbounded pool would let a burst of bytes-wire
# requests steal every core from the dispatch threads.
DECODE_POOL_ENV = "KDLT_DECODE_POOL"
DEFAULT_DECODE_POOL = max(2, min(8, os.cpu_count() or 4))


def resolve_decode_pool(explicit: int | None = None) -> int:
    """Explicit arg > $KDLT_DECODE_POOL > core-scaled default; always >= 1."""
    if explicit is not None:
        return max(1, int(explicit))
    raw = os.environ.get(DECODE_POOL_ENV, "")
    try:
        return max(1, int(raw)) if raw.strip() else DEFAULT_DECODE_POOL
    except ValueError:
        return DEFAULT_DECODE_POOL


def fetch_image_bytes(
    url: str, timeout: float = FETCH_TIMEOUT_S, max_bytes: int = MAX_FETCH_BYTES
) -> bytes:
    """Download raw image bytes (the reference gateway's .from_url step).

    The read is bounded: an attacker-supplied URL pointing at a multi-GB or
    endless stream must not OOM the gateway (the timeout only bounds
    inactivity, not transferred bytes).
    """
    req = urllib.request.Request(url, headers={"User-Agent": USER_AGENT})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        data = resp.read(max_bytes + 1)
    if len(data) > max_bytes:
        raise ValueError(f"image at {url!r} exceeds {max_bytes} byte limit")
    return data


def decode_image(data: bytes) -> np.ndarray:
    """Decode JPEG/PNG bytes to an RGB uint8 HWC array."""
    from PIL import Image

    with Image.open(io.BytesIO(data)) as img:
        if img.mode != "RGB":
            img = img.convert("RGB")
        # kdlt-lint: disable=hot-path-sync -- host decode IS the materialization: it runs in the GIL-released decode pool before any device dispatch, never on the dispatch side
        return np.asarray(img, dtype=np.uint8)


def resize_uint8(
    img: np.ndarray, size: tuple[int, int], filter: str = "bilinear"
) -> np.ndarray:
    """Resize an RGB uint8 HWC array to (H, W).

    ``filter`` comes from ModelSpec.resize_filter: the clothing model uses
    "nearest" because keras-image-helper (the reference's preprocessor,
    reference model_server.py:18) resizes with Image.NEAREST, and the filter
    choice shifts logits far beyond numerical tolerance.  Uses the in-tree
    C++ kernel when available (native/hostops.cc -- bit-exact with PIL for
    both filters, tests/test_native.py), else PIL.
    """
    if filter not in ("bilinear", "nearest"):
        raise ValueError(f"unknown resize filter {filter!r}")
    h, w = int(size[0]), int(size[1])
    if img.shape[0] == h and img.shape[1] == w:
        return np.ascontiguousarray(img)
    if _native is not None:
        fn = _native.resize_bilinear if filter == "bilinear" else _native.resize_nearest
        return fn(img, h, w)
    from PIL import Image

    filters = {"bilinear": Image.BILINEAR, "nearest": Image.NEAREST}
    pil = Image.fromarray(img)
    # kdlt-lint: disable=hot-path-sync -- PIL-fallback resize materializes on host by design (decode-pool stage, pre-dispatch); the native kernel path above avoids the copy
    return np.asarray(pil.resize((w, h), filters[filter]), dtype=np.uint8)


def preprocess_bytes(
    data: bytes, size: tuple[int, int], *, filter: str = "bilinear"
) -> np.ndarray:
    """bytes -> resized RGB uint8 HWC; the full host-side gateway pipeline."""
    return resize_uint8(decode_image(data), size, filter)


class BatchDecoder:
    """The model tier's vectorized decode stage (GUIDE 10q): a bytes-wire
    request's JPEG/PNG blobs -> one resized RGB uint8 (N,H,W,C) batch.

    Decode and resize run in a bounded thread pool: both PIL's decoders
    and the native resize kernel release the GIL, so a 32-image batch
    costs ~one image's wall time on an 8-thread pool instead of 32x
    serial Python.  Per-image failures raise ValueError naming the index
    -- the transports map that to a 400 (a corrupt blob is the CLIENT's
    error, never a 500, and never a crashed worker).

    This is the serving hot path's decode entry point: kdlt-lint's
    hot-path-sync pass roots here, so any future device-blocking call
    slipped into the stage is caught statically.
    """

    def __init__(self, workers: int | None = None):
        self.workers = resolve_decode_pool(workers)
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="kdlt-decode"
        )

    def _decode_one(self, i: int, blob: bytes, size, filter: str) -> np.ndarray:
        try:
            return preprocess_bytes(blob, size, filter=filter)
        except ValueError:
            raise
        except Exception as e:  # noqa: BLE001 - undecodable client bytes
            raise ValueError(f"image {i}: undecodable image bytes ({e})") from e

    def decode_batch(
        self, blobs: list[bytes], size: tuple[int, int], *,
        filter: str = "bilinear",
    ) -> np.ndarray:
        """Encoded blobs -> stacked uint8 (N,H,W,C) batch at ``size``."""
        if not blobs:
            raise ValueError("empty image batch")
        if len(blobs) == 1:
            # No pool hop for the single-image common case: the handler
            # thread decodes inline (the GIL releases either way).
            return self._decode_one(0, blobs[0], size, filter)[None]
        futures = [
            self._pool.submit(self._decode_one, i, blob, size, filter)
            for i, blob in enumerate(blobs)
        ]
        return np.stack([f.result() for f in futures])

    def close(self) -> None:
        self._pool.shutdown(wait=False)


def normalize(x, mode: str):
    """uint8/float image batch -> normalized float input, in jax or numpy.

    Works on both np.ndarray and jax.Array (pure elementwise ops); inside jit
    XLA fuses this into the consuming convolution.
    """
    if mode == "none":
        return x
    # Keep jax out of the pure-numpy (gateway host) path: jax init is heavy
    # and the gateway should not pay it. astype(np.float32) works for both.
    x = x.astype(np.float32)
    if mode == "tf":
        return x / 127.5 - 1.0
    if mode == "caffe":
        # RGB -> BGR, then subtract channel means (no scaling).
        x = x[..., ::-1]
        return x - _CAFFE_MEAN_BGR
    if mode == "torch":
        return (x / 255.0 - _TORCH_MEAN) / _TORCH_STD
    raise ValueError(f"unknown preprocessing mode {mode!r}")
