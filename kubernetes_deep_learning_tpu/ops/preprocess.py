"""Image preprocessing: host-side decode/resize, device-side normalization.

The reference delegates all of this to the ``keras-image-helper`` package
(reference model_server.py:8,18,53: ``create_preprocessor('xception',
target_size=(299, 299)).from_url(url)``), which downloads the image, resizes
with PIL, and normalizes on the *host*.  TPU-first redesign:

- host side does only what must be on host: HTTP fetch, JPEG/PNG decode, and
  resize to the model's input resolution, staying in **uint8** (3x smaller on
  the gateway->server wire than f32);
- normalization (the elementwise scale/shift) runs **on device**, where XLA
  fuses it into the first convolution -- it never costs a separate HBM pass.

A C++ fast path for resize lives in native/ (see ``_native.resize`` below);
PIL is the fallback so the package works without the compiled library.
"""

from __future__ import annotations

import io
import urllib.request

import numpy as np

try:  # optional C++ fast path (native/preprocess.cc)
    from kubernetes_deep_learning_tpu.ops import _native
except Exception:  # pragma: no cover - native lib not built
    _native = None

# Normalization constants, index-aligned with `modelspec.ModelSpec.preprocessing`.
#   tf    : x / 127.5 - 1            (Keras "tf" mode; Xception, reference
#           keras-image-helper behavior for create_preprocessor('xception'))
#   caffe : BGR, subtract ImageNet channel means (Keras "caffe" mode; ResNet50)
#   torch : x / 255, ImageNet mean/std (EfficientNet via torchvision convention)
_CAFFE_MEAN_BGR = np.array([103.939, 116.779, 123.68], np.float32)
_TORCH_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
_TORCH_STD = np.array([0.229, 0.224, 0.225], np.float32)

USER_AGENT = "kdlt-gateway/0.1"
FETCH_TIMEOUT_S = 10.0
MAX_FETCH_BYTES = 32 * 1024 * 1024  # reject pathological/streaming URLs


def fetch_image_bytes(
    url: str, timeout: float = FETCH_TIMEOUT_S, max_bytes: int = MAX_FETCH_BYTES
) -> bytes:
    """Download raw image bytes (the reference gateway's .from_url step).

    The read is bounded: an attacker-supplied URL pointing at a multi-GB or
    endless stream must not OOM the gateway (the timeout only bounds
    inactivity, not transferred bytes).
    """
    req = urllib.request.Request(url, headers={"User-Agent": USER_AGENT})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        data = resp.read(max_bytes + 1)
    if len(data) > max_bytes:
        raise ValueError(f"image at {url!r} exceeds {max_bytes} byte limit")
    return data


def decode_image(data: bytes) -> np.ndarray:
    """Decode JPEG/PNG bytes to an RGB uint8 HWC array."""
    from PIL import Image

    with Image.open(io.BytesIO(data)) as img:
        if img.mode != "RGB":
            img = img.convert("RGB")
        return np.asarray(img, dtype=np.uint8)


def resize_uint8(
    img: np.ndarray, size: tuple[int, int], filter: str = "bilinear"
) -> np.ndarray:
    """Resize an RGB uint8 HWC array to (H, W).

    ``filter`` comes from ModelSpec.resize_filter: the clothing model uses
    "nearest" because keras-image-helper (the reference's preprocessor,
    reference model_server.py:18) resizes with Image.NEAREST, and the filter
    choice shifts logits far beyond numerical tolerance.  Uses the in-tree
    C++ kernel when available (native/hostops.cc -- bit-exact with PIL for
    both filters, tests/test_native.py), else PIL.
    """
    if filter not in ("bilinear", "nearest"):
        raise ValueError(f"unknown resize filter {filter!r}")
    h, w = int(size[0]), int(size[1])
    if img.shape[0] == h and img.shape[1] == w:
        return np.ascontiguousarray(img)
    if _native is not None:
        fn = _native.resize_bilinear if filter == "bilinear" else _native.resize_nearest
        return fn(img, h, w)
    from PIL import Image

    filters = {"bilinear": Image.BILINEAR, "nearest": Image.NEAREST}
    pil = Image.fromarray(img)
    return np.asarray(pil.resize((w, h), filters[filter]), dtype=np.uint8)


def preprocess_bytes(
    data: bytes, size: tuple[int, int], *, filter: str = "bilinear"
) -> np.ndarray:
    """bytes -> resized RGB uint8 HWC; the full host-side gateway pipeline."""
    return resize_uint8(decode_image(data), size, filter)


def normalize(x, mode: str):
    """uint8/float image batch -> normalized float input, in jax or numpy.

    Works on both np.ndarray and jax.Array (pure elementwise ops); inside jit
    XLA fuses this into the consuming convolution.
    """
    if mode == "none":
        return x
    # Keep jax out of the pure-numpy (gateway host) path: jax init is heavy
    # and the gateway should not pay it. astype(np.float32) works for both.
    x = x.astype(np.float32)
    if mode == "tf":
        return x / 127.5 - 1.0
    if mode == "caffe":
        # RGB -> BGR, then subtract channel means (no scaling).
        x = x[..., ::-1]
        return x - _CAFFE_MEAN_BGR
    if mode == "torch":
        return (x / 255.0 - _TORCH_MEAN) / _TORCH_STD
    raise ValueError(f"unknown preprocessing mode {mode!r}")
