"""Fused MBConv residual block: one Pallas kernel per EfficientNet
stride-1 block (expand 1x1 -> depthwise kxk -> squeeze-excite -> project
1x1 -> +residual).

Why: EfficientNet-B3 served at 12% MFU (BENCH.md round 3) -- the MBConv
block is the Xception sepconv pattern (ops.fused_sepconv) plus an expand
GEMM, an SE gate, and silu epilogues, and XLA runs it as 4+ fusions with
the 6x-expanded activation round-tripping HBM between them.  This kernel
keeps the whole (H, W) extent of a batch tile resident in VMEM across the
entire block, exactly like the sepconv kernels:

- **Layout (H, W, bt, C)**: batch on sublanes, channels on lanes; the
  depthwise shifts move along OUTER dims only (no Mosaic relayouts), and
  each pointwise GEMM collapses (H*W*bt, C) rows onto the MXU.
- **Squeeze-excite in-kernel**: the tile holds the full spatial extent of
  its images, so SE's global mean is one in-VMEM reduction to (bt, C_mid);
  the two bottleneck GEMMs are FLOP-trivial.
- **BN folded** (fold_bn), **silu on the VPU** in f32 before the cast back.

Scope, stated: stride-1 blocks only, and only at spatial extents whose
expanded tile fits VMEM (B3's stages at <=38x38 -- which hold most of the
depth: the stride-2 stage openers and the two high-resolution early stages
stay on XLA).  The reference's analog of all of this is "use the
TF-Serving GPU image" (reference tf-serving.dockerfile:1); here the hot
block IS the framework's own kernel.
"""

from __future__ import annotations

import functools

from kubernetes_deep_learning_tpu.ops.fused_sepconv import (
    _legal_bt,
    _pad_batch_to_8,
    fold_bn,
)


def mbconv_block_weights(params: dict, stats: dict, block: str):
    """One stride-1 MBConv block's weights from the flax variable tree
    (models.efficientnet.MBConvBlock's parameter naming), BN folded.

    Returns a dict of arrays ready for fused_mbconv_block_t:
    expand_w (C_in, C_mid) bf16, expand_s/expand_b (C_mid,) f32,
    dw (k, k, C_mid) f32, dw_s/dw_b (C_mid,) f32,
    se_r_w (C_mid, S) bf16, se_r_b (S,) f32,
    se_e_w (S, C_mid) bf16, se_e_b (C_mid,) f32,
    proj_w (C_mid, C_out) bf16, proj_s/proj_b (C_out,) f32.
    """
    import jax.numpy as jnp

    p = params[block]
    s = stats[block]
    exp_s, exp_b = fold_bn(p["expand_bn"], s["expand_bn"])
    dw_s, dw_b = fold_bn(p["dw_bn"], s["dw_bn"])
    pr_s, pr_b = fold_bn(p["project_bn"], s["project_bn"])
    return {
        "expand_w": jnp.asarray(p["expand_conv"]["kernel"], jnp.float32)[0, 0].astype(
            jnp.bfloat16
        ),
        "expand_s": exp_s,
        "expand_b": exp_b,
        "dw": jnp.asarray(p["dwconv"]["kernel"], jnp.float32)[:, :, 0, :],
        "dw_s": dw_s,
        "dw_b": dw_b,
        "se_r_w": jnp.asarray(p["se"]["reduce"]["kernel"], jnp.float32)[0, 0].astype(
            jnp.bfloat16
        ),
        "se_r_b": jnp.asarray(p["se"]["reduce"]["bias"], jnp.float32),
        "se_e_w": jnp.asarray(p["se"]["expand"]["kernel"], jnp.float32)[0, 0].astype(
            jnp.bfloat16
        ),
        "se_e_b": jnp.asarray(p["se"]["expand"]["bias"], jnp.float32),
        "proj_w": jnp.asarray(p["project_conv"]["kernel"], jnp.float32)[0, 0].astype(
            jnp.bfloat16
        ),
        "proj_s": pr_s,
        "proj_b": pr_b,
    }


def mbconv_block_reference(x, w):
    """Plain-jnp semantics of the fused kernel (NHWC), for tests and CPU.

    Matches models.efficientnet.MBConvBlock with expand_ratio != 1,
    stride 1, SE enabled, residual (c_in == c_out), inference BN.
    """
    import jax
    import jax.numpy as jnp

    k = w["dw"].shape[0]
    pad = k // 2
    y = jnp.einsum(
        "bhwc,cd->bhwd",
        x.astype(jnp.bfloat16),
        w["expand_w"],
        preferred_element_type=jnp.float32,
    )
    y = jax.nn.silu(y * w["expand_s"] + w["expand_b"]).astype(jnp.bfloat16)

    yp = jnp.pad(y, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    acc = jnp.zeros(y.shape, jnp.float32)
    for a in range(k):
        for b in range(k):
            acc = acc + (
                yp[:, a : a + y.shape[1], b : b + y.shape[2], :].astype(jnp.float32)
                * w["dw"][a, b, :].astype(jnp.float32)
            )
    y = jax.nn.silu(acc * w["dw_s"] + w["dw_b"]).astype(jnp.bfloat16)

    m = y.astype(jnp.float32).mean(axis=(1, 2))  # (N, C_mid)
    r = jax.nn.silu(
        jnp.einsum("nc,cs->ns", m.astype(jnp.bfloat16), w["se_r_w"],
                   preferred_element_type=jnp.float32)
        + w["se_r_b"]
    )
    g = jax.nn.sigmoid(
        jnp.einsum("ns,sc->nc", r.astype(jnp.bfloat16), w["se_e_w"],
                   preferred_element_type=jnp.float32)
        + w["se_e_b"]
    )
    y = (y.astype(jnp.float32) * g[:, None, None, :]).astype(jnp.bfloat16)

    z = jnp.einsum(
        "bhwc,cd->bhwd", y, w["proj_w"], preferred_element_type=jnp.float32
    )
    z = z * w["proj_s"] + w["proj_b"]
    return x + z.astype(x.dtype)


# The expanded activation's VMEM working set is ~8 bytes/element: the bf16
# tile (2) + its zero-padded copy (2) + the f32 depthwise accumulator (4),
# before register-allocator spill headroom -- the batch-64 B3 compile with
# a bf16-only (2 B/elem) budget OOM'd VMEM at 159.5/128 MiB, 114 MiB of it
# spill slots (recorded in exp/mbconv_variants.py's first run).
_WORKING_SET_BYTES_PER_ELEM = 8
_TILE_BUDGET = 32 << 20
# Scoped-VMEM cap handed to the Mosaic compiler; module-level so
# experiments can raise it alongside _TILE_BUDGET without monkeypatching
# private internals (exp/mbconv_variants.py --tile-budget-mb).
VMEM_LIMIT_BYTES = 96 * 1024 * 1024


def mbconv_fusible(h: int, w: int, c_mid: int) -> bool:
    """Whether the fused kernel's SMALLEST legal tile (bt=8) fits the VMEM
    budget at this spatial extent; callers keep bigger blocks on XLA."""
    return h * w * 8 * c_mid * _WORKING_SET_BYTES_PER_ELEM <= _TILE_BUDGET


def pick_mbconv_bt(h: int, w: int, batch: int, c_mid: int) -> int:
    """Largest 8-multiple batch tile whose working set fits the budget."""
    for cand in (32, 24, 16, 8):
        if (
            batch % cand == 0
            and h * w * cand * c_mid * _WORKING_SET_BYTES_PER_ELEM <= _TILE_BUDGET
        ):
            return cand
    return 8


@functools.cache
def _compiler_params(limit_bytes: int):
    from jax.experimental.pallas import tpu as pltpu

    params_cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    # Same 96 MiB default as fused_sepconv (since round 4, via
    # VMEM_LIMIT_BYTES): the largest fused B3 tile under the default
    # budget peaks well under 64 MiB, and the recurring TPU worker fault
    # made VMEM headroom cheap insurance.
    return params_cls(vmem_limit_bytes=limit_bytes)


def fused_mbconv_block_t(xt, w, *, bt: int = 0, residual: bool = True,
                         interpret: bool = False):
    """The kernel, on (H, W, B, C_in) bf16 input; returns (H, W, B, C_out).

    Stride-1, SAME padding.  ``residual`` adds the input (caller guarantees
    C_out == C_in then); residual=False serves stride-1 stage openers whose
    channel count changes.  ``bt`` 0 = auto; non-8-aligned batches are
    sublane-padded (see fused_sepconv._pad_batch_to_8).  The SE mean
    reduces the spatial extent only -- padded batch rows are junk anyway
    and sliced off.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    xt, B_orig = _pad_batch_to_8(xt)
    H, W, B, C_in = xt.shape
    C_mid = w["expand_w"].shape[1]
    C_out = w["proj_w"].shape[1]
    if residual and C_out != C_in:
        raise ValueError(f"residual block needs C_out == C_in, got {C_in}->{C_out}")
    S = w["se_r_w"].shape[1]
    k = w["dw"].shape[0]
    pad = k // 2
    if bt == 0:
        bt = pick_mbconv_bt(H, W, B, C_mid)
    bt = _legal_bt(bt, B)

    def kernel(x_ref, ew_ref, es_ref, eb_ref, dw_ref, ds_ref, db_ref,
               rw_ref, rb_ref, xw_ref, xb_ref, pw_ref, ps_ref, pb_ref, o_ref):
        x = x_ref[...]  # (H, W, bt, C_in) bf16
        # expand 1x1 -> bn -> silu
        z = jax.lax.dot_general(
            x.reshape(H * W * bt, C_in), ew_ref[...],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        z = z * es_ref[...] + eb_ref[...]
        z = (z * jax.nn.sigmoid(z)).astype(jnp.bfloat16).reshape(H, W, bt, C_mid)
        # depthwise kxk (zero halos = SAME) -> bn -> silu, f32 accumulation
        zp = jnp.pad(z, ((pad, pad), (pad, pad), (0, 0), (0, 0)))
        acc = jnp.zeros((H, W, bt, C_mid), jnp.float32)
        for dh in range(k):
            for dwc in range(k):
                tap = dw_ref[dh, dwc, :].astype(jnp.float32)
                acc = acc + (
                    zp[dh : dh + H, dwc : dwc + W, :, :].astype(jnp.float32) * tap
                )
        acc = acc * ds_ref[...] + db_ref[...]
        y32 = acc * jax.nn.sigmoid(acc)  # (H, W, bt, C_mid) f32
        # squeeze-excite: global spatial mean on the resident tile
        m = y32.mean(axis=(0, 1))  # (bt, C_mid)
        r = jax.lax.dot_general(
            m.astype(jnp.bfloat16), rw_ref[...],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        ) + rb_ref[...]
        r = r * jax.nn.sigmoid(r)  # silu, (bt, S)
        g = jax.lax.dot_general(
            r.astype(jnp.bfloat16), xw_ref[...],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        ) + xb_ref[...]
        g = jax.nn.sigmoid(g)  # (bt, C_mid)
        y = (y32 * g[None, None, :, :]).astype(jnp.bfloat16)
        # project 1x1 -> bn [-> +residual]
        z = jax.lax.dot_general(
            y.reshape(H * W * bt, C_mid), pw_ref[...],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        z = z * ps_ref[...] + pb_ref[...]
        z = z.astype(jnp.bfloat16).reshape(H, W, bt, C_out)
        o_ref[...] = (x_ref[...] + z) if residual else z

    out = pl.pallas_call(
        kernel,
        grid=(B // bt,),
        in_specs=[
            pl.BlockSpec((H, W, bt, C_in), lambda g: (0, 0, g, 0)),
            pl.BlockSpec((C_in, C_mid), lambda g: (0, 0)),
            pl.BlockSpec((C_mid,), lambda g: (0,)),
            pl.BlockSpec((C_mid,), lambda g: (0,)),
            pl.BlockSpec((k, k, C_mid), lambda g: (0, 0, 0)),
            pl.BlockSpec((C_mid,), lambda g: (0,)),
            pl.BlockSpec((C_mid,), lambda g: (0,)),
            pl.BlockSpec((C_mid, S), lambda g: (0, 0)),
            pl.BlockSpec((S,), lambda g: (0,)),
            pl.BlockSpec((S, C_mid), lambda g: (0, 0)),
            pl.BlockSpec((C_mid,), lambda g: (0,)),
            pl.BlockSpec((C_mid, C_out), lambda g: (0, 0)),
            pl.BlockSpec((C_out,), lambda g: (0,)),
            pl.BlockSpec((C_out,), lambda g: (0,)),
        ],
        out_specs=pl.BlockSpec((H, W, bt, C_out), lambda g: (0, 0, g, 0)),
        out_shape=jax.ShapeDtypeStruct((H, W, B, C_out), xt.dtype),
        compiler_params=_compiler_params(VMEM_LIMIT_BYTES),
        interpret=interpret,
    )(
        xt, w["expand_w"], w["expand_s"], w["expand_b"],
        w["dw"], w["dw_s"], w["dw_b"],
        w["se_r_w"], w["se_r_b"], w["se_e_w"], w["se_e_b"],
        w["proj_w"], w["proj_s"], w["proj_b"],
    )
    return out if B_orig == B else out[:, :, :B_orig, :]


def fused_mbconv_block(x, w, *, bt: int = 0, residual: bool = True,
                       interpret: bool = False):
    """NHWC convenience wrapper (transposes in and out; for single use)."""
    xt = x.transpose(1, 2, 0, 3)
    out = fused_mbconv_block_t(xt, w, bt=bt, residual=residual,
                               interpret=interpret)
    return out.transpose(2, 0, 1, 3)
