"""ctypes binding for the native host ops (native/hostops.cc).

Importing this module either finds a prebuilt ``libkdlthostops.so`` (env
``KDLT_NATIVE_LIB``, the package directory, or ``native/build/``) or compiles
one with g++ into a per-user cache.  Any failure raises ImportError, which
``ops.preprocess`` treats as "no native path" and falls back to PIL -- the
package must keep working on machines without a toolchain.

The resize kernels are bit-exact with PIL's (see hostops.cc), verified by
tests/test_native.py, so the gateway can use whichever is available without
perturbing golden logits.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig

import numpy as np

_LIB_NAME = "libkdlthostops.so"


def _repo_native_dir() -> str | None:
    # <repo>/kubernetes_deep_learning_tpu/ops/_native.py -> <repo>/native
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidate = os.path.join(os.path.dirname(pkg), "native")
    return candidate if os.path.isfile(os.path.join(candidate, "hostops.cc")) else None


_SOURCES = ("hostops.cc", "batchqueue.cc")


def _build(source_dir: str) -> str:
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "kdlt",
    )
    os.makedirs(cache, exist_ok=True)
    srcs = [os.path.join(source_dir, s) for s in _SOURCES]
    out = os.path.join(cache, _LIB_NAME)
    if os.path.isfile(out) and all(
        os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs
    ):
        return out
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O3", "-std=c++17", "-fPIC", "-shared", "-o", out, *srcs, "-pthread"]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


def _find_or_build() -> str:
    explicit = os.environ.get("KDLT_NATIVE_LIB")
    if explicit:
        return explicit
    native_dir = _repo_native_dir()
    newest_src = max(
        (os.path.getmtime(os.path.join(native_dir, s)) for s in _SOURCES
         if os.path.isfile(os.path.join(native_dir, s))),
        default=0.0,
    ) if native_dir else 0.0
    here = os.path.dirname(os.path.abspath(__file__))
    for candidate in (
        os.path.join(here, _LIB_NAME),
        os.path.join(os.path.dirname(os.path.dirname(here)), "native", "build", _LIB_NAME),
    ):
        # A prebuilt older than the sources may lack newly added symbols
        # (binding would fail below); prefer rebuilding when we can.
        if os.path.isfile(candidate) and os.path.getmtime(candidate) >= newest_src:
            return candidate
    if native_dir is None:
        raise ImportError("no prebuilt libkdlthostops.so and no source tree")
    return _build(native_dir)


try:
    _lib = ctypes.CDLL(_find_or_build())
except Exception as e:  # toolchain or source missing: PIL fallback
    raise ImportError(f"native host ops unavailable: {e}") from e

_u8p = ctypes.POINTER(ctypes.c_uint8)
_f32p = ctypes.POINTER(ctypes.c_float)
_i64p = ctypes.POINTER(ctypes.c_int64)
try:
    for _fn, _args, _ret in (
        ("kdlt_resize_bilinear", [_u8p] + [ctypes.c_int] * 3 + [_u8p] + [ctypes.c_int] * 2, ctypes.c_int),
        ("kdlt_resize_nearest", [_u8p] + [ctypes.c_int] * 3 + [_u8p] + [ctypes.c_int] * 2, ctypes.c_int),
        ("kdlt_resize_batch", [_u8p] + [ctypes.c_int] * 4 + [_u8p] + [ctypes.c_int] * 4, ctypes.c_int),
        # Batch queue (native/batchqueue.cc), consumed by runtime.native_batcher.
        ("kdlt_bq_create", [ctypes.c_int, ctypes.c_int64, ctypes.c_int], ctypes.c_void_p),
        ("kdlt_bq_destroy", [ctypes.c_void_p], None),
        ("kdlt_bq_submit", [ctypes.c_void_p, _u8p], ctypes.c_int64),
        ("kdlt_bq_take", [ctypes.c_void_p, _u8p, ctypes.c_int, ctypes.c_double, ctypes.c_double, _i64p], ctypes.c_int),
        ("kdlt_bq_complete", [ctypes.c_void_p, _i64p, ctypes.c_int, _f32p, ctypes.c_int], None),
        ("kdlt_bq_fail", [ctypes.c_void_p, _i64p, ctypes.c_int], None),
        ("kdlt_bq_wait", [ctypes.c_void_p, ctypes.c_int64, _f32p, ctypes.c_double], ctypes.c_int),
        ("kdlt_bq_close", [ctypes.c_void_p], None),
        ("kdlt_bq_abort", [ctypes.c_void_p], None),
        ("kdlt_bq_pending", [ctypes.c_void_p], ctypes.c_int),
    ):
        fn = getattr(_lib, _fn)
        fn.argtypes = _args
        fn.restype = _ret
except AttributeError as e:
    # A stale prebuilt library missing newer symbols must surface as the
    # ImportError the module contract promises (callers fall back on it).
    raise ImportError(f"native library is stale: {e}") from e

lib = _lib  # raw handle for runtime.native_batcher


def _check(img: np.ndarray) -> np.ndarray:
    img = np.ascontiguousarray(img)
    if img.dtype != np.uint8 or img.ndim != 3:
        raise ValueError(f"expected uint8 HWC array, got {img.dtype} {img.shape}")
    return img


def resize_bilinear(img: np.ndarray, h: int, w: int) -> np.ndarray:
    img = _check(img)
    out = np.empty((h, w, img.shape[2]), np.uint8)
    rc = _lib.kdlt_resize_bilinear(
        img.ctypes.data_as(_u8p), img.shape[0], img.shape[1], img.shape[2],
        out.ctypes.data_as(_u8p), h, w,
    )
    if rc != 0:
        raise ValueError(f"kdlt_resize_bilinear failed (rc={rc})")
    return out


def resize_nearest(img: np.ndarray, h: int, w: int) -> np.ndarray:
    img = _check(img)
    out = np.empty((h, w, img.shape[2]), np.uint8)
    rc = _lib.kdlt_resize_nearest(
        img.ctypes.data_as(_u8p), img.shape[0], img.shape[1], img.shape[2],
        out.ctypes.data_as(_u8p), h, w,
    )
    if rc != 0:
        raise ValueError(f"kdlt_resize_nearest failed (rc={rc})")
    return out


def resize_batch(
    imgs: np.ndarray, h: int, w: int, filter: str = "bilinear", num_threads: int = 0
) -> np.ndarray:
    """Resize a (N,H,W,C) uint8 batch; shards across C++ threads (GIL-free)."""
    imgs = np.ascontiguousarray(imgs)
    if imgs.dtype != np.uint8 or imgs.ndim != 4:
        raise ValueError(f"expected uint8 NHWC array, got {imgs.dtype} {imgs.shape}")
    n, _, _, c = imgs.shape
    if num_threads <= 0:
        num_threads = min(n, os.cpu_count() or 1)
    out = np.empty((n, h, w, c), np.uint8)
    rc = _lib.kdlt_resize_batch(
        imgs.ctypes.data_as(_u8p), n, imgs.shape[1], imgs.shape[2], c,
        out.ctypes.data_as(_u8p), h, w,
        {"nearest": 0, "bilinear": 1}[filter], num_threads,
    )
    if rc != 0:
        raise ValueError(f"kdlt_resize_batch failed (rc={rc})")
    return out
