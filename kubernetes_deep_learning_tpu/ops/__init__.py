from kubernetes_deep_learning_tpu.ops.preprocess import (
    decode_image,
    fetch_image_bytes,
    normalize,
    preprocess_bytes,
    resize_uint8,
)

__all__ = [
    "decode_image",
    "fetch_image_bytes",
    "normalize",
    "preprocess_bytes",
    "resize_uint8",
]
