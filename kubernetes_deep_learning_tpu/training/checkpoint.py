"""Training checkpoint/resume (orbax) -- the subsystem the reference lacks.

The reference has no training, so its only "checkpoint" mechanism is the
immutable versioned SavedModel baked into the serving image (reference
tf-serving.dockerfile:5; SURVEY.md section 5 "checkpoint/resume").  The
serving side of that story lives in export/artifact.py (versioned artifact
dirs, hot-reload).  This module covers the training side: periodic snapshots
of the full TrainState (params, batch stats, optimizer state, step) with
retention, and restore-on-boot so an interrupted fine-tuning run resumes at
the last saved step.

Orbax is the TPU-native choice here: it writes sharded jax.Arrays as
distributed tensorstore shards (each host saves only its addressable shards
-- no gather to host 0, which matters for model-parallel params), and
restores them with the shardings of the abstract target, so a checkpoint
written on one mesh can be reloaded onto another.
"""

from __future__ import annotations

import os
from typing import Any

import jax


class Checkpointer:
    """Periodic TrainState snapshots with retention, via orbax.

    Saves are asynchronous (orbax's default): the device->host copy blocks
    only briefly and serialization proceeds in the background.  ``wait()``
    (or close/exit) joins outstanding writes; ``save`` of step N+1 joins the
    write of step N automatically.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, state: Any, force: bool = False) -> bool:
        """Snapshot ``state`` at its own step counter.

        The state is copied to HOST memory synchronously before the async
        write starts: the training loop donates ``state`` into the next
        train_step (trainer.build_train_step, ``donate_argnums=(0,)``), so
        orbax's background serializer would otherwise still be reading
        device buffers XLA has already recycled -- an intermittent
        use-after-free segfault (reproduced under the tier-1 suite; the
        race window moves with compile timing).  The copy is the only
        synchronous part; serialization/disk IO stay async.  Multi-host
        (non-fully-addressable) shards pass through untouched -- each
        host's serializer reads only addressable shards, and those fleets
        gate donation differently (the sharded train step returns a NEW
        state before followers save).
        """
        import numpy as np

        def snapshot(x):
            if isinstance(x, jax.Array) and x.is_fully_addressable:
                return np.asarray(x)
            return x

        return self._mngr.save(
            int(jax.device_get(state.step)),
            args=self._ocp.args.StandardSave(jax.tree.map(snapshot, state)),
            force=force,
        )

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, abstract_state: Any, step: int | None = None) -> Any:
        """Restore the latest (or a specific) snapshot.

        ``abstract_state`` is a TrainState of jax.ShapeDtypeStructs (see
        ``abstract_like``) carrying the target shardings: orbax lays the
        restored arrays out directly as specified, so restoring onto a
        different mesh than the one that saved is just a different abstract
        target.  Returns None when the directory has no checkpoint yet.
        """
        if step is None:
            step = self._mngr.latest_step()
        if step is None:
            return None
        return self._mngr.restore(
            step, args=self._ocp.args.StandardRestore(abstract_state)
        )

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.close()


def abstract_like(state: Any) -> Any:
    """TrainState of ShapeDtypeStructs (with shardings) mirroring ``state``.

    The cheap way to build a restore target from the freshly-initialized
    state the training loop creates anyway.
    """

    def to_abstract(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "shape") and hasattr(x, "dtype"):  # np arrays/scalars
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x  # python scalars etc. pass through as concrete targets

    return jax.tree.map(to_abstract, state)
