"""fit(): the training driver -- loop, logging, checkpoint/resume, export.

Composes the pieces this package already has into the one call a user runs:
``build_train_step`` (sharded step), ``data.PrefetchIterator`` (host->device
overlap), ``checkpoint.Checkpointer`` (periodic snapshots + resume), and --
when asked -- ``export.exporter.export_model`` so a finished run lands
directly in the versioned artifact layout the model server scans (the
train->serve handoff the reference does out-of-band with a downloaded .h5,
reference guide.md:176).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable

import jax
import optax

from kubernetes_deep_learning_tpu.modelspec import ModelSpec
from kubernetes_deep_learning_tpu.parallel.mesh import DATA_AXIS, batch_sharding
from kubernetes_deep_learning_tpu.training import checkpoint as ckpt_lib
from kubernetes_deep_learning_tpu.training.data import PrefetchIterator
from kubernetes_deep_learning_tpu.training.trainer import (
    build_eval_step,
    build_train_step,
    create_train_state,
)


def evaluate(
    spec: ModelSpec,
    state,
    batches: Iterable,
    mesh=None,
    eval_step: Callable | None = None,
    topk: int = 5,
) -> dict[str, float]:
    """One validation pass: mean loss, top-1 and top-k accuracy.

    ``batches`` yields (uint8 images, int labels); batches may be uneven --
    aggregation is by per-example sums.  Pass a prebuilt ``eval_step`` when
    calling repeatedly (fit does) to avoid re-jitting.
    """
    import numpy as np

    step_fn = eval_step or build_eval_step(spec, mesh=mesh, topk=topk)
    sharding = batch_sharding(mesh) if mesh is not None else None
    n_axis = 1 if mesh is None else mesh.shape[DATA_AXIS]
    totals = {"loss_sum": 0.0, "top1_sum": 0.0, "topk_sum": 0.0, "count": 0.0}
    for images, labels in batches:
        n = labels.shape[0]
        valid = None
        if sharding is not None:
            # Tail batches must divide the data axis: pad, and mask the
            # padding out of every sum via the step's valid vector.
            pad = (-n) % n_axis
            if pad:
                images = np.concatenate(
                    [images, np.zeros((pad, *images.shape[1:]), images.dtype)]
                )
                labels = np.concatenate(
                    [labels, np.zeros((pad,), labels.dtype)]
                )
            valid = np.concatenate(
                [np.ones(n, np.float32), np.zeros(pad, np.float32)]
            )
            images = jax.device_put(images, sharding)
            labels = jax.device_put(labels, sharding)
            valid = jax.device_put(valid, sharding)
        m = step_fn(state, images, labels) if valid is None else step_fn(
            state, images, labels, valid
        )
        for key in totals:
            totals[key] += float(m[key])
    n = max(totals["count"], 1.0)
    return {
        "val_loss": totals["loss_sum"] / n,
        "val_top1": totals["top1_sum"] / n,
        "val_topk": totals["topk_sum"] / n,
        "count": int(totals["count"]),
    }


def fit(
    spec: ModelSpec,
    tx: optax.GradientTransformation,
    batches: Iterable,
    steps: int,
    mesh=None,
    seed: int = 0,
    ckpt_dir: str | None = None,
    ckpt_every: int = 0,
    max_to_keep: int = 3,
    log_every: int = 0,
    log_fn: Callable[[str], None] = print,
    prefetch: int = 2,
    state: Any = None,
    eval_batches: Callable[[], Iterable] | None = None,
    eval_every: int = 0,
    eval_history: list | None = None,
):
    """Train to ``steps`` total optimizer steps; returns (state, history).

    Evaluation: ``eval_batches`` is a zero-arg factory returning a fresh
    (images, labels) iterable (re-invoked per pass).  With ``eval_every``
    set, a validation pass (loop.evaluate: mean loss, top-1/top-k accuracy)
    runs at that step cadence and once after the final step; results go to
    ``log_fn`` and, if a list is passed as ``eval_history``, are appended as
    ``(step, metrics_dict)``.  Without ``eval_every`` a single pass runs at
    the end.  The reference has no quality gate at all between training and
    serving (SURVEY.md section 4).

    Resume semantics: with ``ckpt_dir`` set, an existing checkpoint is
    restored and training continues from its step counter -- a run killed at
    step 700 of 1000 redoes only 701..1000.  ``batches`` must be an iterator
    the caller positions appropriately (synthetic/shuffled data makes this
    moot).  ``history`` is a list of (step, loss) floats at the logging
    cadence, always including the final *executed* step (so history[-1]
    reflects where training actually stopped, even on early data
    exhaustion); it is empty only when no step ran at all.
    """
    if state is None:
        state = create_train_state(spec, tx, seed=seed, mesh=mesh)

    ckpt = None
    if ckpt_dir is not None:
        ckpt = ckpt_lib.Checkpointer(ckpt_dir, max_to_keep=max_to_keep)
        restored = ckpt.restore(ckpt_lib.abstract_like(state))
        if restored is not None:
            state = restored
            log_fn(f"resumed from {ckpt_dir} at step {int(state.step)}")

    step_fn = build_train_step(spec, tx, mesh=mesh)
    eval_fn = (
        build_eval_step(spec, mesh=mesh) if eval_batches is not None else None
    )
    sharding = batch_sharding(mesh) if mesh is not None else None
    it = PrefetchIterator(batches, sharding=sharding, depth=prefetch)

    history: list[tuple[int, float]] = []
    t0 = time.perf_counter()
    step = start_step = int(state.step)
    metrics = None

    def record():
        # One sync per log line, not per step: float() blocks on the
        # device, so the hot loop never forces a host round-trip.
        loss = float(metrics["loss"])
        history.append((step, loss))
        rate = (step - start_step) / max(time.perf_counter() - t0, 1e-9)
        log_fn(f"step {step}/{steps} loss {loss:.4f} ({rate:.1f} steps/s)")

    def run_eval():
        m = evaluate(spec, state, eval_batches(), mesh=mesh, eval_step=eval_fn)
        if eval_history is not None:
            eval_history.append((step, m))
        log_fn(
            f"eval step {step}: val_loss {m['val_loss']:.4f} "
            f"val_top1 {m['val_top1']:.4f} val_topk {m['val_topk']:.4f} "
            f"({m['count']} examples)"
        )

    try:
        while step < steps:
            try:
                images, labels = next(it)
            except StopIteration:
                log_fn(f"data exhausted at step {step}/{steps}")
                break
            state, metrics = step_fn(state, images, labels)
            step += 1
            if log_every and step % log_every == 0 and step < steps:
                record()
            if (
                eval_fn is not None
                and eval_every
                and step % eval_every == 0
                and step < steps
            ):
                run_eval()
            if ckpt is not None and ckpt_every and step % ckpt_every == 0:
                ckpt.save(state)
    finally:
        # Stop the producer on every exit path -- an abandoned prefetch
        # thread would pin depth+1 device-resident batches forever.
        it.close()

    if metrics is not None:  # always record the final executed step
        record()
    if eval_fn is not None:
        # Final-quality pass regardless of cadence -- including zero-step
        # runs (e.g. resumed already at `steps`): the caller asked for a
        # quality gate, so evaluate the state we are about to hand back.
        run_eval()
    if ckpt is not None:
        ckpt.save(state)  # no-op if this step was already snapshotted
        ckpt.wait()
        ckpt.close()
    return state, history


def fit_and_export(
    spec: ModelSpec,
    tx: optax.GradientTransformation,
    batches: Iterable,
    steps: int,
    artifact_root: str,
    **fit_kwargs,
) -> str:
    """fit(), then export the trained variables as the next served version."""
    from kubernetes_deep_learning_tpu.export.exporter import export_model

    state, _ = fit(spec, tx, batches, steps, **fit_kwargs)
    variables = jax.device_get(state.variables())
    return export_model(spec, variables, artifact_root)
