"""fit(): the training driver -- loop, logging, checkpoint/resume, export.

Composes the pieces this package already has into the one call a user runs:
``build_train_step`` (sharded step), ``data.PrefetchIterator`` (host->device
overlap), ``checkpoint.Checkpointer`` (periodic snapshots + resume), and --
when asked -- ``export.exporter.export_model`` so a finished run lands
directly in the versioned artifact layout the model server scans (the
train->serve handoff the reference does out-of-band with a downloaded .h5,
reference guide.md:176).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable

import jax
import optax

from kubernetes_deep_learning_tpu.modelspec import ModelSpec
from kubernetes_deep_learning_tpu.parallel.mesh import batch_sharding
from kubernetes_deep_learning_tpu.training import checkpoint as ckpt_lib
from kubernetes_deep_learning_tpu.training.data import PrefetchIterator
from kubernetes_deep_learning_tpu.training.trainer import (
    build_train_step,
    create_train_state,
)


def fit(
    spec: ModelSpec,
    tx: optax.GradientTransformation,
    batches: Iterable,
    steps: int,
    mesh=None,
    seed: int = 0,
    ckpt_dir: str | None = None,
    ckpt_every: int = 0,
    max_to_keep: int = 3,
    log_every: int = 0,
    log_fn: Callable[[str], None] = print,
    prefetch: int = 2,
    state: Any = None,
):
    """Train to ``steps`` total optimizer steps; returns (state, history).

    Resume semantics: with ``ckpt_dir`` set, an existing checkpoint is
    restored and training continues from its step counter -- a run killed at
    step 700 of 1000 redoes only 701..1000.  ``batches`` must be an iterator
    the caller positions appropriately (synthetic/shuffled data makes this
    moot).  ``history`` is a list of (step, loss) floats at the logging
    cadence, always including the final *executed* step (so history[-1]
    reflects where training actually stopped, even on early data
    exhaustion); it is empty only when no step ran at all.
    """
    if state is None:
        state = create_train_state(spec, tx, seed=seed, mesh=mesh)

    ckpt = None
    if ckpt_dir is not None:
        ckpt = ckpt_lib.Checkpointer(ckpt_dir, max_to_keep=max_to_keep)
        restored = ckpt.restore(ckpt_lib.abstract_like(state))
        if restored is not None:
            state = restored
            log_fn(f"resumed from {ckpt_dir} at step {int(state.step)}")

    step_fn = build_train_step(spec, tx, mesh=mesh)
    sharding = batch_sharding(mesh) if mesh is not None else None
    it = PrefetchIterator(batches, sharding=sharding, depth=prefetch)

    history: list[tuple[int, float]] = []
    t0 = time.perf_counter()
    step = start_step = int(state.step)
    metrics = None

    def record():
        # One sync per log line, not per step: float() blocks on the
        # device, so the hot loop never forces a host round-trip.
        loss = float(metrics["loss"])
        history.append((step, loss))
        rate = (step - start_step) / max(time.perf_counter() - t0, 1e-9)
        log_fn(f"step {step}/{steps} loss {loss:.4f} ({rate:.1f} steps/s)")

    try:
        while step < steps:
            try:
                images, labels = next(it)
            except StopIteration:
                log_fn(f"data exhausted at step {step}/{steps}")
                break
            state, metrics = step_fn(state, images, labels)
            step += 1
            if log_every and step % log_every == 0 and step < steps:
                record()
            if ckpt is not None and ckpt_every and step % ckpt_every == 0:
                ckpt.save(state)
    finally:
        # Stop the producer on every exit path -- an abandoned prefetch
        # thread would pin depth+1 device-resident batches forever.
        it.close()

    if metrics is not None:  # always record the final executed step
        record()
    if ckpt is not None:
        ckpt.save(state)  # no-op if this step was already snapshotted
        ckpt.wait()
        ckpt.close()
    return state, history


def fit_and_export(
    spec: ModelSpec,
    tx: optax.GradientTransformation,
    batches: Iterable,
    steps: int,
    artifact_root: str,
    **fit_kwargs,
) -> str:
    """fit(), then export the trained variables as the next served version."""
    from kubernetes_deep_learning_tpu.export.exporter import export_model

    state, _ = fit(spec, tx, batches, steps, **fit_kwargs)
    variables = jax.device_get(state.variables())
    return export_model(spec, variables, artifact_root)
