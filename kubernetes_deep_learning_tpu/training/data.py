"""Input pipeline: background host->device prefetch for the training loop.

The reference has no input pipeline (no training; its serving input path is
one HTTP fetch per request, reference model_server.py:53).  For training the
classic TPU bottleneck is the host: if device_put and the forward pass run
in the same Python loop, the accelerator idles while numpy assembles the
next batch.  This stages batches onto the device from a daemon thread ahead
of consumption -- with jax's async dispatch the train step for batch N
overlaps host prep + transfer of batch N+1/N+2.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator

import jax
import numpy as np

from kubernetes_deep_learning_tpu.modelspec import ModelSpec


class PrefetchIterator:
    """Wrap a host batch iterator; yield device-resident pytrees.

    ``sharding`` (e.g. parallel.mesh.batch_sharding(mesh)) spreads each
    batch over the mesh's data axis at transfer time, so the train step's
    in_shardings see already-placed arrays and insert no reshards.  Errors
    raised by the host iterator surface at the consuming ``next()`` call.
    """

    _DONE = object()

    def __init__(self, source: Iterable, sharding=None, depth: int = 2):
        self._sharding = sharding
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._err: BaseException | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(iter(source),), name="kdlt-prefetch", daemon=True
        )
        self._thread.start()

    def _put(self, batch):
        if self._sharding is not None:
            return jax.tree.map(lambda a: jax.device_put(a, self._sharding), batch)
        return jax.tree.map(jax.device_put, batch)

    def _enqueue(self, item) -> bool:
        """put() that aborts on close(): with a bounded queue and an endless
        source, a plain blocking put would pin this thread (and depth+1
        device batches) forever once the consumer walks away."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self, it: Iterator) -> None:
        try:
            for batch in it:
                if self._stop.is_set() or not self._enqueue(self._put(batch)):
                    return
        except BaseException as e:  # surface on the consumer side
            self._err = e
        finally:
            self._enqueue(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the producer and release staged batches.  Idempotent; the
        consumer (training.loop.fit) must call this when it stops early."""
        self._stop.set()
        while True:  # unblock a producer waiting on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def synthetic_batches(
    spec: ModelSpec, batch: int, steps: int | None = None, seed: int = 0
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Endless (or ``steps``-bounded) random (uint8 images, int32 labels).

    The test/bench stand-in for a real dataset; spec-shaped so it plugs
    straight into build_train_step.
    """
    rng = np.random.default_rng(seed)
    n = 0
    while steps is None or n < steps:
        images = rng.integers(0, 256, size=(batch, *spec.input_shape), dtype=np.uint8)
        labels = rng.integers(0, spec.num_classes, size=(batch,), dtype=np.int32)
        yield images, labels
        n += 1


def map_batches(
    source: Iterable, fn: Callable[[Any], Any]
) -> Iterator[Any]:
    """Lazy per-batch transform (augmentation hook) on the host side."""
    for batch in source:
        yield fn(batch)


def image_folder_batches(
    root: str,
    spec: ModelSpec,
    batch: int,
    epochs: int | None = None,
    seed: int = 0,
    drop_remainder: bool = True,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """(images, labels) batches from a ``<root>/<label>/<file>`` directory tree.

    The classic layout (one subdirectory per class, the bookcamp clothing
    dataset's own structure).  Labels map through ``spec.labels`` -- a
    subdirectory not in the spec is a loud error, not silent skipping.
    Decode + resize happen here on the host (the C++ batch-resize kernel
    when available); normalization stays on device as everywhere else.
    Shuffles each epoch; ``epochs=None`` repeats forever.
    """
    import os

    from kubernetes_deep_learning_tpu.ops.preprocess import preprocess_bytes

    image_exts = {".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp"}
    label_to_index = {label: i for i, label in enumerate(spec.labels)}
    samples: list[tuple[str, int]] = []
    for entry in sorted(os.listdir(root)):
        class_dir = os.path.join(root, entry)
        if not os.path.isdir(class_dir):
            continue
        if entry not in label_to_index:
            raise ValueError(
                f"directory {entry!r} is not a spec label; expected one of "
                f"{list(spec.labels)}"
            )
        for fname in sorted(os.listdir(class_dir)):
            path = os.path.join(class_dir, fname)
            # Filter at SCAN time: a stray .DS_Store/README/subdirectory must
            # not crash the iterator mid-epoch.
            if os.path.splitext(fname)[1].lower() in image_exts and os.path.isfile(path):
                samples.append((path, label_to_index[entry]))
    if not samples:
        raise FileNotFoundError(f"no class directories with images under {root!r}")
    if drop_remainder and len(samples) < batch:
        # Fail loudly: every epoch would yield nothing, and with epochs=None
        # the generator would busy-spin forever inside fit()'s next().
        raise ValueError(
            f"drop_remainder=True but only {len(samples)} sample(s) under "
            f"{root!r} < batch={batch}: every epoch would yield zero batches"
        )

    rng = np.random.default_rng(seed)
    size = spec.input_shape[:2]
    epoch = 0
    while epochs is None or epoch < epochs:
        order = rng.permutation(len(samples))
        for start in range(0, len(order), batch):
            idx = order[start : start + batch]
            if drop_remainder and len(idx) < batch:
                break
            images = np.empty((len(idx), *spec.input_shape), np.uint8)
            labels = np.empty(len(idx), np.int32)
            for row, i in enumerate(idx):
                path, label = samples[i]
                with open(path, "rb") as f:
                    # The gateway's exact host pipeline (decode + resize with
                    # the spec's filter), so training and serving can never
                    # diverge on preprocessing.
                    images[row] = preprocess_bytes(
                        f.read(), size, filter=spec.resize_filter
                    )
                labels[row] = label
            yield images, labels
        epoch += 1
