from kubernetes_deep_learning_tpu.training.trainer import (
    TrainState,
    build_train_step,
    create_train_state,
)
from kubernetes_deep_learning_tpu.training.checkpoint import Checkpointer, abstract_like
from kubernetes_deep_learning_tpu.training.data import PrefetchIterator, synthetic_batches
from kubernetes_deep_learning_tpu.training.loop import fit, fit_and_export

__all__ = [
    "Checkpointer",
    "PrefetchIterator",
    "TrainState",
    "abstract_like",
    "build_train_step",
    "create_train_state",
    "fit",
    "fit_and_export",
    "synthetic_batches",
]
