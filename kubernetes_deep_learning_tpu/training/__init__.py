from kubernetes_deep_learning_tpu.training.trainer import (
    TrainState,
    build_train_step,
    create_train_state,
)

__all__ = ["TrainState", "build_train_step", "create_train_state"]
