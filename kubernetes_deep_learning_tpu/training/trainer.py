"""Fine-tuning: sharded training step for the model zoo.

The reference has no training loop at all (SURVEY.md: "It is not a training
framework"); its artifact comes from an out-of-band transfer-learning run
(reference guide.md:176).  This module supplies that missing capability
in-tree -- the loop that *produces* a servable artifact -- designed the JAX
way: a pure ``train_step`` jitted over a (data, model) mesh, batch sharded on
``data``, params replicated or tensor-parallel per parallel.dataparallel's
partition rules, with XLA inserting the gradient all-reduce implied by the
sharding annotations (no hand-written collectives, no NCCL analog).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_deep_learning_tpu.modelspec import ModelSpec
from kubernetes_deep_learning_tpu.models import create_model
from kubernetes_deep_learning_tpu.ops.preprocess import normalize
from kubernetes_deep_learning_tpu.parallel.dataparallel import shard_variables
from kubernetes_deep_learning_tpu.parallel.mesh import DATA_AXIS


@dataclasses.dataclass
class TrainState:
    step: Any
    params: Any
    batch_stats: Any
    opt_state: Any

    def variables(self):
        return {"params": self.params, "batch_stats": self.batch_stats}


jax.tree_util.register_dataclass(
    TrainState, data_fields=["step", "params", "batch_stats", "opt_state"], meta_fields=[]
)


def _replicate_unsharded(tree: Any, mesh: Mesh) -> Any:
    """Commit single-device leaves (optimizer scalars like adam's count, the
    step counter) to a mesh-replicated sharding.

    Freshly-created scalars are uncommitted, so jit places them to match the
    mesh-sharded params -- but a checkpoint restore returns them *committed*
    to one device (orbax restores exactly the shardings of the abstract
    target), and jit rejects mixing committed single-device and committed
    mesh-wide arguments.  Making the initial state mesh-consistent means
    abstract_like targets are too, so restored states are as well.
    """
    from jax.sharding import SingleDeviceSharding

    replicated = NamedSharding(mesh, P())

    def put(x):
        if isinstance(x, jax.Array) and isinstance(x.sharding, SingleDeviceSharding):
            return jax.device_put(x, replicated)
        return x

    return jax.tree.map(put, tree)


def create_train_state(
    spec: ModelSpec,
    tx: optax.GradientTransformation,
    seed: int = 0,
    variables: Any | None = None,
    mesh: Mesh | None = None,
) -> TrainState:
    """Init (or adopt) variables and optimizer state; shard if mesh given."""
    model = create_model(spec)
    if variables is None:
        dummy = jnp.zeros((1, *spec.input_shape), jnp.float32)
        variables = model.init(jax.random.PRNGKey(seed), dummy)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    if mesh is not None:
        sharded = shard_variables(
            {"params": params, "batch_stats": batch_stats}, mesh
        )
        params, batch_stats = sharded["params"], sharded["batch_stats"]
    opt_state = tx.init(params)
    step = jnp.zeros((), jnp.int32)
    if mesh is not None:
        opt_state = _replicate_unsharded(opt_state, mesh)
        step = jax.device_put(step, NamedSharding(mesh, P()))
    return TrainState(step, params, batch_stats, opt_state)


def build_train_step(
    spec: ModelSpec,
    tx: optax.GradientTransformation,
    mesh: Mesh | None = None,
    dtype: Any = None,
) -> Callable:
    """Return jitted ``train_step(state, images_u8, labels) -> (state, metrics)``.

    Images are raw uint8 batches; normalization happens inside the step so
    the input pipeline stays dtype-thin (same choice as serving).  With a
    mesh, the batch arrives sharded over ``data`` and the gradient
    all-reduce is implied by params' (replicated / model-sharded) shardings.
    """
    model = create_model(spec, dtype=dtype)

    def loss_fn(params, batch_stats, images, labels):
        x = normalize(images, spec.preprocessing)
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
        acc = (logits.argmax(-1) == labels).mean()
        # BN-free families (vit) mutate no batch_stats; keep the empty dict.
        return loss, (updates.get("batch_stats", batch_stats), acc)

    def train_step(state: TrainState, images, labels):
        (loss, (new_stats, acc)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, state.batch_stats, images, labels
        )
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(state.step + 1, new_params, new_stats, new_opt_state)
        return new_state, {"loss": loss, "accuracy": acc}

    if mesh is None:
        return jax.jit(train_step, donate_argnums=(0,))

    batch_sharding = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(
        train_step,
        in_shardings=(None, batch_sharding, batch_sharding),
        donate_argnums=(0,),
    )


def build_eval_step(
    spec: ModelSpec,
    mesh: Mesh | None = None,
    dtype: Any = None,
    topk: int = 5,
) -> Callable:
    """Return jitted ``eval_step(state, images_u8, labels) -> metrics``.

    Inference-mode forward (train=False: running BN stats, no dropout, no
    batch_stats mutation) returning per-batch sums -- ``loss_sum``,
    ``top1_sum``, ``topk_sum``, ``count`` -- so the caller can aggregate
    exactly over unevenly-sized validation batches.  VERDICT r1 weak-6: the
    reference validates its artifact by eyeballing logits for one image
    (reference guide.md:628-629); this is the in-tree quality gate for the
    fit -> export -> serve pipeline.
    """
    model = create_model(spec, dtype=dtype)
    k = min(topk, spec.num_classes)

    def eval_step(state: TrainState, images, labels, valid=None):
        # ``valid`` (f32 (N,) of 0/1) masks padding rows: mesh serving pads
        # tail batches up to the data-axis size (loop.evaluate), and padded
        # rows must not count toward any sum.
        x = normalize(images, spec.preprocessing)
        logits = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            x,
            train=False,
        )
        v = jnp.ones(labels.shape[0], jnp.float32) if valid is None else valid
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        top1 = (logits.argmax(-1) == labels).astype(jnp.float32)
        in_topk = (
            (jax.lax.top_k(logits, k)[1] == labels[:, None]).any(-1)
        ).astype(jnp.float32)
        return {
            "loss_sum": (losses * v).sum(),
            "top1_sum": (top1 * v).sum(),
            "topk_sum": (in_topk * v).sum(),
            "count": v.sum(),
        }

    if mesh is None:
        return jax.jit(eval_step)
    batch_sharding = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(
        eval_step,
        in_shardings=(None, batch_sharding, batch_sharding, batch_sharding),
    )
