"""Version compatibility shims over the moving parts of the JAX API.

The framework is written against the current JAX surface (``jax.shard_map``
with ``check_vma``, ``jax.typeof``), but deployment images pin whatever
jaxlib their accelerator stack ships -- which can lag by several minor
versions (this container bakes 0.4.x).  Rather than sprinkling
``try/except ImportError`` at every call site (and silently drifting as
sites are added), every use of an API that has moved or been renamed goes
through here, so exactly one module knows the version matrix:

- ``shard_map``: lived in ``jax.experimental.shard_map`` until ~0.8, then
  graduated to ``jax.shard_map``; its replication-checking kwarg was
  renamed ``check_rep`` -> ``check_vma`` in the same window.  The shim
  accepts the NEW spelling and translates down.
- ``typeof``: ``jax.typeof`` (the aval, carrying ``.vma`` inside
  shard_map) appeared ~0.6; older versions reach the same aval through
  ``jax.core.get_aval`` (which simply has no ``vma`` attribute -- callers
  already treat "no vma" as the not-inside-shard_map case).
- ``enable_cpu_collectives``: pre-0.5 jaxlib does not select the Gloo
  CPU collectives backend by default, so a multi-process CPU fleet dies
  with "Multiprocess computations aren't implemented on the CPU backend"
  unless the config flag is set before ``jax.distributed.initialize``.
  Newer versions default to Gloo and have dropped the flag; the shim is a
  no-op there.
"""

from __future__ import annotations

import inspect
from typing import Any


def _resolve_shard_map():
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is None:  # pre-graduation spelling
        from jax.experimental.shard_map import shard_map as fn
    return fn


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` under either API generation.

    Callers pass the current (``check_vma``) spelling; on a JAX whose
    shard_map still takes ``check_rep`` the flag is translated (the
    semantics -- trace-time validation of output replication/varying-axes
    declarations -- are the same feature under both names).
    """
    fn = _resolve_shard_map()
    params = inspect.signature(fn).parameters
    kwargs: dict[str, Any] = {
        "mesh": mesh, "in_specs": in_specs, "out_specs": out_specs,
    }
    if "check_vma" in params:
        kwargs["check_vma"] = check_vma
    elif "check_rep" in params:
        kwargs["check_rep"] = check_vma
    return fn(f, **kwargs)


def typeof(x):
    """``jax.typeof`` (>= ~0.6) or the equivalent aval lookup.

    The pre-typeof aval has no ``vma`` attribute; callers that read it via
    ``getattr(..., "vma", None)`` get the same None they would outside a
    shard_map -- which is the correct degenerate answer on a JAX too old
    to track varying mesh axes at all.
    """
    import jax

    fn = getattr(jax, "typeof", None)
    if fn is not None:
        return fn(x)
    from jax import core

    return core.get_aval(x)


def platform_dependent(*args, default, **branches):
    """``jax.lax.platform_dependent`` that survives pre-pruning JAX.

    Modern JAX prunes the per-platform branches down to the platforms a
    computation is actually being lowered for, so a Pallas-TPU branch
    inside a CPU lowering is simply dropped.  Older versions lower EVERY
    branch, and the Pallas CPU lowering rule raises ("Only interpret mode
    is supported on CPU backend") for a branch that could never run.  On
    those versions the branch is resolved at TRACE time from the process
    default backend instead -- the one capability lost is baking multiple
    platforms' branches into a single exported module (the exporter's
    multi-platform artifacts then carry the portable default branch for
    non-default platforms, which is numerically identical, just not
    fused).
    """
    import jax

    if hasattr(jax, "typeof"):  # same generation as branch pruning
        return jax.lax.platform_dependent(*args, default=default, **branches)
    fn = branches.get(jax.default_backend(), default)
    return fn(*args)


def shape_dtype_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` with the ``vma`` kwarg where supported.

    On a pre-vma JAX, ``vma`` is dropped: those versions do not track
    varying mesh axes at all, so there is nothing to declare (and the
    caller's ``vma`` is necessarily None there -- ``typeof`` above cannot
    produce one).
    """
    import jax

    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # pre-vma signature
        return jax.ShapeDtypeStruct(shape, dtype)


def enable_cpu_collectives() -> None:
    """Select the Gloo CPU collectives backend where it is not the default.

    Must run BEFORE ``jax.distributed.initialize`` touches the backend.
    On JAX versions where the option has been removed (Gloo became the
    only/default CPU implementation) this is a silent no-op.
    """
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # option gone: Gloo is the default
        pass
