"""Incident flight recorder: a black box for the serving stack.

The tiers already emit rich failure signals -- SLO burn (utils/slo.py),
brownout stage transitions (serving/admission/brownout.py), dispatch-stall
watchdogs (runtime/engine.py), pool churn (serving/upstream.py), quant-gate
downgrades (ops/quantize.py) -- but each is transient: traces age out of
the ring, /debug/* pages show only *current* state, and by the time an
operator arrives the causal evidence is gone.  This module records the
evidence at the moment it happens (Dapper's lesson) at always-on cost
(GWP's discipline):

* **Event timeline** -- a bounded, lock-cheap ring of structured events
  (wall + monotonic stamped, bounded ``kind`` vocabulary) fed by hooks at
  every failure edge in both tiers.
* **Trigger engine** -- declarative rules (``KDLT_INCIDENT_TRIGGERS``,
  grammar ``name[=threshold]``) with per-trigger hysteresis and a dedup
  window, so a flapping signal yields ONE incident, not a bundle storm.
* **Bundle capture** -- on fire, a background worker atomically writes a
  self-contained JSON bundle under ``KDLT_INCIDENT_DIR``: the last-N
  timeline events (sorted), the implicated traces (pinned against Tracer
  eviction via the ``incident`` retention class), every registered
  /debug snapshot, a metrics-delta since the previous capture, and (model
  tier, opt-in ``KDLT_INCIDENT_PROFILE_S``) a short device profile.
  Count/byte caps evict oldest-first.
* **Surfacing** -- ``index()``/``get()`` back the tiers' /debug/incidents
  endpoints; ``kdlt-doctor`` (serving/doctor.py) renders a bundle as an
  ASCII causal timeline.  All kdlt_incident_* series are minted in
  utils/metrics.py (incident_metrics), nowhere else.

The recorder is per-tier and constructor-injected (never process-global:
the benches run a gateway and several model servers in one process).
``KDLT_INCIDENT=0`` is the kill switch -- every hook degrades to a cheap
no-op, which is what bench.py --incident-ab's recorder-off arm measures.
"""

from __future__ import annotations

import collections
import json
import os
import queue
import threading
import time

from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

ENABLE_ENV = "KDLT_INCIDENT"
DIR_ENV = "KDLT_INCIDENT_DIR"
TRIGGERS_ENV = "KDLT_INCIDENT_TRIGGERS"
DEDUP_ENV = "KDLT_INCIDENT_DEDUP_S"
MAX_BUNDLES_ENV = "KDLT_INCIDENT_MAX_BUNDLES"
MAX_MB_ENV = "KDLT_INCIDENT_MAX_MB"
PROFILE_ENV = "KDLT_INCIDENT_PROFILE_S"

DEFAULT_TRIGGERS = "burn-crossing,brownout=1,dispatch-stall,replica-unhealthy"
DEFAULT_DEDUP_S = 60.0
DEFAULT_MAX_BUNDLES = 32
DEFAULT_MAX_MB = 64.0
RING_EVENTS = 512     # timeline ring capacity (per tier)
BUNDLE_EVENTS = 128   # last-N timeline events captured into a bundle
BUNDLE_TRACES = 8     # most-recent implicated traces pinned per bundle

# The closed event vocabulary.  record() REJECTS anything else: an
# unbounded kind set would make the timeline (and any future kind-labeled
# series) unbounded, and every emitter is in-repo -- a new failure edge
# adds its kind here first.
EVENT_KINDS = frozenset({
    "brownout.enter",     # ladder moved up a stage (attrs: stage, burn)
    "brownout.exit",      # ladder moved down a stage (attrs: stage, burn)
    "burn.cross",         # worst-model 5m burn crossed the trigger
                          # threshold (attrs: direction up|down, burn)
    "shed.burst",         # >= threshold admission sheds in one eval tick
    "breaker.open",       # gateway shed because a replica breaker is open
    "breaker.half_open",  # probe re-admitted a previously failed replica
    "dispatch.stall",     # dispatch watchdog declared the pipeline dead
    "pool.join",          # replica joined the upstream pool
    "pool.leave",         # replica left the upstream pool
    "pool.drain",         # replica entered draining
    "pool.quarantine",    # joiner held in probe quarantine
    "pool.unhealthy",     # replica flipped unhealthy (breaker opened)
    "pool.healthy",       # replica flipped back healthy
    "pool.stalled",       # replica advertised a dispatch stall (header)
    "registry.load",      # model version loaded/activated
    "registry.unload",    # model version unloaded
    "quant.gate_fail",    # int8 warmup tolerance gate refused activations
    "warm.compile",       # warmup bucket missed the compile cache
    "incident.capture",   # the recorder itself captured a bundle
    "decode.saturated",   # every decode slot busy while the admission
                          # queue is non-empty (attrs: queued, slots)
    "decode.shed",        # a generation was refused/retired by policy --
                          # queue full or deadline (attrs: reason)
})

# Trigger rules: what fires each one, what clears (re-arms) it, and the
# default threshold.  A trigger with a clear kind is HYSTERETIC: after a
# fire it stays armed -- further fires are suppressed, even past the dedup
# window -- until the clearing signal is seen.  A trigger without one
# (dispatch-stall) re-arms on the dedup window alone: the stall is
# terminal for its dispatcher, so a later fire is a genuinely new stall.
TRIGGER_RULES = {
    "burn-crossing": {
        "fire": "burn.cross", "clear": "burn.cross", "threshold": 1.0,
    },
    "brownout": {
        "fire": "brownout.enter", "clear": "brownout.exit", "threshold": 1.0,
    },
    "dispatch-stall": {
        "fire": "dispatch.stall", "clear": None, "threshold": None,
    },
    "replica-unhealthy": {
        "fire": "pool.unhealthy", "clear": "pool.healthy", "threshold": None,
    },
}


def parse_triggers(spec: str) -> dict:
    """``name[=threshold],...`` -> {name: threshold}.  Unknown names are a
    hard error (the vocabulary bounds the metric label), bad thresholds
    too -- a typo'd trigger spec must fail loudly at construction, not
    silently record nothing during the incident it was meant to catch."""
    out: dict = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, thr = part.partition("=")
        name = name.strip()
        if name not in TRIGGER_RULES:
            raise ValueError(
                f"unknown incident trigger {name!r}; known: "
                f"{', '.join(sorted(TRIGGER_RULES))}"
            )
        out[name] = float(thr) if thr.strip() else TRIGGER_RULES[name]["threshold"]
    return out


def merge_windows(entries: list, window_s: float = 30.0) -> list:
    """Group incident summaries (own + replicas') into causal windows: one
    failure typically fires triggers on several processes within seconds
    (a stalled replica -> model-tier dispatch-stall + gateway
    replica-unhealthy).  Entries closer than ``window_s`` merge."""
    dated = [
        e for e in entries if isinstance(e.get("fired_at_s"), (int, float))
    ]
    dated.sort(key=lambda e: e["fired_at_s"])
    windows: list = []
    for e in dated:
        ref = {
            "id": e.get("id"), "origin": e.get("origin", "local"),
            "tier": e.get("tier"), "trigger": e.get("trigger"),
            "fired_at_s": e["fired_at_s"],
        }
        if windows and e["fired_at_s"] - windows[-1]["end_s"] <= window_s:
            w = windows[-1]
            w["end_s"] = e["fired_at_s"]
            w["incidents"].append(ref)
            if e.get("trigger") and e["trigger"] not in w["triggers"]:
                w["triggers"].append(e["trigger"])
        else:
            windows.append({
                "start_s": e["fired_at_s"], "end_s": e["fired_at_s"],
                "triggers": [e["trigger"]] if e.get("trigger") else [],
                "incidents": [ref],
            })
    return windows


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except (TypeError, ValueError):
        return default


class FlightRecorder:
    """Per-tier event timeline + trigger engine + bundle store.

    Thread model: record() appends to a deque under a short lock and runs
    the trigger gate inline; a fire only *enqueues* a capture -- the
    expensive part (snapshots, metrics parse, optional profile sleep,
    disk write) runs on one daemon worker, so hot paths (request
    handlers, the brownout loop, pool probes) never block on it, and
    concurrent fires serialize into complete, atomic bundles.
    """

    def __init__(
        self,
        tier: str,
        registry=None,
        *,
        tracer=None,
        incident_dir: str | None = None,
        triggers: str | None = None,
        dedup_s: float | None = None,
        max_bundles: int | None = None,
        max_mb: float | None = None,
        profile_s: float | None = None,
        profiler=None,
        clock=time.monotonic,
        wall=time.time,
        enabled: bool | None = None,
        ring_events: int = RING_EVENTS,
        bundle_events: int = BUNDLE_EVENTS,
    ):
        env = os.environ
        if enabled is None:
            enabled = env.get(ENABLE_ENV, "1") not in ("0", "false", "off")
        self.enabled = bool(enabled)
        self.tier = tier
        self.tracer = tracer
        self.incident_dir = (
            env.get(DIR_ENV, "") if incident_dir is None else incident_dir
        )
        spec = env.get(TRIGGERS_ENV, "") or DEFAULT_TRIGGERS
        if triggers is not None:
            spec = triggers
        self._triggers = {
            name: {"threshold": thr, "armed": False, "last_fired_m": None}
            for name, thr in parse_triggers(spec).items()
        }
        self.dedup_s = (
            _env_float(DEDUP_ENV, DEFAULT_DEDUP_S)
            if dedup_s is None else float(dedup_s)
        )
        self.max_bundles = int(
            _env_float(MAX_BUNDLES_ENV, DEFAULT_MAX_BUNDLES)
            if max_bundles is None else max_bundles
        )
        self.max_mb = (
            _env_float(MAX_MB_ENV, DEFAULT_MAX_MB)
            if max_mb is None else float(max_mb)
        )
        self.profile_s = (
            _env_float(PROFILE_ENV, 0.0)
            if profile_s is None else float(profile_s)
        )
        self._profiler = profiler
        self._clock = clock
        self._wall = wall
        self.bundle_events = int(bundle_events)
        self._ring: collections.deque = collections.deque(  # guarded-by: _ring_lock
            maxlen=int(ring_events)
        )
        self._ring_lock = threading.Lock()
        self._trig_lock = threading.Lock()
        self._index_lock = threading.Lock()
        self._index: list = []       # guarded-by: _index_lock
        self._bundles: dict = {}     # guarded-by: _index_lock
        self._seq = 0                # guarded-by: _index_lock
        self._registry = registry
        # _last_metrics is touched only by _metrics_delta on the capture
        # worker thread (single consumer); no lock needed.
        self._last_metrics: dict | None = None
        self._shed_seen = 0          # guarded-by: _trig_lock
        self._shed_mark = 0          # guarded-by: _trig_lock
        self._last_burn: float | None = None  # guarded-by: _trig_lock
        self._m = (
            metrics_lib.incident_metrics(registry)
            if registry is not None else None
        )
        self._queue: queue.Queue = queue.Queue(maxsize=16)
        self._worker: threading.Thread | None = None  # guarded-by: _idle
        self._pending = 0            # guarded-by: _idle
        self._idle = threading.Condition()
        self._closed = False         # guarded-by: _idle
        # Snapshot providers: name -> zero-arg callable returning the same
        # JSON the matching /debug/<name> endpoint serves.  Registered by
        # the owning tier at construction time, read-only afterwards.
        self._providers: dict = {}
        if self.enabled and self.incident_dir:
            self._reindex_dir()

    # --- timeline ----------------------------------------------------------

    def record(self, kind: str, rid: str | None = None, **attrs) -> None:
        """Append one structured event to the ring and run the trigger
        gate.  Cheap by design: a dict build, a deque append under a
        short lock, and a handful of comparisons."""
        if not self.enabled:
            return
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown event kind {kind!r}")
        ev: dict = {
            "t": self._wall(), "m": self._clock(),
            "tier": self.tier, "kind": kind,
        }
        if rid:
            ev["rid"] = rid
        if attrs:
            ev["attrs"] = attrs
        with self._ring_lock:
            self._ring.append(ev)
        self._check_triggers(ev)

    def events(self, last: int | None = None) -> list:
        with self._ring_lock:
            out = list(self._ring)
        if last is not None:
            out = out[-int(last):]
        return out

    def observe_burn(self, burn: float) -> None:
        """Edge-detect the worst-model burn against the burn-crossing
        trigger's threshold and emit burn.cross events on each crossing.
        Called once per brownout eval tick; the crossing threshold IS the
        trigger threshold (``burn-crossing=2.5`` moves both)."""
        if not self.enabled:
            return
        thr = self.trigger_threshold("burn-crossing", 1.0)
        with self._trig_lock:
            prev, self._last_burn = self._last_burn, burn
        if prev is None:
            return
        if prev < thr <= burn:
            self.record(
                "burn.cross", direction="up",
                burn=round(burn, 4), threshold=thr,
            )
        elif burn < thr <= prev:
            self.record(
                "burn.cross", direction="down",
                burn=round(burn, 4), threshold=thr,
            )

    def note_shed(self) -> None:
        """O(1) shed tick from admission hot paths; tick_shed_burst turns
        the per-tick delta into at most one shed.burst event."""
        if self.enabled:
            with self._trig_lock:
                self._shed_seen += 1

    def tick_shed_burst(self, min_burst: int = 10) -> None:
        if not self.enabled:
            return
        with self._trig_lock:
            seen = self._shed_seen
            delta, self._shed_mark = seen - self._shed_mark, seen
        if delta >= min_burst:
            self.record("shed.burst", count=delta)

    def trigger_threshold(self, name: str, default: float) -> float:
        st = self._triggers.get(name)
        if st is None or st["threshold"] is None:
            return default
        return st["threshold"]

    # --- trigger engine ----------------------------------------------------

    def _matches_fire(self, name: str, st: dict, ev: dict) -> bool:
        rule = TRIGGER_RULES[name]
        if ev["kind"] != rule["fire"]:
            return False
        attrs = ev.get("attrs") or {}
        if name == "burn-crossing":
            return (
                attrs.get("direction") == "up"
                and float(attrs.get("burn", 0.0)) >= st["threshold"]
            )
        if name == "brownout":
            return float(attrs.get("stage", 0)) >= st["threshold"]
        return True

    def _matches_clear(self, name: str, st: dict, ev: dict) -> bool:
        rule = TRIGGER_RULES[name]
        if rule["clear"] is None or ev["kind"] != rule["clear"]:
            return False
        attrs = ev.get("attrs") or {}
        if name == "burn-crossing":
            return attrs.get("direction") == "down"
        if name == "brownout":
            return float(attrs.get("stage", 0)) < st["threshold"]
        return True

    def _check_triggers(self, ev: dict) -> None:
        for name, st in self._triggers.items():
            with self._trig_lock:
                if self._matches_clear(name, st, ev):
                    st["armed"] = False
                if not self._matches_fire(name, st, ev):
                    continue
                now = self._clock()
                last = st["last_fired_m"]
                deduped = last is not None and (now - last) < self.dedup_s
                if deduped or st["armed"]:
                    if self._m is not None:
                        c = self._m["suppressed"].get(name)
                        if c is not None:
                            c.inc()
                    continue
                st["last_fired_m"] = now
                if TRIGGER_RULES[name]["clear"] is not None:
                    st["armed"] = True
            self._enqueue_capture(name, ev)

    # --- bundle capture ----------------------------------------------------

    def add_snapshot_provider(self, name: str, fn) -> None:
        """Register a /debug/<name>-shaped snapshot callable (construction
        time only; see the _providers declaration in __init__)."""
        self._providers[name] = fn

    def _enqueue_capture(self, trigger: str, ev: dict) -> None:
        with self._ring_lock:
            tail = list(self._ring)[-self.bundle_events:]
        with self._idle:
            if self._closed:
                return
            self._pending += 1
        try:
            self._queue.put_nowait((trigger, ev, tail, time.perf_counter()))
        except queue.Full:
            # A full capture queue means the worker is wedged (or the
            # dedup window is misconfigured to ~0); losing THIS bundle is
            # better than blocking the failure path that fired it.
            with self._idle:
                self._pending -= 1
                self._idle.notify_all()
            if self._m is not None:
                c = self._m["suppressed"].get(trigger)
                if c is not None:
                    c.inc()
            return
        # kdlt-lint: disable=guarded-by -- double-checked fast path: the unlocked read only skips the lock when a worker already exists; creation re-checks under _idle
        if self._worker is None:
            with self._idle:
                if self._worker is None and not self._closed:
                    self._worker = threading.Thread(
                        target=self._worker_loop,
                        name=f"kdlt-incident-{self.tier}", daemon=True,
                    )
                    self._worker.start()

    def _worker_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            trigger, ev, tail, t0 = item
            try:
                self._capture(trigger, ev, tail, t0)
            except Exception:  # noqa: BLE001 - the recorder must never kill
                pass           # its host tier; a failed capture is just lost
            finally:
                with self._idle:
                    self._pending -= 1
                    self._idle.notify_all()

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until every enqueued capture has been written (tests and
        the bench use this; production never waits)."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._pending > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._idle.wait(left)
        return True

    def _capture(self, trigger: str, ev: dict, tail: list, t0: float) -> None:
        with self._index_lock:
            self._seq += 1
            seq = self._seq
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(ev["t"]))
        bundle_id = f"inc-{stamp}-{seq:04d}-{trigger}"
        events = sorted(tail, key=lambda e: e.get("m", 0.0))
        bundle: dict = {
            "id": bundle_id,
            "tier": self.tier,
            "trigger": trigger,
            "fired_at_s": ev["t"],
            "event": ev,
            "events": events,
            "snapshots": {},
            "traces": {},
            "metrics_delta": self._metrics_delta(),
        }
        for name, fn in self._providers.items():
            try:
                bundle["snapshots"][name] = fn()
            except Exception as e:  # noqa: BLE001 - a broken provider must
                bundle["snapshots"][name] = {"error": str(e)}  # not void the bundle
        if self.tracer is not None:
            rids: list = []
            for e in reversed(events):
                r = e.get("rid")
                if r and r not in rids:
                    rids.append(r)
                if len(rids) >= BUNDLE_TRACES:
                    break
            for r in rids:
                try:
                    # Pin first (upgrade-only), then read: classified
                    # ``incident`` the trace outlives ring churn for as
                    # long as the operator needs the bundle's ids to
                    # resolve via /debug/trace/<rid>.
                    self.tracer.classify(r, "incident")
                    info = self.tracer.trace_info(r)
                except Exception:  # noqa: BLE001 - trace already evicted
                    info = None
                if info:
                    bundle["traces"][r] = info
        if self.profile_s > 0 and self._profiler is not None:
            try:
                bundle["profile"] = self._profiler(self.profile_s)
            except Exception as e:  # noqa: BLE001 - profiling is best-effort
                bundle["profile"] = {"error": str(e)}
        bundle["captured_at_s"] = self._wall()
        bundle["capture_latency_s"] = round(time.perf_counter() - t0, 4)
        self._store(bundle)
        if self._m is not None:
            c = self._m["captures"].get(trigger)
            if c is not None:
                c.inc()
        self.record(
            "incident.capture", incident_id=bundle_id, trigger=trigger,
            latency_s=bundle["capture_latency_s"],
        )

    def _store(self, bundle: dict) -> None:
        data = json.dumps(bundle, indent=1, default=str)
        path = ""
        if self.incident_dir:
            try:
                os.makedirs(self.incident_dir, exist_ok=True)
                path = os.path.join(self.incident_dir, bundle["id"] + ".json")
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(data)
                # Atomic publish: a reader (or a crash) never observes a
                # torn bundle -- it exists complete or not at all.
                os.replace(tmp, path)
            except OSError:
                path = ""
        entry = {
            "id": bundle["id"], "tier": bundle["tier"],
            "trigger": bundle["trigger"],
            "fired_at_s": bundle["fired_at_s"],
            "captured_at_s": bundle.get("captured_at_s"),
            "capture_latency_s": bundle.get("capture_latency_s"),
            "events": len(bundle.get("events", ())),
            "traces": sorted(bundle.get("traces", {})),
            "bytes": len(data), "path": path,
        }
        with self._index_lock:
            self._index.append(entry)
            self._bundles[bundle["id"]] = bundle
            self._evict_locked()
            if self._m is not None:
                self._m["open"].set(len(self._index))

    def _evict_locked(self) -> None:
        max_bytes = int(self.max_mb * 1024 * 1024)
        while len(self._index) > 1 and (
            len(self._index) > self.max_bundles
            or sum(e["bytes"] for e in self._index) > max_bytes
        ):
            old = self._index.pop(0)  # oldest-first
            self._bundles.pop(old["id"], None)
            if old.get("path"):
                try:
                    os.remove(old["path"])
                except OSError:
                    pass
            if self._m is not None:
                c = self._m["dropped"].get(old.get("trigger"))
                if c is not None:
                    c.inc()

    def _reindex_dir(self) -> None:
        """Adopt a previous process's bundles (the dir outlives restarts
        on the cache volume) so caps and the open gauge stay honest."""
        try:
            names = sorted(os.listdir(self.incident_dir))
        except OSError:
            return
        adopted: list = []
        for name in names:
            if not (name.startswith("inc-") and name.endswith(".json")):
                continue
            path = os.path.join(self.incident_dir, name)
            try:
                with open(path, encoding="utf-8") as f:
                    bundle = json.load(f)
                size = os.path.getsize(path)
            except (OSError, ValueError):
                continue
            adopted.append({
                "id": bundle.get("id", name[:-5]),
                "tier": bundle.get("tier"),
                "trigger": bundle.get("trigger"),
                "fired_at_s": bundle.get("fired_at_s"),
                "captured_at_s": bundle.get("captured_at_s"),
                "capture_latency_s": bundle.get("capture_latency_s"),
                "events": len(bundle.get("events", ())),
                "traces": sorted(bundle.get("traces", {})),
                "bytes": size, "path": path,
            })
        with self._index_lock:
            self._index.extend(adopted)
            self._index.sort(key=lambda e: e.get("fired_at_s") or 0.0)
            self._evict_locked()
            if self._m is not None:
                self._m["open"].set(len(self._index))

    def _metrics_delta(self) -> dict:
        """Every series whose value moved since the previous capture,
        parsed back out of the registry's own text exposition -- the one
        format every metric already renders to."""
        if self._registry is None:
            return {}
        cur: dict = {}
        try:
            text = self._registry.render()
        except Exception:  # noqa: BLE001 - diagnostics only
            return {}
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            line = line.split(" # ", 1)[0].rstrip()  # strip exemplars
            try:
                key, val = line.rsplit(" ", 1)
                cur[key] = float(val)
            except ValueError:
                continue
        prev, self._last_metrics = self._last_metrics or {}, cur
        return {
            k: round(v - prev.get(k, 0.0), 6)
            for k, v in cur.items() if v != prev.get(k, 0.0)
        }

    # --- surfacing ---------------------------------------------------------

    def index(self) -> list:
        """Bundle summaries, newest first (what /debug/incidents serves)."""
        with self._index_lock:
            return [dict(e) for e in reversed(self._index)]

    def debug_payload(self) -> dict:
        return {
            "tier": self.tier,
            "enabled": self.enabled,
            "dir": self.incident_dir,
            "triggers": {
                name: {
                    "threshold": st["threshold"], "armed": st["armed"],
                }
                for name, st in self._triggers.items()
            },
            "dedup_s": self.dedup_s,
            "caps": {"max_bundles": self.max_bundles, "max_mb": self.max_mb},
            "incidents": self.index(),
        }

    def get(self, bundle_id: str) -> dict | None:
        """Full bundle by id: memory mirror first, then disk (bundles a
        previous process wrote survive on the volume)."""
        with self._index_lock:
            got = self._bundles.get(bundle_id)
            if got is not None:
                return got
            entry = next(
                (e for e in self._index if e["id"] == bundle_id), None
            )
        if entry is None or not entry.get("path"):
            return None
        try:
            with open(entry["path"], encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def close(self) -> None:
        with self._idle:
            self._closed = True
            worker = self._worker
        if worker is not None:
            self._queue.put(None)
            worker.join(timeout=5.0)
