"""Minimal thread-safe metrics: counters + histograms + Prometheus text.

The reference has no observability at all (SURVEY.md section 5: no /metrics,
no structured logs); both tiers here expose a /metrics endpoint rendered from
one of these registries, which also feeds bench.py's latency percentiles.
"""

from __future__ import annotations

import bisect
import os
import threading
import time

# Default latency buckets in seconds (sub-ms to 20 s, the reference's
# implicit deadline ceiling, reference model_server.py:55).
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.015, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0,
)

# Pipeline-stage buckets reach below the request buckets: the dispatch and
# readback stages of a well-overlapped pipeline are tens of microseconds to
# single-digit milliseconds, which DEFAULT_BUCKETS would collapse into its
# first bin.
PIPELINE_STAGE_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 1.0, 5.0,
)

# The in-flight dispatch pipeline's stages (runtime.engine.InFlightDispatcher),
# in hot-path order.  Stage semantics under JAX async dispatch:
#
# - enqueue_wait: submit() blocked waiting for an in-flight slot -- the
#   backpressure stage; nonzero means the device (not the host) is the
#   bottleneck, which is the healthy steady state.
# - dispatch: host batch assembly + uint8 H2D transfer ENQUEUE (the
#   predict_async call).  JAX returns as soon as the transfer+execution are
#   queued, so this is pure host cost -- the part pipelining hides.
# - execute: dispatch-return -> readback-start on the completion thread.
#   Under overlap this is the time the batch waited in flight while the
#   device worked (on it or its predecessors).
# - readback: the blocking materialization (device sync + D2H copy).
PIPELINE_STAGES = (
    ("enqueue_wait", "submit blocked on the in-flight depth limit (backpressure)"),
    ("dispatch", "host batch assembly + H2D transfer enqueue (predict_async)"),
    ("execute", "in-flight wait: dispatch return to readback start (overlapped device execution)"),
    ("readback", "blocking device sync + D2H materialization"),
)


def pipeline_stage_histograms(
    registry: "Registry", engine: str | None = None, model: str | None = None
) -> dict:
    """The per-stage histograms every in-flight dispatcher emits.

    Centralized so the dispatcher, the bench A/B mode, and any future
    pipelined caller emit the SAME series names (kdlt_pipeline_<stage>_seconds)
    and dashboards/alerts need one set of queries.  ``engine`` labels the
    series (engine="crosshost" for the cross-host dispatch pipeline) so
    one dashboard separates per-chip dispatch from fleet rounds; None
    keeps the unlabeled single-host series.  ``model`` adds the bounded
    serving-model label (multi-model scheduling: the SHARED dispatcher
    attributes each batch's stage time to the model that dispatched it);
    callers must memoize per model -- re-minting the same (name, labels)
    pair is a registry error by design.
    """
    if engine:
        registry = registry.with_labels(engine=engine)

    def mint(reg):
        return {
            stage: reg.histogram(
                f"kdlt_pipeline_{stage}_seconds", help,
                buckets=PIPELINE_STAGE_BUCKETS,
            )
            for stage, help in PIPELINE_STAGES
        }

    if model is None:
        return mint(registry)
    return _memo_on_child(
        model_registry(registry, model), "_kdlt_pipeline_stages", mint
    )


# --- the bounded ``model`` label (multi-model serving) ----------------------
#
# Every per-model series on a shared /metrics page carries a ``model`` label
# minted HERE and nowhere else (tools/check_metrics.py lints for stray
# with_labels(model=...) calls).  Central minting is what keeps the label's
# cardinality bounded: values come from the model registry's directory scan,
# and even a hostile/buggy caller cannot mint more than MODEL_LABEL_CAP
# distinct values per root registry -- the overflow bucket absorbs the rest
# instead of growing the exposition without bound.

MODEL_LABEL_CAP = 32
MODEL_LABEL_OVERFLOW = "__other__"

_model_children_lock = threading.Lock()


def model_registry(registry: "Registry", model: str) -> "Registry":
    """The child registry carrying the bounded ``model`` label.

    Memoized per root registry (the same model always lands on the same
    child, so helpers minting through it dedupe naturally); past
    MODEL_LABEL_CAP distinct models every further name collapses into the
    MODEL_LABEL_OVERFLOW bucket.
    """
    model = str(model)
    with _model_children_lock:
        children = getattr(registry, "_kdlt_model_children", None)
        if children is None:
            children = {}
            registry._kdlt_model_children = children
        if model not in children:
            if len(children) >= MODEL_LABEL_CAP:
                model = MODEL_LABEL_OVERFLOW
                if model in children:
                    return children[model]
            children[model] = registry.with_labels(model=model)
        return children[model]


def model_version_registry(
    registry: "Registry", model: str, version: int
) -> "Registry":
    """A served model VERSION's labeled child registry (one per ServedModel;
    dropped via registry.remove on unload, so version is not
    cardinality-bounded the way ``model`` is -- at most one version per
    model is live at a time)."""
    return registry.with_labels(model=model, version=str(version))


def _memo_on_child(child: "Registry", attr: str, factory):
    """Mint-once-per-child memoization for the model-labeled helpers.

    Two distinct raw model names can land on the SAME child registry (the
    overflow bucket), so memoizing by raw name in the caller is not enough
    -- the second name would re-mint the same (name, labels) series and
    raise.  Stamping the minted dict on the child itself makes every
    helper idempotent per label set.
    """
    with _model_children_lock:
        got = getattr(child, attr, None)
        if got is None:
            got = factory(child)
            setattr(child, attr, got)
        return got


def model_request_counter(registry: "Registry", model: str) -> "Counter":
    """Per-model request count on a tier's /metrics page (bounded label)."""
    child = model_registry(registry, model)
    return _memo_on_child(
        child, "_kdlt_model_requests", lambda c: c.counter(
            "kdlt_model_requests_total", "predict requests by served model"
        ),
    )


def admission_model_metrics(registry: "Registry", model: str) -> dict:
    """Per-model admission accounting (requests seen / admitted), the
    model-granular slice of the kdlt_admission_* contract.  The registry
    passed in is the controller's tier-labeled registry, so the series is
    distinguished by (tier, model)."""
    child = model_registry(registry, model)
    return _memo_on_child(
        child, "_kdlt_admission_model", lambda c: {
            "requests": c.counter(
                "kdlt_admission_requests_total",
                "requests seen by admission control",
            ),
            "admitted": c.counter(
                "kdlt_admission_admitted_total",
                "requests admitted to execution",
            ),
        },
    )


def scheduler_lane_metrics(registry: "Registry", model: str) -> dict:
    """One scheduling lane's series (runtime.scheduler.UnifiedScheduler).

    kdlt_batcher_batch_size keeps the historical batcher series name (the
    invariant dashboard contract) under the model label; the kdlt_sched_*
    series are the scheduler's own: queue depth, dispatch count, the
    weight-floor starvation guard, and estimated device-time consumption
    (the share the weighted policy arbitrates).
    """
    child = model_registry(registry, model)
    return _memo_on_child(child, "_kdlt_sched_lane", _mint_lane_metrics)


def _mint_lane_metrics(child: "Registry") -> dict:
    return {
        "batch_size": child.histogram(
            "kdlt_batcher_batch_size",
            "dispatched batch sizes",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
        ),
        "queue_full": child.counter(
            "kdlt_batcher_rejected_total",
            "requests rejected because queue was full",
        ),
        "queue_depth": child.gauge(
            "kdlt_sched_queue_depth", "images queued awaiting dispatch"
        ),
        "dispatch": child.counter(
            "kdlt_sched_dispatch_total", "batches dispatched for this model"
        ),
        "floor_boosts": child.counter(
            "kdlt_sched_floor_boosts_total",
            "dispatches granted by the weight-floor starvation guard ahead "
            "of the deadline order",
        ),
        "device_seconds": child.counter(
            "kdlt_sched_device_seconds_total",
            "observed dispatch->completion device time consumed by this "
            "model (the share the weighted policy arbitrates)",
        ),
        "weight": child.gauge(
            "kdlt_sched_weight", "configured scheduling weight"
        ),
        "queue_age": child.histogram(
            "kdlt_sched_queue_age_seconds",
            "age of queued units when their dispatch plan was taken "
            "(enqueue -> scheduled): the queuing-delay component of "
            "cross-model arbitration",
            buckets=PIPELINE_STAGE_BUCKETS,
        ),
    }


# --- SLO engine series (utils.slo) -----------------------------------------
#
# kdlt_slo_* is the second observability layer on top of the admission/
# pipeline substrate: per-model sliding-window goodput and multi-window burn
# rates against $KDLT_SLO_TARGET.  Minted HERE and nowhere else
# (tools/check_metrics.py rejects kdlt_slo_ mints outside this module): the
# ``model`` label stays bounded through model_registry, and the ``window``
# label's value set is exactly utils.slo.WINDOWS.

def slo_tier_metrics(registry: "Registry") -> dict:
    """The per-tier SLO statics: the configured objective itself."""
    return {
        "target": registry.gauge(
            "kdlt_slo_target",
            "configured SLO target (KDLT_SLO_TARGET): the fraction of "
            "requests that must complete in-deadline",
        ),
    }


def slo_model_window_metrics(
    registry: "Registry", model: str, window: str
) -> dict:
    """One (model, window) cell of the SLO engine's gauge matrix.

    Memoized per (model child, window) like the other model-labeled
    helpers; ``window`` values come from utils.slo.WINDOWS (e.g. "5m",
    "1h"), so both labels are bounded by construction.
    """
    child = model_registry(registry, model)

    def mint(c: "Registry") -> dict:
        w = c.with_labels(window=window)
        return {
            "goodput_ratio": w.gauge(
                "kdlt_slo_goodput_ratio",
                "fraction of SLO-eligible requests completed in-deadline "
                "over the window",
            ),
            "burn_rate": w.gauge(
                "kdlt_slo_burn_rate",
                "error-budget burn rate over the window (bad fraction / "
                "(1 - target)); 1.0 = burning exactly at the sustainable rate",
            ),
            "shed_ratio": w.gauge(
                "kdlt_slo_shed_ratio",
                "fraction of SLO-eligible requests shed (503/504) over the "
                "window",
            ),
            "error_ratio": w.gauge(
                "kdlt_slo_error_ratio",
                "fraction of SLO-eligible requests failed server-side over "
                "the window",
            ),
            "requests": w.gauge(
                "kdlt_slo_window_requests",
                "SLO-eligible requests observed in the window",
            ),
        }

    return _memo_on_child(child, f"_kdlt_slo_{window}", mint)


# Tail-based trace retention (utils.trace.Tracer): every finished trace is
# classified into exactly one of these, and eviction prefers dropping
# ``routine`` traces first -- the label set is this tuple, nothing else.
TRACE_RETENTION_CLASSES = (
    ("incident", "the trace is pinned by a flight-recorder incident bundle"),
    ("error", "the request failed server-side (5xx/disconnect)"),
    ("shed", "the request was shed (503/504)"),
    ("deadline", "the request completed but violated its deadline budget"),
    ("slow", "the request landed in the tier's slowest percentile"),
    ("routine", "an unremarkable request"),
)


def trace_retention_metrics(registry: "Registry") -> dict:
    """The tracer's retention accounting: traces classified (retained) and
    traces evicted from the ring (dropped), by retention class.  A rising
    dropped{class!="routine"} means interesting traces are being lost --
    grow the ring or scrape /debug/trace faster."""
    return {
        "retained": {
            cls: registry.with_labels(**{"class": cls}).counter(
                "kdlt_trace_retained_total",
                f"traces classified for retention: {help}",
            )
            for cls, help in TRACE_RETENTION_CLASSES
        },
        "dropped": {
            cls: registry.with_labels(**{"class": cls}).counter(
                "kdlt_trace_dropped_total",
                f"traces evicted from the ring buffer: {help}",
            )
            for cls, help in TRACE_RETENTION_CLASSES
        },
    }


def mfu_bucket_gauge(registry: "Registry", bucket: int) -> "Gauge":
    """Live per-bucket MFU gauge (runtime.flops.MfuAccountant); the caller's
    registry carries the model/version labels, ``bucket`` values are the
    engine's compiled ladder -- bounded by construction."""
    return registry.with_labels(bucket=str(int(bucket))).gauge(
        "kdlt_mfu_pct",
        "live model FLOP/s utilization of the device's dense peak, per "
        "compiled batch bucket (EWMA over dispatch->sync timings; compare "
        "with bench.py's offline mfu_pct)",
    )


def device_busy_gauge(registry: "Registry") -> "Gauge":
    return registry.gauge(
        "kdlt_device_busy_ratio",
        "decayed fraction of wall time the device spent executing this "
        "engine's batches (dispatch->sync timings; ~30 s half-life)",
    )


def crosshost_metrics(registry: "Registry") -> dict:
    """The cross-host round series (kdlt_crosshost_*), one set per serving
    engine/version (parallel.crosshost.CrossHostForward.attach_metrics).

    Centralized like pipeline_stage_histograms so the leader, bench.py
    --crosshost-ab, and dashboards key one set of names.  Stage semantics
    mirror the round protocol: ``broadcast`` is the leader's DCN
    control+payload broadcast (host-blocking, the part pipelining
    overlaps), ``collective`` is dispatch->device-completion of the SPMD
    program (execution incl. the on-device logits all-gather), ``gather``
    is the leader-local D2H materialization.
    """
    return {
        "depth": registry.gauge(
            "kdlt_crosshost_pipeline_depth",
            "configured cross-host in-flight round budget (KDLT_XH_PIPELINE_DEPTH)",
        ),
        "inflight": registry.gauge(
            "kdlt_crosshost_inflight_rounds",
            "rounds broadcast+dispatched but not yet materialized",
        ),
        "rounds": registry.counter(
            "kdlt_crosshost_rounds_total", "cross-host predict rounds dispatched"
        ),
        "reloads": registry.counter(
            "kdlt_crosshost_reload_total", "fleet-wide RELOAD rounds broadcast"
        ),
        "broadcast": registry.histogram(
            "kdlt_crosshost_broadcast_seconds",
            "leader DCN control+payload broadcast per round",
            buckets=PIPELINE_STAGE_BUCKETS,
        ),
        "collective": registry.histogram(
            "kdlt_crosshost_collective_seconds",
            "round dispatch -> device completion (SPMD execution incl. "
            "on-device logits all-gather; overlapped under pipelining)",
        ),
        "gather": registry.histogram(
            "kdlt_crosshost_gather_seconds",
            "leader-local D2H materialization of a round's replicated logits",
            buckets=PIPELINE_STAGE_BUCKETS,
        ),
    }


# The mesh axes the per-axis device-count gauge enumerates -- a BOUNDED
# label set by construction (parallel.mesh's axis convention).
MESH_AXES = ("data", "model")


def mesh_metrics(registry: "Registry") -> dict:
    """The mesh-serving series (kdlt_mesh_*), one set per engine/version.

    Static layout facts set once at engine construction --
    ``model_parallel`` (the model-axis degree), per-axis device counts
    (labelled ``axis``, bounded to MESH_AXES), and per-device resident
    param bytes (the "fits where it didn't" number, shrinking ~1/mp as the
    partition rules shard the wide kernels) -- plus cumulative
    dispatch->sync device seconds, the denominator for estimating the
    collective overhead a model axis adds over an mp=1 baseline.
    """
    return {
        "model_parallel": registry.gauge(
            "kdlt_mesh_model_parallel",
            "model-axis size of the serving mesh (1 = pure data-parallel)",
        ),
        "axis_devices": {
            axis: registry.with_labels(axis=axis).gauge(
                "kdlt_mesh_axis_devices", "devices along one mesh axis"
            )
            for axis in MESH_AXES
        },
        "param_bytes": registry.gauge(
            "kdlt_mesh_param_bytes_per_device",
            "resident parameter bytes per device under the partition rules",
        ),
        "collective": registry.counter(
            "kdlt_mesh_collective_seconds_total",
            "cumulative dispatch->sync device seconds on the mesh (includes "
            "the model-axis collectives XLA inserted)",
        ),
    }


# Admission control (serving.admission): every way a tier can refuse work,
# as the ``shed_reason`` label on kdlt_admission_shed_total.  Shared between
# both tiers so one dashboard query covers the whole path.
ADMISSION_SHED_REASONS = (
    ("deadline_exhausted", "the deadline budget was spent before execution (504)"),
    ("queue_timeout", "no concurrency slot freed within the bounded queue wait"),
    ("queue_full", "the admission queue's waiter cap was reached"),
    ("breaker_open", "the model-tier circuit breaker refused the call"),
    ("draining", "the tier is draining for shutdown"),
    ("budget_exhausted", "the model's per-tenant admission budget was spent "
                         "and no borrowed slot could be reclaimed"),
    ("preempted", "a queued waiter was evicted by a higher-priority or "
                  "under-budget arrival (borrowed slots shed first)"),
    ("brownout", "rejected by the brownout controller's staged class "
                 "shedding (429: the caller's class is out of budget, not "
                 "a server failure)"),
)

# Priority classes (serving.protocol.PRIORITY_CLASSES): the bounded value
# set of the ``class`` label on the per-class admission series.  Spelled
# here too so the mint below cannot drift cardinality with a caller's
# typo'd header -- admission normalizes through parse_priority first.
ADMISSION_PRIORITY_CLASSES = ("interactive", "batch", "best-effort")


def admission_class_metrics(registry: "Registry") -> dict:
    """Per-priority-class admission accounting (admitted / shed), keyed by
    the bounded ``class`` label.  One dict per tier registry: which class
    is paying for an overload is THE question during a brownout, and
    per-class goodput is what the ISSUE's class-shedding gates read."""
    out: dict = {}
    for cls in ADMISSION_PRIORITY_CLASSES:
        child = registry.with_labels(**{"class": cls})
        out[cls] = {
            "admitted": child.counter(
                "kdlt_admission_class_admitted_total",
                "requests admitted to execution, by priority class",
            ),
            "shed": child.counter(
                "kdlt_admission_class_shed_total",
                "requests shed, by priority class (lowest class sheds first)",
            ),
        }
    return out


# Brownout controller (serving.admission.brownout): staged graceful
# degradation driven by the SLO engine's burn rate.  kdlt_brownout_* is
# minted HERE and nowhere else (tools/check_metrics.py confines the prefix
# and the ``stage``/``direction`` labels to this module): the stage set is
# exactly 1..4 and direction is up|down, both bounded by construction.
BROWNOUT_STAGES = (1, 2, 3, 4)


def brownout_metrics(registry: "Registry") -> dict:
    """The brownout controller's series: the current stage (0 = healthy;
    alert on ``kdlt_brownout_stage > 0``) and every stage-boundary
    transition, labeled by the stage being entered (up) or left (down)."""
    return {
        "stage": registry.gauge(
            "kdlt_brownout_stage",
            "current brownout degradation stage (0 = off, 1 = hedging "
            "disabled, 2 = stale-while-revalidate serving, 3 = shedding "
            "best-effort, 4 = shedding batch)",
        ),
        "transitions": {
            (stage, direction): registry.with_labels(
                stage=str(stage), direction=direction
            ).counter(
                "kdlt_brownout_transitions_total",
                "brownout stage transitions: direction=up counts entering "
                "this stage from below, direction=down counts leaving it "
                "downward (a flapping controller shows as paired up/down "
                "increments)",
            )
            for stage in BROWNOUT_STAGES
            for direction in ("up", "down")
        },
    }

# Incident flight recorder (utils.flightrecorder): trigger-driven diagnostic
# bundle capture.  kdlt_incident_* is minted HERE and nowhere else
# (tools/check_metrics.py confines the prefix and the ``trigger`` label to
# this module); the trigger vocabulary is exactly this tuple -- the trigger
# parser rejects unknown names, so the label is bounded by construction.
INCIDENT_TRIGGERS = (
    "burn-crossing", "brownout", "dispatch-stall", "replica-unhealthy",
)


def incident_metrics(registry: "Registry") -> dict:
    """The flight recorder's series: bundles captured / suppressed (dedup or
    hysteresis swallowed a repeat fire) / dropped (dir caps evicted an old
    bundle), per trigger, plus how many bundles are currently on disk.
    Alert on rate(kdlt_incident_captures_total[5m]) > 0 (GUIDE 10m).

    Idempotent per registry (the _memo_on_child pattern): a tier that
    builds its recorder twice against one registry must not re-mint."""
    return _memo_on_child(registry, "_kdlt_incident", _mint_incident)


def _mint_incident(registry: "Registry") -> dict:
    return {
        "captures": {
            trig: registry.with_labels(trigger=trig).counter(
                "kdlt_incident_captures_total",
                "incident bundles captured, by firing trigger",
            )
            for trig in INCIDENT_TRIGGERS
        },
        "suppressed": {
            trig: registry.with_labels(trigger=trig).counter(
                "kdlt_incident_suppressed_total",
                "trigger fires suppressed inside the dedup window (a "
                "flapping signal yields ONE bundle plus this counter)",
            )
            for trig in INCIDENT_TRIGGERS
        },
        "dropped": {
            trig: registry.with_labels(trigger=trig).counter(
                "kdlt_incident_dropped_total",
                "incident bundles evicted oldest-first by the "
                "KDLT_INCIDENT_MAX_BUNDLES / KDLT_INCIDENT_MAX_MB caps, "
                "by the evicted bundle's trigger",
            )
            for trig in INCIDENT_TRIGGERS
        },
        "open": registry.gauge(
            "kdlt_incident_open",
            "incident bundles currently retained on disk under "
            "KDLT_INCIDENT_DIR",
        ),
    }


# Deadline budgets are ms-scale; the request-latency buckets (seconds) would
# collapse every remaining-budget observation into two bins.
DEADLINE_MS_BUCKETS = (
    1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000,
    10_000, 20_000, 60_000, 120_000,
)


def admission_metrics(registry: "Registry") -> dict:
    """The per-tier admission series (kdlt_admission_*).

    Centralized like pipeline_stage_histograms: the gateway controller, the
    model-tier controller, and the overload bench all emit the SAME names,
    distinguished only by the registry's tier label.
    """
    return {
        "requests": registry.counter(
            "kdlt_admission_requests_total", "requests seen by admission control"
        ),
        "admitted": registry.counter(
            "kdlt_admission_admitted_total", "requests admitted to execution"
        ),
        "queue_wait": registry.histogram(
            "kdlt_admission_queue_wait_seconds",
            "wait for a concurrency slot before execution",
            buckets=PIPELINE_STAGE_BUCKETS,
        ),
        "deadline_remaining_ms": registry.histogram(
            "kdlt_admission_deadline_remaining_ms",
            "remaining deadline budget at admission (propagation evidence: "
            "each tier down the path observes strictly less)",
            buckets=DEADLINE_MS_BUCKETS,
        ),
        "limit": registry.gauge(
            "kdlt_admission_concurrency_limit", "current AIMD concurrency limit"
        ),
        "inflight": registry.gauge(
            "kdlt_admission_inflight", "admitted requests currently executing"
        ),
        "draining": registry.gauge(
            "kdlt_admission_draining", "1 while the tier refuses new work for shutdown"
        ),
        "shed": {
            reason: registry.with_labels(shed_reason=reason).counter(
                "kdlt_admission_shed_total", help
            )
            for reason, help in ADMISSION_SHED_REASONS
        },
    }


# Serving-path fault tolerance (serving.upstream, serving.faults, the
# dispatcher watchdog).  Centralized like the helpers above so the gateway
# pool, the model tier, and bench.py --chaos-ab emit the SAME series names.


def upstream_pool_metrics(registry: "Registry") -> dict:
    """The gateway-tier replica-pool series (failover + hedging)."""
    return {
        "failover": registry.counter(
            "kdlt_upstream_failover_total",
            "upstream attempts redirected to another replica after a failure",
        ),
        "hedge_fired": registry.counter(
            "kdlt_hedge_fired_total",
            "hedged second attempts fired after the hedge delay",
        ),
        "hedge_won": registry.counter(
            "kdlt_hedge_won_total",
            "hedged attempts whose response was the one used",
        ),
    }


# Gateway response cache + singleflight coalescing (serving.cache).  Every
# way an entry can leave the cache, as the bounded ``reason`` label on
# kdlt_cache_evictions_total; minted HERE and nowhere else
# (tools/check_metrics.py confines the kdlt_cache_ prefix and the reason
# label to this module).
CACHE_EVICTION_REASONS = (
    ("lru", "evicted to fit the KDLT_CACHE_MAX_MB byte budget"),
    ("ttl", "expired past KDLT_CACHE_TTL_S"),
    ("reload", "dropped because the model's artifact hash changed (hot "
               "reload with different bytes)"),
)


def cache_metrics(registry: "Registry") -> dict:
    """The gateway-tier response-cache series (kdlt_cache_*).

    Centralized like the helpers above so the cache, /debug/cache, and
    bench.py --cache-ab key one set of names.  ``hits`` never touched
    admission or the upstream; ``coalesced`` rode another request's
    flight (admitted-but-not-dispatched); ``misses`` paid the full path.
    """
    return {
        "hits": registry.counter(
            "kdlt_cache_hits_total",
            "requests served from the response cache (no admission slot, "
            "no upstream call, no device work)",
        ),
        "misses": registry.counter(
            "kdlt_cache_misses_total",
            "cacheable requests that missed and led their own upstream flight",
        ),
        "coalesced": registry.counter(
            "kdlt_cache_coalesced_total",
            "requests coalesced onto another identical request's in-flight "
            "upstream call (singleflight followers)",
        ),
        "stale_hits": registry.counter(
            "kdlt_cache_stale_hits_total",
            "requests served a TTL-expired entry under brownout "
            "stale-while-revalidate (within KDLT_CACHE_SWR_S past expiry; "
            "marked X-Kdlt-Cache: stale)",
        ),
        "neg_hits": registry.counter(
            "kdlt_cache_negative_hits_total",
            "requests answered from a negative-cache entry (a recent 404/"
            "400 for the same content key, held for KDLT_CACHE_NEG_TTL_S)",
        ),
        "bytes": registry.counter(
            "kdlt_cache_bytes_total",
            "response bytes inserted into the cache",
        ),
        "resident": registry.gauge(
            "kdlt_cache_resident_bytes",
            "response bytes currently held by the cache",
        ),
        "entries": registry.gauge(
            "kdlt_cache_entries", "entries currently held by the cache"
        ),
        "hit_ratio": registry.gauge(
            "kdlt_cache_hit_ratio",
            "lifetime hits / (hits + misses) of the response cache",
        ),
        "evictions": {
            reason: registry.with_labels(reason=reason).counter(
                "kdlt_cache_evictions_total", help
            )
            for reason, help in CACHE_EVICTION_REASONS
        },
    }


# Decoded-uint8 cache tier (serving.cache.DecodedCache): content-addressed
# decode results shared across models.  kdlt_cache_decoded_* rides the
# kdlt_cache_ central prefix, so it is minted HERE and nowhere else.
def cache_decoded_metrics(registry: "Registry") -> dict:
    """The decoded-uint8 cache tier's series (kdlt_cache_decoded_*).

    Keys are (payload content hash, resolved preprocess params), so a hit
    means a previously decoded image's pixels were reused -- across
    requests AND across models sharing an input contract -- skipping the
    JPEG/PNG decode + resize entirely.  Entries are content-addressed and
    therefore immutable: there is no TTL and no artifact invalidation,
    only the LRU byte budget (KDLT_CACHE_DECODED_MB)."""
    return {
        "hits": registry.counter(
            "kdlt_cache_decoded_hits_total",
            "decode-stage lookups served a previously decoded uint8 tensor "
            "(no JPEG/PNG decode, no resize)",
        ),
        "misses": registry.counter(
            "kdlt_cache_decoded_misses_total",
            "decode-stage lookups that paid the full decode+resize",
        ),
        "resident": registry.gauge(
            "kdlt_cache_decoded_resident_bytes",
            "decoded uint8 tensor bytes currently held by the decoded tier",
        ),
        "entries": registry.gauge(
            "kdlt_cache_decoded_entries",
            "entries currently held by the decoded tier",
        ),
        "evictions": registry.counter(
            "kdlt_cache_decoded_evictions_total",
            "decoded entries evicted to fit the KDLT_CACHE_DECODED_MB "
            "byte budget (content-addressed entries never expire; LRU is "
            "the only way out)",
        ),
    }


# Raw-bytes ingest wire (serving/protocol + GUIDE 10q).  The ``reason``
# label's value set is exactly this tuple (bounded by construction); the
# kdlt_ingest_ prefix is confined to this module by kdlt-lint.
INGEST_FALLBACK_REASONS = (
    ("format", "payload failed the JPEG/PNG magic-byte sniff (exotic "
               "format decodes at the gateway, rides the tensor wire)"),
    ("negotiation", "the model tier did not advertise the bytes capability "
                    "on its spec response (old server or KDLT_INGEST=0)"),
    ("rejected", "a bytes-wire POST came back 4xx and the request was "
                 "re-sent decoded on the legacy tensor wire"),
)


def ingest_gateway_metrics(registry: "Registry") -> dict:
    """The gateway tier's raw-bytes ingest series (kdlt_ingest_*): how
    much traffic rides the bytes wire, why the rest fell back, and the
    wire bytes actually shipped (the payload-diet receipt bench.py
    --ingest-ab cross-checks)."""
    return {
        "bytes_requests": registry.counter(
            "kdlt_ingest_bytes_requests_total",
            "upstream predict calls sent on the raw-bytes wire",
        ),
        "wire_bytes": registry.counter(
            "kdlt_ingest_wire_bytes_total",
            "request-body bytes shipped on the raw-bytes wire",
        ),
        "fallbacks": {
            reason: registry.with_labels(reason=reason).counter(
                "kdlt_ingest_fallbacks_total", help
            )
            for reason, help in INGEST_FALLBACK_REASONS
        },
    }


def ingest_server_metrics(registry: "Registry") -> dict:
    """The model tier's decode-stage series (kdlt_ingest_*): images
    decoded at this tier and the per-batch decode latency (the stage a
    trace waterfall shows as server.ingest_decode)."""
    return {
        "decoded_images": registry.counter(
            "kdlt_ingest_decoded_images_total",
            "images decoded+resized by the model tier's decode stage",
        ),
        "decode_seconds": registry.histogram(
            "kdlt_ingest_decode_seconds",
            "wall seconds per bytes-wire batch in the thread-pooled "
            "decode stage",
            buckets=PIPELINE_STAGE_BUCKETS,
        ),
    }


# Quantization serving state (ops.quantize + runtime.engine).  The scheme
# label's value set is exactly this tuple (bounded by construction); minted
# HERE and nowhere else -- tools/check_metrics.py confines the kdlt_quant_
# prefix and the ``scheme`` label to this module.
QUANT_SCHEMES = (
    ("float32", "unquantized float serving"),
    ("int8-weight-only", "int8 weights dequantized inline; float activations"),
    ("int8-w8a8", "int8 weights AND calibrated int8 activations (MXU 2x path)"),
)


def quant_metrics(registry: "Registry") -> dict:
    """One engine's quantization accounting: which scheme is ACTIVE (the
    gauge is 1 for exactly one scheme -- post-tolerance-gate, post-
    $KDLT_QUANT_SCHEME override, so a silently-downgraded pod is
    alertable) and how many times the warmup tolerance gate refused
    int8 activations (kdlt_quant_gate_failures_total)."""
    return {
        "scheme": {
            scheme: registry.with_labels(scheme=scheme).gauge(
                "kdlt_quant_scheme",
                f"1 while this scheme is the one actually serving: {help}",
            )
            for scheme, help in QUANT_SCHEMES
        },
        "gate_failures": registry.counter(
            "kdlt_quant_gate_failures_total",
            "warmup golden-logits tolerance gate failures: a calibrated "
            "int8-w8a8 artifact drifted past KDLT_QUANT_TOL (or top-1 "
            "agreement) and was downgraded to weight-only serving",
        ),
    }


def pool_membership_metrics(registry: "Registry") -> dict:
    """Pool-level dynamic-membership series (kdlt_pool_*).

    Minted HERE and nowhere else (tools/check_metrics.py confines the
    kdlt_pool_ prefix to this module) so the gateway pool and bench.py
    --churn-ab key one set of names.  ``members`` counts replicas in
    rotation OR quarantine (everything the resolver currently believes
    in); joins/leaves count membership transitions, which is what the
    churn bench's assertions and any flap alert key on.
    """
    return {
        "members": registry.gauge(
            "kdlt_pool_members",
            "upstream replicas currently known to the pool (in rotation, "
            "quarantined, or draining)",
        ),
        "joins": registry.counter(
            "kdlt_pool_joins_total",
            "replicas added to the pool by dynamic membership (resolver "
            "or set_membership)",
        ),
        "leaves": registry.counter(
            "kdlt_pool_leaves_total",
            "replicas removed from the pool by dynamic membership",
        ),
    }


def pool_replica_metrics(registry: "Registry", host: str) -> dict:
    """One replica's pool series, minted under a single labeled child so
    dynamic membership can retire ALL of a departed replica's series
    atomically (``registry.remove(child)``) without leaving stale samples
    on /metrics.  ``child`` is that handle; callers never mint through it
    directly."""
    child = registry.with_labels(replica=host)
    return {
        "child": child,
        "healthy": child.gauge(
            "kdlt_upstream_replica_healthy",
            "1 while the upstream replica is considered healthy",
        ),
        "picks": child.counter(
            "kdlt_pool_pick_total",
            "times power-of-two-choices selection routed a primary "
            "attempt to this replica",
        ),
        "ewma_ms": child.gauge(
            "kdlt_pool_replica_ewma_ms",
            "EWMA of this replica's observed request latency (the "
            "power-of-two-choices ranking signal)",
        ),
    }


def engine_warm_source_metrics(registry: "Registry") -> dict:
    """Per-engine warmup provenance: how many buckets of the ladder came
    up as persistent-compile-cache hits vs live XLA compiles.  The
    ``source`` label's value set is exactly these two (bounded by
    construction); a scaled-up pod whose AOT-warmed image is working
    reports ``compile`` == 0, which is the zero-cold-start proof the
    churn bench and the GUIDE §10k recipe key on."""
    return {
        source: registry.with_labels(source=source).counter(
            "kdlt_engine_warm_source", help
        )
        for source, help in (
            ("cache", "warmup buckets satisfied from the persistent "
                      "compile cache (fast path)"),
            ("compile", "warmup buckets that paid a live XLA compile"),
        )
    }


def dispatch_stall_counter(registry: "Registry") -> "Counter":
    """In-flight dispatch handles the watchdog declared stuck and failed."""
    return registry.counter(
        "kdlt_dispatch_stall_total",
        "in-flight dispatches failed by the engine watchdog as stuck",
    )


# --- generative decode lane (runtime.decode / serving.generate) -------------
#
# kdlt_decode_* is the generative lane's per-token observability surface:
# TTFT and TPOT distributions (the per-token SLO signals the SloEngine and
# the brownout ladder consume), token/generation/step throughput, and the
# continuous-batching occupancy gauges.  Minted HERE and nowhere else
# (kdlt-lint's metrics pass confines the kdlt_decode_ prefix to this
# module) with the bounded ``model`` label.

# TTFT spans prefill (tens of ms on CPU, sub-ms warm on device) up to
# queue-dominated seconds; TPOT is one decode step amortized per token.
DECODE_TTFT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0,
)
DECODE_TPOT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 1.0,
)


def decode_metrics(registry: "Registry", model: str) -> dict:
    """One generative model's decode-lane series (bounded model label,
    memoized per child like every model-labeled helper)."""
    child = model_registry(registry, model)

    def mint(c: "Registry") -> dict:
        return {
            "ttft": c.histogram(
                "kdlt_decode_ttft_seconds",
                "time to first token: generation admitted -> first token "
                "materialized (prefill + queue wait included)",
                buckets=DECODE_TTFT_BUCKETS,
            ),
            "tpot": c.histogram(
                "kdlt_decode_tpot_seconds",
                "time per output token after the first "
                "((t_last - t_first) / (n - 1)) for each finished "
                "generation",
                buckets=DECODE_TPOT_BUCKETS,
            ),
            "tokens": c.counter(
                "kdlt_decode_tokens_total", "output tokens emitted"
            ),
            "generations": c.counter(
                "kdlt_decode_generations_total", "generations finished"
            ),
            "steps": c.counter(
                "kdlt_decode_steps_total",
                "batched decode steps executed (each advances every "
                "active slot by one token)",
            ),
            "step_seconds": c.histogram(
                "kdlt_decode_step_seconds",
                "wall time of one batched decode step (dispatch + "
                "materialize)",
                buckets=PIPELINE_STAGE_BUCKETS,
            ),
            "prefill_seconds": c.histogram(
                "kdlt_decode_prefill_seconds",
                "wall time of one prompt prefill (bucketed compile ladder)",
                buckets=PIPELINE_STAGE_BUCKETS,
            ),
            "active_slots": c.gauge(
                "kdlt_decode_active_slots",
                "decode batch slots currently occupied by live generations",
            ),
            "queue_depth": c.gauge(
                "kdlt_decode_queue_depth",
                "admitted generations waiting for a free decode slot",
            ),
            "pages_in_use": c.gauge(
                "kdlt_decode_kv_pages_in_use",
                "KV-cache pages currently allocated to live generations",
            ),
        }

    return _memo_on_child(child, "_kdlt_decode", mint)


# --- OpenMetrics exemplars ---------------------------------------------------
#
# Behind $KDLT_METRICS_EXEMPLARS=1 the latency histograms annotate bucket
# samples with the trace id of a recent observation that landed there
# (``... # {trace_id="..."} value timestamp``), so a burn-rate spike on a
# dashboard links DIRECTLY to /debug/trace/<rid> waterfalls of the requests
# that caused it.  Off (the default) the exposition is byte-identical to the
# legacy format -- classic Prometheus text-format parsers never see the
# annotation.  Exemplars exist on histograms ONLY (the OpenMetrics rule);
# tools/check_metrics.py rejects exemplar= on counter/gauge mutations.

EXEMPLARS_ENV = "KDLT_METRICS_EXEMPLARS"


def exemplars_enabled() -> bool:
    """Read the env gate afresh (cheap: a handful of calls per request);
    in-process A/B arms flip the env between servers."""
    return os.environ.get(EXEMPLARS_ENV, "").strip() == "1"


def _escape_label_value(v) -> str:
    """Prometheus text-format label escaping: backslash, quote, newline.
    Without it a label value containing '"' or '\\n' desyncs strict
    parsers for the whole exposition."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    """HELP text escaping per the exposition format: backslash + newline."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str] | None, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in (labels or {}).items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    kind = "counter"

    def __init__(self, name: str, help: str = "", labels: dict[str, str] | None = None):
        self.name, self.help, self.labels = name, help, labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def sample_lines(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} {self._value}"]

    def render(self) -> str:
        return (
            f"# HELP {self.name} {_escape_help(self.help)}\n"
            f"# TYPE {self.name} {self.kind}\n"
            + "\n".join(self.sample_lines()) + "\n"
        )


class Gauge(Counter):
    kind = "gauge"

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v


class Histogram:
    def __init__(
        self,
        name: str,
        help: str = "",
        buckets=DEFAULT_BUCKETS,
        labels: dict[str, str] | None = None,
    ):
        self.name, self.help, self.labels = name, help, labels
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +inf bucket
        self._sum = 0.0
        self._n = 0
        # Last exemplar per bucket index: (trace_id, value, unix_ts).  Only
        # ever populated by callers passing exemplar= (the request-latency
        # observe sites, behind the env gate), so plain histograms pay one
        # None check.
        self._exemplars: dict[int, tuple[str, float, float]] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, exemplar: str | None = None) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._n += 1
            if exemplar is not None:
                self._exemplars[i] = (str(exemplar), v, time.time())

    def percentile(self, q: float) -> float:
        """Approximate percentile from bucket upper bounds (q in [0,1])."""
        with self._lock:
            n = self._n
            if n == 0:
                return 0.0
            target = q * n
            cum = 0
            for i, c in enumerate(self._counts):
                cum += c
                if cum >= target:
                    return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    kind = "histogram"

    def _exemplar_suffix(self, i: int, with_exemplars: bool) -> str:
        """The OpenMetrics exemplar annotation for bucket index ``i``, or ""
        (always "" unless the env gate is on, so the legacy exposition is
        byte-identical with the flag off)."""
        if not with_exemplars:
            return ""
        ex = self._exemplars.get(i)
        if ex is None:
            return ""
        trace_id, value, ts = ex
        return (
            f' # {{trace_id="{_escape_label_value(trace_id)}"}} '
            f"{value:.6g} {ts:.3f}"
        )

    def sample_lines(self) -> list[str]:
        out = []
        cum = 0
        with_ex = bool(self._exemplars) and exemplars_enabled()
        with self._lock:
            for i, (le, c) in enumerate(zip(self.buckets, self._counts)):
                cum += c
                le_label = f'le="{le}"'
                out.append(
                    f"{self.name}_bucket{_fmt_labels(self.labels, le_label)} {cum}"
                    + self._exemplar_suffix(i, with_ex)
                )
            cum += self._counts[-1]
            inf_label = 'le="+Inf"'
            out.append(
                f"{self.name}_bucket{_fmt_labels(self.labels, inf_label)} {cum}"
                + self._exemplar_suffix(len(self.buckets), with_ex)
            )
            out.append(f"{self.name}_sum{_fmt_labels(self.labels)} {self._sum}")
            out.append(f"{self.name}_count{_fmt_labels(self.labels)} {self._n}")
        return out

    def render(self) -> str:
        return (
            f"# HELP {self.name} {_escape_help(self.help)}\n"
            f"# TYPE {self.name} {self.kind}\n"
            + "\n".join(self.sample_lines()) + "\n"
        )


class Registry:
    def __init__(self, labels: dict[str, str] | None = None):
        """``labels`` are applied to every metric created through this
        registry (e.g. Registry(labels={"model": name}) per served model, so
        two models' engines never emit colliding series)."""
        self._metrics: list = []
        self._labels = dict(labels or {})
        self._keys: set = set()
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = "") -> Counter:
        return self._add(Counter(name, help, labels=self._labels or None))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._add(Gauge(name, help, labels=self._labels or None))

    def histogram(self, name: str, help: str = "", buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._add(Histogram(name, help, buckets, labels=self._labels or None))

    def with_labels(self, **labels: str) -> "Registry":
        """Child registry sharing this one's output but adding labels."""
        child = Registry({**self._labels, **labels})
        self._add(child)
        return child

    def _add(self, m):
        with self._lock:
            name = getattr(m, "name", None)
            if name is not None:
                key = (name, tuple(sorted((m.labels or {}).items())))
                if key in self._keys:
                    raise ValueError(f"duplicate metric {name!r} with same labels")
                self._keys.add(key)
            self._metrics.append(m)
        return m

    def remove(self, m) -> None:
        """Drop a metric or child registry (e.g. an unloaded model version's
        series) from this registry's output."""
        with self._lock:
            if m in self._metrics:
                self._metrics.remove(m)
                name = getattr(m, "name", None)
                if name is not None:
                    self._keys.discard(
                        (name, tuple(sorted((m.labels or {}).items())))
                    )

    def _leaves(self):
        """Every leaf metric under this registry, depth-first, in creation
        order (child registries flattened in place)."""
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            if isinstance(m, Registry):
                yield from m._leaves()
            else:
                yield m

    def render(self) -> str:
        """Prometheus text exposition, grouped by metric name.

        Labeled series sharing a name (e.g. the per-reason
        kdlt_admission_shed_total counters) must render under ONE
        ``# HELP``/``# TYPE`` block: the format forbids repeating the
        metadata lines, and strict parsers (promtool, the Prometheus
        scraper in some configurations) reject the duplicate blocks the
        naive per-metric concatenation used to produce.  First-seen
        ordering keeps the page stable across renders; the first series'
        HELP/TYPE wins for its name.
        """
        order: list[str] = []
        meta: dict[str, tuple[str, str]] = {}
        samples: dict[str, list[str]] = {}
        for m in self._leaves():
            name = m.name
            if name not in meta:
                order.append(name)
                meta[name] = (m.kind, m.help)
                samples[name] = []
            samples[name].extend(m.sample_lines())
        out: list[str] = []
        for name in order:
            kind, help = meta[name]
            out.append(f"# HELP {name} {_escape_help(help)}")
            out.append(f"# TYPE {name} {kind}")
            out.extend(samples[name])
        return "\n".join(out) + "\n" if out else ""
