"""Dapper-style per-request span tracing for the serving path.

Aggregate histograms (utils.metrics) answer "how slow is the fleet"; they
cannot answer "where did THIS request's 480 ms go" -- the question tail
debugging actually asks (Sigelman et al. 2010; Dean & Barroso, "The Tail
at Scale", 2013).  This module is the in-process tracing core both serving
tiers share:

- a **trace id** rides the existing ``X-Request-Id`` propagation path (the
  sanitized request id IS the trace id -- one grep key for logs, headers,
  and traces);
- each tier records **spans** (name, start, duration, parent span id,
  tags) into a bounded in-process ring buffer (:class:`Tracer`), exposed
  at ``/debug/trace/<rid>``;
- the **parent span id** crosses tier boundaries in the
  ``X-Kdlt-Parent-Span`` header (gRPC: ``x-kdlt-parent-span`` metadata),
  so the model tier's spans nest under the exact gateway upstream attempt
  that carried them -- a hedged request shows BOTH attempts, each with its
  own subtree;
- every response carries a ``Server-Timing``-style ``X-Kdlt-Trace``
  summary header, so a curl sees the per-tier breakdown without a second
  round trip.

Timestamps come from one wall-anchored monotonic clock per process
(``now_s``): spans recorded by different threads of one process can never
be reordered by wall-clock steps, so child intervals derived from shared
perf-counter boundaries (the dispatcher's pipeline stages) are exactly
non-overlapping in the waterfall.
"""

from __future__ import annotations

import re
import threading
import time
import uuid
from collections import OrderedDict
from contextlib import contextmanager

from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

# Response header: Server-Timing-style per-tier span summary.
TRACE_HEADER = "X-Kdlt-Trace"
# Request header: the caller's active span id, which becomes the parent of
# this tier's root span.  Rides next to X-Request-Id (the trace id).
PARENT_SPAN_HEADER = "X-Kdlt-Parent-Span"
GRPC_PARENT_SPAN_KEY = "x-kdlt-parent-span"  # gRPC metadata keys are lowercase

_SPAN_ID_RE = re.compile(r"[^A-Za-z0-9]")

# --- span-name vocabulary ---------------------------------------------------
# The single source of truth for every span name the tree records.  The
# waterfall renderers, the Server-Timing summary header, and the trace
# tooling all key on these exact strings, so the set is CLOSED: recording
# sites use these constants (or a literal that is a member -- enforced
# statically by kdlt-lint's closed-vocab pass), and adding a span means
# adding it here first.
SPAN_GATEWAY_REQUEST = "gateway.request"
SPAN_GATEWAY_ADMISSION = "gateway.admission"
SPAN_GATEWAY_PREPROCESS = "gateway.preprocess"
SPAN_GATEWAY_MICROBATCH = "gateway.microbatch"
SPAN_GATEWAY_CACHE = "gateway.cache"
SPAN_GATEWAY_UPSTREAM = "gateway.upstream"
SPAN_SERVER_REQUEST = "server.request"
SPAN_SERVER_ADMISSION = "server.admission"
SPAN_SERVER_DECODE = "server.decode"
# Raw-bytes ingest wire (GUIDE 10q): the model tier's image-decode stage --
# thread-pooled JPEG/PNG decode + resize of the blobs a bytes-wire request
# carried.  Nested inside server.decode's request-parse span so a waterfall
# separates wire parse cost from pixel decode cost.
SPAN_SERVER_INGEST_DECODE = "server.ingest_decode"
SPAN_SERVER_PREDICT = "server.predict"
SPAN_ENGINE_PREDICT = "engine.predict"
SPAN_BATCHER_QUEUE_WAIT = "batcher.queue_wait"
SPAN_BATCHER_WAIT = "batcher.wait"
SPAN_PIPELINE_ENQUEUE_WAIT = "pipeline.enqueue_wait"
SPAN_PIPELINE_DISPATCH = "pipeline.dispatch"
SPAN_PIPELINE_EXECUTE = "pipeline.execute"
SPAN_PIPELINE_READBACK = "pipeline.readback"
SPAN_CROSSHOST_BROADCAST = "crosshost.broadcast"
SPAN_CROSSHOST_COLLECTIVE = "crosshost.collective"
SPAN_CROSSHOST_GATHER = "crosshost.gather"
# Generative (decode) lane: the gateway proxy span, the model tier's
# handler span, and the decode engine's internal stages.  first_token
# covers admission-to-first-emission (the TTFT interval as the server saw
# it); stream covers the remainder of the token loop.
SPAN_GATEWAY_GENERATE = "gateway.generate"
SPAN_SERVER_GENERATE = "server.generate"
SPAN_DECODE_QUEUE_WAIT = "decode.queue_wait"
SPAN_DECODE_PREFILL = "decode.prefill"
SPAN_DECODE_FIRST_TOKEN = "decode.first_token"
SPAN_DECODE_STREAM = "decode.stream"

SPAN_NAMES = frozenset({
    SPAN_GATEWAY_REQUEST,
    SPAN_GATEWAY_ADMISSION,
    SPAN_GATEWAY_PREPROCESS,
    SPAN_GATEWAY_MICROBATCH,
    SPAN_GATEWAY_CACHE,
    SPAN_GATEWAY_UPSTREAM,
    SPAN_SERVER_REQUEST,
    SPAN_SERVER_ADMISSION,
    SPAN_SERVER_DECODE,
    SPAN_SERVER_INGEST_DECODE,
    SPAN_SERVER_PREDICT,
    SPAN_ENGINE_PREDICT,
    SPAN_BATCHER_QUEUE_WAIT,
    SPAN_BATCHER_WAIT,
    SPAN_PIPELINE_ENQUEUE_WAIT,
    SPAN_PIPELINE_DISPATCH,
    SPAN_PIPELINE_EXECUTE,
    SPAN_PIPELINE_READBACK,
    SPAN_CROSSHOST_BROADCAST,
    SPAN_CROSSHOST_COLLECTIVE,
    SPAN_CROSSHOST_GATHER,
    SPAN_GATEWAY_GENERATE,
    SPAN_SERVER_GENERATE,
    SPAN_DECODE_QUEUE_WAIT,
    SPAN_DECODE_PREFILL,
    SPAN_DECODE_FIRST_TOKEN,
    SPAN_DECODE_STREAM,
})

# One wall-anchored monotonic clock per process: perf_counter deltas on a
# wall-time anchor.  time.time() alone can step (NTP) mid-request, which
# would fabricate overlapping/negative child intervals.
_WALL0 = time.time()
_PERF0 = time.perf_counter()


def now_s() -> float:
    """Current wall time on the process's monotonic-anchored clock."""
    return _WALL0 + (time.perf_counter() - _PERF0)


def new_span_id() -> str:
    return uuid.uuid4().hex[:8]


def ensure_span_id(raw: str | None) -> str | None:
    """Sanitized inbound parent span id, or None (same hostile-header
    posture as tracing.ensure_request_id: a client-chosen value must not
    inject header or log structure)."""
    if not raw:
        return None
    sid = _SPAN_ID_RE.sub("", raw)[:32]
    return sid or None


class Span:
    """One recorded interval; mutable tags so e.g. a hedge winner can be
    marked after its attempt span was already recorded."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "tier",
                 "start_s", "dur_s", "tags")

    def __init__(self, trace_id, span_id, parent_id, name, tier,
                 start_s, dur_s, tags=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.tier = tier
        self.start_s = start_s
        self.dur_s = dur_s
        self.tags = dict(tags or {})

    @property
    def end_s(self) -> float:
        return self.start_s + self.dur_s

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "tier": self.tier,
            "start_s": round(self.start_s, 6),
            "dur_ms": round(self.dur_s * 1e3, 3),
            "tags": {k: v for k, v in self.tags.items()},
        }


# Retention classes, most-protected first.  Eviction walks the ring oldest
# first but skips protected traces while any routine one remains: the
# traces tail debugging actually needs (errors, sheds, deadline misses, the
# slowest percentile) outlive the routine churn around them.  ``incident``
# outranks everything: the flight recorder (utils/flightrecorder.py) pins a
# captured bundle's causal traces so they survive until an operator reads
# the bundle -- an evicted trace would leave the bundle's trace ids dangling.
RETENTION_PRIORITY = {
    "incident": 5, "error": 4, "shed": 3, "deadline": 2, "slow": 1,
    "routine": 0,
}


def retention_class(status: int, deadline_exceeded: bool = False,
                    slow: bool = False) -> str:
    """A finished request's retention class from its observable outcome
    (shared by both tiers so the classes mean the same thing fleet-wide)."""
    if status in (503, 504):
        return "shed"
    if status < 0 or status >= 500:
        return "error"
    if status == 200 and deadline_exceeded:
        return "deadline"
    if slow:
        return "slow"
    return "routine"


class _TraceEntry:
    __slots__ = ("spans", "cls", "dropped_spans")

    def __init__(self):
        self.spans: list[Span] = []
        self.cls: str | None = None  # None = not yet classified
        self.dropped_spans = 0


class Tracer:
    """Bounded per-tier span buffer: an OrderedDict ring of recent traces.

    Eviction is by TRACE and **tail-biased**: when ``max_traces`` is
    exceeded, the oldest *routine* (or unclassified) trace goes first;
    error/shed/deadline-violating/slowest-percentile traces (see
    :func:`retention_class`, set via :meth:`classify`) are only evicted
    when nothing routine is left.  Each trace's span list is capped at
    ``max_spans`` -- excess spans are COUNTED (``dropped_spans``), never
    silently discarded, so a truncated waterfall is distinguishable from
    missing instrumentation.  All methods are thread-safe; record() is
    O(1) amortized -- cheap enough for the hot path unconditionally, so
    tracing needs no sampling knob at this scale.

    ``registry`` (optional) mints the retention accounting series
    ``kdlt_trace_{retained,dropped}_total{class=...}``.
    """

    def __init__(self, tier: str, max_traces: int = 512, max_spans: int = 128,
                 registry: metrics_lib.Registry | None = None):
        self.tier = tier
        self.max_traces = max_traces
        self.max_spans = max_spans
        self._traces: OrderedDict[str, _TraceEntry] = OrderedDict()
        self._lock = threading.Lock()
        self.evicted_traces = 0  # ring evictions (any class), process total
        self.dropped_spans = 0   # spans past a trace's span cap, process total
        self._m = (
            metrics_lib.trace_retention_metrics(registry)
            if registry is not None else None
        )

    def _evict_one_locked(self) -> None:
        """Drop one trace to make room: the oldest routine/unclassified one,
        or -- only when every resident trace is protected -- the oldest
        overall (the ring must stay bounded even under a pure error storm).
        """
        victim = None
        for trace_id, entry in self._traces.items():  # oldest first
            if entry.cls is None or entry.cls == "routine":
                victim = trace_id
                break
        if victim is None:
            victim, entry = next(iter(self._traces.items()))
        else:
            entry = self._traces[victim]
        del self._traces[victim]
        self.evicted_traces += 1
        if self._m is not None:
            counter = self._m["dropped"].get(entry.cls or "routine")
            if counter is not None:
                counter.inc()

    def record(
        self,
        trace_id: str,
        name: str,
        start_s: float,
        dur_s: float,
        parent_id: str | None = None,
        span_id: str | None = None,
        **tags,
    ) -> Span:
        span = Span(
            trace_id, span_id or new_span_id(), parent_id, name, self.tier,
            start_s, max(0.0, dur_s), tags,
        )
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                while len(self._traces) >= self.max_traces:
                    self._evict_one_locked()
                entry = self._traces[trace_id] = _TraceEntry()
            if len(entry.spans) < self.max_spans:
                entry.spans.append(span)
            else:
                entry.dropped_spans += 1
                self.dropped_spans += 1
        return span

    def classify(self, trace_id: str, cls: str) -> None:
        """Stamp a finished trace's retention class (handlers call this in
        their finally block).  Upgrades only: a trace already classified
        more severe (a hedged request whose first attempt errored) keeps
        the severer class."""
        if cls not in RETENTION_PRIORITY:
            cls = "routine"
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                return  # already evicted (or never recorded): nothing to keep
            prev = entry.cls
            if prev is not None and (
                RETENTION_PRIORITY[prev] >= RETENTION_PRIORITY[cls]
            ):
                return
            entry.cls = cls
        if self._m is not None:
            counter = self._m["retained"].get(cls)
            if counter is not None:
                counter.inc()

    def request_trace(self, trace_id: str, parent_id: str | None = None) -> "RequestTrace":
        """A RequestTrace rooted at a freshly minted span id; the caller
        records the root span itself (typically in its finally block) with
        ``span_id=rt.span_id, parent_id=rt.parent_id``."""
        return RequestTrace(self, trace_id, new_span_id(), parent_id)

    def spans(self, trace_id: str) -> list[dict] | None:
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                return None
            return [s.to_dict() for s in entry.spans]

    def trace_info(self, trace_id: str) -> dict | None:
        """The /debug/trace view of one trace: spans plus the retention
        class and this trace's dropped-span count (a nonzero count marks a
        TRUNCATED waterfall -- the instrumentation fired, the ring cap
        bit)."""
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                return None
            return {
                "spans": [s.to_dict() for s in entry.spans],
                "retention_class": entry.cls or "routine",
                "spans_dropped": entry.dropped_spans,
            }

    def stats(self) -> dict:
        """Tier-level ring accounting, surfaced on /debug/trace 404s so a
        missing trace reads as "probably evicted" vs "never instrumented"."""
        with self._lock:
            return {
                "traces_resident": len(self._traces),
                "max_traces": self.max_traces,
                "traces_evicted_total": self.evicted_traces,
                "spans_dropped_total": self.dropped_spans,
            }

    def summary(self, trace_id: str) -> str:
        """Server-Timing-style summary: ``name;dur=12.3, ...`` (ms), in
        record order.  Empty string when the trace is unknown."""
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None or not entry.spans:
                return ""
            return ", ".join(
                f"{s.name};dur={s.dur_s * 1e3:.1f}" for s in entry.spans
            )


class RequestTrace:
    """The per-request carrier plumbed down a tier's predict path.

    ``span_id`` is the currently-active span -- the parent every child
    recorded through this carrier nests under.  ``None`` is the universal
    no-trace value: every instrumented callee takes ``trace=None`` and
    stays zero-cost when tracing is not engaged for the request.
    """

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "tags")

    def __init__(self, tracer: Tracer, trace_id: str, span_id: str,
                 parent_id: str | None = None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.tags: dict = {}

    def record(self, name: str, start_s: float, dur_s: float, **tags) -> Span:
        """Record a completed child interval under the active span."""
        return self.tracer.record(
            self.trace_id, name, start_s, dur_s, parent_id=self.span_id, **tags
        )

    @contextmanager
    def span(self, name: str, **tags):
        """Time a block as a child span; yields the child's RequestTrace so
        nested work (and cross-tier propagation) parents correctly.  The
        span records even when the block raises -- a shed or failed stage
        still belongs on the waterfall.  Extra tags set on the yielded
        carrier's ``tags`` dict are merged at record time."""
        child = RequestTrace(self.tracer, self.trace_id, new_span_id(), self.span_id)
        t0 = now_s()
        try:
            yield child
        finally:
            self.tracer.record(
                self.trace_id, name, t0, now_s() - t0,
                parent_id=self.span_id, span_id=child.span_id,
                **{**tags, **child.tags},
            )


# --- waterfall rendering (client.py --trace, bench --trace-breakdown) ------


def sort_spans(spans: list[dict]) -> list[dict]:
    return sorted(spans, key=lambda s: (s.get("start_s", 0.0), -s.get("dur_ms", 0.0)))


def span_children(spans: list[dict]) -> dict:
    """parent span_id -> children (start-ordered); key None = roots
    (spans whose parent is absent from the set count as roots too)."""
    ids = {s["span_id"] for s in spans}
    out: dict = {}
    for s in sort_spans(spans):
        parent = s.get("parent_id")
        key = parent if parent in ids else None
        out.setdefault(key, []).append(s)
    return out


def render_waterfall(spans: list[dict], width: int = 40) -> str:
    """ASCII waterfall of a merged trace: indent = parent depth, bar =
    position/extent on the trace's global timeline."""
    if not spans:
        return "(no spans)"
    t0 = min(s["start_s"] for s in spans)
    t1 = max(s["start_s"] + s["dur_ms"] / 1e3 for s in spans)
    total = max(t1 - t0, 1e-9)
    children = span_children(spans)
    lines = [
        f"trace {spans[0]['trace_id']}: {len(spans)} spans, "
        f"{total * 1e3:.1f} ms total"
    ]

    def emit(span: dict, depth: int) -> None:
        off = int((span["start_s"] - t0) / total * width)
        n = max(1, int(span["dur_ms"] / 1e3 / total * width))
        bar = " " * off + "#" * min(n, width - off)
        label = "  " * depth + f"[{span['tier']}] {span['name']}"
        tags = "".join(
            f" {k}={v}" for k, v in sorted(span.get("tags", {}).items())
        )
        lines.append(
            f"{label:<44s} |{bar:<{width}s}| {span['dur_ms']:9.2f} ms{tags}"
        )
        for c in children.get(span["span_id"], ()):
            emit(c, depth + 1)

    for root in children.get(None, ()):
        emit(root, 0)
    return "\n".join(lines)
