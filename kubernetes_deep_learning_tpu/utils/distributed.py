"""Multi-host runtime initialization: the DCN half of the comm backend.

The reference's inter-node "backend" is gRPC between k8s pods (SURVEY.md
section 2); it never coordinates accelerators across hosts.  This framework's
collectives ride ICI within a slice (parallel/), and spanning *hosts* needs
exactly one extra step: ``jax.distributed.initialize`` so every process joins
one global runtime -- after which jax.devices() is the whole pod slice, a
Mesh built over it spans hosts, and XLA routes collectives over ICI within a
slice and DCN between slices.  This module wraps that step with the env
conventions of the deployment targets:

- **GKE TPU pod slices** (deploy/): the TPU runtime carries its own
  coordinator/topology metadata, so a bare initialize() with no arguments is
  correct -- every worker of a multi-host node pool auto-discovers.
- **Manual / CPU-fleet bring-up** (tests, dev boxes): coordinates through
  ``KDLT_COORDINATOR`` (host:port of process 0), ``KDLT_NUM_PROCESSES``, and
  ``KDLT_PROCESS_ID``, mirroring the reference's pattern of wiring tiers
  together by env var (reference serving-gateway-deployment.yaml:22-24).
"""

from __future__ import annotations

import os

COORDINATOR_ENV = "KDLT_COORDINATOR"
NUM_PROCESSES_ENV = "KDLT_NUM_PROCESSES"
PROCESS_ID_ENV = "KDLT_PROCESS_ID"
INIT_TIMEOUT_ENV = "KDLT_DIST_INIT_TIMEOUT_S"


def env_spec(environ=None) -> dict | None:
    """Parse the manual-coordination env triplet; None when unset.

    All three must be present together -- a partial spec is a deployment
    bug, surfaced loudly rather than half-initializing.
    """
    environ = os.environ if environ is None else environ
    keys = (COORDINATOR_ENV, NUM_PROCESSES_ENV, PROCESS_ID_ENV)
    present = [k for k in keys if k in environ]
    if not present:
        return None
    if len(present) != len(keys):
        missing = sorted(set(keys) - set(present))
        raise ValueError(f"partial multi-host env: missing {missing}")
    num = int(environ[NUM_PROCESSES_ENV])
    pid = int(environ[PROCESS_ID_ENV])
    if num <= 0 or not 0 <= pid < num:
        raise ValueError(
            f"invalid multi-host env: num_processes={num}, process_id={pid}"
        )
    spec = {
        "coordinator_address": environ[COORDINATOR_ENV],
        "num_processes": num,
        "process_id": pid,
    }
    # Coordination-service join deadline, env-overridable for contended
    # CI hosts (VERDICT r4 weak-6: a shared-core parallel test run starved
    # a worker past a fixed deadline).  NOTE this covers jax's coordination
    # service only; the CPU backend's Gloo key-value rendezvous deadline is
    # hardcoded in XLA's C++ (make_gloo_tcp_collectives takes no timeout),
    # which is why the 2-process tests ALSO serialize behind a cross-
    # process file lock (tests/test_crosshost.py _fleet_lock).
    if INIT_TIMEOUT_ENV in environ:
        spec["initialization_timeout"] = int(environ[INIT_TIMEOUT_ENV])
    return spec


def initialize(environ=None) -> bool:
    """Join the global runtime if this looks like a multi-host deployment.

    Returns True when jax.distributed.initialize ran.  Order matters: call
    before the first jax.devices()/backend touch (same constraint as
    utils.platform.force_platform).  Safe to call in single-process runs --
    with no env spec and no TPU pod metadata requirement, it is a no-op.
    """
    environ = os.environ if environ is None else environ
    spec = env_spec(environ)
    if spec is not None:
        import jax

        from kubernetes_deep_learning_tpu.utils.jaxcompat import (
            enable_cpu_collectives,
        )

        # CPU fleets (tests, dev boxes) need the Gloo collectives backend
        # selected before the runtime boots on jax versions where it is
        # not yet the default; no-op elsewhere.
        enable_cpu_collectives()
        jax.distributed.initialize(**spec)
        return True
    # On a multi-host TPU slice the runtime self-coordinates; initialize()
    # with no args is required there and harmless to skip elsewhere.  The
    # TPU case is recognizable by the platform env / plugin, but only the
    # operator knows intent on shared dev boxes -- so auto-run only when
    # explicitly requested.
    if environ.get("KDLT_MULTIHOST", "") == "1":
        import jax

        jax.distributed.initialize()
        return True
    return False
