"""Per-model SLO accounting: sliding windows, multi-window burn rates.

PR 2/6 gave the serving path deadline *enforcement* (admission sheds what
cannot finish, the scheduler orders by effective deadline); this module is
the layer that *reports* whether any model is actually meeting its
objective -- the signal an autoscaler, an alert, or an operator consumes.
The methodology is the SRE-workbook multi-window burn rate (Beyer et al.,
"The Site Reliability Workbook", ch. 5): against a configured target
fraction of in-deadline completions (``KDLT_SLO_TARGET``), each model's
**burn rate** is how fast it is consuming its error budget::

    burn_rate(w) = bad_fraction(w) / (1 - target)

1.0 means burning exactly at the sustainable rate; 14.4 over 5 m means the
30-day budget would be gone in ~2 days (the classic page threshold).  Two
windows (5 m and 1 h) are tracked so a burst and a slow leak are both
visible, and alerts can require BOTH to fire (fast window for reaction
time, slow window to de-bounce).

Outcome classes, decided at the same boundary as the existing
``kdlt_admission_*`` / request-latency series (the handler's finally
block, so the numbers reconcile against those counters):

- ``good``   -- 200 inside its deadline budget (and the optional
  ``KDLT_SLO_LATENCY_MS`` latency objective);
- ``late``   -- 200, but the deadline budget or latency objective was
  violated by completion time (delivered, but not goodput);
- ``shed``   -- 503/504: the tier refused it (admission, overload, drain);
- ``error``  -- 5xx/connection failure: the serving path broke it;
- ``client`` -- 4xx: the caller's fault, excluded from the SLO entirely
  (standard practice: a bad URL must not page the serving on-call).

Events land in per-second bins per model (bounded memory: one small count
row per second per model, pruned past the widest window), so record() is
O(1) on the hot path and a snapshot is a short sum.  Gauges
(``kdlt_slo_*``, minted centrally in utils.metrics) are refreshed on
scrape; ``/debug/slo`` on both tiers serves the same snapshot as JSON, and
the gateway's endpoint merges every model-tier replica's view.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

SLO_ENABLED_ENV = "KDLT_SLO"
SLO_TARGET_ENV = "KDLT_SLO_TARGET"
SLO_LATENCY_MS_ENV = "KDLT_SLO_LATENCY_MS"
DEFAULT_SLO_TARGET = 0.99

# (label, seconds): the multi-window pair.  5 m is the reaction-time window
# (a burst shows within minutes), 1 h the de-bounce window (a blip that
# stopped does not keep paging).  The labels are the bounded ``window``
# label values on every kdlt_slo_* gauge.
WINDOWS = (("5m", 300.0), ("1h", 3600.0))

# Bin columns, in storage order.
_COLS = ("total", "good", "late", "shed", "error", "client")
_COL_IDX = {c: i for i, c in enumerate(_COLS)}


def slo_enabled(explicit: bool | None = None) -> bool:
    """Explicit arg > $KDLT_SLO > enabled-by-default (the layer is the
    point of this subsystem; the env kill switch exists for overhead A/Bs
    and emergencies)."""
    if explicit is not None:
        return bool(explicit)
    raw = os.environ.get(SLO_ENABLED_ENV, "").strip().lower()
    return raw not in ("0", "false", "off", "no")


def resolve_target(explicit: float | None = None) -> float:
    """Explicit arg > $KDLT_SLO_TARGET > 0.99, clamped to (0, 1): a target
    of 1.0 would make every burn rate infinite (zero error budget), and a
    malformed env value degrades to the default rather than killing
    serving."""
    target = explicit
    if target is None:
        raw = os.environ.get(SLO_TARGET_ENV, "").strip()
        try:
            target = float(raw) if raw else DEFAULT_SLO_TARGET
        except ValueError:
            target = DEFAULT_SLO_TARGET
    return min(max(float(target), 1e-6), 1.0 - 1e-6)


def resolve_latency_objective_ms(explicit: float | None = None) -> float | None:
    """Optional per-request latency objective (ms).  None = deadline-only
    accounting (requests without a deadline budget are good unless shed or
    errored)."""
    if explicit is not None:
        return float(explicit)
    raw = os.environ.get(SLO_LATENCY_MS_ENV, "").strip()
    if not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        return None
    return value if value > 0 else None


def classify(status: int, deadline_exceeded: bool,
             latency_violated: bool = False) -> str:
    """Map one finished request to its outcome class (module docstring)."""
    if status == 200:
        return "late" if (deadline_exceeded or latency_violated) else "good"
    if status in (503, 504):
        return "shed"
    if 400 <= status < 500:
        return "client"
    return "error"


def derive(counts: dict, target: float) -> dict:
    """The per-window derived figures from one raw count row.

    An empty window reports goodput 1.0 / burn 0.0 (nothing happened, so
    nothing burned) -- the quiet state an alert must not fire on.
    """
    counted = counts["total"] - counts["client"]
    if counted <= 0:
        ratios = {"goodput_ratio": 1.0, "burn_rate": 0.0,
                  "shed_ratio": 0.0, "error_ratio": 0.0}
    else:
        good = counts["good"] / counted
        ratios = {
            "goodput_ratio": round(good, 6),
            "burn_rate": round((1.0 - good) / (1.0 - target), 4),
            "shed_ratio": round(counts["shed"] / counted, 6),
            "error_ratio": round(counts["error"] / counted, 6),
        }
    return {**counts, **ratios}


def merge_model_views(views: list[dict], target: float) -> dict:
    """Sum several tiers'/replicas' per-model raw counts and re-derive the
    ratios -- the gateway's fleet-wide view.  Each ``views`` entry is a
    snapshot's ``models`` dict ({model: {window: row}})."""
    merged: dict[str, dict[str, dict]] = {}
    for view in views:
        for model, windows in (view or {}).items():
            dst = merged.setdefault(model, {})
            for window, row in windows.items():
                cell = dst.setdefault(window, {c: 0 for c in _COLS})
                for c in _COLS:
                    cell[c] += int(row.get(c, 0))
    return {
        model: {w: derive(cell, target) for w, cell in windows.items()}
        for model, windows in merged.items()
    }


class SloEngine:
    """One tier's SLO accountant: record() on the hot path, refresh() on
    scrape, snapshot() for /debug/slo.

    ``clock`` is injectable (tests drive synthetic request streams through
    hours of window time without sleeping); it must be monotonic.
    """

    def __init__(
        self,
        registry: metrics_lib.Registry,
        tier: str,
        enabled: bool | None = None,
        target: float | None = None,
        latency_objective_ms: float | None = None,
        windows=WINDOWS,
        clock=time.monotonic,
    ):
        self.tier = tier
        self.enabled = slo_enabled(enabled)
        self.target = resolve_target(target)
        self.latency_objective_ms = resolve_latency_objective_ms(
            latency_objective_ms
        )
        self.windows = tuple(windows)
        self._max_window_s = max(s for _, s in self.windows)
        self._clock = clock
        self._lock = threading.Lock()
        # model -> deque of [bin_second, c_total, c_good, ...]; bins append
        # at the right, prune from the left past the widest window.
        self._bins: dict[str, deque] = {}
        self._registry = registry.with_labels(tier=tier)
        self._gauges: dict[tuple[str, str], dict] = {}
        if self.enabled:
            self._m = metrics_lib.slo_tier_metrics(self._registry)
            self._m["target"].set(self.target)

    # --- hot path -----------------------------------------------------------

    def record(
        self,
        model: str,
        status: int,
        latency_s: float,
        deadline_exceeded: bool = False,
    ) -> None:
        """Account one finished request.  Call from the handler's finally
        block -- the same boundary as the tier's request-latency histogram,
        so /debug/slo reconciles against /metrics."""
        if not self.enabled or not model:
            return
        violated = (
            self.latency_objective_ms is not None
            and latency_s * 1e3 > self.latency_objective_ms
        )
        outcome = classify(status, deadline_exceeded, violated)
        now_bin = int(self._clock())
        with self._lock:
            bins = self._bins.get(model)
            if bins is None:
                bins = self._bins[model] = deque()
            if not bins or bins[-1][0] != now_bin:
                bins.append([now_bin] + [0] * len(_COLS))
                # Prune past the widest window (+2 s slack for bin edges).
                horizon = now_bin - self._max_window_s - 2
                while bins and bins[0][0] < horizon:
                    bins.popleft()
            row = bins[-1]
            row[1 + _COL_IDX["total"]] += 1
            row[1 + _COL_IDX[outcome]] += 1

    # --- snapshots ----------------------------------------------------------

    def _window_counts(self, bins, now: float, window_s: float) -> dict:
        cutoff = now - window_s
        counts = [0] * len(_COLS)
        for row in reversed(bins):
            if row[0] < cutoff:
                break
            for i in range(len(_COLS)):
                counts[i] += row[1 + i]
        return dict(zip(_COLS, counts))

    def model_windows(self) -> dict[str, dict[str, dict]]:
        """{model: {window_label: derived row}} over the live bins."""
        now = self._clock()
        with self._lock:
            models = {m: list(b) for m, b in self._bins.items()}
        return {
            model: {
                label: derive(self._window_counts(bins, now, seconds),
                              self.target)
                for label, seconds in self.windows
            }
            for model, bins in models.items()
        }

    def refresh(self) -> dict:
        """Recompute every (model, window) cell and push it into the
        kdlt_slo_* gauges; returns the snapshot.  Called on scrape
        (/metrics) and on /debug/slo -- the gauges are as fresh as the last
        read, which is exactly a pull-model scraper's contract."""
        if not self.enabled:
            return {}
        per_model = self.model_windows()
        for model, windows in per_model.items():
            for window, row in windows.items():
                key = (model, window)
                gauges = self._gauges.get(key)
                if gauges is None:
                    gauges = metrics_lib.slo_model_window_metrics(
                        self._registry, model, window
                    )
                    self._gauges[key] = gauges
                gauges["goodput_ratio"].set(row["goodput_ratio"])
                gauges["burn_rate"].set(row["burn_rate"])
                gauges["shed_ratio"].set(row["shed_ratio"])
                gauges["error_ratio"].set(row["error_ratio"])
                gauges["requests"].set(
                    float(row["total"] - row["client"])
                )
        return per_model

    def debug_payload(self) -> dict:
        """The /debug/slo JSON body for this tier."""
        payload = {
            "tier": self.tier,
            "enabled": self.enabled,
            "target": self.target,
            "latency_objective_ms": self.latency_objective_ms,
            "windows": [label for label, _ in self.windows],
        }
        if self.enabled:
            payload["models"] = self.refresh()
        return payload
