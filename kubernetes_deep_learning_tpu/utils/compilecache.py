"""Persistent XLA compilation-cache wiring (shared by bench + serving).

Why this exists (VERDICT r4 weak-1/weak-5): every fresh process pays
20-55 s of XLA compile per program on the v5e, which (a) made the official
bench sweep slower than the driver's budget four rounds running, and
(b) makes a serving pod restart cost ~10 minutes of warmup while the
reference's TF-Serving binary boots and serves immediately
(/root/reference/tf-serving.dockerfile:1-5).  JAX ships a persistent
compilation cache keyed on the compiled HLO + compile options; pointing it
at a directory that outlives the process makes every re-compile of an
already-seen program a disk read instead.

Two activation routes, both best-effort:

1. Environment: ``KDLT_COMPILE_CACHE_DIR`` (ours) or JAX's own
   ``JAX_COMPILATION_CACHE_DIR``.  The env route matters for child
   processes whose interpreter imports jax at startup (sitecustomize on
   this machine) -- by the time library code runs, config-from-env has
   already latched, so a parent that wants its children cached must export
   the variable before spawning them (see bench.py run_isolated_sweep).
2. Runtime: :func:`enable_compile_cache` calls ``jax.config.update``
   directly, which works after import in the current process.

The cache is content-addressed and concurrency-safe for our use: parallel
writers of the same key race benignly (last rename wins, identical bytes),
so bench subprocesses and serving warmup threads can share one directory.
"""

from __future__ import annotations

import os

ENV_VAR = "KDLT_COMPILE_CACHE_DIR"
JAX_ENV_VAR = "JAX_COMPILATION_CACHE_DIR"


def resolve_cache_dir(cache_dir: str | None = None,
                      default_dir: str | None = None) -> str | None:
    """Pick the cache directory: explicit arg > env vars > default (or off).

    ``KDLT_COMPILE_CACHE_DIR=off`` (or ``none``/``0``) disables the env and
    default routes -- the sentinel lives here so every caller (bench,
    serving) gets the same semantics instead of a directory literally
    named "off".  An EXPLICIT ``cache_dir`` argument still wins over the
    sentinel: a programmatic caller (a test, exp/cache_restart.py) that
    passes a directory has stated intent more specifically than a
    lingering env var.

    An EMPTY ``KDLT_COMPILE_CACHE_DIR`` is treated as UNSET, not as a
    disable sentinel: k8s manifests commonly template the var to "" to
    mean "no override", and silently disabling the cache there also
    suppressed the ``JAX_COMPILATION_CACHE_DIR`` fallback and the
    caller's default (ADVICE r5).  Disabling requires the explicit
    ``off``/``none``/``0`` sentinels.
    """
    if cache_dir:
        return cache_dir
    env = os.environ.get(ENV_VAR)
    if env is not None and env.strip().lower() in ("off", "none", "0"):
        return None
    return env or os.environ.get(JAX_ENV_VAR) or default_dir


def active_cache_dir() -> str | None:
    """The cache directory the CURRENT process compiles against, or None.

    Prefers the live jax config (set by :func:`enable_compile_cache` or
    jax's own env latch at import) and falls back to the env contract for
    callers probing before jax is imported.  Read-only: never flips the
    cache on.
    """
    try:
        import jax

        path = getattr(jax.config, "jax_compilation_cache_dir", None)
        if path:
            return path
    except Exception:  # noqa: BLE001 - probing is best-effort
        pass
    return resolve_cache_dir(None)


def enable_compile_cache(cache_dir: str | None = None, *,
                         default_dir: str | None = None) -> str | None:
    """Enable JAX's persistent compilation cache in THIS process.

    Returns the cache directory on success, None when disabled (no dir
    resolved) or unavailable (old jax / unwritable dir) -- callers treat
    None as "cold compiles, as before", never as an error: the cache is a
    pure latency optimization and must not take down serving or a bench.
    """
    path = resolve_cache_dir(cache_dir, default_dir)
    if not path:
        return None
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # noqa: BLE001 - cache is best-effort by contract
        return None
    # jax latches an is-the-cache-used verdict per process on the FIRST
    # compile; a process that compiled anything before this call (bench
    # preamble, an embedding app) would keep that stale "no" forever and
    # silently never read or write the cache.  Un-latch it so enabling
    # mid-process takes effect from the next compile on.
    try:
        from jax._src import compilation_cache as _jax_cc

        _jax_cc.reset_cache()
    except Exception:  # noqa: BLE001 - private surface; absent is fine
        pass
    # The cache is now ON; the threshold knobs below are tuning only and
    # must not flip the return to None on a jax that lacks them -- a
    # half-enabled-but-reported-disabled cache would desynchronize every
    # caller (and the env export below) from the actual process state.
    for knob, value in (
        # Default thresholds skip "cheap" compiles; our cold-start problem
        # IS many ~1-60 s compiles, so cache everything non-trivial.
        ("jax_persistent_cache_min_compile_time_secs", 0.5),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(knob, value)
        except Exception:  # noqa: BLE001 - knob absent on older jax
            pass
    # Export for any child interpreters (their sitecustomize imports
    # jax before library code runs, so only env reaches them in time).
    os.environ[ENV_VAR] = path
    os.environ[JAX_ENV_VAR] = path
    return path
