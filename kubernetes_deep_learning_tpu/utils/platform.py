"""Select the JAX platform explicitly (dev-on-CPU vs serve-on-TPU).

SURVEY.md section 4 calls for a CPU backend so the serving path is testable
without TPUs.  Selecting it via ``JAX_PLATFORMS`` alone is not reliable in
environments whose sitecustomize pre-imports jax with a TPU plugin already
latched, so this helper rewrites the live config and, when leaving a
non-standard plugin platform, drops its backend factory so nothing dials
accelerator hardware from a CPU-only process.
"""

from __future__ import annotations

import os

PLATFORM_ENV = "KDLT_PLATFORM"


def force_platform(name: str | None) -> None:
    """name: "cpu", "tpu", ... or None => honor $KDLT_PLATFORM, else default."""
    if name is None:
        name = os.environ.get(PLATFORM_ENV)
    if not name:
        return
    import jax

    jax.config.update("jax_platforms", name)
    if name == "cpu":
        try:
            import jax._src.xla_bridge as xb

            # Drop only foreign PJRT plugins (e.g. the host's "axon" TPU
            # tunnel): they dial hardware at backend init.  Builtin platform
            # factories must stay registered -- removing "tpu" breaks MLIR
            # lowering-rule registration in libraries imported later.
            builtin = {"cpu", "tpu", "cuda", "rocm", "gpu", "metal", "METAL"}
            for plugin in list(xb._backend_factories):
                if plugin not in builtin:
                    xb._backend_factories.pop(plugin, None)
        except Exception:
            pass  # jax internals moved; config update above still applies
