"""Select the JAX platform explicitly (dev-on-CPU vs serve-on-TPU).

SURVEY.md section 4 calls for a CPU backend so the serving path is testable
without TPUs.  Selecting it via ``JAX_PLATFORMS`` alone is not reliable in
environments whose sitecustomize pre-imports jax with a TPU plugin already
latched, so this helper rewrites the live config and, when leaving a
non-standard plugin platform, drops its backend factory so nothing dials
accelerator hardware from a CPU-only process.
"""

from __future__ import annotations

import os

PLATFORM_ENV = "KDLT_PLATFORM"


def force_virtual_cpu(n_devices: int) -> None:
    """Re-point a process at an n-device virtual CPU mesh, even if a real
    accelerator backend has already been initialized.

    ``--xla_force_host_platform_device_count`` is parsed from $XLA_FLAGS once
    per process by XLA's C++ flag parser, so it cannot help after any backend
    init; instead this clears jax's backend caches and uses the
    ``jax_num_cpu_devices`` config, which is read at (re-)creation of the CPU
    client.  Used by the driver's ``dryrun_multichip`` entry when the host
    sitecustomize latched a single-chip TPU plugin before our env took effect.
    """
    import jax

    force_platform("cpu")
    try:
        import jax._src.xla_bridge as xb

        xb._clear_backends()
        if hasattr(xb.get_backend, "cache_clear"):
            xb.get_backend.cache_clear()
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception as e:  # pragma: no cover - depends on jax internals
        raise RuntimeError(
            "force_virtual_cpu could not rebuild the CPU backend with "
            f"{n_devices} devices (jax {jax.__version__} internals changed?). "
            "Start the process with JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices} "
            "before any jax import instead."
        ) from e


def force_platform(name: str | None) -> None:
    """name: "cpu", "tpu", ... or None => honor $KDLT_PLATFORM, else default."""
    if name is None:
        name = os.environ.get(PLATFORM_ENV)
    if not name:
        return
    import jax

    jax.config.update("jax_platforms", name)
    if name == "cpu":
        try:
            import jax._src.xla_bridge as xb

            # Drop only foreign PJRT plugins (e.g. the host's "axon" TPU
            # tunnel): they dial hardware at backend init.  Builtin platform
            # factories must stay registered -- removing "tpu" breaks MLIR
            # lowering-rule registration in libraries imported later.
            builtin = {"cpu", "tpu", "cuda", "rocm", "gpu", "metal", "METAL"}
            for plugin in list(xb._backend_factories):
                if plugin not in builtin:
                    xb._backend_factories.pop(plugin, None)
        except Exception:
            pass  # jax internals moved; config update above still applies
