from kubernetes_deep_learning_tpu.export.artifact import (
    ModelArtifact,
    latest_version,
    load_artifact,
    scan_versions,
)
from kubernetes_deep_learning_tpu.export.exporter import export_model

__all__ = [
    "ModelArtifact",
    "export_model",
    "latest_version",
    "load_artifact",
    "scan_versions",
]
