"""kdlt-warm: AOT-compile every registry model's bucket ladder into the
persistent compile cache (zero-cold-start scale-up).

BENCH_r05 measured 7-28 s of live XLA compile per bucket, which makes a
freshly scaled model-server pod dead weight exactly when the HPA added it
because load spiked.  The persistent compile cache (utils.compilecache,
GUIDE §10b) already makes a RE-compile a disk read; what was missing is
anything that fills the cache BEFORE the first pod boots.  This CLI is
that filler, with two call sites:

- **image build**: ``RUN kdlt-warm --models /models --compile-cache-dir
  /var/cache/kdlt-xla`` in the serving Dockerfile bakes a hot cache into
  the image layer, so every pod the image ever starts warms from disk;
- **pod init**: ``kdlt-model-server --aot-warm`` (or ``KDLT_AOT_WARM=1``
  on an init container sharing the cache volume) runs the same pass
  against a persistent volume before serving starts.

Either way, a scaled pod's ``InferenceEngine.warmup()`` is cache-hits
only -- ``kdlt_engine_warm_source{source="compile"} == 0`` is the proof
-- while readiness stays gated on all-buckets-warm exactly as before.

The scan rule is shared with the serving registry
(serving.registry.iter_latest_versions): the set of models pre-warmed is
exactly the set a booted server would load.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from kubernetes_deep_learning_tpu.utils import compilecache

# The model-server image's cache mount (deploy/k8s +
# deploy/model-server.dockerfile agree on this path).
DEFAULT_CACHE_DIR = "/var/cache/kdlt-xla"


def warm_decode(engine_factory=None) -> dict:
    """Warm the generative lane's decode ladder; returns its report dict.

    The decode lane has its own compile grid, disjoint from the image
    bucket ladder: one prefill program per prompt-length bucket, plus the
    single fixed-width step program that serves every batch-slot
    composition (continuous batching admits into a fixed [S]-slot step,
    so slot count never recompiles -- the grid is buckets x slots wide
    but only buckets + 1 programs deep).  A scaled pod started with
    KDLT_DECODE=1 compiles exactly these programs in
    GenerateLane.warmup(); running them here lands them in the same
    persistent cache the pod reads.
    """
    from kubernetes_deep_learning_tpu.runtime import decode as decode_lib
    from kubernetes_deep_learning_tpu.serving.generate import (
        DECODE_MODEL_ENV,
        DEFAULT_DECODE_MODEL,
    )

    model = os.environ.get(DECODE_MODEL_ENV) or DEFAULT_DECODE_MODEL
    engine = (engine_factory or decode_lib.DecodeEngine)(model=model)
    entry = dict(engine.warmup())
    # The learned grid: every (prompt bucket, batch slots) cell the two
    # program families above cover.  Asserted by tests/test_warm.py.
    entry["grid"] = {
        "prompt_buckets": [int(b) for b in entry.get("buckets", {})],
        "slots": int(getattr(engine, "max_slots", 0)),
    }
    return entry


def warm_models(
    model_root: str,
    buckets=None,
    cache_dir: str | None = None,
    workers: int = 4,
    engine_factory=None,
    decode: bool | None = None,
    decode_engine_factory=None,
) -> dict:
    """Warm every model under ``model_root``; returns the report dict.

    One engine per model's latest version, full bucket ladder (the
    DEFAULT_BUCKETS every serving pod compiles), warmup() per engine --
    the compiled programs land in the persistent cache as a side effect.
    ``engine_factory`` swaps the engine class (tests); the default is the
    serving InferenceEngine, so the programs cached here are bit-the-same
    programs a pod will look up.
    """
    from kubernetes_deep_learning_tpu.runtime import engine as engine_lib
    from kubernetes_deep_learning_tpu.serving.registry import (
        iter_latest_versions,
    )

    resolved = compilecache.enable_compile_cache(
        cache_dir, default_dir=DEFAULT_CACHE_DIR
    )
    factory = engine_factory or _default_factory
    report: dict = {
        "cache_dir": resolved,
        "buckets": list(buckets or engine_lib.DEFAULT_BUCKETS),
        "models": {},
    }
    for name, version, directory in iter_latest_versions(model_root):
        t0 = time.perf_counter()
        try:
            engine = factory(
                directory, buckets or engine_lib.DEFAULT_BUCKETS
            )
            engine.warmup(workers=workers)
        except Exception as e:  # noqa: BLE001 - warm the REST of the fleet
            report["models"][name] = {
                "version": version, "error": str(e),
            }
            print(
                f"kdlt-warm: {name} v{version} FAILED: {e}", file=sys.stderr
            )
            continue
        entry = {
            "version": version,
            "seconds": round(time.perf_counter() - t0, 3),
            **getattr(engine, "warm_report", {}),
        }
        report["models"][name] = entry
        srcs = [
            b.get("source") for b in entry.get("buckets", {}).values()
        ] if isinstance(entry.get("buckets"), dict) else []
        print(
            f"kdlt-warm: {name} v{version}: {entry['seconds']}s "
            f"({srcs.count('cache')} cached / {srcs.count('compile')} "
            "compiled buckets)",
            file=sys.stderr,
        )
    # The decode ladder rides the same pass when the generative lane is
    # on (--decode, or KDLT_DECODE=1 -- the same switch the pods read),
    # so an image baked with the lane enabled boots with prefill + step
    # programs already cached.
    from kubernetes_deep_learning_tpu.serving.generate import decode_enabled

    if decode_enabled(decode):
        t0 = time.perf_counter()
        try:
            report["decode"] = warm_decode(decode_engine_factory)
        except Exception as e:  # noqa: BLE001 - image models still warmed
            report["decode"] = {"error": str(e)}
            print(f"kdlt-warm: decode ladder FAILED: {e}", file=sys.stderr)
        else:
            grid = report["decode"]["grid"]
            print(
                f"kdlt-warm: decode {report['decode'].get('model')}: "
                f"{round(time.perf_counter() - t0, 3)}s (prefill buckets "
                f"{grid['prompt_buckets']} x {grid['slots']} slots + step)",
                file=sys.stderr,
            )
    return report


def _default_factory(directory: str, buckets):
    from kubernetes_deep_learning_tpu.export.artifact import load_artifact
    from kubernetes_deep_learning_tpu.runtime.engine import InferenceEngine

    return InferenceEngine(load_artifact(directory), buckets=buckets)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="AOT-compile every registry model's bucket ladder into "
        "the persistent compile cache (zero-cold-start scale-up; run at "
        "image build or pod init)"
    )
    p.add_argument(
        "--models",
        default=os.environ.get("KDLT_MODEL_ROOT", "/models"),
        help="artifact root (the model server's --models; default "
        "$KDLT_MODEL_ROOT or /models)",
    )
    p.add_argument(
        "--buckets",
        default=None,
        help="comma-separated bucket ladder override (default: the "
        "serving DEFAULT_BUCKETS, which is what pods compile)",
    )
    p.add_argument(
        "--compile-cache-dir",
        default=None,
        help="persistent compile cache directory (default "
        f"$KDLT_COMPILE_CACHE_DIR or {DEFAULT_CACHE_DIR})",
    )
    p.add_argument(
        "--workers", type=int, default=4,
        help="concurrent bucket compiles per model",
    )
    p.add_argument(
        "--platform",
        default=None,
        help="force a JAX platform (e.g. cpu) via JAX_PLATFORMS -- an "
        "image BUILD host usually has no TPU; note cache keys include "
        "the target platform, so warming on cpu only serves cpu pods",
    )
    p.add_argument(
        "--decode", action="store_true", default=None,
        help="also warm the generative lane's decode ladder (prompt-length "
        "buckets x batch slots; default: follows KDLT_DECODE, the same "
        "switch serving pods read)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="print the full warm report as JSON on stdout",
    )
    args = p.parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    buckets = None
    if args.buckets:
        buckets = tuple(
            sorted({int(b) for b in args.buckets.split(",") if b.strip()})
        )
    report = warm_models(
        args.models,
        buckets=buckets,
        cache_dir=args.compile_cache_dir,
        workers=args.workers,
        decode=args.decode,
    )
    if args.json:
        print(json.dumps(report, indent=2))
    failed = [
        n for n, m in report["models"].items() if "error" in m
    ]
    if "error" in (report.get("decode") or {}):
        failed.append("decode")
    if not report["models"]:
        print(f"kdlt-warm: no models under {args.models}", file=sys.stderr)
        return 1
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
