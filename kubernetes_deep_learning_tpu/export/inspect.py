"""Artifact inspector: the ``saved_model_cli show`` equivalent.

The reference's workflow requires running ``saved_model_cli show --dir ...``
to discover signature/tensor names and then hand-copying them into the
gateway (reference guide.md:199-236).  Here the inspector just renders what
``spec.json`` and the StableHLO module already declare -- nothing needs to be
hand-copied because every consumer reads the same ModelSpec.

CLI::

    python -m kubernetes_deep_learning_tpu.export.inspect --dir models/clothing-model/1
    python -m kubernetes_deep_learning_tpu.export.inspect --root models  # list all
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from kubernetes_deep_learning_tpu.export import artifact as art


def describe(directory: str) -> str:
    a = art.load_artifact(directory)
    spec = a.spec
    lines = [
        f"Artifact: {directory}",
        f"  model:         {spec.name} (family={spec.family})",
        f"  description:   {spec.description}",
        f"  input:         {spec.input_name} "
        f"(-1, {', '.join(map(str, spec.input_shape))}) {spec.input_dtype}",
        f"  output:        {spec.output_name} (-1, {spec.num_classes}) float32",
        f"  preprocessing: {spec.preprocessing} (resize={spec.resize_filter})",
        f"  labels:        {', '.join(spec.labels[:10])}"
        + (" ..." if len(spec.labels) > 10 else ""),
    ]
    n_params = sum(int(np.prod(v.shape)) for v in _leaves(a.variables))
    n_bytes = sum(v.nbytes for v in _leaves(a.variables))
    lines.append(f"  params:        {n_params:,} ({n_bytes / 1e6:.1f} MB)")
    if a.exported_bytes is not None:
        exp = a.exported
        lines.append(f"  stablehlo:     {len(a.exported_bytes):,} bytes, platforms={exp.platforms}")
        lines.append(f"  calling conv:  v{exp.calling_convention_version}, batch dim symbolic")
    for platform, blob in sorted(a.platform_modules.items()):
        exp = a.exported_for(platform)
        lines.append(
            f"  stablehlo[{platform}]: {len(blob):,} bytes, "
            f"calling conv v{exp.calling_convention_version}, batch dim symbolic"
        )
    for k, v in sorted(a.metadata.items()):
        lines.append(f"  meta.{k}: {v}")
    return "\n".join(lines)


def _leaves(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _leaves(v)
    else:
        yield tree


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description="Inspect exported model artifacts")
    p.add_argument("--dir", help="one artifact version directory")
    p.add_argument("--root", help="artifact root: list every model/version")
    args = p.parse_args(argv)
    if not args.dir and not args.root:
        p.error("pass --dir or --root")
    if args.dir:
        print(describe(args.dir))
    if args.root:
        import os

        for name in sorted(os.listdir(args.root)):
            for v in art.scan_versions(args.root, name):
                print(describe(art.version_dir(args.root, name, v)))
                print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
