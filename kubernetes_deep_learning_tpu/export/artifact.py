"""Versioned model-artifact layout: ``<root>/<model-name>/<version>/``.

Mirrors the reference's TF-Serving convention of ``/models/<name>/<n>``
(reference tf-serving.dockerfile:5) where the server scans for the highest
numeric version directory.  An artifact directory contains:

- ``spec.json``        -- the ModelSpec (single source of truth; replaces the
                          reference's saved_model_cli-then-hardcode contract,
                          reference guide.md:199-236)
- ``params.msgpack``   -- flax variables ({params, batch_stats}), float32
- ``module.stablehlo`` -- jax.export-serialized StableHLO of the forward fn
                          with a symbolic batch dimension (the SavedModel
                          equivalent, per BASELINE.json north star)
- ``metadata.json``    -- export provenance (jax version, platforms, dtype)
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any

from kubernetes_deep_learning_tpu.modelspec import ModelSpec

SPEC_FILE = "spec.json"
PARAMS_FILE = "params.msgpack"
MODULE_FILE = "module.stablehlo"
META_FILE = "metadata.json"


@dataclasses.dataclass
class ModelArtifact:
    spec: ModelSpec
    variables: Any                 # nested dict of np arrays
    exported_bytes: bytes | None   # serialized jax.export.Exported, if present
    metadata: dict
    path: str = ""

    _exported = None  # lazily deserialized Exported

    @property
    def exported(self):
        """The deserialized jax.export.Exported module (lazy)."""
        if self._exported is None:
            if self.exported_bytes is None:
                raise ValueError(f"artifact at {self.path!r} has no StableHLO module")
            from jax import export as jax_export

            self._exported = jax_export.deserialize(self.exported_bytes)
        return self._exported


def save_artifact(
    directory: str,
    spec: ModelSpec,
    variables: Any,
    exported_bytes: bytes | None,
    metadata: dict,
) -> str:
    import flax.serialization

    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, SPEC_FILE), "w") as f:
        f.write(spec.to_json())
    with open(os.path.join(directory, PARAMS_FILE), "wb") as f:
        f.write(flax.serialization.to_bytes(variables))
    if exported_bytes is not None:
        with open(os.path.join(directory, MODULE_FILE), "wb") as f:
            f.write(exported_bytes)
    with open(os.path.join(directory, META_FILE), "w") as f:
        json.dump(metadata, f, indent=2, sort_keys=True)
    return directory


def load_artifact(directory: str) -> ModelArtifact:
    import flax.serialization

    with open(os.path.join(directory, SPEC_FILE)) as f:
        spec = ModelSpec.from_json(f.read())
    with open(os.path.join(directory, PARAMS_FILE), "rb") as f:
        # msgpack_restore needs no template: restores a plain nested dict.
        variables = flax.serialization.msgpack_restore(f.read())
    exported_bytes = None
    module_path = os.path.join(directory, MODULE_FILE)
    if os.path.exists(module_path):
        with open(module_path, "rb") as f:
            exported_bytes = f.read()
    metadata = {}
    meta_path = os.path.join(directory, META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            metadata = json.load(f)
    return ModelArtifact(spec, variables, exported_bytes, metadata, path=directory)


def scan_versions(root: str, name: str) -> list[int]:
    """Numeric version dirs under <root>/<name>/, ascending (TF-Serving rule)."""
    model_dir = os.path.join(root, name)
    if not os.path.isdir(model_dir):
        return []
    versions = [
        int(d) for d in os.listdir(model_dir)
        if re.fullmatch(r"\d+", d) and os.path.isdir(os.path.join(model_dir, d))
    ]
    return sorted(versions)


def latest_version(root: str, name: str) -> int | None:
    versions = scan_versions(root, name)
    return versions[-1] if versions else None


def version_dir(root: str, name: str, version: int) -> str:
    return os.path.join(root, name, str(version))
