"""Versioned model-artifact layout: ``<root>/<model-name>/<version>/``.

Mirrors the reference's TF-Serving convention of ``/models/<name>/<n>``
(reference tf-serving.dockerfile:5) where the server scans for the highest
numeric version directory.  An artifact directory contains:

- ``spec.json``        -- the ModelSpec (single source of truth; replaces the
                          reference's saved_model_cli-then-hardcode contract,
                          reference guide.md:199-236)
- ``params.msgpack``   -- flax variables ({params, batch_stats}), float32
- ``module.stablehlo`` -- jax.export-serialized StableHLO of the forward fn
                          with a symbolic batch dimension (the SavedModel
                          equivalent, per BASELINE.json north star)
- ``module.<platform>.stablehlo`` -- per-platform modules, emitted instead of
                          the single multi-platform file when the forward
                          contains platform-gated code that cannot co-lower
                          (e.g. the ViT's Pallas flash-attention branch: a
                          multi-platform module keeps every
                          jax.lax.platform_dependent branch, so the Mosaic
                          kernel would hit the CPU lowering rule)
- ``metadata.json``    -- export provenance (jax version, platforms, dtype)
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any

from kubernetes_deep_learning_tpu.modelspec import ModelSpec

SPEC_FILE = "spec.json"
PARAMS_FILE = "params.msgpack"
MODULE_FILE = "module.stablehlo"
META_FILE = "metadata.json"
_PLATFORM_MODULE_RE = re.compile(r"^module\.([a-z0-9_]+)\.stablehlo$")


def platform_module_file(platform: str) -> str:
    return f"module.{platform}.stablehlo"


@dataclasses.dataclass
class ModelArtifact:
    spec: ModelSpec
    variables: Any                 # nested dict of np arrays
    exported_bytes: bytes | None   # serialized multi-platform Exported, if present
    metadata: dict
    path: str = ""
    # platform -> serialized Exported, for artifacts exported per-platform.
    platform_modules: dict[str, bytes] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        self._exported_cache: dict[str | None, Any] = {}

    def module_bytes_for(self, platform: str) -> bytes | None:
        """Best serialized module for ``platform`` (multi-platform wins)."""
        if self.exported_bytes is not None:
            return self.exported_bytes
        return self.platform_modules.get(platform)

    def exported_for(self, platform: str):
        """Deserialized jax.export.Exported usable on ``platform`` (lazy)."""
        if self.exported_bytes is not None:
            return self.exported  # multi-platform module: one shared deserialize
        blob = self.platform_modules.get(platform)
        if blob is None:
            raise ValueError(
                f"artifact at {self.path!r} has no StableHLO module for "
                f"{platform!r} (available: {sorted(self.platform_modules)})"
            )
        if platform not in self._exported_cache:
            from jax import export as jax_export

            self._exported_cache[platform] = jax_export.deserialize(blob)
        return self._exported_cache[platform]

    @property
    def exported(self):
        """The deserialized multi-platform Exported module (lazy).

        For per-platform artifacts use ``exported_for(platform)``.
        """
        if None not in self._exported_cache:
            if self.exported_bytes is None:
                raise ValueError(f"artifact at {self.path!r} has no StableHLO module")
            from jax import export as jax_export

            self._exported_cache[None] = jax_export.deserialize(self.exported_bytes)
        return self._exported_cache[None]


def save_artifact(
    directory: str,
    spec: ModelSpec,
    variables: Any,
    exported_bytes: "bytes | dict[str, bytes] | None",
    metadata: dict,
) -> str:
    """Write one artifact dir.  ``exported_bytes`` may be a single
    multi-platform module or a {platform: module} dict (see module doc)."""
    import flax.serialization

    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, SPEC_FILE), "w") as f:
        f.write(spec.to_json())
    with open(os.path.join(directory, PARAMS_FILE), "wb") as f:
        f.write(flax.serialization.to_bytes(variables))
    if isinstance(exported_bytes, dict):
        for platform, blob in exported_bytes.items():
            with open(os.path.join(directory, platform_module_file(platform)), "wb") as f:
                f.write(blob)
    elif exported_bytes is not None:
        with open(os.path.join(directory, MODULE_FILE), "wb") as f:
            f.write(exported_bytes)
    with open(os.path.join(directory, META_FILE), "w") as f:
        json.dump(metadata, f, indent=2, sort_keys=True)
    return directory


def load_artifact(directory: str) -> ModelArtifact:
    import flax.serialization

    with open(os.path.join(directory, SPEC_FILE)) as f:
        spec = ModelSpec.from_json(f.read())
    with open(os.path.join(directory, PARAMS_FILE), "rb") as f:
        # msgpack_restore needs no template: restores a plain nested dict.
        variables = flax.serialization.msgpack_restore(f.read())
    exported_bytes = None
    module_path = os.path.join(directory, MODULE_FILE)
    if os.path.exists(module_path):
        with open(module_path, "rb") as f:
            exported_bytes = f.read()
    platform_modules: dict[str, bytes] = {}
    for entry in os.listdir(directory):
        m = _PLATFORM_MODULE_RE.match(entry)
        if m:
            with open(os.path.join(directory, entry), "rb") as f:
                platform_modules[m.group(1)] = f.read()
    metadata = {}
    meta_path = os.path.join(directory, META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            metadata = json.load(f)
    return ModelArtifact(
        spec,
        variables,
        exported_bytes,
        metadata,
        path=directory,
        platform_modules=platform_modules,
    )


def scan_versions(root: str, name: str) -> list[int]:
    """Numeric version dirs under <root>/<name>/, ascending (TF-Serving rule)."""
    model_dir = os.path.join(root, name)
    if not os.path.isdir(model_dir):
        return []
    versions = [
        int(d) for d in os.listdir(model_dir)
        if re.fullmatch(r"\d+", d) and os.path.isdir(os.path.join(model_dir, d))
    ]
    return sorted(versions)


def latest_version(root: str, name: str) -> int | None:
    versions = scan_versions(root, name)
    return versions[-1] if versions else None


def version_dir(root: str, name: str, version: int) -> str:
    return os.path.join(root, name, str(version))
