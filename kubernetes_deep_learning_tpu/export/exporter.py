"""Model exporter: the reference ``convert.py`` equivalent, TPU-native.

The reference exports Keras .h5 -> TF SavedModel (reference convert.py:4-6).
Here the export is jax.export-traced StableHLO with a **symbolic batch
dimension** plus float32 params, written into the versioned artifact layout.
The exported module is lowered for both "cpu" and "tpu" so the same artifact
serves on a dev laptop and a v5e pod, and takes uint8 images so normalization
runs on device, fused into the first conv.

CLI::

    python -m kubernetes_deep_learning_tpu.export.exporter \
        --model clothing-model --weights model.h5 --output ./models
"""

from __future__ import annotations

import argparse
import sys
from typing import Any

import jax
import jax.numpy as jnp

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, get_spec
from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.parallel import mesh as mesh_lib

DEFAULT_PLATFORMS = ("cpu", "tpu")


def trace_forward(
    spec: ModelSpec,
    variables: Any,
    dtype: Any = jnp.bfloat16,
    platforms: tuple[str, ...] = DEFAULT_PLATFORMS,
) -> bytes:
    """jax.export the forward fn with symbolic batch; return serialized bytes.

    The exported module takes (variables, uint8 images[b,H,W,C]) so params
    stay outside the module and can be hot-swapped per version.
    """
    from jax import export as jax_export

    # fast=False: exported StableHLO must lower on every target platform;
    # the Pallas fast path is a live-jit serving optimization, not a
    # portable artifact format.
    forward = build_forward(spec, dtype=dtype, fast=False)
    (b,) = jax_export.symbolic_shape("b")
    img_spec = jax.ShapeDtypeStruct((b, *spec.input_shape), jnp.uint8)
    var_specs = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), variables
    )
    exported = jax_export.export(jax.jit(forward), platforms=list(platforms))(
        var_specs, img_spec
    )
    return exported.serialize()


def cast_params(variables: Any, dtype: Any) -> Any:
    """Cast float32 leaves (params + batch stats) to a storage dtype.

    bfloat16 storage halves the artifact size and load time.  Serving-speed
    impact on v5e measured neutral at batch>=32 (XLA casts f32 weights to
    the bf16 compute dtype once and reuses them), so the serving default
    remains float32 for exact logit parity; use bfloat16 when artifact
    size/cold-start matters.  Non-float leaves pass through.
    """
    import jax.numpy as jnp_

    dtype = jnp_.dtype(dtype)
    return jax.tree.map(
        lambda a: a.astype(dtype) if a.dtype == jnp_.float32 else a, variables
    )


def export_model(
    spec: ModelSpec,
    variables: Any,
    root: str,
    version: int | None = None,
    dtype: Any = jnp.bfloat16,
    params_dtype: Any = None,
    platforms: tuple[str, ...] = DEFAULT_PLATFORMS,
) -> str:
    """Export spec+variables into <root>/<name>/<version>/; returns the dir.

    ``dtype`` is the compute dtype baked into the traced module;
    ``params_dtype`` optionally re-casts stored variables (bfloat16 for
    serving speed, see cast_params; None keeps them as-is).
    """
    if version is None:
        latest = art.latest_version(root, spec.name)
        version = 1 if latest is None else latest + 1
    if params_dtype is not None:
        variables = cast_params(variables, params_dtype)
    exported_bytes: bytes | dict[str, bytes]
    try:
        exported_bytes = trace_forward(spec, variables, dtype=dtype, platforms=platforms)
        layout = "single"
    except ValueError as e:
        # Forwards with platform-gated code (jax.lax.platform_dependent, e.g.
        # the ViT's Pallas attention) cannot co-lower into one multi-platform
        # module -- every branch is kept and lowered for every platform, so
        # the Mosaic kernel hits the CPU rule.  Trace one single-platform
        # module each instead; the loader picks by runtime platform.  Any
        # multi-platform ValueError triggers the fallback (matching JAX's
        # error wording would be fragile across versions); if the fallback
        # fails too, the multi-platform error is primary with the
        # per-platform one chained as its cause -- both stay visible.
        if len(platforms) <= 1:
            raise
        try:
            exported_bytes = {
                p: trace_forward(spec, variables, dtype=dtype, platforms=(p,))
                for p in platforms
            }
        except ValueError as per_platform_err:
            raise e from per_platform_err
        layout = "per-platform"
    metadata = {
        "jax_version": jax.__version__,
        "platforms": list(platforms),
        "module_layout": layout,
        "compute_dtype": jnp.dtype(dtype).name,
        "params_dtype": jnp.dtype(params_dtype).name if params_dtype is not None else None,
        "framework_version": __import__("kubernetes_deep_learning_tpu").__version__,
        # Partition-rule provenance: the family rule a mesh-serving replica
        # will resolve for this artifact (parallel.mesh.PARTITION_RULES) at
        # the framework version that exported it.  Purely informational --
        # the engine re-resolves at load time -- but it lets an operator
        # see from the artifact alone whether (and which leaves of) a model
        # shards over the model axis.
        "partition_rule": dict(mesh_lib.partition_rule(spec.family)),
    }
    # Write-then-rename so a concurrently polling model server (its version
    # watcher scans every few seconds) can never observe a half-written
    # version dir; dot-prefixed temp names are invisible to the numeric
    # version scan (artifact.scan_versions).
    import os

    directory = art.version_dir(root, spec.name, version)
    staging = os.path.join(os.path.dirname(directory), f".tmp-{version}")
    art.save_artifact(staging, spec, variables, exported_bytes, metadata)
    os.rename(staging, directory)
    return directory


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description="Export a model for serving")
    p.add_argument("--model", required=True, help="ModelSpec name (e.g. clothing-model)")
    p.add_argument("--output", required=True, help="artifact root directory")
    p.add_argument("--weights", default=None, help="Keras .h5 weights to import")
    p.add_argument("--seed", type=int, default=None, help="random-init seed (no .h5)")
    p.add_argument("--version", type=int, default=None, help="explicit version number")
    p.add_argument("--dtype", default="bfloat16", choices=["bfloat16", "float32"])
    p.add_argument(
        "--params-dtype",
        default=None,
        choices=["bfloat16", "float32"],
        help="storage dtype for variables (bfloat16 = half the HBM traffic)",
    )
    p.add_argument(
        "--platform",
        default=None,
        help="jax platform override (e.g. cpu; export itself only traces)",
    )
    p.add_argument(
        "--calibrate",
        type=int,
        nargs="?",
        const=0,
        default=None,
        help="ALSO write a calibrated int8-w8a8 artifact as the NEXT "
        "version: run N representative uint8 images (default 32) through "
        "the float graph, record per-layer activation absmax under the "
        "percentile clip, and store the static scales next to the _q8 "
        "weight leaves.  Calibration happens HERE, at artifact build -- "
        "never at serving time; the engine gates activation with "
        "KDLT_QUANT_TOL at warmup (GUIDE 9d)",
    )
    p.add_argument(
        "--calibrate-percentile",
        type=float,
        default=None,
        help="percentile clip on |activation| for --calibrate (default "
        "99.9; 100 = plain absmax)",
    )
    p.add_argument(
        "--calibrate-dir",
        default=None,
        help="directory of representative images for --calibrate (default: "
        "seeded noise; production should calibrate on real traffic samples)",
    )
    p.add_argument("--calibrate-seed", type=int, default=0)
    args = p.parse_args(argv)

    from kubernetes_deep_learning_tpu.utils.platform import force_platform

    force_platform(args.platform)

    spec = get_spec(args.model)
    if args.weights:
        from kubernetes_deep_learning_tpu.models.keras_import import load_keras_h5

        variables = load_keras_h5(spec, args.weights)
        print(f"imported Keras weights from {args.weights}")
    else:
        seed = 0 if args.seed is None else args.seed
        variables = init_variables(spec, seed=seed)
        print(f"random-initialized weights (seed={seed})")

    directory = export_model(
        spec,
        variables,
        args.output,
        version=args.version,
        dtype=jnp.dtype(args.dtype),
        params_dtype=jnp.dtype(args.params_dtype) if args.params_dtype else None,
    )
    print(f"exported {spec.name} -> {directory}")
    if args.calibrate is not None:
        # The w8a8 build step (ops.quantize): quantize the just-exported
        # float version and calibrate activation scales offline, landing
        # as the next version so the watcher hot-rolls it like any other.
        from kubernetes_deep_learning_tpu.ops import quantize as quant_lib

        n = args.calibrate or quant_lib.DEFAULT_CALIB_IMAGES
        calib = quant_lib.representative_images(
            spec, n, seed=args.calibrate_seed, image_dir=args.calibrate_dir
        )
        percentile = (
            args.calibrate_percentile
            if args.calibrate_percentile is not None
            else quant_lib.DEFAULT_CALIB_PERCENTILE
        )
        qdir = quant_lib.write_quantized_version(
            args.output,
            spec.name,
            scheme=quant_lib.SCHEME_W8A8,
            calib_images=calib,
            percentile=percentile,
        )
        print(
            f"calibrated int8-w8a8 ({n} images, p{percentile:g} clip) -> {qdir}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
