"""TPU-native model-serving framework.

A from-scratch rebuild of the capabilities of
``alexeygrigorev/kubernetes-deep-learning`` (Flask gateway -> TF-Serving on
Kubernetes; see /root/reference) designed TPU-first on JAX/XLA:

- ``modelspec``   -- single source of truth for a served model (replaces the
                     hardcoded names contract of reference model_server.py:40-47)
- ``models``      -- Flax model zoo (Xception, ResNet50, EfficientNet-B3) with
                     Keras .h5 weight import for parity with reference convert.py
- ``ops``         -- host- and device-side image preprocessing
- ``export``      -- jit-traced StableHLO + params exporter and inspector
                     (replaces reference convert.py + saved_model_cli)
- ``runtime``     -- the in-tree TPU model-execution engine + dynamic batcher
                     (replaces the external TF-Serving C++ binary,
                     reference tf-serving.dockerfile:1-5)
- ``serving``     -- model server (RPC tier) and IO gateway with the exact
                     request/response schema of reference model_server.py:62-66
- ``parallel``    -- device mesh / sharding helpers; data-parallel serving over
                     ICI (the NCCL-analog the reference lacks)
- ``training``    -- fine-tuning loop (sharded train step)
- ``utils``       -- config, logging, metrics
"""

__version__ = "0.1.0"

from kubernetes_deep_learning_tpu.modelspec import ModelSpec, get_spec, register_spec

__all__ = ["ModelSpec", "get_spec", "register_spec", "__version__"]
