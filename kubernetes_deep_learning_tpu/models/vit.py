"""Vision Transformer in flax.linen, attention via the in-tree Pallas kernel.

The reference zoo is CNN-only (Xception/ResNet/EfficientNet, all served
through the same gateway contract, reference guide.md:220-231).  This family
extends the zoo with a transformer classifier so the framework's attention
stack -- ops.attention's fused flash kernel and, at long sequence lengths,
parallel.ring's context parallelism -- has a first-class consumer in the
serving path rather than existing as free-floating ops.

TPU-first choices:

- **Mean-pool instead of a cls token.**  Token count stays the patch grid
  (H/p * W/p), a multiple of the flash kernel's 128-wide MXU tiles for the
  registered input sizes; a cls token would make S=197-style primes and force
  either padding or the unfused path.
- **Shape-routed attention.**  ``train=False`` goes through
  ops.attention.attention_serving: the einsum path while the (B, H, S, S)
  score matrix is HBM-cheap (measured 6.5x faster than the fused kernel
  at ViT-B's serving shape -- the kernel is per-grid-step-overhead-bound
  at D=64), and ops.attention.flash_attention (online softmax, no (S,S)
  matrix in HBM, resolved per lowering platform via
  jax.lax.platform_dependent) past the score-memory budget -- the
  long-context regime the kernel exists for.
  ``train=True`` routes through ops.attention.attention_trainable: the
  flash forward plus a custom-VJP blockwise-recompute backward, so training
  activations stay O(S * block).
- Params stay float32; compute dtype is a module arg (bf16 for serving),
  with LayerNorm always computed in f32 for stability.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from kubernetes_deep_learning_tpu.ops import attention


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    patch: int
    width: int
    depth: int
    heads: int
    mlp_ratio: int = 4

    @property
    def head_dim(self) -> int:
        return self.width // self.heads


# Family registry: ModelSpec.family -> architecture hyperparameters.
VIT_CONFIGS: dict[str, ViTConfig] = {
    "vit-s16": ViTConfig(patch=16, width=384, depth=12, heads=6),
    "vit-b16": ViTConfig(patch=16, width=768, depth=12, heads=12),
    # Test-scale config: small enough for CPU pallas-interpret runs.
    "vit-tiny": ViTConfig(patch=8, width=64, depth=2, heads=2),
}


class SelfAttention(nn.Module):
    """Multi-head self-attention over (B, S, C) tokens."""

    heads: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, s, c = x.shape
        head_dim = c // self.heads
        proj = lambda name: nn.DenseGeneral(
            (self.heads, head_dim), dtype=self.dtype, name=name
        )
        # (B, S, H, D) -> (B, H, S, D), the attention-kernel layout.
        q = proj("query")(x).transpose(0, 2, 1, 3)
        k = proj("key")(x).transpose(0, 2, 1, 3)
        v = proj("value")(x).transpose(0, 2, 1, 3)

        if train:
            # Differentiable memory-efficient path: flash forward (on TPU)
            # with the blockwise-recompute backward -- O(S * block)
            # activations for block-tileable S (all registered specs: the
            # cls-token-free design keeps S = the patch grid).  Ragged S
            # still falls back to the einsum reference INSIDE
            # attention_trainable (the custom-vjp backward is not yet
            # padded) -- inference is ragged-safe via
            # flash_attention_padded, training is not.
            o = attention.attention_trainable(q, k, v)
        else:
            # Shape-routed serving attention (round 4): einsum while the
            # score matrix is HBM-cheap -- measured 6.5x faster than the
            # flash kernel at ViT-B's serving shape -- and the fused
            # kernel (resolved per lowering platform, ragged-safe via
            # flash_attention_padded) past the score-memory budget.  See
            # ops.attention.attention_serving.
            o = attention.attention_serving(q, k, v)
        o = o.transpose(0, 2, 1, 3)  # back to (B, S, H, D)
        return nn.DenseGeneral(
            c, axis=(-2, -1), dtype=self.dtype, name="out"
        )(o)


class TransformerBlock(nn.Module):
    """Pre-LayerNorm residual block: MHA then GELU MLP."""

    heads: int
    mlp_ratio: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = x.shape[-1]
        # LayerNorm in f32 (param_dtype default); cast back for the matmuls.
        y = nn.LayerNorm(name="ln_attn")(x.astype(jnp.float32)).astype(x.dtype)
        x = x + SelfAttention(self.heads, dtype=self.dtype, name="attn")(
            y, train=train
        )
        y = nn.LayerNorm(name="ln_mlp")(x.astype(jnp.float32)).astype(x.dtype)
        y = nn.Dense(c * self.mlp_ratio, dtype=self.dtype, name="mlp_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(c, dtype=self.dtype, name="mlp_out")(y)
        return x + y


class ViT(nn.Module):
    num_classes: int
    config: ViTConfig
    dtype: Any = None  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = self.config
        h, w = x.shape[1], x.shape[2]
        if h % cfg.patch or w % cfg.patch:
            raise ValueError(
                f"input {h}x{w} not divisible by patch size {cfg.patch}"
            )
        # Patchify as a strided conv: one MXU matmul over p*p*3 -> width.
        x = nn.Conv(
            cfg.width,
            (cfg.patch, cfg.patch),
            strides=(cfg.patch, cfg.patch),
            padding="VALID",
            dtype=self.dtype,
            name="patch_embed",
        )(x)
        b = x.shape[0]
        seq = x.shape[1] * x.shape[2]
        x = x.reshape(b, seq, cfg.width)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, seq, cfg.width),
            jnp.float32,
        )
        x = x + pos.astype(x.dtype)

        for i in range(cfg.depth):
            x = TransformerBlock(
                cfg.heads, cfg.mlp_ratio, dtype=self.dtype, name=f"block_{i}"
            )(x, train=train)

        x = nn.LayerNorm(name="ln_final")(x.astype(jnp.float32))
        x = x.mean(axis=1)  # token mean-pool (no cls token, see module doc)
        return nn.Dense(self.num_classes, name="head")(x)
