"""Model zoo registry: ModelSpec.family -> flax module factory.

``build_forward`` is the one entry point the rest of the framework uses: it
returns a pure function ``f(variables, uint8_images) -> float32 logits`` with
normalization fused on-device (see ops.preprocess.normalize) -- the unit the
exporter traces and the serving engine compiles.
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp

from kubernetes_deep_learning_tpu.modelspec import ModelSpec
from kubernetes_deep_learning_tpu.ops.preprocess import normalize


def create_model(spec: ModelSpec, dtype: Any = None):
    """Instantiate the flax module for a spec (dtype = compute dtype)."""
    if spec.family == "xception":
        from kubernetes_deep_learning_tpu.models.xception import Xception

        return Xception(spec.num_classes, head_hidden=spec.head_hidden, dtype=dtype)
    if spec.family == "resnet50":
        from kubernetes_deep_learning_tpu.models.resnet import ResNet50

        return ResNet50(spec.num_classes, dtype=dtype)
    if spec.family.startswith("efficientnet-"):
        from kubernetes_deep_learning_tpu.models.efficientnet import (
            SCALING,
            build_efficientnet,
        )

        variant = spec.family.removeprefix("efficientnet-")
        if variant in SCALING:  # else fall through to the registry error
            return build_efficientnet(
                variant,
                spec.num_classes,
                head_hidden=spec.head_hidden,
                dtype=dtype,
            )
    if spec.family in _vit_families():
        from kubernetes_deep_learning_tpu.models.vit import VIT_CONFIGS, ViT

        return ViT(spec.num_classes, config=VIT_CONFIGS[spec.family], dtype=dtype)
    raise KeyError(f"unknown model family {spec.family!r}")


def _vit_families() -> tuple[str, ...]:
    from kubernetes_deep_learning_tpu.models.vit import VIT_CONFIGS

    return tuple(VIT_CONFIGS)


def init_variables(spec: ModelSpec, seed: int = 0, dtype: Any = None):
    """Random-init variables with the spec's input shape (for tests/bench)."""
    import jax

    model = create_model(spec, dtype=dtype)
    dummy = jnp.zeros((1, *spec.input_shape), jnp.float32)
    return model.init(jax.random.PRNGKey(seed), dummy)


def has_fast_forward(spec: ModelSpec) -> bool:
    """Whether a fused-Pallas fast path exists for this family."""
    return spec.family == "xception"


def resolve_fast(
    spec: ModelSpec, dtype: Any, fast: bool | str, backend: str | None = None
) -> bool:
    """The fast-flag resolution build_forward applies, exposed so callers
    (the serving engine's compile-failure fallback) can know ahead of time
    whether the fused Pallas path will be in the traced program.

    ``backend`` defaults to jax.default_backend(); the serving engine passes
    its actual device's platform instead, so an engine pinned to a non-TPU
    device on a TPU-backend host resolves "auto" to the graph that can
    actually compile there.
    """
    if fast == "auto":
        if backend is None:
            import jax

            backend = jax.default_backend()
        return (
            has_fast_forward(spec)
            and jnp.dtype(dtype) == jnp.bfloat16
            and backend == "tpu"
        )
    return bool(fast) and has_fast_forward(spec)


def build_forward(
    spec: ModelSpec, dtype: Any = jnp.bfloat16, fast: bool | str = "auto"
) -> Callable[[Any, Any], Any]:
    """Return ``f(variables, images) -> logits`` ready for jit/export.

    ``images`` may be uint8 HWC batches straight off the wire (the gateway
    ships uint8; see serving.protocol) or pre-normalized float32.  The uint8
    path normalizes on device so the scale/shift fuses into the first conv.
    Logits are returned as float32 regardless of compute dtype.

    ``fast``: "auto" uses the fused-Pallas fast path (models.xception_fast)
    when the family has one and the default backend is TPU -- same variable
    tree, bf16-noise-level logit difference, ~20% faster (BENCH.md).  True
    forces it (tests use interpret mode via the module directly); False
    keeps the flax graph (exact parity; the exporter uses this so artifacts
    stay portable across platforms).
    """
    if resolve_fast(spec, dtype, fast):
        from kubernetes_deep_learning_tpu.models.xception_fast import (
            build_fast_forward,
        )

        inner = build_fast_forward(spec, dtype=dtype)
    else:
        model = create_model(spec, dtype=dtype)
        inner = lambda variables, x: model.apply(variables, x, train=False)  # noqa: E731

    def forward(variables, images):
        if images.dtype == jnp.uint8:
            x = normalize(images, spec.preprocessing)
        else:
            x = images.astype(jnp.float32)
        return inner(variables, x).astype(jnp.float32)

    return forward
