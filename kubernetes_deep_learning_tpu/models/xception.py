"""Xception in flax.linen, matching the Keras architecture weight-for-weight.

This is the flagship model: the reference serves an Xception-based 10-class
clothing classifier with input contract ``input_8 (-1,299,299,3) f32 ->
dense_7 (-1,10) f32`` (reference guide.md:220-231).  Module names mirror Keras
layer names (block1_conv1, block4_sepconv2_bn, ...) so the .h5 importer in
``models.keras_import`` can map weights structurally.

Architecture (Chollet 2017, as implemented by keras.applications.xception):
entry flow (2 convs + 3 strided separable residual blocks), middle flow
(8 identical 728-wide residual blocks), exit flow (strided block + 1536/2048
separable convs), global average pool, classifier head.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn

from kubernetes_deep_learning_tpu.models.layers import (
    ClassifierHead,
    SeparableConv2D,
    batch_norm,
)

# Entry-flow residual block widths; block index -> features.
_ENTRY_BLOCKS = ((2, 128), (3, 256), (4, 728))
_MIDDLE_BLOCKS = range(5, 13)  # blocks 5..12, 728 features each


class Xception(nn.Module):
    num_classes: int
    head_hidden: tuple[int, ...] = ()
    dropout_rate: float = 0.0
    dtype: Any = None  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(batch_norm, train, self.dtype)
        sep = partial(SeparableConv2D, dtype=self.dtype)
        pool = partial(nn.max_pool, window_shape=(3, 3), strides=(2, 2), padding="SAME")

        # --- Entry flow ---
        x = conv(32, (3, 3), strides=2, padding="VALID", name="block1_conv1")(x)
        x = nn.relu(bn("block1_conv1_bn")(x))
        x = conv(64, (3, 3), padding="VALID", name="block1_conv2")(x)
        x = nn.relu(bn("block1_conv2_bn")(x))

        for idx, feat in _ENTRY_BLOCKS:
            residual = conv(feat, (1, 1), strides=2, padding="SAME", name=f"block{idx}_res_conv")(x)
            residual = bn(f"block{idx}_res_bn")(residual)
            if idx > 2:  # block2 has no leading activation (Keras quirk)
                x = nn.relu(x)
            x = sep(feat, name=f"block{idx}_sepconv1")(x)
            x = bn(f"block{idx}_sepconv1_bn")(x)
            x = nn.relu(x)
            x = sep(feat, name=f"block{idx}_sepconv2")(x)
            x = bn(f"block{idx}_sepconv2_bn")(x)
            x = pool(x) + residual

        # --- Middle flow: 8 residual blocks of 3 separable convs ---
        for idx in _MIDDLE_BLOCKS:
            residual = x
            for j in (1, 2, 3):
                x = nn.relu(x)
                x = sep(728, name=f"block{idx}_sepconv{j}")(x)
                x = bn(f"block{idx}_sepconv{j}_bn")(x)
            x = x + residual

        # --- Exit flow ---
        residual = conv(1024, (1, 1), strides=2, padding="SAME", name="block13_res_conv")(x)
        residual = bn("block13_res_bn")(residual)
        x = nn.relu(x)
        x = sep(728, name="block13_sepconv1")(x)
        x = bn("block13_sepconv1_bn")(x)
        x = nn.relu(x)
        x = sep(1024, name="block13_sepconv2")(x)
        x = bn("block13_sepconv2_bn")(x)
        x = pool(x) + residual

        x = sep(1536, name="block14_sepconv1")(x)
        x = nn.relu(bn("block14_sepconv1_bn")(x))
        x = sep(2048, name="block14_sepconv2")(x)
        x = nn.relu(bn("block14_sepconv2_bn")(x))

        return ClassifierHead(
            self.num_classes,
            hidden=self.head_hidden,
            dropout_rate=self.dropout_rate,
            dtype=self.dtype,
            name="head",
        )(x, train=train)
