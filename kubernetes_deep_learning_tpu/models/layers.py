"""Shared building blocks for the model zoo (flax.linen).

Design notes (TPU-first): all convolutions are expressed as ``nn.Conv`` so XLA
lowers them onto the MXU; depthwise separable convolution is depthwise
(``feature_group_count = C_in``) followed by a 1x1 pointwise conv, the exact
decomposition Keras' ``SeparableConv2D`` uses, so weights from the reference's
.h5 artifact (reference convert.py:4) map one-to-one.  Compute dtype is a
module argument (bf16 for serving); parameters stay f32.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn

# Keras BatchNormalization default epsilon (TF 2.3), needed for logit parity.
KERAS_BN_EPS = 1e-3


class SeparableConv2D(nn.Module):
    """Depthwise 3x3 + pointwise 1x1, both bias-free (Keras SeparableConv2D)."""

    features: int
    kernel: tuple[int, int] = (3, 3)
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        c_in = x.shape[-1]
        x = nn.Conv(
            c_in,
            self.kernel,
            feature_group_count=c_in,
            use_bias=False,
            padding="SAME",
            dtype=self.dtype,
            name="depthwise",
        )(x)
        x = nn.Conv(
            self.features, (1, 1), use_bias=False, dtype=self.dtype, name="pointwise"
        )(x)
        return x


def batch_norm(train: bool, dtype: Any, name: str, eps: float = KERAS_BN_EPS):
    return nn.BatchNorm(
        use_running_average=not train,
        epsilon=eps,
        momentum=0.99,
        dtype=dtype,
        name=name,
    )


class ClassifierHead(nn.Module):
    """Global-average-pool head: optional hidden Dense layers, then logits.

    Mirrors the reference's transfer-learning head (GlobalAveragePooling2D ->
    Dense(inner, relu) -> Dropout -> Dense(10); reference guide.md:176's
    xception_v4_large artifact).  Dropout is inference-inert and only applied
    when ``train`` and ``dropout_rate > 0``.
    """

    num_classes: int
    hidden: tuple[int, ...] = ()
    dropout_rate: float = 0.0
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: (N, H, W, C) -> global average pool over spatial dims.
        x = x.mean(axis=(1, 2))
        for i, width in enumerate(self.hidden):
            x = nn.Dense(width, dtype=self.dtype, name=f"hidden_{i}")(x)
            x = nn.relu(x)
            if train and self.dropout_rate > 0:
                x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
