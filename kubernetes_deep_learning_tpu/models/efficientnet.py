"""EfficientNet (B0-scalable, B3 served) in flax.linen.

BASELINE.json config 4 is "EfficientNet-B3 with server-side dynamic batching
on TPU"; like ResNet50 this family exists to exercise the serving stack with
a third architecture (the reference serves exactly one model,
reference tf-serving.dockerfile:4-5).

Architecture follows Tan & Le 2019 (MBConv + squeeze-excite), with compound
scaling: B3 = width 1.2x, depth 1.4x at 300x300 input.  TPU-first notes:
depthwise convs use ``feature_group_count`` so XLA emits native depthwise
ops; squeeze-excite's global pool reduces to a (N,1,1,C) tensor that stays
on-chip; silu/sigmoid epilogues fuse into the surrounding convs.  Stochastic
depth is omitted (serving-only framework: it is inference-inert).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any

import flax.linen as nn

from kubernetes_deep_learning_tpu.models.layers import ClassifierHead, batch_norm

# EfficientNet-B0 base blocks: (expand_ratio, channels, repeats, stride, kernel).
_BASE_BLOCKS = (
    (1, 16, 1, 1, 3),
    (6, 24, 2, 2, 3),
    (6, 40, 2, 2, 5),
    (6, 80, 3, 2, 3),
    (6, 112, 3, 1, 5),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)
_SE_RATIO = 0.25


def round_filters(filters: int, width: float, divisor: int = 8) -> int:
    """Compound-scale a channel count, snapped to a multiple of 8 (MXU-friendly)."""
    filters *= width
    new = max(divisor, int(filters + divisor / 2) // divisor * divisor)
    if new < 0.9 * filters:  # never round down by more than 10%
        new += divisor
    return int(new)


def round_repeats(repeats: int, depth: float) -> int:
    return int(math.ceil(depth * repeats))


class SqueezeExcite(nn.Module):
    """Global-pool -> bottleneck Dense(silu) -> Dense(sigmoid) channel gate."""

    se_features: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        s = x.mean(axis=(1, 2), keepdims=True)  # (N,1,1,C)
        s = nn.Conv(self.se_features, (1, 1), dtype=self.dtype, name="reduce")(s)
        s = nn.silu(s)
        s = nn.Conv(c, (1, 1), dtype=self.dtype, name="expand")(s)
        return x * nn.sigmoid(s)


class MBConvBlock(nn.Module):
    """Inverted residual: 1x1 expand -> depthwise kxk -> SE -> 1x1 project."""

    features: int
    expand_ratio: int
    kernel: int = 3
    strides: int = 1
    se_features: int = 0
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(batch_norm, train, self.dtype)

        c_in = x.shape[-1]
        y = x
        if self.expand_ratio != 1:
            y = conv(c_in * self.expand_ratio, (1, 1), name="expand_conv")(y)
            y = nn.silu(bn("expand_bn")(y))

        c_mid = y.shape[-1]
        y = conv(
            c_mid,
            (self.kernel, self.kernel),
            strides=self.strides,
            feature_group_count=c_mid,
            padding="SAME",
            name="dwconv",
        )(y)
        y = nn.silu(bn("dw_bn")(y))

        if self.se_features > 0:
            y = SqueezeExcite(self.se_features, dtype=self.dtype, name="se")(y)

        y = conv(self.features, (1, 1), name="project_conv")(y)
        y = bn("project_bn")(y)

        if self.strides == 1 and c_in == self.features:
            y = y + x
        return y


class EfficientNet(nn.Module):
    num_classes: int
    width: float = 1.0
    depth: float = 1.0
    head_hidden: tuple[int, ...] = ()
    dropout_rate: float = 0.0
    dtype: Any = None  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(batch_norm, train, self.dtype)

        x = conv(round_filters(32, self.width), (3, 3), strides=2, padding="SAME", name="stem_conv")(x)
        x = nn.silu(bn("stem_bn")(x))

        block_id = 0
        for expand, channels, repeats, stride, kernel in _BASE_BLOCKS:
            features = round_filters(channels, self.width)
            for rep in range(round_repeats(repeats, self.depth)):
                c_in = x.shape[-1]
                x = MBConvBlock(
                    features,
                    expand_ratio=expand,
                    kernel=kernel,
                    strides=stride if rep == 0 else 1,
                    se_features=max(1, int(c_in * _SE_RATIO)),
                    dtype=self.dtype,
                    name=f"block{block_id}",
                )(x, train=train)
                block_id += 1

        x = conv(round_filters(1280, self.width), (1, 1), name="top_conv")(x)
        x = nn.silu(bn("top_bn")(x))

        return ClassifierHead(
            self.num_classes,
            hidden=self.head_hidden,
            dropout_rate=self.dropout_rate,
            dtype=self.dtype,
            name="head",
        )(x, train=train)


# Compound-scaling table, Tan & Le 2019 table 1 / keras.applications:
# variant -> (width, depth, dropout).  Native resolution is carried by the
# ModelSpec's input_shape, not the module (any input size works).
SCALING = {
    "b0": (1.0, 1.0, 0.2),
    "b1": (1.0, 1.1, 0.2),
    "b2": (1.1, 1.2, 0.3),
    "b3": (1.2, 1.4, 0.3),
    "b4": (1.4, 1.8, 0.4),
    "b5": (1.6, 2.2, 0.4),
    "b6": (1.8, 2.6, 0.5),
    "b7": (2.0, 3.1, 0.5),
}


def build_efficientnet(variant: str, num_classes: int, dtype: Any = None, **kw) -> EfficientNet:
    """Any B0-B7 variant by name ("b0".."b7")."""
    if variant not in SCALING:
        raise KeyError(
            f"unknown EfficientNet variant {variant!r}; supported: {sorted(SCALING)}"
        )
    width, depth, dropout = SCALING[variant]
    kw.setdefault("dropout_rate", dropout)
    return EfficientNet(num_classes, width=width, depth=depth, dtype=dtype, **kw)


def EfficientNetB3(num_classes: int, dtype: Any = None, **kw) -> EfficientNet:
    """B3 compound scaling: width 1.2, depth 1.4, input 300x300, dropout 0.3."""
    return build_efficientnet("b3", num_classes, dtype=dtype, **kw)
