"""TPU fast path for EfficientNet: the flax graph with stride-1 MBConv
blocks swapped for the fused Pallas kernel (ops.fused_mbconv).

Same design as models.xception_fast: a pure function over the SAME variable
tree the flax module owns (init/import/export/training unchanged); only how
serving COMPUTES the forward changes.  Round-3 context: B3 served at 12%
MFU with the whole block graph on XLA fusions, the 6x-expanded activation
round-tripping HBM between them (BENCH.md; VERDICT r3 #4).

Layout strategy: the network alternates XLA segments (stem, expand-ratio-1
stage 1, stride-2 stage openers) with runs of fusible stride-1 blocks.
Fusible runs execute in the kernels' (H, W, B, C) layout; the forward
transposes lazily on entry to a run and back on exit, so consecutive
fused blocks -- including stride-1 stage openers, fused with
``residual=False`` -- pay no intermediate transposes.  Fusibility is
decided at trace time from static shapes: stride 1, expand_ratio > 1, and
the expanded bf16 tile at bt=8 within a VMEM budget (the two
high-resolution early stages stay on XLA).

Numerics: BN folded to f32 affines, silu in f32 before the bf16 cast back
(asserted <2% relative against the flax block in tests/test_fused_mbconv.py
and end-to-end in tests/test_efficientnet_fast.py); exact-parity paths
(golden verification, export) keep the flax graph.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from kubernetes_deep_learning_tpu.models.efficientnet import (
    _BASE_BLOCKS,
    _SE_RATIO,
    SCALING,
    round_filters,
    round_repeats,
)
from kubernetes_deep_learning_tpu.models.layers import KERAS_BN_EPS
from kubernetes_deep_learning_tpu.modelspec import ModelSpec
from kubernetes_deep_learning_tpu.ops.fused_mbconv import (
    fused_mbconv_block_t,
    mbconv_block_weights,
    mbconv_fusible,
)


def block_plan(width: float, depth: float):
    """Static per-block structure: (name, stride, kernel, features, expand)."""
    plan = []
    block_id = 0
    for expand, channels, repeats, stride, kernel in _BASE_BLOCKS:
        features = round_filters(channels, width)
        for rep in range(round_repeats(repeats, depth)):
            plan.append((
                f"block{block_id}",
                stride if rep == 0 else 1,
                kernel,
                features,
                expand,
            ))
            block_id += 1
    return plan


def build_fast_forward(
    spec: ModelSpec,
    dtype: Any = jnp.bfloat16,
    interpret: bool = False,
) -> Callable:
    """Return ``f(variables, normalized_f32_images) -> logits (dtype)``.

    The caller (models.build_forward) handles uint8 normalization and the
    final f32 cast, exactly as for the flax path.
    """
    variant = spec.family.removeprefix("efficientnet-")
    width, depth, _ = SCALING[variant]
    plan = block_plan(width, depth)

    def conv(x, kernel, stride=1, groups=1):
        return jax.lax.conv_general_dilated(
            x.astype(dtype),
            jnp.asarray(kernel, dtype),
            (stride, stride),
            "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups,
        )

    def bn(x, p, s):
        mean = jnp.asarray(s["mean"], dtype)
        var = jnp.asarray(s["var"], dtype)
        scale = jnp.asarray(p["scale"], dtype)
        bias = jnp.asarray(p["bias"], dtype)
        y = (x - mean) * jax.lax.rsqrt(var + jnp.asarray(KERAS_BN_EPS, dtype))
        return y * scale + bias

    silu = jax.nn.silu

    def mbconv_xla(x, bp, bs, stride, features, expand):
        """flax MBConvBlock semantics, functionally (NHWC, XLA fusions)."""
        c_in = x.shape[-1]
        y = x
        if expand != 1:
            y = conv(y, bp["expand_conv"]["kernel"])
            y = silu(bn(y, bp["expand_bn"], bs["expand_bn"]))
        y = conv(y, bp["dwconv"]["kernel"], stride=stride, groups=y.shape[-1])
        y = silu(bn(y, bp["dw_bn"], bs["dw_bn"]))
        se = bp["se"]
        m = y.mean(axis=(1, 2), keepdims=True)
        r = silu(
            conv(m, se["reduce"]["kernel"]) + jnp.asarray(se["reduce"]["bias"], dtype)
        )
        g = jax.nn.sigmoid(
            conv(r, se["expand"]["kernel"]) + jnp.asarray(se["expand"]["bias"], dtype)
        )
        y = y * g
        y = conv(y, bp["project_conv"]["kernel"])
        y = bn(y, bp["project_bn"], bs["project_bn"])
        if stride == 1 and c_in == features:
            y = y + x
        return y

    def fusible(h, w, stride, expand, c_in):
        return (
            stride == 1
            and expand != 1
            and mbconv_fusible(h, w, c_in * expand)
        )

    def forward(variables, x):
        p = variables["params"]
        s = variables["batch_stats"]
        batch = x.shape[0]
        # Batch rides the sublane axis in the fused runs; pad once to a
        # multiple of 8 (Mosaic row-collapse legality, see fused_sepconv)
        # and slice after the head mean.
        pad_rows = (-batch) % 8

        x = conv(x, p["stem_conv"]["kernel"], stride=2)
        x = silu(bn(x, p["stem_bn"], s["stem_bn"]))
        if pad_rows:
            x = jnp.pad(x, ((0, pad_rows), (0, 0), (0, 0), (0, 0)))

        xt = None  # transposed (H, W, B, C) tensor while inside a fused run
        for name, stride, _kernel, features, expand in plan:
            h, w = (xt.shape[0], xt.shape[1]) if xt is not None else (x.shape[1], x.shape[2])
            c_in = xt.shape[3] if xt is not None else x.shape[-1]
            if fusible(h, w, stride, expand, c_in):
                if xt is None:
                    xt = x.transpose(1, 2, 0, 3).astype(jnp.bfloat16)
                xt = fused_mbconv_block_t(
                    xt,
                    mbconv_block_weights(p, s, name),
                    residual=(c_in == features),
                    interpret=interpret,
                ).astype(dtype)
            else:
                if xt is not None:
                    x = xt.transpose(2, 0, 1, 3)
                    xt = None
                x = mbconv_xla(x, p[name], s[name], stride, features, expand)
        if xt is not None:
            x = xt.transpose(2, 0, 1, 3)

        x = conv(x, p["top_conv"]["kernel"])
        x = silu(bn(x, p["top_bn"], s["top_bn"]))

        x = x.mean(axis=(1, 2))[:batch]
        head = p["head"]
        i = 0
        while f"hidden_{i}" in head:
            hdn = head[f"hidden_{i}"]
            x = jax.nn.relu(
                x @ jnp.asarray(hdn["kernel"], dtype) + jnp.asarray(hdn["bias"], dtype)
            )
            i += 1
        logits = head["logits"]
        return x @ jnp.asarray(logits["kernel"], dtype) + jnp.asarray(
            logits["bias"], dtype
        )

    return forward
