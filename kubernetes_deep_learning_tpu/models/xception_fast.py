"""TPU fast path for Xception: the flax graph with the middle flow swapped
for the fused Pallas sepconv kernel (ops.fused_sepconv).

A pure function over the SAME variable tree the flax module owns -- the
module stays the single source of structure (init, .h5 import, export,
training all unchanged); this path only changes how serving COMPUTES the
forward.  Measured on a v5e chip at batch 256: 83 -> 69 ms per forward
(+20% throughput, BENCH.md).  Entry/exit flows mirror flax.linen numerics
op for op (bf16 compute, Keras BN epsilon); the middle flow runs the fused
kernel in the (H, W, B, C) layout, paying one transpose in and one out.

Numerics: the fused middle folds BN to an f32 affine, so logits differ from
the flax path by bf16-rounding-level noise (asserted < 1% relative in
tests/test_fused_sepconv.py); exact-parity paths (golden verification,
export) keep using the flax graph.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubernetes_deep_learning_tpu.models.layers import KERAS_BN_EPS
from kubernetes_deep_learning_tpu.modelspec import ModelSpec
from kubernetes_deep_learning_tpu.ops.fused_sepconv import (
    fused_sepconv_block_t,
    middle_block_weights,
)

_ENTRY_BLOCKS = ((2, 128), (3, 256), (4, 728))  # keep in sync with models.xception
_MIDDLE_BLOCKS = tuple(range(5, 13))


def build_fast_forward(
    spec: ModelSpec, dtype: Any = jnp.bfloat16, interpret: bool = False
) -> Callable:
    """Return ``f(variables, normalized_f32_images) -> logits (dtype)``.

    The caller (models.build_forward) handles uint8 normalization and the
    final f32 cast, exactly as for the flax path.
    """

    def conv(x, kernel, stride=1, padding="SAME"):
        # flax nn.Conv(dtype=...) semantics: operands promoted to dtype,
        # no preferred accumulation type override.
        return jax.lax.conv_general_dilated(
            x.astype(dtype),
            jnp.asarray(kernel, dtype),
            (stride, stride),
            padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def depthwise(x, kernel):
        return jax.lax.conv_general_dilated(
            x.astype(dtype),
            jnp.asarray(kernel, dtype),
            (1, 1),
            "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=x.shape[-1],
        )

    def bn(x, p, s):
        # flax BatchNorm(use_running_average=True, dtype=...): stats and
        # params promoted to dtype, computed in dtype.
        mean = jnp.asarray(s["mean"], dtype)
        var = jnp.asarray(s["var"], dtype)
        scale = jnp.asarray(p["scale"], dtype)
        bias = jnp.asarray(p["bias"], dtype)
        y = (x - mean) * jax.lax.rsqrt(var + jnp.asarray(KERAS_BN_EPS, dtype))
        return y * scale + bias

    def sepconv(x, p):
        x = depthwise(x, p["depthwise"]["kernel"])
        return conv(x, p["pointwise"]["kernel"])

    pool = lambda x: nn.max_pool(  # noqa: E731 - mirrors models.xception
        x, window_shape=(3, 3), strides=(2, 2), padding="SAME"
    )

    def forward(variables, x):
        p = variables["params"]
        s = variables["batch_stats"]

        # --- entry flow (flax-identical ops) ---
        x = conv(x, p["block1_conv1"]["kernel"], stride=2, padding="VALID")
        x = nn.relu(bn(x, p["block1_conv1_bn"], s["block1_conv1_bn"]))
        x = conv(x, p["block1_conv2"]["kernel"], padding="VALID")
        x = nn.relu(bn(x, p["block1_conv2_bn"], s["block1_conv2_bn"]))
        for idx, _feat in _ENTRY_BLOCKS:
            residual = conv(x, p[f"block{idx}_res_conv"]["kernel"], stride=2)
            residual = bn(residual, p[f"block{idx}_res_bn"], s[f"block{idx}_res_bn"])
            if idx > 2:
                x = nn.relu(x)
            x = sepconv(x, p[f"block{idx}_sepconv1"])
            x = bn(x, p[f"block{idx}_sepconv1_bn"], s[f"block{idx}_sepconv1_bn"])
            x = nn.relu(x)
            x = sepconv(x, p[f"block{idx}_sepconv2"])
            x = bn(x, p[f"block{idx}_sepconv2_bn"], s[f"block{idx}_sepconv2_bn"])
            x = pool(x) + residual

        # --- middle flow: fused Pallas chain in (H, W, B, C) layout ---
        xt = x.transpose(1, 2, 0, 3)
        for idx in _MIDDLE_BLOCKS:
            dw, pw, scale, shift = middle_block_weights(p, s, f"block{idx}")
            xt = fused_sepconv_block_t(xt, dw, pw, scale, shift, interpret=interpret)
        x = xt.transpose(2, 0, 1, 3)

        # --- exit flow (flax-identical ops) ---
        residual = conv(x, p["block13_res_conv"]["kernel"], stride=2)
        residual = bn(residual, p["block13_res_bn"], s["block13_res_bn"])
        x = nn.relu(x)
        x = sepconv(x, p["block13_sepconv1"])
        x = bn(x, p["block13_sepconv1_bn"], s["block13_sepconv1_bn"])
        x = nn.relu(x)
        x = sepconv(x, p["block13_sepconv2"])
        x = bn(x, p["block13_sepconv2_bn"], s["block13_sepconv2_bn"])
        x = pool(x) + residual
        x = sepconv(x, p["block14_sepconv1"])
        x = nn.relu(bn(x, p["block14_sepconv1_bn"], s["block14_sepconv1_bn"]))
        x = sepconv(x, p["block14_sepconv2"])
        x = nn.relu(bn(x, p["block14_sepconv2_bn"], s["block14_sepconv2_bn"]))

        # --- head (ClassifierHead semantics) ---
        x = x.mean(axis=(1, 2))
        head = p["head"]
        i = 0
        while f"hidden_{i}" in head:
            h = head[f"hidden_{i}"]
            x = nn.relu(
                x @ jnp.asarray(h["kernel"], dtype) + jnp.asarray(h["bias"], dtype)
            )
            i += 1
        logits = head["logits"]
        return x @ jnp.asarray(logits["kernel"], dtype) + jnp.asarray(
            logits["bias"], dtype
        )

    return forward
