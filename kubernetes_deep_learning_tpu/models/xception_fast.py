"""TPU fast path for Xception: the flax graph with the middle flow swapped
for the fused Pallas sepconv kernel (ops.fused_sepconv).

A pure function over the SAME variable tree the flax module owns -- the
module stays the single source of structure (init, .h5 import, export,
training all unchanged); this path only changes how serving COMPUTES the
forward.  Measured on a v5e chip at batch 256: 83 -> 69 ms per forward
(+20% throughput, BENCH.md).  Entry/exit flows mirror flax.linen numerics
op for op (bf16 compute, Keras BN epsilon); the middle flow runs the fused
kernel in the (H, W, B, C) layout, paying one transpose in and one out.

Numerics: the fused middle folds BN to an f32 affine, so logits differ from
the flax path by bf16-rounding-level noise (asserted < 1% relative in
tests/test_fused_sepconv.py); exact-parity paths (golden verification,
export) keep using the flax graph.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubernetes_deep_learning_tpu.models.layers import KERAS_BN_EPS
from kubernetes_deep_learning_tpu.modelspec import ModelSpec
from kubernetes_deep_learning_tpu.ops.fused_entry import (
    entry_block_weights,
    fused_entry_block_t,
)
from kubernetes_deep_learning_tpu.ops.fused_sepconv import (
    fold_bn,
    fused_sepconv_block_t,
    fused_sepconv_chain_t,
    middle_block_weights,
    sepconv_stage_weights,
)

_ENTRY_BLOCKS = ((2, 128), (3, 256), (4, 728))  # keep in sync with models.xception
_MIDDLE_BLOCKS = tuple(range(5, 13))

# Microbatch chunking (round 4).  The fused path's device time per image is
# non-monotonic in batch: 197 us/img at batch 16 but 222/232/209 at
# 32/48/64 (exp/batch_dip_trace.py) -- XLA picks worse entry-flow fusion
# schedules at those sizes.  Running those batches as UNROLLED 16-image
# chunks inside one jitted program restores the batch-16 schedule per
# chunk: 0.88x/0.84x/0.92x device span at 32/48/64, while 128 is faster
# monolithic (1.07x chunked) -- measured on a v5e chip
# (exp/chunked_forward.py).  8-multiples that are not 16-multiples (40,
# 56) take a trailing 8-image chunk (batch-8 also beats the 32-64
# monoliths per image): 0.87x at 40.  lax.map chunking is NOT equivalent:
# the loop body compiles ~2x slower than the same chunk standalone
# (1.7-1.8x net).
_CHUNK = 16
_TAIL = 8  # trailing-chunk granularity (the kernels' sublane alignment)
_CHUNK_MIN, _CHUNK_MAX = 32, 64


def _chunk_sizes(batch: int) -> list[int] | None:
    """Chunk sizes to split ``batch`` into, or None for monolithic."""
    if batch % _TAIL or not _CHUNK_MIN <= batch <= _CHUNK_MAX:
        return None
    k, r = divmod(batch, _CHUNK)
    sizes = [_CHUNK] * k + ([r] if r else [])
    return sizes if len(sizes) > 1 else None


def build_fast_forward(
    spec: ModelSpec,
    dtype: Any = jnp.bfloat16,
    interpret: bool = False,
    entry_kernel: bool = False,
    conv1_t: bool = False,
    chunk: bool = True,
) -> Callable:
    """Return ``f(variables, normalized_f32_images) -> logits (dtype)``.

    The caller (models.build_forward) handles uint8 normalization and the
    final f32 cast, exactly as for the flax path.

    ``chunk`` (default on) runs 8-multiple batches in [32, 64] (i.e.
    32/40/48/56/64; 40 and 56 take a trailing 8-image chunk) as unrolled
    16-image microbatches
    inside the same program, which sidesteps XLA's worse
    entry-flow schedules at those sizes (+9-19% device throughput,
    exp/chunked_forward.py; see ``_chunk_sizes``).  Per-image numerics are
    those of the batch-16 program -- same bf16-noise tolerance vs flax.
    Off for the experimental entry-kernel paths so their measurements stay
    monolithic and attributable.

    ``entry_kernel`` (EXPERIMENTAL, default off) routes conv2+block2
    through the fused entry Pallas kernel (ops.fused_entry) and blocks 3/4
    through the fused sepconv chains, so everything from conv1's output to
    the head runs in the (H, W, B, C) layout.  Round-3 verdict: the kernel
    body (4.18 ms at batch 64) beats the XLA fusions it replaces
    (4.43 ms), but the halo-slab staging it needs costs another ~1.4 ms
    XLA-side, so the net is a LOSS (exp/model_fused_entry.py: 21.1 vs
    19.0 ms full-forward) -- manual DMA staging is blocked by Mosaic's
    128-aligned-lane sliced-DMA rule at c_in=32.  Kept off the serving
    path (models.build_forward never enables it) until the staging cost is
    solved; blocks 3/4 chains are only reachable through this flag too.

    ``conv1_t`` (EXPERIMENTAL, requires entry_kernel) attacks that staging
    loss from the other side (VERDICT r3 #5): transpose the INPUT once
    (3 channels -- the cheapest tensor in the model) and run conv1/bn/relu
    directly in the (H, W, B, C) layout via conv dimension_numbers
    ("HWNC", "HWIO", "HWNC"), so the entry kernel's halo-slab gather reads
    a tensor already resident in its layout and the output-side staging
    transpose disappears.  Whether XLA:TPU compiles the HWNC conv without
    re-transposing internally is exactly what exp/model_fused_entry.py
    measures.
    """
    if conv1_t and not entry_kernel:
        raise ValueError(
            "conv1_t requires entry_kernel=True (without the entry kernel "
            "there is no transposed consumer; silently measuring the plain "
            "XLA path would misattribute results)"
        )

    def conv(x, kernel, stride=1, padding="SAME"):
        # flax nn.Conv(dtype=...) semantics: operands promoted to dtype,
        # no preferred accumulation type override.
        return jax.lax.conv_general_dilated(
            x.astype(dtype),
            jnp.asarray(kernel, dtype),
            (stride, stride),
            padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def depthwise(x, kernel):
        return jax.lax.conv_general_dilated(
            x.astype(dtype),
            jnp.asarray(kernel, dtype),
            (1, 1),
            "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=x.shape[-1],
        )

    def bn(x, p, s):
        # flax BatchNorm(use_running_average=True, dtype=...): stats and
        # params promoted to dtype, computed in dtype.
        mean = jnp.asarray(s["mean"], dtype)
        var = jnp.asarray(s["var"], dtype)
        scale = jnp.asarray(p["scale"], dtype)
        bias = jnp.asarray(p["bias"], dtype)
        y = (x - mean) * jax.lax.rsqrt(var + jnp.asarray(KERAS_BN_EPS, dtype))
        return y * scale + bias

    def sepconv(x, p):
        x = depthwise(x, p["depthwise"]["kernel"])
        return conv(x, p["pointwise"]["kernel"])

    pool = lambda x: nn.max_pool(  # noqa: E731 - mirrors models.xception
        x, window_shape=(3, 3), strides=(2, 2), padding="SAME"
    )

    def downsample_t(xt, p, s, block, vmem_limit_bytes=0):
        """Residual 1x1/2 conv (XLA einsum) + fused 2-sepconv chain +
        max-pool + add, in the (H, W, B, C) layout -- the shared pattern of
        blocks 3, 4, and 13 (relu -> sep -> bn, twice, then pool+res).
        Blocks 3/4 (entry path only) pass a raised VMEM limit: their
        74x74/37x37 chains need ~107 MiB at bt=8."""
        res_scale, res_shift = fold_bn(p[f"{block}_res_bn"], s[f"{block}_res_bn"])
        res = jnp.einsum(
            "hwbc,cd->hwbd",
            xt[::2, ::2],
            jnp.asarray(p[f"{block}_res_conv"]["kernel"], dtype)[0, 0],
        )
        res = (res.astype(jnp.float32) * res_scale + res_shift).astype(dtype)
        y = fused_sepconv_chain_t(
            xt,
            [
                sepconv_stage_weights(
                    p, s, f"{block}_sepconv1", f"{block}_sepconv1_bn",
                    pre_relu=True, post_relu=False,
                ),
                sepconv_stage_weights(
                    p, s, f"{block}_sepconv2", f"{block}_sepconv2_bn",
                    pre_relu=True, post_relu=False,
                ),
            ],
            interpret=interpret,
            vmem_limit_bytes=vmem_limit_bytes,
        )
        pooled = jax.lax.reduce_window(
            y, -jnp.inf, jax.lax.max, (3, 3, 1, 1), (2, 2, 1, 1), "SAME"
        )
        return pooled + res

    def forward_one(variables, x):
        p = variables["params"]
        s = variables["batch_stats"]

        # Batch rides the sublane axis in the kernels' (H, W, B, C) layout,
        # and their (H, W, bt) -> rows collapse is only Mosaic-legal when
        # the batch tile is 8-aligned (BENCH_r02's batch-1 compile
        # failure).  Pad the batch ONCE to a multiple of 8 and slice after
        # the head mean, so the per-kernel padding in ops.fused_sepconv
        # stays a no-op and small serving buckets (1, 2, 4) compile the
        # same fused program.
        batch = x.shape[0]
        pad_rows = (-batch) % 8

        if entry_kernel and conv1_t:
            # --- transposed from the INPUT: conv1 computes directly in
            # (H, W, B, C), so the entry kernel's slab gather reads data
            # already resident in its layout (VERDICT r3 #5) -------------
            if pad_rows:
                x = jnp.pad(x, ((0, pad_rows), (0, 0), (0, 0), (0, 0)))
            xt = x.transpose(1, 2, 0, 3)  # (H, W, B, 3): the cheap transpose
            xt = jax.lax.conv_general_dilated(
                xt.astype(dtype),
                jnp.asarray(p["block1_conv1"]["kernel"], dtype),
                (2, 2),
                "VALID",
                dimension_numbers=("HWNC", "HWIO", "HWNC"),
            )
            xt = nn.relu(bn(xt, p["block1_conv1_bn"], s["block1_conv1_bn"]))
            xt = fused_entry_block_t(
                xt.astype(jnp.bfloat16), entry_block_weights(p, s),
                interpret=interpret,
            ).astype(dtype)
            xt = downsample_t(xt, p, s, "block3", vmem_limit_bytes=110 << 20)
            xt = downsample_t(xt, p, s, "block4", vmem_limit_bytes=110 << 20)
        elif entry_kernel:
            x = conv(x, p["block1_conv1"]["kernel"], stride=2, padding="VALID")
            x = nn.relu(bn(x, p["block1_conv1_bn"], s["block1_conv1_bn"]))
            # --- transposed from conv1 out to the head: conv2+block2 in
            # the fused entry kernel, blocks 3/4 as fused chains ---------
            if pad_rows:
                x = jnp.pad(x, ((0, pad_rows), (0, 0), (0, 0), (0, 0)))
            xt = x.transpose(1, 2, 0, 3).astype(jnp.bfloat16)
            xt = fused_entry_block_t(
                xt, entry_block_weights(p, s), interpret=interpret
            ).astype(dtype)
            xt = downsample_t(xt, p, s, "block3", vmem_limit_bytes=110 << 20)
            xt = downsample_t(xt, p, s, "block4", vmem_limit_bytes=110 << 20)
        else:
            x = conv(x, p["block1_conv1"]["kernel"], stride=2, padding="VALID")
            x = nn.relu(bn(x, p["block1_conv1_bn"], s["block1_conv1_bn"]))
            # --- entry flow on XLA fusions (flax-identical ops) ----------
            x = conv(x, p["block1_conv2"]["kernel"], padding="VALID")
            x = nn.relu(bn(x, p["block1_conv2_bn"], s["block1_conv2_bn"]))
            for idx, _feat in _ENTRY_BLOCKS:
                residual = conv(x, p[f"block{idx}_res_conv"]["kernel"], stride=2)
                residual = bn(residual, p[f"block{idx}_res_bn"], s[f"block{idx}_res_bn"])
                if idx > 2:
                    x = nn.relu(x)
                x = sepconv(x, p[f"block{idx}_sepconv1"])
                x = bn(x, p[f"block{idx}_sepconv1_bn"], s[f"block{idx}_sepconv1_bn"])
                x = nn.relu(x)
                x = sepconv(x, p[f"block{idx}_sepconv2"])
                x = bn(x, p[f"block{idx}_sepconv2_bn"], s[f"block{idx}_sepconv2_bn"])
                x = pool(x) + residual
            if pad_rows:
                x = jnp.pad(x, ((0, pad_rows), (0, 0), (0, 0), (0, 0)))
            xt = x.transpose(1, 2, 0, 3)

        # --- middle + exit flows: fused Pallas chains ---------------------
        # Everything stays in (H, W, B, C): the exit flow's pool/residual
        # are layout-agnostic XLA ops, so the transpose back never happens
        # -- the head mean reduces over the leading spatial axes directly.
        for idx in _MIDDLE_BLOCKS:
            dw, pw, scale, shift = middle_block_weights(p, s, f"block{idx}")
            xt = fused_sepconv_block_t(xt, dw, pw, scale, shift, interpret=interpret)

        xt = downsample_t(xt, p, s, "block13")

        # block14: two sepconvs (sep -> bn -> relu pattern), fused.
        xt = fused_sepconv_chain_t(
            xt,
            [
                sepconv_stage_weights(
                    p, s, "block14_sepconv1", "block14_sepconv1_bn",
                    pre_relu=False, post_relu=True,
                ),
                sepconv_stage_weights(
                    p, s, "block14_sepconv2", "block14_sepconv2_bn",
                    pre_relu=False, post_relu=True,
                ),
            ],
            interpret=interpret,
        )

        # --- head (ClassifierHead semantics; spatial = leading axes) ---
        x = xt.mean(axis=(0, 1))[:batch]
        head = p["head"]
        i = 0
        while f"hidden_{i}" in head:
            h = head[f"hidden_{i}"]
            x = nn.relu(
                x @ jnp.asarray(h["kernel"], dtype) + jnp.asarray(h["bias"], dtype)
            )
            i += 1
        logits = head["logits"]
        return x @ jnp.asarray(logits["kernel"], dtype) + jnp.asarray(
            logits["bias"], dtype
        )

    def forward(variables, x):
        sizes = _chunk_sizes(x.shape[0]) if chunk and not entry_kernel else None
        if sizes:
            outs, lo = [], 0
            for n in sizes:
                outs.append(forward_one(variables, x[lo : lo + n]))
                lo += n
            return jnp.concatenate(outs, axis=0)
        return forward_one(variables, x)

    return forward
