"""Import Keras .h5 weights into the flax model zoo.

The reference's model artifact is a Keras .h5 (``xception_v4_large_08_0.894.h5``,
reference guide.md:176) which ``convert.py`` re-saves as a TF SavedModel.  Here
the equivalent step loads that .h5 **directly** into flax params -- no
TensorFlow in the loop -- so the reference's expected logits
(reference guide.md:623-625) are reproducible from the same artifact.

Keras layer names are preserved by the flax modules for named layers
(block1_conv1, ...); layers Keras auto-names (the four residual 1x1 convs and
their BatchNorms, and the head Dense layers) are matched structurally by
weight shape, which is unique per site in Xception.  ResNet50 imports are a
purely syntactic rename (keras.applications names are flat, ours nest the
identical components).
"""

from __future__ import annotations

import re

import numpy as np

from kubernetes_deep_learning_tpu.modelspec import ModelSpec

# Residual 1x1 conv kernel shape -> our module name (unique per site).
_XCEPTION_RES_CONVS = {
    (1, 1, 64, 128): "block2_res_conv",
    (1, 1, 128, 256): "block3_res_conv",
    (1, 1, 256, 728): "block4_res_conv",
    (1, 1, 728, 1024): "block13_res_conv",
}
# Residual BatchNorm channel count -> our module name.
_XCEPTION_RES_BNS = {128: "block2_res_bn", 256: "block3_res_bn", 728: "block4_res_bn", 1024: "block13_res_bn"}


def read_keras_h5(path: str) -> dict[str, dict[str, np.ndarray]]:
    """Flatten a Keras .h5 into {layer_name: {weight_name: array}}.

    Walks the file recursively so both flat models and nested-submodel layouts
    (transfer learning: model_weights/xception/<layer>/<weight>:0) work.
    """
    import h5py

    layers: dict[str, dict[str, np.ndarray]] = {}

    def visit(name: str, obj) -> None:
        if not isinstance(obj, h5py.Dataset):
            return
        parts = name.split("/")
        weight = parts[-1].split(":")[0]
        layer = parts[-2] if len(parts) >= 2 else parts[-1]
        layers.setdefault(layer, {})[weight] = np.asarray(obj)

    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        root.visititems(visit)
    return layers


def _bn(layer: dict[str, np.ndarray]):
    params = {"scale": layer["gamma"], "bias": layer["beta"]}
    stats = {"mean": layer["moving_mean"], "var": layer["moving_variance"]}
    return params, stats


def _sepconv(layer: dict[str, np.ndarray]):
    dw = layer["depthwise_kernel"]  # keras (kh, kw, c_in, 1)
    pw = layer["pointwise_kernel"]  # (1, 1, c_in, c_out)
    return {
        "depthwise": {"kernel": np.transpose(dw, (0, 1, 3, 2))},  # flax (kh, kw, 1, c_in)
        "pointwise": {"kernel": pw},
    }


def _dense_layers_in_order(layers: dict[str, dict[str, np.ndarray]]):
    """Auto-named head Dense layers (dense, dense_1, ...) in creation order."""
    found = []
    for name, w in layers.items():
        m = re.fullmatch(r"dense(?:_(\d+))?", name)
        if m and "kernel" in w and w["kernel"].ndim == 2:
            found.append((int(m.group(1) or 0), name, w))
    return [(name, w) for _, name, w in sorted(found)]


def _head_from_denses(spec: ModelSpec, layers: dict[str, dict[str, np.ndarray]]):
    """Build the ClassifierHead params from the .h5's Dense layers.

    Auto-named chains (dense, dense_1, ...) map in creation order, last one
    = logits; otherwise a single Dense under any name (Keras calls the
    ImageNet head "predictions") is the logits layer.  Validates hidden
    sizes and class count against the spec so mismatched artifacts fail
    with a clear message, not a structure diff.
    """
    denses = _dense_layers_in_order(layers)
    if not denses:
        others = [
            (n, w) for n, w in layers.items()
            if "kernel" in w and w["kernel"].ndim == 2
        ]
        if len(others) != 1:
            raise ValueError(
                "no Dense head layers found in .h5"
                if not others
                else f"ambiguous head Dense layers: {[n for n, _ in others]}"
            )
        denses = others
    head: dict = {}
    *hidden, (_, logits_w) = denses
    for i, (_, w) in enumerate(hidden):
        head[f"hidden_{i}"] = {"kernel": w["kernel"], "bias": w["bias"]}
    head["logits"] = {"kernel": logits_w["kernel"], "bias": logits_w["bias"]}

    hidden_sizes = tuple(w["kernel"].shape[1] for _, w in hidden)
    if hidden_sizes != spec.head_hidden:
        raise ValueError(
            f".h5 head hidden sizes {hidden_sizes} do not match spec "
            f"{spec.head_hidden}; fix the ModelSpec to match the artifact"
        )
    if logits_w["kernel"].shape[1] != spec.num_classes:
        raise ValueError(
            f".h5 logits width {logits_w['kernel'].shape[1]} != "
            f"{spec.num_classes} labels"
        )
    return head


def xception_variables_from_keras(
    spec: ModelSpec, layers: dict[str, dict[str, np.ndarray]]
):
    """Build flax variables for models.xception.Xception from Keras weights."""
    params: dict = {}
    stats: dict = {}

    def put_bn(name: str, layer):
        p, s = _bn(layer)
        params[name] = p
        stats[name] = s

    # Explicitly-named Keras layers map one-to-one.
    for name, w in layers.items():
        if re.fullmatch(r"block\d+_conv\d", name):
            params[name] = {"kernel": w["kernel"]}
        elif re.fullmatch(r"block\d+_sepconv\d", name):
            params[name] = _sepconv(w)
        elif re.fullmatch(r"block\d+_(conv|sepconv)\d_bn", name):
            put_bn(name, w)

    # Auto-named residual convs + BNs: match by shape (unique per site).
    for name, w in layers.items():
        if "kernel" in w and w["kernel"].ndim == 4 and w["kernel"].shape in _XCEPTION_RES_CONVS:
            params[_XCEPTION_RES_CONVS[w["kernel"].shape]] = {"kernel": w["kernel"]}
        elif "gamma" in w and not name.startswith("block"):
            channels = w["gamma"].shape[0]
            target = _XCEPTION_RES_BNS.get(channels)
            if target is not None:
                put_bn(target, w)

    # Head: auto-named Dense layers in creation order; last one is logits.
    params["head"] = _head_from_denses(spec, layers)

    variables = {"params": params, "batch_stats": stats}
    _check_structure(spec, variables)
    return variables


_RESNET_CONV_RE = re.compile(r"(conv\d_block\d+)_(\d)_conv")
_RESNET_BN_RE = re.compile(r"(conv\d_block\d+)_(\d)_bn")


def resnet50_variables_from_keras(
    spec: ModelSpec, layers: dict[str, dict[str, np.ndarray]]
):
    """Build flax variables for models.resnet.ResNet50 from Keras weights.

    keras.applications.ResNet50 names are flat (``conv2_block1_1_conv``);
    our module nests the same names (``conv2_block1/1_conv``), so the map is
    purely syntactic -- no shape-based matching needed.
    """
    params: dict = {}
    stats: dict = {}

    def put_bn(block: str | None, name: str, layer):
        p, s = _bn(layer)
        if block is None:
            params[name] = p
            stats[name] = s
        else:
            params.setdefault(block, {})[name] = p
            stats.setdefault(block, {})[name] = s

    for name, w in layers.items():
        if name == "conv1_conv":
            params[name] = {"kernel": w["kernel"], "bias": w["bias"]}
        elif name == "conv1_bn":
            put_bn(None, name, w)
        elif m := _RESNET_CONV_RE.fullmatch(name):
            params.setdefault(m.group(1), {})[f"{m.group(2)}_conv"] = {
                "kernel": w["kernel"], "bias": w["bias"]
            }
        elif m := _RESNET_BN_RE.fullmatch(name):
            put_bn(m.group(1), f"{m.group(2)}_bn", w)

    # Head: "predictions" (stock ImageNet) or a dense/dense_1/... fine-tuned
    # chain -- same handling as xception, including head_hidden support.
    params["head"] = _head_from_denses(spec, layers)

    variables = {"params": params, "batch_stats": stats}
    _check_structure(spec, variables)
    return variables


_EFF_BLOCK_RE = re.compile(
    r"block(\d+)([a-z])_"
    r"(expand_conv|expand_bn|dwconv|bn|se_reduce|se_expand|project_conv|project_bn)"
)


def efficientnet_variables_from_keras(
    spec: ModelSpec, layers: dict[str, dict[str, np.ndarray]]
):
    """Build flax variables for models.efficientnet from Keras weights.

    keras.applications.EfficientNetB* names blocks ``block{stage}{letter}_*``
    (block1a, block1b, block2a, ...); our module numbers them flat in the same
    creation order (block0, block1, ...), so sorting the Keras names by
    (stage, letter) and zipping is an exact rename.  The depthwise kernel
    transposes (kh,kw,c,1) -> (kh,kw,1,c) as in ``_sepconv``; Keras's dw
    BatchNorm is named bare ``_bn`` where ours is ``dw_bn``.

    keras.applications builds Rescaling+Normalization INTO the model; those
    layers are skipped here because the framework normalizes outside the
    model (ops.preprocess), so the spec must say ``preprocessing="torch"``
    (the equivalent recipe) or logits will not match the Keras model.
    """
    # Keras auto-numbers repeated layer instances (normalization_1, ...) when
    # several models were built in one session before saving.
    has_norm = any(
        n == "normalization" or n.startswith("normalization_") for n in layers
    )
    if has_norm and spec.preprocessing != "torch":
        raise ValueError(
            ".h5 contains a keras Normalization layer (EfficientNet-style "
            "built-in preprocessing) but the spec's preprocessing is "
            f"{spec.preprocessing!r}; use 'torch' for logit parity"
        )

    params: dict = {}
    stats: dict = {}

    def put_bn(tree_p, tree_s, name: str, layer):
        p, s = _bn(layer)
        tree_p[name] = p
        tree_s[name] = s

    params["stem_conv"] = {"kernel": layers["stem_conv"]["kernel"]}
    put_bn(params, stats, "stem_bn", layers["stem_bn"])
    params["top_conv"] = {"kernel": layers["top_conv"]["kernel"]}
    put_bn(params, stats, "top_bn", layers["top_bn"])

    blocks: dict[tuple[int, str], dict[str, dict[str, np.ndarray]]] = {}
    for name, w in layers.items():
        if m := _EFF_BLOCK_RE.fullmatch(name):
            blocks.setdefault((int(m.group(1)), m.group(2)), {})[m.group(3)] = w

    for i, key in enumerate(sorted(blocks)):
        sub = blocks[key]
        bp: dict = {}
        bs: dict = {}
        if "expand_conv" in sub:
            bp["expand_conv"] = {"kernel": sub["expand_conv"]["kernel"]}
            put_bn(bp, bs, "expand_bn", sub["expand_bn"])
        dw = sub["dwconv"]["depthwise_kernel"]  # keras (kh, kw, c, 1)
        bp["dwconv"] = {"kernel": np.transpose(dw, (0, 1, 3, 2))}
        put_bn(bp, bs, "dw_bn", sub["bn"])
        if "se_reduce" in sub:
            bp["se"] = {
                "reduce": {
                    "kernel": sub["se_reduce"]["kernel"],
                    "bias": sub["se_reduce"]["bias"],
                },
                "expand": {
                    "kernel": sub["se_expand"]["kernel"],
                    "bias": sub["se_expand"]["bias"],
                },
            }
        bp["project_conv"] = {"kernel": sub["project_conv"]["kernel"]}
        put_bn(bp, bs, "project_bn", sub["project_bn"])
        params[f"block{i}"] = bp
        stats[f"block{i}"] = bs

    params["head"] = _head_from_denses(spec, layers)

    variables = {"params": params, "batch_stats": stats}
    _check_structure(spec, variables)
    return variables


def _check_structure(spec: ModelSpec, variables) -> None:
    """Verify imported tree matches the module's own init structure."""
    import jax

    from kubernetes_deep_learning_tpu.models import init_variables

    expected = jax.eval_shape(lambda: init_variables(spec, seed=0))

    def paths_to_shapes(tree):
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        return {jax.tree_util.keystr(k): tuple(v.shape) for k, v in flat}

    exp_map = paths_to_shapes(expected)
    got_map = paths_to_shapes(variables)
    missing = sorted(set(exp_map) - set(got_map))
    extra = sorted(set(got_map) - set(exp_map))
    bad = [k for k in exp_map.keys() & got_map.keys() if tuple(exp_map[k]) != tuple(got_map[k])]
    if missing or extra or bad:
        raise ValueError(
            "imported Keras weights do not match model structure:\n"
            f"  missing: {missing[:10]}\n  unexpected: {extra[:10]}\n"
            f"  shape mismatch: {[(k, exp_map[k], got_map[k]) for k in bad[:10]]}"
        )


def load_keras_h5(spec: ModelSpec, path: str):
    """One-call import: .h5 file -> flax variables for ``spec``."""
    layers = read_keras_h5(path)
    if spec.family == "xception":
        return xception_variables_from_keras(spec, layers)
    if spec.family == "resnet50":
        return resnet50_variables_from_keras(spec, layers)
    if spec.family.startswith("efficientnet-"):
        return efficientnet_variables_from_keras(spec, layers)
    raise NotImplementedError(f"Keras import not implemented for {spec.family!r}")
