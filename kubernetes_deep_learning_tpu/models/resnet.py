"""ResNet50 (v1, bottleneck) in flax.linen.

BASELINE.json config 3 serves "ResNet50/ImageNet SavedModel ... via same
gateway path"; the reference itself ships only the Xception clothing model
(reference convert.py:1-6), so this family exists to prove the serving stack
is model-agnostic: any ``ModelSpec`` + registered family exports and serves
through the identical artifact/engine/gateway path.

TPU-first notes: plain NHWC ``nn.Conv`` everywhere (XLA tiles these onto the
MXU), compute dtype is a module argument (bf16 for serving) with f32 params,
and the residual adds fuse into the preceding conv epilogues under XLA.
Layer names mirror ``keras.applications.ResNet50`` (conv1_conv,
conv2_block1_1_conv, ...) so an .h5 importer can map weights structurally the
same way models.keras_import does for Xception.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn

from kubernetes_deep_learning_tpu.models.layers import ClassifierHead, batch_norm

# Keras ResNet50 BatchNormalization epsilon (differs from Xception's 1e-3).
RESNET_BN_EPS = 1.001e-5

# stage -> (bottleneck width, block count); expansion is 4x.
_STAGES = ((64, 3), (128, 4), (256, 6), (512, 3))


class BottleneckBlock(nn.Module):
    """1x1 reduce -> 3x3 -> 1x1 expand, residual add, post-add relu."""

    features: int          # bottleneck width; output is 4 * features
    strides: int = 1
    project: bool = False  # downsample/widen the shortcut with a 1x1 conv
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=True, dtype=self.dtype)
        bn = partial(batch_norm, train, self.dtype, eps=RESNET_BN_EPS)

        shortcut = x
        if self.project:
            shortcut = conv(4 * self.features, (1, 1), strides=self.strides, name="0_conv")(x)
            shortcut = bn("0_bn")(shortcut)

        y = conv(self.features, (1, 1), strides=self.strides, name="1_conv")(x)
        y = nn.relu(bn("1_bn")(y))
        y = conv(self.features, (3, 3), padding="SAME", name="2_conv")(y)
        y = nn.relu(bn("2_bn")(y))
        y = conv(4 * self.features, (1, 1), name="3_conv")(y)
        y = bn("3_bn")(y)
        return nn.relu(y + shortcut)


class ResNet50(nn.Module):
    num_classes: int
    head_hidden: tuple[int, ...] = ()
    dropout_rate: float = 0.0
    dtype: Any = None  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=True, dtype=self.dtype)
        bn = partial(batch_norm, train, self.dtype, eps=RESNET_BN_EPS)

        # Stem: 7x7/2 conv (Keras pads 3px then VALID; SAME matches for 224).
        x = conv(64, (7, 7), strides=2, padding=[(3, 3), (3, 3)], name="conv1_conv")(x)
        x = nn.relu(bn("conv1_bn")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])

        for stage_idx, (features, blocks) in enumerate(_STAGES, start=2):
            for block_idx in range(1, blocks + 1):
                # First block of each stage projects; stage 2 keeps stride 1
                # (the stem's max_pool already downsampled).
                strides = 2 if (block_idx == 1 and stage_idx > 2) else 1
                x = BottleneckBlock(
                    features,
                    strides=strides,
                    project=block_idx == 1,
                    dtype=self.dtype,
                    name=f"conv{stage_idx}_block{block_idx}",
                )(x, train=train)

        return ClassifierHead(
            self.num_classes,
            hidden=self.head_hidden,
            dropout_rate=self.dropout_rate,
            dtype=self.dtype,
            name="head",
        )(x, train=train)
