"""Framework core: one parse per file, shared resolution, suppressions.

The driver parses every production module exactly once into a
:class:`ModuleInfo` (AST + source lines + import aliases + suppression
comments) and hands the same objects to every registered pass.  Passes
implement per-module checks and/or whole-tree finalization (call graphs,
lock-order graphs, cross-file deploy agreement); findings carry
``file:line`` plus a stable rule id so CI can key on them.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE = "kubernetes_deep_learning_tpu"
EXTRA_FILES = ("bench.py",)
SKIP_PARTS = {"tfs_gen", "__pycache__"}

SUPPRESS_RE = re.compile(
    r"#\s*kdlt-lint:\s*disable=([A-Za-z0-9_,\- ]+?)(?:\s+--\s+(?P<why>.*))?\s*$"
)
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")


@dataclass
class Finding:
    rule: str
    rel: str
    line: int
    message: str
    suppressed: bool = False

    def format(self) -> str:
        return f"{self.rel}:{self.line}: [{self.rule}] {self.message}"

    def as_json(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.rel,
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
        }


@dataclass
class Suppression:
    line: int            # line the comment sits on
    applies_to: int      # line whose findings it suppresses
    rules: tuple[str, ...]
    justification: str | None
    used: bool = False


class ModuleInfo:
    """One parsed production module, shared by every pass."""

    def __init__(self, rel: str, src: str, tree: ast.Module | None = None):
        self.rel = rel.replace(os.sep, "/")
        self.src = src
        self.lines = src.splitlines()
        self.tree = tree if tree is not None else ast.parse(src, filename=rel)
        self.suppressions = self._parse_suppressions()
        # name -> dotted module ("np" -> "numpy"); covers `import a.b as c`
        self.module_aliases: dict[str, str] = {}
        # name -> fully-qualified symbol ("Lock" -> "threading.Lock")
        self.symbol_aliases: dict[str, str] = {}
        self._collect_imports()

    # --- imports / resolution ---------------------------------------------

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.module_aliases[a.asname] = a.name
                    else:
                        self.module_aliases[a.name.split(".")[0]] = a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    self.symbol_aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.expr) -> str | None:
        """Fully-qualified dotted name of a Name/Attribute chain, resolved
        through this module's imports; None when the chain has a non-name
        head (calls, subscripts)."""
        parts = dotted(node)
        if not parts:
            return None
        head, rest = parts[0], parts[1:]
        if head in self.module_aliases:
            head = self.module_aliases[head]
        elif head in self.symbol_aliases:
            head = self.symbol_aliases[head]
        return ".".join([head, *rest]) if rest else head

    # --- suppressions ------------------------------------------------------

    def _parse_suppressions(self) -> list[Suppression]:
        out: list[Suppression] = []
        for i, text in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = tuple(
                r.strip() for r in m.group(1).split(",") if r.strip()
            )
            standalone = not text[: m.start()].strip()
            out.append(Suppression(
                line=i,
                applies_to=i + 1 if standalone else i,
                rules=rules,
                justification=m.group("why"),
            ))
        return out

    def is_suppressed(self, rule: str, line: int) -> bool:
        hit = False
        for s in self.suppressions:
            if s.applies_to == line and rule in s.rules:
                s.used = True
                hit = True
        return hit

    # --- annotations -------------------------------------------------------

    def guarded_by_on_line(self, line: int) -> str | None:
        """The ``# guarded-by: <lock>`` annotation on a source line."""
        if 1 <= line <= len(self.lines):
            m = GUARDED_BY_RE.search(self.lines[line - 1])
            if m:
                return m.group(1)
        return None


def dotted(node: ast.expr) -> list[str] | None:
    """["a", "b", "c"] for a Name/Attribute chain ``a.b.c``, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def literal_head(node: ast.expr) -> str | None:
    """The statically-known head of a string argument: the whole string for
    a constant, the leading constant of an f-string, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    return None


class LintContext:
    """Whole-tree state shared across passes: the repo root, every parsed
    module, and a scratch dict passes use between collect and finalize."""

    def __init__(self, repo: str = REPO):
        self.repo = repo
        self.modules: list[ModuleInfo] = []
        self.scratch: dict[str, object] = {}

    def module(self, rel: str) -> ModuleInfo | None:
        rel = rel.replace(os.sep, "/")
        for m in self.modules:
            if m.rel == rel:
                return m
        return None


class LintPass:
    """Base pass: override ``check_module`` (per file) and/or ``finalize``
    (after every module has been seen -- call graphs, cross-file rules)."""

    name = "base"
    # every rule id this pass can emit, for --list-rules and the
    # unused-suppression check
    rules: tuple[str, ...] = ()

    def check_module(self, mod: ModuleInfo, ctx: LintContext) -> list[Finding]:
        return []

    def finalize(self, ctx: LintContext) -> list[Finding]:
        return []


def iter_production_files(repo: str = REPO) -> list[str]:
    files: list[str] = [
        os.path.join(repo, f)
        for f in EXTRA_FILES
        if os.path.exists(os.path.join(repo, f))
    ]
    for dirpath, dirnames, filenames in os.walk(os.path.join(repo, PACKAGE)):
        dirnames[:] = [d for d in dirnames if d not in SKIP_PARTS]
        files.extend(
            os.path.join(dirpath, f) for f in sorted(filenames)
            if f.endswith(".py")
        )
    return files


def default_passes() -> list[LintPass]:
    # Imported here so the shims (tools/check_metrics.py, tools/check_env.py)
    # can import their single pass without pulling the whole suite.
    from kdlt_lint.passes.closed_vocab import ClosedVocabPass
    from kdlt_lint.passes.donation import DonationSafetyPass
    from kdlt_lint.passes.env_knobs import EnvKnobsPass
    from kdlt_lint.passes.hotpath import HotPathSyncPass
    from kdlt_lint.passes.locks import LockDisciplinePass
    from kdlt_lint.passes.metrics_names import MetricsNamingPass

    return [
        LockDisciplinePass(),
        HotPathSyncPass(),
        DonationSafetyPass(),
        ClosedVocabPass(),
        MetricsNamingPass(),
        EnvKnobsPass(),
    ]


def run_lint(
    passes: list[LintPass] | None = None,
    repo: str = REPO,
    files: list[str] | None = None,
) -> list[Finding]:
    """Parse every production file once, run every pass, apply suppressions.

    Returns ALL findings; suppressed ones carry ``suppressed=True``.  The
    unused-suppression check runs last so a comment that suppressed nothing
    is itself reported.
    """
    if passes is None:
        passes = default_passes()
    ctx = LintContext(repo)
    findings: list[Finding] = []
    for path in files if files is not None else iter_production_files(repo):
        rel = os.path.relpath(path, repo)
        with open(path) as f:
            src = f.read()
        try:
            ctx.modules.append(ModuleInfo(rel, src))
        except SyntaxError as e:
            findings.append(Finding("parse", rel, e.lineno or 0, f"unparsable: {e}"))
    for p in passes:
        for mod in ctx.modules:
            findings.extend(p.check_module(mod, ctx))
    for p in passes:
        findings.extend(p.finalize(ctx))
    by_rel = {m.rel: m for m in ctx.modules}
    for f in findings:
        mod = by_rel.get(f.rel)
        if mod is not None and mod.is_suppressed(f.rule, f.line):
            f.suppressed = True
    known_rules = {r for p in passes for r in p.rules}
    for mod in ctx.modules:
        for s in mod.suppressions:
            if not s.used and any(r in known_rules for r in s.rules):
                findings.append(Finding(
                    "unused-suppression", mod.rel, s.line,
                    f"suppression for {', '.join(s.rules)} matched no finding; "
                    "remove it (stale suppressions hide future regressions)",
                ))
    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    return findings
