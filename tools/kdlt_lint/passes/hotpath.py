"""Hot-path host-sync pass.

The serving forward path -- everything reachable from the dispatcher's
``submit`` and the engine's ``predict_async`` via the in-package call graph
-- must never block on the device or serialize host work it does not have
to:

- ``hot-path-sync``: no ``np.asarray`` / ``np.array`` on the dispatch side,
  no ``.block_until_ready()``, no ``.item()``, no ``float(...)`` of a
  non-constant (the classic implicit device sync);
- ``lock-around-jit``: no jitted call (an attribute built by ``jax.jit`` /
  ``_donate_jit``, i.e. any ``self.*jit*`` callable) invoked while holding
  a lock, unless the lock exists precisely to serialize the enqueue (which
  must then be suppressed with a justification at the site).

Roots are seeded by name below; the closure follows ``self.method()``
calls, same-module functions, and ``module_alias.function()`` calls into
other package modules.  Calls through untyped parameters are not followed
-- the roots list names both sides of such seams explicitly.
"""

from __future__ import annotations

import ast

from kdlt_lint.core import (
    PACKAGE,
    Finding,
    LintContext,
    LintPass,
    ModuleInfo,
    dotted,
)

# (rel, class-or-None, function): the forward path's entry points.
HOT_PATH_ROOTS = (
    (f"{PACKAGE}/runtime/engine.py", "InFlightDispatcher", "submit"),
    (f"{PACKAGE}/runtime/engine.py", "InferenceEngine", "predict_async"),
    # The mesh/cross-host forward entry: the leader's broadcast+dispatch
    # half is what overlaps round N+1 with round N's collective, so a host
    # sync here stalls the whole fleet's pipeline, not one process.
    (f"{PACKAGE}/parallel/crosshost.py", "CrossHostForward", "predict_async"),
    # The decode token loop's per-step dispatch: one host sync here is
    # paid EVERY token of EVERY active generation, so the step must stay
    # async -- materialization happens once per iteration in the scheduler
    # loop (emit_tokens), never inside the step dispatch itself.
    (f"{PACKAGE}/runtime/decode.py", "DecodeEngine", "step_async"),
    # Raw-bytes ingest (GUIDE 10q): the model tier's decode-stage entry
    # and the engine's fused-ingest dispatch surface.  decode_batch runs
    # pre-dispatch by design -- its intentional host materializations
    # carry explicit suppressions in ops/preprocess.py; anything NEW that
    # blocks on device work from these roots is flagged.
    (f"{PACKAGE}/ops/preprocess.py", "BatchDecoder", "decode_batch"),
    (f"{PACKAGE}/runtime/engine.py", "InferenceEngine", "predict_ingest_async"),
    (f"{PACKAGE}/parallel/crosshost.py", "CrossHostForward", "predict_encoded_async"),
)

SYNC_NP_FUNCS = {"numpy.asarray", "numpy.array"}
LOCK_FACTORIES = {"threading.Lock", "threading.RLock", "threading.Condition"}


def _rel_to_dotted(rel: str) -> str | None:
    rel = rel.replace("\\", "/")
    if not rel.startswith(PACKAGE + "/") or not rel.endswith(".py"):
        return None
    mod = rel[: -len(".py")].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class _FnInfo:
    def __init__(self, key):
        self.key = key                      # (rel, cls|None, name)
        self.calls: list[tuple] = []        # ("self"|"module", target)
        self.sync_sites: list[tuple[int, str]] = []
        self.jit_under_lock: list[int] = []


class HotPathSyncPass(LintPass):
    name = "hot-path"
    rules = ("hot-path-sync", "lock-around-jit")

    def check_module(self, mod: ModuleInfo, ctx: LintContext) -> list[Finding]:
        fns: dict = ctx.scratch.setdefault("hotpath.fns", {})
        dotted_mod = _rel_to_dotted(mod.rel)

        def scan_function(fn, cls_name: str | None, jit_attrs: set[str],
                          lock_attrs: set[str]) -> None:
            key = (mod.rel, cls_name, fn.name)
            info = _FnInfo(key)
            fns[key] = info
            self._scan_body(mod, fn, info, jit_attrs, lock_attrs, dotted_mod)

        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_function(node, None, set(), set())
            elif isinstance(node, ast.ClassDef):
                jit_attrs: set[str] = set()
                lock_attrs: set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign):
                        for tgt in sub.targets:
                            if (
                                isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"
                            ):
                                if isinstance(sub.value, ast.Call):
                                    resolved = mod.resolve(sub.value.func) or ""
                                    if resolved in LOCK_FACTORIES:
                                        lock_attrs.add(tgt.attr)
                                    elif "jit" in resolved.split(".")[-1].lower():
                                        jit_attrs.add(tgt.attr)
                                if "jit" in tgt.attr.lower():
                                    jit_attrs.add(tgt.attr)
                for fn in node.body:
                    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        scan_function(fn, node.name, jit_attrs, lock_attrs)
        return []

    def _scan_body(self, mod: ModuleInfo, fn, info: _FnInfo,
                   jit_attrs: set[str], lock_attrs: set[str],
                   dotted_mod: str | None) -> None:
        held_depth = [0]

        def walk(node, in_lock: bool) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquires = False
                for item in node.items:
                    expr = item.context_expr
                    if (
                        isinstance(expr, ast.Attribute)
                        and isinstance(expr.value, ast.Name)
                        and expr.value.id == "self"
                        and (expr.attr in lock_attrs or "lock" in expr.attr)
                    ):
                        acquires = True
                for child in ast.iter_child_nodes(node):
                    walk(child, in_lock or acquires)
                return
            if isinstance(node, ast.Call):
                self._scan_call(mod, node, info, jit_attrs, dotted_mod, in_lock)
            for child in ast.iter_child_nodes(node):
                walk(child, in_lock)

        for stmt in fn.body:
            walk(stmt, False)

    def _scan_call(self, mod: ModuleInfo, node: ast.Call, info: _FnInfo,
                   jit_attrs: set[str], dotted_mod: str | None,
                   in_lock: bool) -> None:
        fnode = node.func
        resolved = mod.resolve(fnode) or ""
        # --- call-graph edges ---
        if (
            isinstance(fnode, ast.Attribute)
            and isinstance(fnode.value, ast.Name)
            and fnode.value.id == "self"
        ):
            info.calls.append(("self", fnode.attr))
            if fnode.attr in jit_attrs and in_lock:
                info.jit_under_lock.append(node.lineno)
        elif isinstance(fnode, ast.Name):
            if dotted_mod is not None:
                info.calls.append(("module", (mod.rel, fnode.id)))
        elif isinstance(fnode, ast.Attribute) and resolved.startswith(PACKAGE + "."):
            target_mod, _, name = resolved.rpartition(".")
            info.calls.append(("module", (target_mod.replace(".", "/") + ".py", name)))
        # --- sync sites ---
        if resolved in SYNC_NP_FUNCS:
            info.sync_sites.append((node.lineno, f"{resolved}() host materialization"))
        elif isinstance(fnode, ast.Attribute) and fnode.attr == "block_until_ready":
            info.sync_sites.append((node.lineno, ".block_until_ready() device sync"))
        elif isinstance(fnode, ast.Attribute) and fnode.attr == "item" and not node.args:
            info.sync_sites.append((node.lineno, ".item() scalar device sync"))
        elif (
            isinstance(fnode, ast.Name)
            and fnode.id == "float"
            and node.args
            and not isinstance(node.args[0], ast.Constant)
        ):
            info.sync_sites.append(
                (node.lineno, "float(...) of a runtime value (implicit device sync)")
            )

    def finalize(self, ctx: LintContext) -> list[Finding]:
        fns: dict = ctx.scratch.get("hotpath.fns", {})
        # closure over the call graph from the seeded roots
        reachable: dict[tuple, tuple] = {}  # key -> root it was reached from
        work = [(root, root) for root in HOT_PATH_ROOTS if root in fns]
        while work:
            key, root = work.pop()
            if key in reachable:
                continue
            reachable[key] = root
            info = fns[key]
            rel, cls, _name = key
            for kind, target in info.calls:
                if kind == "self" and cls is not None:
                    nxt = (rel, cls, target)
                    if nxt in fns:
                        work.append((nxt, root))
                elif kind == "module":
                    t_rel, t_name = target
                    nxt = (t_rel, None, t_name)
                    if nxt in fns:
                        work.append((nxt, root))
        findings: list[Finding] = []
        for key, root in sorted(reachable.items(), key=str):
            info = fns[key]
            rel, cls, name = key
            qual = f"{cls}.{name}" if cls else name
            root_qual = f"{root[1]}.{root[2]}" if root[1] else root[2]
            for line, what in info.sync_sites:
                findings.append(Finding(
                    "hot-path-sync", rel, line,
                    f"{what} in {qual}, which is on the serving hot path "
                    f"(reachable from {root_qual}); host syncs here "
                    "serialize the dispatch pipeline",
                ))
            for line in info.jit_under_lock:
                findings.append(Finding(
                    "lock-around-jit", rel, line,
                    f"jitted call under a lock in {qual} (hot path via "
                    f"{root_qual}); holding a lock across dispatch "
                    "serializes callers against device work",
                ))
        return findings
