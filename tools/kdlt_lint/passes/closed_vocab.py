"""Closed-vocabulary pass.

Span names, fault-injection points, flight-recorder event kinds, and
incident trigger names are operational contracts: dashboards,
``kdlt-doctor`` and the trace tooling key on the exact strings.  Each
vocabulary has exactly one declaring registry; any string literal used at a
recording/firing call site must be a member:

- span names          -> ``utils/trace.py``          ``SPAN_NAMES``
- fault points        -> ``serving/faults.py``       ``FAULT_POINTS``
- event kinds         -> ``utils/flightrecorder.py`` ``EVENT_KINDS``
- incident triggers   -> ``utils/flightrecorder.py`` ``TRIGGER_RULES``
- sharding schemes    -> ``parallel/mesh.py``        ``SHARDING_SCHEMES``

The registries are extracted from the AST (module-level assignments of
string-literal collections, with module-level ``NAME = "literal"``
constants resolved), so the pass needs no imports of the production tree.

Call-site dispatch is by receiver shape: ``*.span("x")`` and
``*tracer.record(rid, "x", ...)`` / ``*trace.record("x", ...)`` are span
sites; ``*recorder.record("x", ...)`` (and ``self.record`` /
``self._emit`` inside the recorder/pool modules) are event-kind sites;
``*.fire("x")`` / ``*.corrupt("x", ...)`` are fault points;
``*.trigger_threshold("x", ...)`` is a trigger name.  Non-literal
arguments are skipped -- they are validated at runtime by the registries
themselves.
"""

from __future__ import annotations

import ast

from kdlt_lint.core import (
    PACKAGE,
    Finding,
    LintContext,
    LintPass,
    ModuleInfo,
    dotted,
)

TRACE_MODULE = f"{PACKAGE}/utils/trace.py"
FAULTS_MODULE = f"{PACKAGE}/serving/faults.py"
RECORDER_MODULE = f"{PACKAGE}/utils/flightrecorder.py"
MESH_MODULE = f"{PACKAGE}/parallel/mesh.py"

VOCABS = (
    ("span", TRACE_MODULE, "SPAN_NAMES"),
    ("fault-point", FAULTS_MODULE, "FAULT_POINTS"),
    ("event-kind", RECORDER_MODULE, "EVENT_KINDS"),
    ("trigger", RECORDER_MODULE, "TRIGGER_RULES"),
    # Sharding-scheme tags (registry status / GET /v1/models key on them).
    ("sharding", MESH_MODULE, "SHARDING_SCHEMES"),
    # Ingest wire capabilities (the X-Kdlt-Ingest negotiation tokens,
    # GUIDE 10q): gateway.supports_ingest call sites must name a
    # registered capability.
    ("ingest-cap", f"{PACKAGE}/serving/protocol.py", "INGEST_CAPS"),
)

# Modules whose bare self.record / self._emit / self.fire calls are
# in-registry emitters rather than consumer call sites.
SELF_EMITTER_MODULES = {
    RECORDER_MODULE: "event-kind",
    f"{PACKAGE}/serving/upstream.py": "event-kind",
}


def extract_vocab(mod: ModuleInfo, name: str) -> frozenset[str] | None:
    """Evaluate a module-level registry assignment into a set of strings."""
    consts: dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            if isinstance(node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        consts[tgt.id] = node.value.value

    def ev(node: ast.expr) -> list[str] | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, ast.Name) and node.id in consts:
            return [consts[node.id]]
        if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
            out: list[str] = []
            for e in node.elts:
                got = ev(e)
                if got is None:
                    return None
                out.extend(got)
            return out
        if isinstance(node, ast.Dict):
            out = []
            for k in node.keys:
                got = ev(k) if k is not None else None
                if got is None:
                    return None
                out.extend(got)
            return out
        if isinstance(node, ast.Call):
            parts = dotted(node.func)
            if parts and parts[-1] in ("frozenset", "set", "tuple", "dict") and node.args:
                return ev(node.args[0])
        return None

    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    got = ev(node.value)
                    if got is not None:
                        return frozenset(got)
    return None


class ClosedVocabPass(LintPass):
    name = "closed-vocab"
    rules = ("closed-vocab",)

    def _vocabs(self, ctx: LintContext) -> dict[str, frozenset[str] | None]:
        cached = ctx.scratch.get("vocab.sets")
        if cached is None:
            cached = {}
            for vocab, rel, reg in VOCABS:
                mod = ctx.module(rel)
                cached[vocab] = extract_vocab(mod, reg) if mod else None
            ctx.scratch["vocab.sets"] = cached
        return cached

    def check_module(self, mod: ModuleInfo, ctx: LintContext) -> list[Finding]:
        vocabs = self._vocabs(ctx)
        findings: list[Finding] = []

        def member(vocab: str, value: str, line: int, what: str) -> None:
            known = vocabs.get(vocab)
            if known is None:
                findings.append(Finding(
                    "closed-vocab", mod.rel, line,
                    f"{what} {value!r} used but the {vocab} registry is "
                    "missing from its declaring module",
                ))
            elif value not in known:
                findings.append(Finding(
                    "closed-vocab", mod.rel, line,
                    f"{what} {value!r} is not in the declared {vocab} "
                    f"vocabulary; add it to the registry or fix the typo",
                ))

        def lit(node: ast.expr | None) -> str | None:
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                return node.value
            return None

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            recv = dotted(node.func.value)  # e.g. ["self", "recorder"]
            recv_tail = recv[-1] if recv else None
            arg0 = lit(node.args[0]) if node.args else None
            if meth == "span":
                if arg0 is not None:
                    member("span", arg0, node.lineno, "span name")
            elif meth in ("fire", "corrupt"):
                if arg0 is not None:
                    member("fault-point", arg0, node.lineno, "fault point")
            elif meth == "trigger_threshold":
                if arg0 is not None:
                    member("trigger", arg0, node.lineno, "incident trigger")
            elif meth == "sharding_scheme":
                if arg0 is not None:
                    member("sharding", arg0, node.lineno, "sharding scheme")
            elif meth == "supports_ingest":
                if arg0 is not None:
                    member("ingest-cap", arg0, node.lineno, "ingest capability")
            elif meth == "record" and recv_tail is not None:
                if recv_tail == "recorder" or (
                    recv == ["self"] and SELF_EMITTER_MODULES.get(mod.rel) == "event-kind"
                ):
                    if arg0 is not None:
                        member("event-kind", arg0, node.lineno, "event kind")
                elif recv_tail == "tracer":
                    name = lit(node.args[1]) if len(node.args) > 1 else None
                    if name is not None:
                        member("span", name, node.lineno, "span name")
                elif recv_tail in ("trace", "tr", "rt", "pt") or (
                    recv is not None and recv[-1] == "trace"
                ):
                    if arg0 is not None:
                        member("span", arg0, node.lineno, "span name")
            elif meth == "_emit" and recv == ["self"]:
                if SELF_EMITTER_MODULES.get(mod.rel) == "event-kind" and arg0 is not None:
                    member("event-kind", arg0, node.lineno, "event kind")
        return findings
