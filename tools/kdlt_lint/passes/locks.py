"""Lock-discipline pass.

Three rules over the same per-class model:

- ``guarded-by``: an attribute whose declaration carries a
  ``# guarded-by: _lock`` annotation may only be read or written inside
  ``with self._lock:`` (or a ``threading.Condition`` constructed over that
  lock).  ``__init__`` is exempt (construction happens before the object is
  published) and so are methods whose name ends in ``_locked`` (the tree's
  convention for "caller already holds the lock").
- ``lock-order``: the cross-class lock-acquisition graph (edges from every
  lock held to every lock acquired under it, following same-class method
  calls and calls through attributes whose class is statically known) must
  be acyclic -- a cycle is a static deadlock.
- ``blocking-under-lock``: no ``time.sleep``, network calls
  (``requests.get/post/...``, ``urllib.request.urlopen``, ``.recv`` /
  ``.accept``), or ``.result()`` without a timeout while a lock is held.
  ``Condition.wait`` is exempt: it releases the lock.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from kdlt_lint.core import (
    PACKAGE,
    Finding,
    LintContext,
    LintPass,
    ModuleInfo,
    dotted,
)

LOCK_FACTORIES = {"threading.Lock", "threading.RLock", "threading.Condition"}
SOCKET_READ_ATTRS = {"recv", "recv_into", "recvfrom", "accept", "getresponse"}
# Fully-resolved callables that hit the network (constructors like
# requests.Session() are cheap and deliberately NOT listed).
BLOCKING_CALLS = {
    "requests.get", "requests.post", "requests.put", "requests.delete",
    "requests.head", "requests.patch", "requests.request",
    "urllib.request.urlopen",
}
# Attributes whose calls release or merely bound the lock: Condition.wait
# releases it; a bounded .result(timeout) / .join(timeout) is the caller's
# explicit choice and carries the timeout we check for.
EXEMPT_ATTRS = {"wait", "wait_for", "acquire", "release", "notify", "notify_all"}


def _rel_to_dotted(rel: str) -> str | None:
    rel = rel.replace("\\", "/")
    if not rel.startswith(PACKAGE + "/") or not rel.endswith(".py"):
        return None
    mod = rel[: -len(".py")].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _self_attr(node: ast.expr) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclass
class ClassModel:
    rel: str
    name: str
    lock_attrs: set[str] = field(default_factory=set)
    # Condition attr -> the lock attr it wraps (Condition(self._lock))
    cond_proxy: dict[str, str] = field(default_factory=dict)
    guarded: dict[str, str] = field(default_factory=dict)  # attr -> lock attr
    guard_lines: dict[str, int] = field(default_factory=dict)
    # attr -> (rel, ClassName) of the instance assigned to it, when the
    # constructor call is statically resolvable to an in-tree class
    attr_types: dict[str, tuple[str, str]] = field(default_factory=dict)
    # method -> [(lock, locks held lexically at the acquire, line)]
    acquires: dict[str, list[tuple[str, frozenset[str], int]]] = field(default_factory=dict)
    # method -> [(kind, name, locks held at the call, line)]
    #   kind: "self" (self.m()), "attr" ((attrname, m)), "ext" (resolved dotted)
    calls: dict[str, list[tuple[str, object, frozenset[str], int]]] = field(default_factory=dict)

    @property
    def key(self) -> tuple[str, str]:
        return (self.rel, self.name)

    def holds(self, held: frozenset[str], lock: str) -> bool:
        if lock in held:
            return True
        return any(self.cond_proxy.get(h) == lock for h in held)


class _MethodVisitor(ast.NodeVisitor):
    """Walk one method tracking the lexically-held lock set."""

    def __init__(self, pass_, mod: ModuleInfo, cm: ClassModel, fn: ast.FunctionDef):
        self.p = pass_
        self.mod = mod
        self.cm = cm
        self.fn = fn
        self.held: tuple[str, ...] = ()
        self.findings: list[Finding] = []
        self.check_guards = not (
            fn.name == "__init__" or fn.name.endswith("_locked")
        )

    def _frozen(self) -> frozenset[str]:
        return frozenset(self.held)

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    def _visit_with(self, node) -> None:
        acquired: list[str] = []
        for item in node.items:
            expr = item.context_expr
            attr = _self_attr(expr)
            if attr is not None and attr in self.cm.lock_attrs:
                self.cm.acquires.setdefault(self.fn.name, []).append(
                    (attr, self._frozen(), expr.lineno)
                )
                acquired.append(attr)
            # still visit the context expression itself (it may read
            # guarded attributes, e.g. `with self._flights[key]:`)
            self.visit(expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held = self.held + tuple(acquired)
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            self.held = self.held[: len(self.held) - len(acquired)]

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if (
            self.check_guards
            and attr is not None
            and attr in self.cm.guarded
        ):
            lock = self.cm.guarded[attr]
            if not self.cm.holds(self._frozen(), lock):
                self.findings.append(Finding(
                    "guarded-by", self.mod.rel, node.lineno,
                    f"self.{attr} is declared guarded-by {lock} "
                    f"({self.mod.rel}:{self.cm.guard_lines.get(attr, 0)}) but "
                    f"is touched in {self.cm.name}.{self.fn.name} without "
                    f"holding self.{lock}",
                ))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        held = self._frozen()
        fn = node.func
        # record the call for the lock-order graph
        attr = _self_attr(fn)
        if attr is not None:
            self.cm.calls.setdefault(self.fn.name, []).append(
                ("self", attr, held, node.lineno)
            )
        elif isinstance(fn, ast.Attribute):
            recv_attr = _self_attr(fn.value)
            if recv_attr is not None:
                self.cm.calls.setdefault(self.fn.name, []).append(
                    ("attr", (recv_attr, fn.attr), held, node.lineno)
                )
        if held:
            self._check_blocking(node, held)
        self.generic_visit(node)

    def _check_blocking(self, node: ast.Call, held: frozenset[str]) -> None:
        fn = node.func
        resolved = self.mod.resolve(fn) or ""
        where = f"while holding self.{'/self.'.join(sorted(held))}"
        if resolved == "time.sleep":
            self.findings.append(Finding(
                "blocking-under-lock", self.mod.rel, node.lineno,
                f"time.sleep() {where}; sleeping under a lock stalls every "
                "waiter for the full duration",
            ))
            return
        if resolved in BLOCKING_CALLS:
            self.findings.append(Finding(
                "blocking-under-lock", self.mod.rel, node.lineno,
                f"network call {resolved}() {where}; socket reads under a "
                "lock stall every waiter on the peer's latency",
            ))
            return
        if isinstance(fn, ast.Attribute) and fn.attr in EXEMPT_ATTRS:
            return
        if isinstance(fn, ast.Attribute) and fn.attr in SOCKET_READ_ATTRS:
            self.findings.append(Finding(
                "blocking-under-lock", self.mod.rel, node.lineno,
                f".{fn.attr}() {where}; socket reads under a lock stall "
                "every waiter on the peer's latency",
            ))
            return
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr == "result"
            and not node.args
            and not any(kw.arg == "timeout" for kw in node.keywords)
        ):
            self.findings.append(Finding(
                "blocking-under-lock", self.mod.rel, node.lineno,
                f".result() without a timeout {where}; an unbounded future "
                "wait under a lock can deadlock against the completer",
            ))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested defs inherit the lexical held set (closures run later, but
        # flagging a guarded access inside one is conservative-correct for
        # this tree, where nested defs run inline or on unlocked threads)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


class LockDisciplinePass(LintPass):
    name = "lock-discipline"
    rules = ("guarded-by", "lock-order", "blocking-under-lock")

    def check_module(self, mod: ModuleInfo, ctx: LintContext) -> list[Finding]:
        findings: list[Finding] = []
        models: list[ClassModel] = ctx.scratch.setdefault("lock.models", [])
        for cls in [n for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)]:
            cm = self._build_class_model(mod, cls)
            models.append(cm)
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    v = _MethodVisitor(self, mod, cm, fn)
                    for stmt in fn.body:
                        v.visit(stmt)
                    findings.extend(v.findings)
        return findings

    def _build_class_model(self, mod: ModuleInfo, cls: ast.ClassDef) -> ClassModel:
        cm = ClassModel(mod.rel, cls.name)
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            value = node.value
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if isinstance(value, ast.Call):
                    resolved = mod.resolve(value.func) or ""
                    if resolved in LOCK_FACTORIES:
                        cm.lock_attrs.add(attr)
                        if resolved.endswith("Condition") and value.args:
                            wrapped = _self_attr(value.args[0])
                            if wrapped is not None:
                                cm.cond_proxy[attr] = wrapped
                    else:
                        cls_key = self._class_of(mod, value.func)
                        if cls_key is not None:
                            cm.attr_types[attr] = cls_key
                lock = mod.guarded_by_on_line(node.lineno)
                if lock is not None:
                    cm.guarded[attr] = lock
                    cm.guard_lines[attr] = node.lineno
        return cm

    def _class_of(self, mod: ModuleInfo, func: ast.expr) -> tuple[str, str] | None:
        """(rel, ClassName) when ``func`` names a class defined in the
        scanned tree (same module, or imported from a package module)."""
        parts = dotted(func)
        if not parts:
            return None
        resolved = mod.resolve(func) or ""
        if resolved.startswith(PACKAGE + "."):
            dotted_mod, _, name = resolved.rpartition(".")
            rel = dotted_mod.replace(".", "/") + ".py"
            return (rel, name)
        if len(parts) == 1:
            return (mod.rel, parts[0])  # same-module class (verified later)
        return None

    # --- lock-order graph --------------------------------------------------

    def finalize(self, ctx: LintContext) -> list[Finding]:
        models: list[ClassModel] = ctx.scratch.get("lock.models", [])
        by_key = {cm.key: cm for cm in models}
        edges: dict[str, set[str]] = {}
        sites: dict[tuple[str, str], tuple[str, int]] = {}

        def node_id(cm: ClassModel, lock: str) -> str:
            return f"{cm.name}.{lock}"

        def add_edge(a: str, b: str, rel: str, line: int) -> None:
            if a == b:
                return
            edges.setdefault(a, set()).add(b)
            sites.setdefault((a, b), (rel, line))

        def walk(cm: ClassModel, method: str, held_nodes: frozenset[str],
                 depth: int, seen: set) -> None:
            if depth > 4 or (cm.key, method, held_nodes) in seen:
                return
            seen.add((cm.key, method, held_nodes))
            for lock, local_held, line in cm.acquires.get(method, ()):  # direct
                target = node_id(cm, lock)
                context = held_nodes | {node_id(cm, l) for l in local_held}
                for h in context:
                    add_edge(h, target, cm.rel, line)
            for kind, name, local_held, _line in cm.calls.get(method, ()):
                context = held_nodes | {node_id(cm, l) for l in local_held}
                if kind == "self":
                    if name in cm.acquires or name in cm.calls:
                        walk(cm, name, frozenset(context), depth + 1, seen)
                elif kind == "attr":
                    attr, meth = name
                    target_key = cm.attr_types.get(attr)
                    target = by_key.get(target_key) if target_key else None
                    if target is not None and (
                        meth in target.acquires or meth in target.calls
                    ):
                        walk(target, meth, frozenset(context), depth + 1, seen)

        seen: set = set()
        for cm in models:
            for method in set(cm.acquires) | set(cm.calls):
                walk(cm, method, frozenset(), 0, seen)

        return self._find_cycles(edges, sites)

    def _find_cycles(self, edges, sites) -> list[Finding]:
        findings: list[Finding] = []
        # iterative Tarjan SCC; any SCC of size > 1 is a potential deadlock
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        onstack: set[str] = set()
        stack: list[str] = []
        counter = [0]
        sccs: list[list[str]] = []

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(edges.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            onstack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        onstack.add(w)
                        work.append((w, iter(sorted(edges.get(w, ())))))
                        advanced = True
                        break
                    elif w in onstack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        onstack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(scc)

        for v in sorted(edges):
            if v not in index:
                strongconnect(v)
        for scc in sccs:
            members = sorted(scc)
            pairs = [
                (a, b) for a in members for b in edges.get(a, ())
                if b in scc
            ]
            rel, line = sites.get(pairs[0], ("<tree>", 0)) if pairs else ("<tree>", 0)
            findings.append(Finding(
                "lock-order", rel, line,
                "lock-acquisition-order cycle between "
                f"{' and '.join(members)}: two threads taking these locks "
                "in opposite orders deadlock; impose one global order",
            ))
        return findings
