"""Env-knob pass: tools/check_env.py's rules on the framework.

Every whole-string ``KDLT_*`` literal in production code must be
documented in GUIDE.md; deploy-manifest keys must be read by code; the
compose replica pair must be identical; and each tier's compose/k8s
mirrors must agree modulo DEPLOY_AGREEMENT's declared drift allowances.

DEPLOY_AGREEMENT is the pass's declarative config: the tier mirror map and
the two drift lists are data here, not logic in the checker -- adding an
allowance is a one-line config change (tools/check_env.py re-exports them
for its tests).
"""

from __future__ import annotations

import ast
import os
import re

from kdlt_lint.core import Finding, LintContext, LintPass, ModuleInfo

GUIDE = "GUIDE.md"
ENV_RE = re.compile(r"KDLT_[A-Z0-9_]+\Z")

COMPOSE = os.path.join("deploy", "docker-compose.yaml")
K8S_GATEWAY = os.path.join("deploy", "k8s", "gateway-deployment.yaml")
K8S_MODEL = os.path.join("deploy", "k8s", "model-server-deployment.yaml")

# Declarative deploy-agreement config: which compose services mirror which
# k8s manifest, which replica pairs must match exactly, and which knobs may
# legitimately drift between environments.
DEPLOY_AGREEMENT = {
    # (tier name, compose service names, k8s manifest)
    "tiers": (
        ("gateway", ("gateway",), K8S_GATEWAY),
        ("model-server", ("model-server", "model-server-b"), K8S_MODEL),
    ),
    # compose services that fail over behind one gateway: identical maps
    "replica_pairs": (("model-server", "model-server-b"),),
    # host-ish knobs: the VALUE legitimately differs between compose
    # (service names on the compose network) and k8s (cluster DNS)
    "allow_value_drift": frozenset({"KDLT_SERVING_HOST"}),
    # path-ish knobs tied to a volume only one environment mounts;
    # presence on one side only is fine
    "allow_presence_drift": frozenset({
        "KDLT_COMPILE_CACHE_DIR", "KDLT_PROFILE_DIR",
    }),
}


def env_literals(src: str, rel: str) -> dict[str, int]:
    """Whole-string KDLT_* literals in a module -> first line seen."""
    found: dict[str, int] = {}
    for node in ast.walk(ast.parse(src, filename=rel)):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and ENV_RE.match(node.value)
        ):
            found.setdefault(node.value, node.lineno)
    return found


def compose_env(doc: dict, service: str) -> dict[str, str]:
    svc = (doc.get("services") or {}).get(service) or {}
    env = svc.get("environment") or {}
    if isinstance(env, list):  # compose also allows ["K=V", ...]
        env = dict(item.split("=", 1) for item in env)
    return {k: str(v) for k, v in env.items() if k.startswith("KDLT_")}


def k8s_env(doc: dict) -> dict[str, str]:
    tmpl = doc.get("spec", {}).get("template", {}).get("spec", {})
    out: dict[str, str] = {}
    for container in tmpl.get("containers") or []:
        for item in container.get("env") or []:
            name = item.get("name", "")
            if name.startswith("KDLT_"):
                out[name] = str(item.get("value", ""))
    return out


class EnvKnobsPass(LintPass):
    name = "env"
    rules = ("env-knobs",)

    def check_module(self, mod: ModuleInfo, ctx: LintContext) -> list[Finding]:
        code_envs: dict = ctx.scratch.setdefault("env.code_envs", {})
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and ENV_RE.match(node.value)
            ):
                code_envs.setdefault(node.value, (mod.rel, node.lineno))
        return []

    def finalize(self, ctx: LintContext) -> list[Finding]:
        findings: list[Finding] = []
        code_envs: dict = ctx.scratch.get("env.code_envs", {})

        guide_path = os.path.join(ctx.repo, GUIDE)
        with open(guide_path) as f:
            guide_text = f.read()
        for name in sorted(code_envs):
            rel, line = code_envs[name]
            if name not in guide_text:
                findings.append(Finding(
                    "env-knobs", rel, line,
                    f"{name} is read by production code but "
                    f"never mentioned in {GUIDE}; document the knob",
                ))

        import yaml

        with open(os.path.join(ctx.repo, COMPOSE)) as f:
            compose_doc = yaml.safe_load(f)
        k8s_docs = {}
        for manifest in (K8S_GATEWAY, K8S_MODEL):
            with open(os.path.join(ctx.repo, manifest)) as f:
                k8s_docs[manifest] = yaml.safe_load(f)

        deploy_maps: list[tuple[str, dict[str, str]]] = []
        for tier, services, manifest in DEPLOY_AGREEMENT["tiers"]:
            for svc in services:
                deploy_maps.append(
                    (f"{COMPOSE}:{svc}", compose_env(compose_doc, svc))
                )
            deploy_maps.append((manifest, k8s_env(k8s_docs[manifest])))
        for where, env in deploy_maps:
            for name in sorted(env):
                if name not in code_envs:
                    findings.append(Finding(
                        "env-knobs", where.split(":")[0], 0,
                        f"{where}: {name} is set but no production code reads "
                        "it (typo'd knob names are silently ignored at runtime)",
                    ))

        for pair_names in DEPLOY_AGREEMENT["replica_pairs"]:
            pair = [compose_env(compose_doc, s) for s in pair_names]
            if pair[0] != pair[1]:
                diff = sorted(set(pair[0].items()) ^ set(pair[1].items()))
                findings.append(Finding(
                    "env-knobs", COMPOSE, 0,
                    f"{COMPOSE}: {' and '.join(pair_names)} disagree on "
                    f"{sorted({k for k, _ in diff})}; the gateway fails over "
                    "between them, so their KDLT_* maps must be identical",
                ))

        allow_presence = DEPLOY_AGREEMENT["allow_presence_drift"]
        allow_value = DEPLOY_AGREEMENT["allow_value_drift"]
        for tier, services, manifest in DEPLOY_AGREEMENT["tiers"]:
            c_env = compose_env(compose_doc, services[0])
            k_env = k8s_env(k8s_docs[manifest])
            for name in sorted(set(c_env) | set(k_env)):
                if name in allow_presence:
                    continue
                if name not in c_env or name not in k_env:
                    missing = COMPOSE if name not in c_env else manifest
                    findings.append(Finding(
                        "env-knobs", missing, 0,
                        f"{tier}: {name} is wired in one environment but "
                        f"missing from {missing}; compose and k8s mirrors of "
                        "a tier must set the same knobs",
                    ))
                elif name not in allow_value and c_env[name] != k_env[name]:
                    findings.append(Finding(
                        "env-knobs", COMPOSE, 0,
                        f"{tier}: {name} disagrees between {COMPOSE} "
                        f"({c_env[name]!r}) and {manifest} ({k_env[name]!r})",
                    ))
        ctx.scratch["env.knob_count"] = len(code_envs)
        return findings
