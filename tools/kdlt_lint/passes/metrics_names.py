"""Metrics-naming pass: tools/check_metrics.py's rules on the framework.

Same rules, same message text (tools/check_metrics.py is now a shim over
this pass and its tests assert on these strings): every series is
kdlt_-prefixed and minted through the central helpers in utils/metrics.py;
bounded labels and the central prefixes stay confined to that module;
exemplars attach to histograms only.
"""

from __future__ import annotations

import ast

from kdlt_lint.core import (
    PACKAGE,
    Finding,
    LintContext,
    LintPass,
    ModuleInfo,
    literal_head,
)

METRIC_PREFIX = "kdlt_"
MINT_METHODS = {"counter", "gauge", "histogram"}
METRIC_CLASSES = {"Counter", "Gauge", "Histogram"}
CENTRAL_LABELS = {
    "model", "window", "class", "reason", "scheme", "source",
    "stage", "direction", "trigger", "axis",
}
CENTRAL_PREFIXES = (
    "kdlt_slo_", "kdlt_cache_", "kdlt_quant_", "kdlt_pool_", "kdlt_brownout_",
    "kdlt_incident_", "kdlt_mesh_", "kdlt_decode_", "kdlt_ingest_",
)
CENTRAL_NAMES = ("kdlt_engine_warm_source",)
METRICS_MODULE = f"{PACKAGE}.utils.metrics"


def _name_arg(call: ast.Call) -> ast.expr | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


class MetricsNamingPass(LintPass):
    name = "metrics"
    rules = ("metrics-naming",)

    def check_module(self, mod: ModuleInfo, ctx: LintContext) -> list[Finding]:
        violations: list[Finding] = []
        tree = mod.tree
        rel = mod.rel

        def flag(line: int, message: str) -> None:
            violations.append(Finding("metrics-naming", rel, line, message))

        # Aliases under which this module can reach the metric classes.
        metrics_module_aliases: set[str] = set()
        metric_class_aliases: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == METRICS_MODULE:
                        metrics_module_aliases.add((a.asname or a.name).split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                if node.module == METRICS_MODULE.rsplit(".", 1)[0]:
                    for a in node.names:
                        if a.name == "metrics":
                            metrics_module_aliases.add(a.asname or a.name)
                elif node.module == METRICS_MODULE:
                    for a in node.names:
                        if a.name in METRIC_CLASSES:
                            metric_class_aliases.add(a.asname or a.name)

        is_metrics_module = rel.endswith("utils/metrics.py")
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not is_metrics_module and (
                (isinstance(fn, ast.Name) and fn.id in metric_class_aliases)
                or (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in METRIC_CLASSES
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in metrics_module_aliases
                )
            ):
                cls = fn.id if isinstance(fn, ast.Name) else fn.attr
                flag(
                    node.lineno,
                    f"direct {cls}(...) construction; mint "
                    "through a Registry / the utils.metrics helpers instead",
                )
                continue
            if (
                not is_metrics_module
                and isinstance(fn, ast.Attribute)
                and fn.attr == "with_labels"
            ):
                bounded = {
                    kw.arg for kw in node.keywords if kw.arg in CENTRAL_LABELS
                }
                for kw in node.keywords:
                    if kw.arg is None and isinstance(kw.value, ast.Dict):
                        bounded.update(
                            k.value for k in kw.value.keys
                            if isinstance(k, ast.Constant)
                            and k.value in CENTRAL_LABELS
                        )
                if bounded:
                    labels = ", ".join(sorted(bounded))
                    flag(
                        node.lineno,
                        f".with_labels({labels}=...) outside "
                        "utils/metrics.py; mint bounded labels through the "
                        "central helpers (model_registry / "
                        "slo_model_window_metrics / trace_retention_metrics)",
                    )
                    continue
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("inc", "set")
                and any(kw.arg == "exemplar" for kw in node.keywords)
            ):
                flag(
                    node.lineno,
                    f"exemplar= on .{fn.attr}(); exemplars "
                    "attach to histogram observe() only (non-histogram series "
                    "cannot carry them)",
                )
                continue
            if isinstance(fn, ast.Attribute) and fn.attr in MINT_METHODS:
                arg = _name_arg(node)
                if arg is None:
                    continue
                head = literal_head(arg)
                if head is None:
                    flag(
                        node.lineno,
                        f".{fn.attr}() with a non-literal "
                        "metric name; names must be statically auditable",
                    )
                elif not head.startswith(METRIC_PREFIX):
                    flag(
                        node.lineno,
                        f"metric name {head!r} is not "
                        f"{METRIC_PREFIX}-prefixed",
                    )
                elif not is_metrics_module and (
                    any(head.startswith(p) for p in CENTRAL_PREFIXES)
                    or head in CENTRAL_NAMES
                ):
                    flag(
                        node.lineno,
                        f"{head!r} minted outside "
                        "utils/metrics.py; kdlt_slo_*/kdlt_cache_*/kdlt_quant_*/"
                        "kdlt_pool_*/kdlt_brownout_*/kdlt_incident_*/kdlt_mesh_*/"
                        "kdlt_decode_*/kdlt_ingest_* "
                        "series (and kdlt_engine_warm_source) are minted only by "
                        "the central helpers (bounded label sets by construction)",
                    )
        return violations
