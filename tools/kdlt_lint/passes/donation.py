"""Donation-safety pass.

Buffer donation (``jax.jit(..., donate_argnums=...)``) invalidates the
donated device buffer the moment the program runs; reading the same array
afterwards is a use-after-free that surfaces as intermittent corruption or
a segfault timed by the async dispatch (the PR 9 ``training/checkpoint.py``
bug: the train loop donated ``state`` into the next step while orbax's
background serializer was still reading its device buffers).

Rule ``donation-safety``: inside one function, after an array expression is
passed at a donated position of a donating callable, any later read of the
same name (or ``self.attr``) is flagged until it is reassigned.

Donating callables are recognized as:

- names or ``self`` attributes assigned ``jax.jit(fn, donate_argnums=...)``
  (or ``pjit``) anywhere in the module/class;
- names assigned from a call to an in-module factory whose return statement
  is such a jit (``build_train_step``-style);
- ``_donate_jit(fn, ...)`` -- the engine's helper -- which donates argnum 1
  by contract.
"""

from __future__ import annotations

import ast

from kdlt_lint.core import Finding, LintContext, LintPass, ModuleInfo, dotted

JIT_FUNCS = {"jax.jit", "jax.pjit", "pjit.pjit"}


def _donated_positions(call: ast.Call) -> tuple[int, ...] | None:
    """donate_argnums positions of a jit call, or None when not donating."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, int):
                        out.append(e.value)
                return tuple(out)
            return ()  # dynamic: donating, positions unknown
    return None


def _expr_key(node: ast.expr) -> str | None:
    """A stable key for a donatable argument: a bare name or self.attr."""
    if isinstance(node, ast.Name):
        return node.id
    parts = dotted(node)
    if parts and parts[0] == "self" and len(parts) == 2:
        return f"self.{parts[1]}"
    return None


class DonationSafetyPass(LintPass):
    name = "donation"
    rules = ("donation-safety",)

    def check_module(self, mod: ModuleInfo, ctx: LintContext) -> list[Finding]:
        donating = self._collect_donating(mod)
        findings: list[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(mod, node, donating))
        return findings

    # --- donating-callable discovery --------------------------------------

    def _is_donating_jit(self, mod: ModuleInfo, value: ast.expr):
        """(positions) when ``value`` is a donating jit construction."""
        if not isinstance(value, ast.Call):
            return None
        resolved = mod.resolve(value.func) or ""
        if resolved in JIT_FUNCS or resolved.endswith(".pjit"):
            return _donated_positions(value)
        if resolved.rpartition(".")[2] == "_donate_jit":
            return (1,)  # the engine helper's contract: argnum 1 is donated
        return None

    def _collect_donating(self, mod: ModuleInfo) -> dict[str, tuple[int, ...]]:
        """Names/attrs known to be donating callables, module-wide:
        ``name`` / ``self.name`` -> donated positions."""
        donating: dict[str, tuple[int, ...]] = {}
        factories: dict[str, tuple[int, ...]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) and sub.value is not None:
                        pos = self._is_donating_jit(mod, sub.value)
                        if pos:
                            factories[node.name] = pos
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            pos = self._is_donating_jit(mod, node.value)
            if pos is None and isinstance(node.value, ast.Call):
                resolved = mod.resolve(node.value.func) or ""
                tail = resolved.rpartition(".")[2]
                if tail in factories:
                    pos = factories[tail]

            if not pos:
                continue
            for tgt in node.targets:
                key = _expr_key(tgt)
                if key is not None:
                    donating[key] = pos
                    if key.startswith("self."):
                        donating[key[len("self."):]] = pos
        return donating

    # --- per-function use-after-donate check -------------------------------

    def _check_function(self, mod: ModuleInfo, fn,
                        donating: dict[str, tuple[int, ...]]) -> list[Finding]:
        local_donating = dict(donating)
        events: list[tuple[int, int, str, object]] = []  # (line, col, kind, payload)

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                pos = self._is_donating_jit(mod, node.value)
                if pos:
                    for tgt in node.targets:
                        key = _expr_key(tgt)
                        if key is not None:
                            local_donating[key] = pos
            if isinstance(node, ast.Call):
                callee = node.func
                ckey = _expr_key(callee) or (
                    f"self.{callee.attr}"
                    if isinstance(callee, ast.Attribute)
                    and isinstance(callee.value, ast.Name)
                    and callee.value.id == "self"
                    else None
                )
                pos = local_donating.get(ckey or "")
                if pos:
                    for p in pos:
                        if p < len(node.args):
                            akey = _expr_key(node.args[p])
                            if akey is not None:
                                events.append((
                                    node.lineno, node.col_offset, "donate",
                                    (akey, ckey, node.end_lineno or node.lineno),
                                ))

        if not any(e[2] == "donate" for e in events):
            return []

        # second walk: loads and kills, ordered by position
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                events.append((node.lineno, node.col_offset, "load", node.id))
            elif isinstance(node, ast.Attribute):
                key = _expr_key(node)
                if key is not None and isinstance(node.ctx, ast.Load):
                    events.append((node.lineno, node.col_offset, "load", key))
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for tgt in targets:
                    key = _expr_key(tgt)
                    if key is not None:
                        # a kill takes effect AFTER the statement's RHS ran,
                        # so order it at the statement's end: `state =
                        # step(state, ...)` donates then rebinds
                        events.append((
                            node.end_lineno or node.lineno,
                            (node.end_col_offset or 0) + 10_000, "kill", key,
                        ))

        events.sort(key=lambda e: (e[0], e[1], e[2] != "donate"))
        findings: list[Finding] = []
        # key -> (donate line, end line of the donating call, callee)
        tainted: dict[str, tuple[int, int, str]] = {}
        for line, _col, kind, payload in events:
            if kind == "donate":
                akey, ckey, end_line = payload
                tainted[akey] = (line, end_line, ckey or "a donating jit")
            elif kind == "kill":
                tainted.pop(payload, None)
            elif kind == "load" and payload in tainted:
                dline, dend, ckey = tainted[payload]
                if line > dend:
                    findings.append(Finding(
                        "donation-safety", mod.rel, line,
                        f"{payload} was donated to {ckey} at line {dline} "
                        "and is read afterwards; the donated device buffer "
                        "may already be recycled (use-after-donate -- the "
                        "PR 9 checkpoint bug class). Copy to host before "
                        "donating, or reassign the result",
                    ))
                    tainted.pop(payload, None)  # one report per donation
        return findings
