"""kdlt-lint: the repo's unified static-analysis suite.

One AST parse per production file; passes are registered visitors sharing
that parse plus the module's import/alias resolution.  Rules:

- ``guarded-by``          attributes annotated ``# guarded-by: _lock`` are
                          only touched inside ``with self._lock``
- ``lock-order``          the cross-class lock-acquisition graph is acyclic
- ``blocking-under-lock`` no time.sleep / socket reads / .result() without
                          timeout while holding a lock
- ``hot-path-sync``       no host syncs (np.asarray / block_until_ready /
                          .item() / float()) in functions reachable from the
                          dispatcher/engine forward path
- ``lock-around-jit``     no lock held around a jitted call on the hot path
- ``donation-safety``     no reads of an array after it was passed to a
                          donate_argnums jit in the same function
- ``closed-vocab``        span names, fault points, flight-recorder event
                          kinds and incident trigger names are members of
                          their declared vocabularies
- ``metrics-naming``      the tools/check_metrics.py rules, as a pass
- ``env-knobs``           the tools/check_env.py rules, as a pass
- ``unused-suppression``  every ``# kdlt-lint: disable=`` comment must
                          actually suppress something

Suppression grammar (same line, or a standalone comment line covering the
next line)::

    x = self._hits  # kdlt-lint: disable=guarded-by -- monitoring read, torn reads OK
"""

from kdlt_lint.core import (  # noqa: F401
    Finding,
    LintContext,
    ModuleInfo,
    iter_production_files,
    run_lint,
)
