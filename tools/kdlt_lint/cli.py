"""kdlt-lint command line: human and --json output over the full suite."""

from __future__ import annotations

import argparse
import json
import sys

from kdlt_lint.core import REPO, default_passes, run_lint


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kdlt-lint",
        description="unified static-analysis suite for the serving tree",
    )
    ap.add_argument("--json", action="store_true", help="stable JSON output")
    ap.add_argument(
        "--rule", action="append", default=None, metavar="RULE",
        help="only report these rule ids (repeatable)",
    )
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="also list findings silenced by kdlt-lint: disable comments",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--repo", default=REPO, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    passes = default_passes()
    if args.list_rules:
        for p in passes:
            for r in p.rules:
                print(f"{r}  ({p.name} pass)")
        print("unused-suppression  (framework)")
        return 0

    findings = run_lint(passes, repo=args.repo)
    if args.rule:
        wanted = set(args.rule)
        findings = [f for f in findings if f.rule in wanted]
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.json:
        print(json.dumps({
            "version": 1,
            "findings": [f.as_json() for f in findings],
            "summary": {
                "active": len(active),
                "suppressed": len(suppressed),
            },
        }, indent=2, sort_keys=True))
        return 1 if active else 0

    for f in active:
        print(f.format())
    if args.show_suppressed:
        for f in suppressed:
            print(f"{f.format()}  [suppressed]")
    if active:
        print(f"kdlt-lint: {len(active)} finding(s) "
              f"({len(suppressed)} suppressed)")
        return 1
    print(f"kdlt-lint: clean ({len(suppressed)} suppressed finding(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
