#!/usr/bin/env python
"""Regenerate requirements.lock: the exact transitive dependency closure of
the package's runtime/serve/grpc roots, resolved against the CURRENT
environment (the one the test suite and benchmarks run under).

The reference pins its whole transitive set in Pipfile.lock (46 packages,
SURVEY.md component 15); this is the TPU stack's equivalent.  No hashes:
this environment has no package egress to fetch archives to hash.
"""

from __future__ import annotations

import re
from importlib import metadata

ROOTS = [
    "jax", "flax", "numpy", "msgpack", "Pillow", "requests", "optax",
    "gunicorn", "grpcio", "protobuf", "h5py", "pyyaml",
    "orbax-checkpoint", "chex", "jaxlib",
]

HEADER = """\
# requirements.lock -- full transitive dependency closure, exact versions.
# The reference pins 46 transitive packages in Pipfile.lock (SURVEY.md
# component 15); this is the equivalent for the TPU stack: every package
# reachable from the runtime/serve/grpc dependency roots, resolved against
# the environment the test suite and benchmarks run under.  No hashes: the
# build environment has no package egress to compute them from; versions
# are exact.  Regenerate with: python tools/gen_lock.py
# Used by deploy/*.dockerfile as the pip constraints file.
"""


def main() -> None:
    seen: dict[str, tuple[str, str]] = {}

    def norm(n: str) -> str:
        return re.sub(r"[-_.]+", "-", n).lower()

    def visit(name: str) -> None:
        n = norm(name)
        if n in seen:
            return
        try:
            dist = metadata.distribution(name)
        except metadata.PackageNotFoundError:
            return
        seen[n] = (dist.metadata["Name"], dist.version)
        for req in dist.requires or []:
            if "extra ==" in req:  # extras-gated: not part of the closure
                continue
            m = re.match(r"^\s*([A-Za-z0-9_.\-]+)", req)
            if m:
                visit(m.group(1))

    for r in ROOTS:
        visit(r)
    # Roots not installed in THIS env (e.g. gunicorn lives only in the
    # gateway image) fall back to constraints.txt's explicit pin.
    constraints = {}
    for line in open("constraints.txt"):
        line = line.strip()
        if line and not line.startswith("#") and "==" in line:
            n, _, v = line.partition("==")
            constraints[norm(n)] = (n, v)
    for r in ROOTS:
        if norm(r) not in seen:
            if norm(r) not in constraints:
                raise SystemExit(f"root {r} neither installed nor in constraints.txt")
            seen[norm(r)] = constraints[norm(r)]
    lines = sorted(f"{name}=={ver}" for name, ver in seen.values())
    with open("requirements.lock", "w") as f:
        f.write(HEADER + "\n".join(lines) + "\n")
    print(f"{len(lines)} packages locked")


if __name__ == "__main__":
    main()
