#!/usr/bin/env python
"""Static metrics-naming lint: every series is kdlt_-prefixed and minted
through the central helpers in utils/metrics.py.

The /metrics pages are the operational contract of both serving tiers;
dashboards and alerts key on series names.  Two failure modes creep in as
the tree grows: a module minting an un-prefixed name (invisible to every
``kdlt_``-scoped dashboard query), and a module constructing Counter/
Gauge/Histogram objects directly instead of going through a Registry or
the helper functions (its series silently never reach /metrics, or reach
it unlabeled).  This lint walks the AST of every production module and
flags both.  Wired into tier-1 via tests/test_check_metrics.py.

Rules (production code only; tests/, exp/, tfs_gen/ are exempt):

- every ``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")``
  call must pass a string (or f-string with a literal head) starting with
  ``kdlt_`` -- dynamic names with non-literal heads are flagged too, since
  they cannot be audited statically;
- Counter/Gauge/Histogram must not be instantiated directly outside
  utils/metrics.py (the Registry mint methods are the only sanctioned
  constructors -- they dedupe, label, and register);
- the ``model`` label must be minted centrally: ``.with_labels(model=...)``
  outside utils/metrics.py is flagged -- modules attach the label through
  utils.metrics.model_registry / model_version_registry and friends, which
  is what keeps its cardinality BOUNDED (MODEL_LABEL_CAP + the overflow
  bucket) no matter what names a caller feeds in.  The same rule covers
  the other bounded labels: ``window`` (the SLO engine's fixed window set),
  ``class`` (the tracer's retention classes), ``reason`` (cache eviction
  reasons), ``scheme`` (the quantization scheme list), ``source`` (the
  warmup provenance pair), and ``trigger`` (the flight recorder's fixed
  trigger-rule names);
- ``kdlt_slo_*`` series must be minted inside utils/metrics.py: the SLO
  engine's gauge matrix is (bounded model) x (fixed window), and a module
  minting its own slice would bypass both bounds at once;
- ``exemplar=`` is histogram-only (the OpenMetrics rule): passing it to a
  counter/gauge mutation (``.inc()``/``.set()``) is flagged -- at runtime
  it would TypeError, but the lint catches it before a request does.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "kubernetes_deep_learning_tpu"
EXTRA_FILES = ("bench.py",)
METRIC_PREFIX = "kdlt_"
MINT_METHODS = {"counter", "gauge", "histogram"}
METRIC_CLASSES = {"Counter", "Gauge", "Histogram"}
# Labels whose value sets are bounded by construction inside utils/metrics.py
# (model: MODEL_LABEL_CAP + overflow; window: the SLO window list; class:
# the trace retention classes; reason: the cache eviction reasons; scheme:
# the quantization scheme list; source: the warmup provenance pair;
# stage/direction: the brownout ladder's four stages and two directions;
# trigger: the flight recorder's fixed trigger-rule names) -- attaching
# them anywhere else escapes the bound.
CENTRAL_LABELS = {
    "model", "window", "class", "reason", "scheme", "source",
    "stage", "direction", "trigger",
}
# Series prefixes whose minting is confined to utils/metrics.py even beyond
# the general helper conventions (the SLO gauge matrix, the response
# cache's series, the quantization scheme/gate series, the dynamic-
# membership pool series, and the flight recorder's incident series: all
# carry bounded labels a stray mint would escape).
CENTRAL_PREFIXES = (
    "kdlt_slo_", "kdlt_cache_", "kdlt_quant_", "kdlt_pool_", "kdlt_brownout_",
    "kdlt_incident_",
)
# Exact series names likewise confined to utils/metrics.py: these live
# under prefixes too broad to confine wholesale (kdlt_engine_* is minted
# per-engine in runtime/engine.py) but carry a bounded label.
CENTRAL_NAMES = ("kdlt_engine_warm_source",)
METRICS_MODULE = f"{PACKAGE}.utils.metrics"
SKIP_PARTS = {"tfs_gen", "__pycache__"}


def _literal_head(node: ast.expr) -> str | None:
    """The statically-known head of a name argument: the whole string for
    a constant, the leading constant of an f-string, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    return None


def _name_arg(call: ast.Call) -> ast.expr | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def lint_source(src: str, rel: str) -> list[str]:
    """Lint one module's source; returns violation strings."""
    violations: list[str] = []
    tree = ast.parse(src, filename=rel)
    # Aliases under which this module can reach the metric classes.
    metrics_module_aliases: set[str] = set()
    metric_class_aliases: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == METRICS_MODULE:
                    metrics_module_aliases.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module == METRICS_MODULE.rsplit(".", 1)[0]:
                for a in node.names:
                    if a.name == "metrics":
                        metrics_module_aliases.add(a.asname or a.name)
            elif node.module == METRICS_MODULE:
                for a in node.names:
                    if a.name in METRIC_CLASSES:
                        metric_class_aliases.add(a.asname or a.name)

    is_metrics_module = rel.replace(os.sep, "/").endswith("utils/metrics.py")
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        # Direct Counter/Gauge/Histogram construction outside the central
        # module (via `from ..utils.metrics import Histogram` or
        # `metrics_lib.Histogram(...)`).
        if not is_metrics_module and (
            (isinstance(fn, ast.Name) and fn.id in metric_class_aliases)
            or (
                isinstance(fn, ast.Attribute)
                and fn.attr in METRIC_CLASSES
                and isinstance(fn.value, ast.Name)
                and fn.value.id in metrics_module_aliases
            )
        ):
            cls = fn.id if isinstance(fn, ast.Name) else fn.attr
            violations.append(
                f"{rel}:{node.lineno}: direct {cls}(...) construction; mint "
                "through a Registry / the utils.metrics helpers instead"
            )
            continue
        # The bounded labels: with_labels(model=.../window=.../class=...)
        # may only happen inside the central module (model_registry, the
        # slo/retention helpers); anywhere else it bypasses the cardinality
        # caps and the memoized dedupe.  Keyword "class" also arrives as
        # with_labels(**{"class": ...}) -- a dict-literal double-star with
        # a matching constant key counts too.
        if (
            not is_metrics_module
            and isinstance(fn, ast.Attribute)
            and fn.attr == "with_labels"
        ):
            bounded = {
                kw.arg for kw in node.keywords if kw.arg in CENTRAL_LABELS
            }
            for kw in node.keywords:
                if kw.arg is None and isinstance(kw.value, ast.Dict):
                    bounded.update(
                        k.value for k in kw.value.keys
                        if isinstance(k, ast.Constant)
                        and k.value in CENTRAL_LABELS
                    )
            if bounded:
                labels = ", ".join(sorted(bounded))
                violations.append(
                    f"{rel}:{node.lineno}: .with_labels({labels}=...) outside "
                    "utils/metrics.py; mint bounded labels through the "
                    "central helpers (model_registry / "
                    "slo_model_window_metrics / trace_retention_metrics)"
                )
                continue
        # Exemplars are a histogram concept (OpenMetrics): counter/gauge
        # mutations must not carry one.
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in ("inc", "set")
            and any(kw.arg == "exemplar" for kw in node.keywords)
        ):
            violations.append(
                f"{rel}:{node.lineno}: exemplar= on .{fn.attr}(); exemplars "
                "attach to histogram observe() only (non-histogram series "
                "cannot carry them)"
            )
            continue
        # Mint calls: .counter / .gauge / .histogram on anything (in this
        # tree only Registry objects expose these method names).
        if isinstance(fn, ast.Attribute) and fn.attr in MINT_METHODS:
            arg = _name_arg(node)
            if arg is None:
                continue
            head = _literal_head(arg)
            if head is None:
                violations.append(
                    f"{rel}:{node.lineno}: .{fn.attr}() with a non-literal "
                    "metric name; names must be statically auditable"
                )
            elif not head.startswith(METRIC_PREFIX):
                violations.append(
                    f"{rel}:{node.lineno}: metric name {head!r} is not "
                    f"{METRIC_PREFIX}-prefixed"
                )
            elif not is_metrics_module and (
                any(head.startswith(p) for p in CENTRAL_PREFIXES)
                or head in CENTRAL_NAMES
            ):
                violations.append(
                    f"{rel}:{node.lineno}: {head!r} minted outside "
                    "utils/metrics.py; kdlt_slo_*/kdlt_cache_*/kdlt_quant_*/"
                    "kdlt_pool_*/kdlt_brownout_*/kdlt_incident_* series (and "
                    "kdlt_engine_warm_source) are minted only by the central "
                    "helpers (bounded label sets by construction)"
                )
    return violations


def iter_production_files() -> list[str]:
    files: list[str] = [os.path.join(REPO, f) for f in EXTRA_FILES]
    for dirpath, dirnames, filenames in os.walk(os.path.join(REPO, PACKAGE)):
        dirnames[:] = [d for d in dirnames if d not in SKIP_PARTS]
        files.extend(
            os.path.join(dirpath, f) for f in sorted(filenames)
            if f.endswith(".py")
        )
    return files


def main() -> int:
    violations: list[str] = []
    for path in iter_production_files():
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            try:
                violations.extend(lint_source(f.read(), rel))
            except SyntaxError as e:
                violations.append(f"{rel}: unparsable: {e}")
    for v in violations:
        print(v)
    if not violations:
        print("check_metrics: all metric names kdlt_-prefixed and centrally minted")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
