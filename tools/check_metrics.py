#!/usr/bin/env python
"""Metrics-naming lint CLI -- a thin shim over kdlt-lint's metrics pass.

The rules (every series kdlt_-prefixed and minted through the central
helpers in utils/metrics.py; bounded labels and the central prefixes
confined to that module; exemplars histogram-only) now live in
tools/kdlt_lint/passes/metrics_names.py, where they run as one pass of
the unified suite alongside lock-discipline, hot-path-sync, donation-
safety and closed-vocab.  This shim keeps the original CLI and the
``lint_source(src, rel)`` API (tests/test_check_metrics.py asserts on its
exact message strings) so nothing keyed on ``check_metrics`` breaks.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from kdlt_lint.core import ModuleInfo, LintContext  # noqa: E402
from kdlt_lint.passes.metrics_names import (  # noqa: E402,F401
    CENTRAL_LABELS,
    CENTRAL_NAMES,
    CENTRAL_PREFIXES,
    METRIC_CLASSES,
    METRIC_PREFIX,
    METRICS_MODULE,
    MINT_METHODS,
    MetricsNamingPass,
)
from kdlt_lint.core import (  # noqa: E402,F401
    EXTRA_FILES,
    PACKAGE,
    REPO,
    SKIP_PARTS,
    iter_production_files as _iter_files,
)


def lint_source(src: str, rel: str) -> list[str]:
    """Lint one module's source; returns violation strings."""
    mod = ModuleInfo(rel.replace(os.sep, "/"), src)
    findings = MetricsNamingPass().check_module(mod, LintContext(REPO))
    return [f"{f.rel}:{f.line}: {f.message}" for f in findings]


def iter_production_files() -> list[str]:
    return _iter_files(REPO)


def main() -> int:
    violations: list[str] = []
    for path in iter_production_files():
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            try:
                violations.extend(lint_source(f.read(), rel))
            except SyntaxError as e:
                violations.append(f"{rel}: unparsable: {e}")
    for v in violations:
        print(v)
    if not violations:
        print("check_metrics: all metric names kdlt_-prefixed and centrally minted")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
