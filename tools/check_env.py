#!/usr/bin/env python
"""Static env-knob lint: every ``KDLT_*`` variable the tree reads is
documented, and the deploy manifests that mirror each serving tier agree.

Env vars are the repo's operational API -- every knob in GUIDE.md's worked
runs, the compose file, and the k8s manifests is one.  Two failure modes
creep in as the tree grows: a module growing a knob nobody documents (the
operator discovers it by reading source, or never), and the compose /
k8s mirrors of a tier drifting apart (a replica pair that disagrees on
KDLT_SCHED_POLICY serves two latency profiles; a compose gateway without
the k8s gateway's cache knobs behaves differently in the only environment
most contributors test in).  This lint catches both statically.  Wired
into tier-1 via tests/test_check_env.py.

Rules:

- every string literal in production code (the package + bench.py) that
  IS an env-var name -- a whole-string match of ``KDLT_[A-Z0-9_]+`` --
  must appear somewhere in GUIDE.md.  Scanning literals rather than
  ``os.environ`` call sites is deliberate: the tree's idiom is
  ``FOO_ENV = "KDLT_FOO"`` constants passed through helpers, and a
  reference-only literal that never reaches a read is vanishingly rare
  next to the drift this catches;
- every ``KDLT_*`` key in a deploy manifest must be a name production
  code actually reads (catches manifest typos: a misspelled knob is
  silently default-valued at runtime);
- the two compose model-tier replicas must set IDENTICAL ``KDLT_*`` maps
  (the gateway fails over between them: any disagreement is a latency /
  behavior split);
- for each tier, the compose services and the k8s manifest must set the
  same ``KDLT_*`` keys with the same values, except:
  - ``ALLOW_VALUE_DRIFT`` keys may differ in value (host-ish: compose
    service names vs cluster DNS),
  - ``ALLOW_PRESENCE_DRIFT`` keys may be absent on one side (path-ish
    knobs tied to a volume only one environment mounts).
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "kubernetes_deep_learning_tpu"
EXTRA_FILES = ("bench.py",)
GUIDE = "GUIDE.md"
SKIP_PARTS = {"tfs_gen", "__pycache__"}
ENV_RE = re.compile(r"KDLT_[A-Z0-9_]+\Z")

COMPOSE = os.path.join("deploy", "docker-compose.yaml")
K8S_GATEWAY = os.path.join("deploy", "k8s", "gateway-deployment.yaml")
K8S_MODEL = os.path.join("deploy", "k8s", "model-server-deployment.yaml")

# Tier mirrors: (tier name, compose service names, k8s manifest).
TIERS = (
    ("gateway", ("gateway",), K8S_GATEWAY),
    ("model-server", ("model-server", "model-server-b"), K8S_MODEL),
)

# Host-ish knobs: the VALUE legitimately differs between compose (service
# names on the compose network) and k8s (cluster DNS).
ALLOW_VALUE_DRIFT = {"KDLT_SERVING_HOST"}
# Path-ish knobs tied to a volume/filesystem only one environment mounts;
# presence on one side only is fine.
ALLOW_PRESENCE_DRIFT = {"KDLT_COMPILE_CACHE_DIR", "KDLT_PROFILE_DIR"}


def iter_production_files() -> list[str]:
    files: list[str] = [os.path.join(REPO, f) for f in EXTRA_FILES]
    for dirpath, dirnames, filenames in os.walk(os.path.join(REPO, PACKAGE)):
        dirnames[:] = [d for d in dirnames if d not in SKIP_PARTS]
        files.extend(
            os.path.join(dirpath, f) for f in sorted(filenames)
            if f.endswith(".py")
        )
    return files


def env_literals(src: str, rel: str) -> dict[str, int]:
    """Whole-string KDLT_* literals in a module -> first line seen."""
    found: dict[str, int] = {}
    for node in ast.walk(ast.parse(src, filename=rel)):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and ENV_RE.match(node.value)
        ):
            found.setdefault(node.value, node.lineno)
    return found


def compose_env(doc: dict, service: str) -> dict[str, str]:
    svc = (doc.get("services") or {}).get(service) or {}
    env = svc.get("environment") or {}
    if isinstance(env, list):  # compose also allows ["K=V", ...]
        env = dict(item.split("=", 1) for item in env)
    return {k: str(v) for k, v in env.items() if k.startswith("KDLT_")}


def k8s_env(doc: dict) -> dict[str, str]:
    tmpl = doc.get("spec", {}).get("template", {}).get("spec", {})
    out: dict[str, str] = {}
    for container in tmpl.get("containers") or []:
        for item in container.get("env") or []:
            name = item.get("name", "")
            if name.startswith("KDLT_"):
                out[name] = str(item.get("value", ""))
    return out


def main() -> int:
    violations: list[str] = []

    # 1. Every env literal in production code is documented in GUIDE.md.
    code_envs: dict[str, str] = {}  # name -> "rel:line" of first sighting
    for path in iter_production_files():
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            try:
                for name, line in env_literals(f.read(), rel).items():
                    code_envs.setdefault(name, f"{rel}:{line}")
            except SyntaxError as e:
                violations.append(f"{rel}: unparsable: {e}")
    with open(os.path.join(REPO, GUIDE)) as f:
        guide_text = f.read()
    for name in sorted(code_envs):
        if name not in guide_text:
            violations.append(
                f"{code_envs[name]}: {name} is read by production code but "
                f"never mentioned in {GUIDE}; document the knob"
            )

    # 2+3+4. Deploy manifests: keys exist in code, mirrors agree.
    import yaml

    with open(os.path.join(REPO, COMPOSE)) as f:
        compose_doc = yaml.safe_load(f)
    k8s_docs = {}
    for manifest in (K8S_GATEWAY, K8S_MODEL):
        with open(os.path.join(REPO, manifest)) as f:
            k8s_docs[manifest] = yaml.safe_load(f)

    deploy_maps: list[tuple[str, dict[str, str]]] = []
    for tier, services, manifest in TIERS:
        for svc in services:
            deploy_maps.append(
                (f"{COMPOSE}:{svc}", compose_env(compose_doc, svc))
            )
        deploy_maps.append((manifest, k8s_env(k8s_docs[manifest])))
    for where, env in deploy_maps:
        for name in sorted(env):
            if name not in code_envs:
                violations.append(
                    f"{where}: {name} is set but no production code reads "
                    "it (typo'd knob names are silently ignored at runtime)"
                )

    # Compose replica pair: identical maps, no exceptions.
    pair = [compose_env(compose_doc, s) for s in ("model-server", "model-server-b")]
    if pair[0] != pair[1]:
        diff = sorted(
            set(pair[0].items()) ^ set(pair[1].items())
        )
        violations.append(
            f"{COMPOSE}: model-server and model-server-b disagree on "
            f"{sorted({k for k, _ in diff})}; the gateway fails over "
            "between them, so their KDLT_* maps must be identical"
        )

    # Cross-environment tier mirrors.
    for tier, services, manifest in TIERS:
        c_env = compose_env(compose_doc, services[0])
        k_env = k8s_env(k8s_docs[manifest])
        for name in sorted(set(c_env) | set(k_env)):
            if name in ALLOW_PRESENCE_DRIFT:
                continue
            if name not in c_env or name not in k_env:
                missing = COMPOSE if name not in c_env else manifest
                violations.append(
                    f"{tier}: {name} is wired in one environment but "
                    f"missing from {missing}; compose and k8s mirrors of "
                    "a tier must set the same knobs"
                )
            elif name not in ALLOW_VALUE_DRIFT and c_env[name] != k_env[name]:
                violations.append(
                    f"{tier}: {name} disagrees between {COMPOSE} "
                    f"({c_env[name]!r}) and {manifest} ({k_env[name]!r})"
                )

    for v in violations:
        print(v)
    if not violations:
        print(
            f"check_env: {len(code_envs)} KDLT_* knobs documented; deploy "
            "mirrors agree"
        )
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
