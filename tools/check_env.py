#!/usr/bin/env python
"""Env-knob lint CLI -- a thin shim over kdlt-lint's env pass.

The rules (every whole-string ``KDLT_*`` literal documented in GUIDE.md,
deploy-manifest keys read by code, the compose replica pair identical,
compose/k8s tier mirrors agreeing modulo the declared drift allowances)
now live in tools/kdlt_lint/passes/env_knobs.py, where they run as one
pass of the unified suite alongside lock-discipline, hot-path-sync,
donation-safety and closed-vocab.  The drift allowances themselves moved
into that pass's DEPLOY_AGREEMENT declarative config; this shim re-exports
them plus the ``env_literals``/``compose_env``/``k8s_env`` helpers
(tests/test_check_env.py exercises each directly) so nothing keyed on
``check_env`` breaks.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from kdlt_lint.core import (  # noqa: E402,F401
    EXTRA_FILES,
    PACKAGE,
    REPO,
    SKIP_PARTS,
    LintContext,
    ModuleInfo,
    iter_production_files as _iter_files,
)
from kdlt_lint.passes.env_knobs import (  # noqa: E402,F401
    COMPOSE,
    DEPLOY_AGREEMENT,
    ENV_RE,
    GUIDE,
    K8S_GATEWAY,
    K8S_MODEL,
    EnvKnobsPass,
    compose_env,
    env_literals,
    k8s_env,
)

# Back-compat views of the pass's declarative config.
TIERS = DEPLOY_AGREEMENT["tiers"]
ALLOW_VALUE_DRIFT = set(DEPLOY_AGREEMENT["allow_value_drift"])
ALLOW_PRESENCE_DRIFT = set(DEPLOY_AGREEMENT["allow_presence_drift"])


def iter_production_files() -> list[str]:
    return _iter_files(REPO)


def main() -> int:
    violations: list[str] = []
    env_pass = EnvKnobsPass()
    ctx = LintContext(REPO)
    for path in iter_production_files():
        rel = os.path.relpath(path, REPO).replace(os.sep, "/")
        with open(path) as f:
            src = f.read()
        try:
            mod = ModuleInfo(rel, src)
        except SyntaxError as e:
            violations.append(f"{rel}: unparsable: {e}")
            continue
        env_pass.check_module(mod, ctx)
    for f in env_pass.finalize(ctx):
        # Manifest-level findings (line 0) already carry their location in
        # the message; code-level ones get the classic rel:line prefix.
        violations.append(f"{f.rel}:{f.line}: {f.message}" if f.line else f.message)
    for v in violations:
        print(v)
    if not violations:
        print(
            f"check_env: {ctx.scratch.get('env.knob_count', 0)} KDLT_* knobs "
            "documented; deploy mirrors agree"
        )
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
