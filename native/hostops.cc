// kdlt host ops: native C++ image resize for the gateway hot path.
//
// The reference's IO tier resizes with Pillow via keras-image-helper
// (reference model_server.py:18); SURVEY.md 3.1 identifies image
// download + resize as the gateway's hot spot.  This library is the in-tree
// native replacement: uint8 RGB/HWC resize with PIL-identical output --
// nearest uses the same affine sampling, bilinear reproduces Pillow's
// two-pass fixed-point resampling (triangle filter with support scaling on
// downscale, 8-bit clip between passes) so swapping it in cannot move the
// golden logits (BASELINE.md) by even one ulp.
//
// Build: see native/Makefile (g++ -O3 -shared; no deps).
// Python binding: ctypes in kubernetes_deep_learning_tpu/ops/_native.py.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr int kPrecisionBits = 32 - 8 - 2;  // Pillow's 8bpc fixed-point scale

inline uint8_t clip8(int in) {
  int v = in >> kPrecisionBits;
  if (v < 0) return 0;
  if (v > 255) return 255;
  return static_cast<uint8_t>(v);
}

inline double triangle_filter(double x) {
  if (x < 0.0) x = -x;
  return x < 1.0 ? 1.0 - x : 0.0;
}

// Precompute, for every output index, the source window [xmin, xmin+n) and
// its normalized fixed-point weights.  This is the standard separable
// resampling schedule: window center at (out + 0.5) * scale, filter support
// widened by the scale factor when minifying so every source pixel
// contributes (area averaging), plain triangle interpolation when
// magnifying.
struct Schedule {
  std::vector<int> xmin;
  std::vector<int> xsize;
  std::vector<std::vector<int>> coeffs;
};

Schedule make_schedule(int in_size, int out_size) {
  Schedule s;
  s.xmin.resize(out_size);
  s.xsize.resize(out_size);
  s.coeffs.resize(out_size);

  const double scale = static_cast<double>(in_size) / out_size;
  const double filterscale = scale < 1.0 ? 1.0 : scale;
  const double support = 1.0 * filterscale;  // triangle filter support = 1

  std::vector<double> w;
  for (int xx = 0; xx < out_size; ++xx) {
    const double center = (xx + 0.5) * scale;
    int xmin = static_cast<int>(center - support + 0.5);
    if (xmin < 0) xmin = 0;
    int xmax = static_cast<int>(center + support + 0.5);
    if (xmax > in_size) xmax = in_size;
    const int n = xmax - xmin;

    w.assign(n, 0.0);
    double total = 0.0;
    for (int j = 0; j < n; ++j) {
      w[j] = triangle_filter((j + xmin - center + 0.5) / filterscale);
      total += w[j];
    }
    s.xmin[xx] = xmin;
    s.xsize[xx] = n;
    s.coeffs[xx].resize(n);
    for (int j = 0; j < n; ++j) {
      const double norm = total > 0.0 ? w[j] / total : 0.0;
      s.coeffs[xx][j] =
          static_cast<int>(std::lround(norm * (1 << kPrecisionBits)));
    }
  }
  return s;
}

void resample_horizontal(const uint8_t* src, int w_in, uint8_t* dst, int h,
                         int w_out, int c, const Schedule& s) {
  for (int y = 0; y < h; ++y) {
    const uint8_t* row = src + static_cast<int64_t>(y) * w_in * c;
    uint8_t* out = dst + static_cast<int64_t>(y) * w_out * c;
    for (int x = 0; x < w_out; ++x) {
      const int xmin = s.xmin[x];
      const int n = s.xsize[x];
      const int* k = s.coeffs[x].data();
      for (int ch = 0; ch < c; ++ch) {
        int acc = 1 << (kPrecisionBits - 1);
        for (int j = 0; j < n; ++j)
          acc += row[(xmin + j) * c + ch] * k[j];
        out[x * c + ch] = clip8(acc);
      }
    }
  }
}

void resample_vertical(const uint8_t* src, uint8_t* dst, int h_out, int w,
                       int c, const Schedule& s) {
  for (int y = 0; y < h_out; ++y) {
    const int ymin = s.xmin[y];
    const int n = s.xsize[y];
    const int* k = s.coeffs[y].data();
    uint8_t* out = dst + static_cast<int64_t>(y) * w * c;
    for (int x = 0; x < w * c; ++x) {
      int acc = 1 << (kPrecisionBits - 1);
      for (int j = 0; j < n; ++j)
        acc += src[static_cast<int64_t>(ymin + j) * w * c + x] * k[j];
      out[x] = clip8(acc);
    }
  }
}

}  // namespace

extern "C" {

// dst must hold h_out * w_out * c bytes.  Returns 0 on success.
int kdlt_resize_bilinear(const uint8_t* src, int h_in, int w_in, int c,
                         uint8_t* dst, int h_out, int w_out) {
  if (h_in <= 0 || w_in <= 0 || h_out <= 0 || w_out <= 0 || c <= 0) return 1;
  const Schedule sh = make_schedule(w_in, w_out);
  const Schedule sv = make_schedule(h_in, h_out);
  // Two passes with a uint8 intermediate (clipping between passes), the
  // 8-bits-per-channel pipeline Pillow uses -- required for exact parity.
  std::vector<uint8_t> mid(static_cast<size_t>(h_in) * w_out * c);
  resample_horizontal(src, w_in, mid.data(), h_in, w_out, c, sh);
  resample_vertical(mid.data(), dst, h_out, w_out, c, sv);
  return 0;
}

// Nearest neighbour via the same affine sampling Pillow's NEAREST uses:
// source coordinate starts at scale/2 and is accumulated incrementally per
// output pixel (the accumulation order matters -- recomputing
// (x + 0.5) * scale per pixel rounds differently and shifts pixels on
// upscales).
int kdlt_resize_nearest(const uint8_t* src, int h_in, int w_in, int c,
                        uint8_t* dst, int h_out, int w_out) {
  if (h_in <= 0 || w_in <= 0 || h_out <= 0 || w_out <= 0 || c <= 0) return 1;
  const double sx = static_cast<double>(w_in) / w_out;
  const double sy = static_cast<double>(h_in) / h_out;
  std::vector<int> xmap(w_out);
  double xin = sx * 0.5;
  for (int x = 0; x < w_out; ++x, xin += sx) {
    int xs = static_cast<int>(xin);
    xmap[x] = xs < w_in ? xs : w_in - 1;
  }
  double yin = sy * 0.5;
  for (int y = 0; y < h_out; ++y, yin += sy) {
    int ys = static_cast<int>(yin);
    if (ys >= h_in) ys = h_in - 1;
    const uint8_t* row = src + static_cast<int64_t>(ys) * w_in * c;
    uint8_t* out = dst + static_cast<int64_t>(y) * w_out * c;
    for (int x = 0; x < w_out; ++x)
      std::memcpy(out + x * c, row + xmap[x] * c, c);
  }
  return 0;
}

// Batched resize across images, one std::thread per shard (the GIL is
// released for the whole batch on the Python side).  filter: 0=nearest,
// 1=bilinear.
int kdlt_resize_batch(const uint8_t* src, int n, int h_in, int w_in, int c,
                      uint8_t* dst, int h_out, int w_out, int filter,
                      int num_threads) {
  if (n <= 0) return 1;
  const int64_t in_stride = static_cast<int64_t>(h_in) * w_in * c;
  const int64_t out_stride = static_cast<int64_t>(h_out) * w_out * c;
  int threads = num_threads > 0 ? num_threads : 1;
  if (threads > n) threads = n;

  int err = 0;
  auto work = [&](int t) {
    for (int i = t; i < n; i += threads) {
      int rc = filter == 0
                   ? kdlt_resize_nearest(src + i * in_stride, h_in, w_in, c,
                                         dst + i * out_stride, h_out, w_out)
                   : kdlt_resize_bilinear(src + i * in_stride, h_in, w_in, c,
                                          dst + i * out_stride, h_out, w_out);
      if (rc != 0) err = rc;
    }
  };
  if (threads == 1) {
    work(0);
  } else {
    std::vector<std::thread> pool;
    pool.reserve(threads);
    for (int t = 0; t < threads; ++t) pool.emplace_back(work, t);
    for (auto& th : pool) th.join();
  }
  return err;
}

}  // extern "C"
