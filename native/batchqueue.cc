// kdlt batch queue: native C++ request coalescing for the model server.
//
// The reference delegates server-side batching to TF-Serving's C++ binary
// (available there, unconfigured: SURVEY.md component 7).  The in-tree
// Python DynamicBatcher (runtime/batcher.py) reproduces the policy; this is
// its native engine-room variant: submit/wait and batch assembly run
// entirely outside the GIL, so request threads block in C (no Python
// condvar wakeups on the hot path), the linger timer is immune to GIL
// contention jitter, and the gather of N request images into one contiguous
// batch buffer is a C++ memcpy loop rather than np.stack under the GIL.
//
// Lifecycle of one request (ticket = slot index + generation):
//   submit():  free slot -> copy image into the slot -> PENDING, wake taker
//   take():    dispatcher pops <=max_batch PENDING (lingering up to
//              max_delay when the batch is small), copies slots into the
//              caller's batch buffer OUTSIDE the lock -> INFLIGHT
//   complete():writes each row of logits into its slot -> DONE, broadcast
//   wait():    request thread wakes, copies its row out, frees the slot
// Waiters that time out mark the slot abandoned; whichever of take/complete
// sees the flag reclaims the slot, so stragglers never leak capacity.
//
// Build: part of libkdlthostops.so (native/Makefile; auto-built by
// ops/_native.py).  Python binding: runtime/native_batcher.py via ctypes
// (ctypes releases the GIL around every call).

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

enum class SlotState : uint8_t { kFree, kPending, kInflight, kDone, kFailed };

struct Slot {
  SlotState state = SlotState::kFree;
  bool abandoned = false;
  uint64_t gen = 0;
  std::vector<uint8_t> image;
  std::vector<float> out;
};

struct BatchQueue {
  std::mutex mu;
  std::condition_variable cv_work;   // submit -> take
  std::condition_variable cv_done;   // complete/fail/close -> wait
  std::condition_variable cv_drain;  // wait/take exit -> destroy
  std::vector<Slot> slots;
  std::deque<int> pending;           // slot indices in arrival order
  std::deque<int> free_slots;
  int64_t item_bytes;
  int out_floats;
  int active = 0;                    // threads inside wait()/take()
  bool closed = false;

  BatchQueue(int capacity, int64_t item_bytes_, int out_floats_)
      : slots(capacity), item_bytes(item_bytes_), out_floats(out_floats_) {
    for (int i = 0; i < capacity; ++i) {
      // Image buffers are allocated lazily on first use (submit): eagerly
      // sizing capacity x item_bytes would pin ~550 MB for a 2048-deep
      // 299x299x3 queue, where actual residency only needs the high-water
      // mark of concurrent requests.  The tiny logits row is eager.
      slots[i].out.resize(out_floats);
      free_slots.push_back(i);
    }
  }
};

inline int64_t ticket_of(const BatchQueue& q, int slot, uint64_t gen) {
  return static_cast<int64_t>(gen) * static_cast<int64_t>(q.slots.size()) +
         slot;
}

inline void split_ticket(const BatchQueue& q, int64_t ticket, int* slot,
                         uint64_t* gen) {
  *slot = static_cast<int>(ticket % static_cast<int64_t>(q.slots.size()));
  *gen = static_cast<uint64_t>(ticket / static_cast<int64_t>(q.slots.size()));
}

void free_slot_locked(BatchQueue* q, int idx) {
  Slot& s = q->slots[idx];
  s.state = SlotState::kFree;
  s.abandoned = false;
  s.gen++;  // invalidates any stale ticket for this slot
  q->free_slots.push_back(idx);
}

// RAII guard for the active-call count destroy() drains on.
struct ActiveGuard {
  BatchQueue* q;
  explicit ActiveGuard(BatchQueue* q_, std::unique_lock<std::mutex>& lk)
      : q(q_) {
    (void)lk;  // caller must hold q->mu
    q->active++;
  }
  void release(std::unique_lock<std::mutex>& lk) {
    (void)lk;
    if (q) {
      q->active--;
      if (q->active == 0) q->cv_drain.notify_all();
      q = nullptr;
    }
  }
};

}  // namespace

extern "C" {

// capacity: max queued+in-flight requests; item_bytes: one image;
// out_floats: one logits row.
void* kdlt_bq_create(int capacity, int64_t item_bytes, int out_floats) {
  if (capacity <= 0 || item_bytes <= 0 || out_floats <= 0) return nullptr;
  return new BatchQueue(capacity, item_bytes, out_floats);
}

// Safe teardown: closes the queue, fails every unresolved slot (after
// destroy no dispatcher will ever complete them -- without this, stranded
// waiters would pin destroy until their own timeouts), then blocks until
// every thread inside wait()/take() has left before freeing.
void kdlt_bq_destroy(void* handle) {
  auto* q = static_cast<BatchQueue*>(handle);
  {
    std::unique_lock<std::mutex> lk(q->mu);
    q->closed = true;
    for (auto& s : q->slots) {
      if (s.state == SlotState::kPending || s.state == SlotState::kInflight)
        s.state = SlotState::kFailed;
    }
    q->pending.clear();
    q->cv_work.notify_all();
    q->cv_done.notify_all();
    q->cv_drain.wait(lk, [&] { return q->active == 0; });
  }
  delete q;
}

// Returns a ticket (>=0), -1 when full (retryable), -2 when closed.
int64_t kdlt_bq_submit(void* handle, const uint8_t* image) {
  auto* q = static_cast<BatchQueue*>(handle);
  int idx;
  uint64_t gen;
  {
    std::unique_lock<std::mutex> lk(q->mu);
    if (q->closed) return -2;
    if (q->free_slots.empty()) return -1;
    idx = q->free_slots.front();
    q->free_slots.pop_front();
    gen = q->slots[idx].gen;
    // Copy under the lock: the slot buffer is exclusively ours once popped,
    // but the pending publish must not precede the copy.  Unlock-copy-relock
    // would also be correct; a ~270 KB memcpy is cheap enough to keep simple.
    if (q->slots[idx].image.size() < static_cast<size_t>(q->item_bytes))
      q->slots[idx].image.resize(q->item_bytes);  // lazy, kept thereafter
    std::memcpy(q->slots[idx].image.data(), image, q->item_bytes);
    q->slots[idx].state = SlotState::kPending;
    q->pending.push_back(idx);
  }
  q->cv_work.notify_one();
  return ticket_of(*q, idx, gen);
}

// Dispatcher side.  Waits for work (forever when wait_s < 0, else up to
// wait_s -- the bounded mode lets a pipelining dispatcher come back to sync
// an in-flight batch instead of blocking on an idle queue); lingers up to
// max_delay_s while the batch is smaller than max_batch; then copies the
// taken images into dst (contiguous, arrival order) and writes their
// tickets.  Returns the batch size, 0 when the queue is closed and drained
// (the dispatcher should exit), or -1 when wait_s expired with no work.
int kdlt_bq_take(void* handle, uint8_t* dst, int max_batch,
                 double max_delay_s, double wait_s, int64_t* tickets) {
  auto* q = static_cast<BatchQueue*>(handle);
  std::vector<int> taken;
  std::unique_lock<std::mutex> lk(q->mu);
  ActiveGuard guard(q, lk);
  auto work_ready = [&] { return q->closed || !q->pending.empty(); };
  auto wait_deadline =
      wait_s < 0 ? Clock::time_point::max()
                 : Clock::now() + std::chrono::duration_cast<Clock::duration>(
                                      std::chrono::duration<double>(wait_s));
  // Outer loop: a round may pop only abandoned slots (every queued waiter
  // timed out while the engine was stuck on the previous batch).  That must
  // NOT return 0 -- 0 is the dispatcher-exit sentinel, and exiting on an
  // open queue would leave the model silently dead -- so go back to waiting.
  while (taken.empty()) {
    if (wait_s < 0) {
      q->cv_work.wait(lk, work_ready);
    } else if (!q->cv_work.wait_until(lk, wait_deadline, work_ready)) {
      guard.release(lk);
      return -1;  // bounded wait expired with no work
    }
    if (q->pending.empty()) {  // closed and drained
      guard.release(lk);
      return 0;
    }
    if (static_cast<int>(q->pending.size()) < max_batch && max_delay_s > 0) {
      auto deadline =
          Clock::now() + std::chrono::duration_cast<Clock::duration>(
                             std::chrono::duration<double>(max_delay_s));
      while (static_cast<int>(q->pending.size()) < max_batch) {
        if (q->cv_work.wait_until(lk, deadline) == std::cv_status::timeout)
          break;
        if (q->closed) break;
      }
    }
    while (!q->pending.empty() && static_cast<int>(taken.size()) < max_batch) {
      int idx = q->pending.front();
      q->pending.pop_front();
      Slot& s = q->slots[idx];
      if (s.abandoned) {  // waiter gave up (timeout/close) while queued
        free_slot_locked(q, idx);
        continue;
      }
      if (s.state != SlotState::kPending) continue;  // defensive
      s.state = SlotState::kInflight;
      taken.push_back(idx);
    }
  }
  // Tickets are computed under the lock: gen is stable for slots this
  // thread just marked kInflight, but an abort() racing this point marks
  // them kFailed, and a waking waiter then frees them (gen++ under the
  // lock) -- reading gen after unlock would be an unsynchronized
  // read/write race with that increment.
  for (size_t i = 0; i < taken.size(); ++i)
    tickets[i] = ticket_of(*q, taken[i], q->slots[taken[i]].gen);
  // Assemble with the lock released: in-flight slots are owned by the
  // dispatcher, so a large batch gather never blocks submitters.  The
  // active guard (still held) keeps destroy() from freeing slots under us.
  // The unlocked image reads cannot race a writer: image bytes are written
  // only by submit(), which requires a free slot, and an inflight slot can
  // only become free via abort()/destroy() -- both of which also close the
  // queue, so no submit can follow.  (If the slot IS freed mid-gather, the
  // stale bytes are copied but complete() drops the row on gen mismatch.)
  lk.unlock();
  for (size_t i = 0; i < taken.size(); ++i) {
    std::memcpy(dst + static_cast<int64_t>(i) * q->item_bytes,
                q->slots[taken[i]].image.data(), q->item_bytes);
  }
  lk.lock();
  guard.release(lk);
  return static_cast<int>(taken.size());
}

// Publish one batch of results: logits is n x row_floats, row i belongs to
// tickets[i].  row_floats must equal out_floats from create.
void kdlt_bq_complete(void* handle, const int64_t* tickets, int n,
                      const float* logits, int row_floats) {
  auto* q = static_cast<BatchQueue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  for (int i = 0; i < n; ++i) {
    int idx;
    uint64_t gen;
    split_ticket(*q, tickets[i], &idx, &gen);
    Slot& s = q->slots[idx];
    if (s.gen != gen || s.state != SlotState::kInflight) continue;  // stale
    if (s.abandoned) {
      free_slot_locked(q, idx);
      continue;
    }
    std::memcpy(s.out.data(), logits + static_cast<int64_t>(i) * row_floats,
                sizeof(float) * std::min(row_floats, q->out_floats));
    s.state = SlotState::kDone;
  }
  lk.unlock();
  q->cv_done.notify_all();
}

// Fail every ticket in the batch (engine raised): waiters get rc=2.
void kdlt_bq_fail(void* handle, const int64_t* tickets, int n) {
  auto* q = static_cast<BatchQueue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  for (int i = 0; i < n; ++i) {
    int idx;
    uint64_t gen;
    split_ticket(*q, tickets[i], &idx, &gen);
    Slot& s = q->slots[idx];
    if (s.gen != gen || s.state != SlotState::kInflight) continue;
    if (s.abandoned) {
      free_slot_locked(q, idx);
      continue;
    }
    s.state = SlotState::kFailed;
  }
  lk.unlock();
  q->cv_done.notify_all();
}

// Request side: block until the ticket resolves.  0 = ok (row in out),
// 1 = timeout (slot marked abandoned; its capacity is reclaimed later),
// 2 = failed (engine error, or the queue was aborted/destroyed),
// 4 = stale ticket.  A drain-close keeps queued waiters waiting for their
// results rather than failing them.
int kdlt_bq_wait(void* handle, int64_t ticket, float* out, double timeout_s) {
  auto* q = static_cast<BatchQueue*>(handle);
  int idx;
  uint64_t gen;
  split_ticket(*q, ticket, &idx, &gen);
  auto deadline =
      Clock::now() + std::chrono::duration_cast<Clock::duration>(
                         std::chrono::duration<double>(timeout_s));
  std::unique_lock<std::mutex> lk(q->mu);
  ActiveGuard guard(q, lk);
  Slot& s = q->slots[idx];
  int rc;
  bool timed_out = false;
  for (;;) {
    // State checks come BEFORE the timeout verdict: a completion racing the
    // deadline (cv_status::timeout with the slot already kDone/kFailed)
    // must resolve normally -- abandoning a completed slot would leak it
    // forever, since take() only reclaims abandoned slots still pending.
    if (s.gen != gen) {
      rc = 4;
      break;
    }
    if (s.state == SlotState::kDone) {
      std::memcpy(out, s.out.data(), sizeof(float) * q->out_floats);
      free_slot_locked(q, idx);
      rc = 0;
      break;
    }
    if (s.state == SlotState::kFailed) {
      free_slot_locked(q, idx);
      rc = 2;
      break;
    }
    // NOTE deliberately no closed+kPending early-out: close() means DRAIN
    // (matching DynamicBatcher.close(drain=True)) -- the dispatcher keeps
    // taking until the queue is empty, so a queued waiter just keeps
    // waiting for its result; abort()/destroy() fail the slots instead,
    // which resolves waiters through the kFailed branch above.
    if (timed_out) {
      // Genuinely unresolved past the deadline: flag the slot so
      // take/complete reclaims it; the result (if any) is dropped.
      s.abandoned = true;
      rc = 1;
      break;
    }
    timed_out =
        q->cv_done.wait_until(lk, deadline) == std::cv_status::timeout;
  }
  guard.release(lk);
  return rc;
}

// Stop accepting work (drain-close): new submits return -2, but queued
// requests are still taken, completed, and delivered; the dispatcher's
// take() returns 0 once the queue is empty.  Use abort/destroy to fail
// unresolved requests instead.
void kdlt_bq_close(void* handle) {
  auto* q = static_cast<BatchQueue*>(handle);
  {
    std::unique_lock<std::mutex> lk(q->mu);
    q->closed = true;
  }
  q->cv_work.notify_all();
  q->cv_done.notify_all();
}

// Close AND fail everything unresolved immediately (close without drain):
// queued waiters wake with rc=2 instead of being served.  The queue stays
// allocated; call destroy after joining the dispatcher.
void kdlt_bq_abort(void* handle) {
  auto* q = static_cast<BatchQueue*>(handle);
  {
    std::unique_lock<std::mutex> lk(q->mu);
    q->closed = true;
    for (auto& s : q->slots) {
      if (s.state == SlotState::kPending || s.state == SlotState::kInflight)
        s.state = SlotState::kFailed;
    }
    q->pending.clear();
  }
  q->cv_work.notify_all();
  q->cv_done.notify_all();
}

// Introspection for tests/metrics: current pending depth.
int kdlt_bq_pending(void* handle) {
  auto* q = static_cast<BatchQueue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  return static_cast<int>(q->pending.size());
}

}  // extern "C"
