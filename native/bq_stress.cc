// Batch-queue stress driver for race detection (SURVEY.md section 5: the
// dynamic batcher is where this framework has real shared-state concurrency,
// so it gets an explicit sanitizer harness -- the reference has nothing to
// sanitize because its gateway state is per-process globals).
//
//   make -C native stress      # builds with -fsanitize=thread and runs
//
// Scenario per iteration: one dispatcher thread (take -> fake "inference"
// -> complete, with occasional injected failures) against many producer
// threads hammering submit/wait with a mix of generous and tiny timeouts
// (tiny ones force the abandoned-slot reclamation paths).  Ends with a
// drain-close while traffic is still in flight, then a full teardown.
// Exit code 0 = every invariant held under the sanitizer.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* kdlt_bq_create(int capacity, int64_t item_bytes, int out_floats);
void kdlt_bq_destroy(void* q);
int64_t kdlt_bq_submit(void* q, const uint8_t* image);
int kdlt_bq_take(void* q, uint8_t* dst, int max_batch, double max_delay_s,
                 double wait_s, int64_t* tickets);
void kdlt_bq_complete(void* q, const int64_t* tickets, int n,
                      const float* logits, int row_floats);
void kdlt_bq_fail(void* q, const int64_t* tickets, int n);
int kdlt_bq_wait(void* q, int64_t ticket, float* out, double timeout_s);
void kdlt_bq_close(void* q);
void kdlt_bq_abort(void* q);
}

namespace {

constexpr int kItemBytes = 64;
constexpr int kOutFloats = 2;
constexpr int kCapacity = 32;
constexpr int kMaxBatch = 8;
constexpr int kProducers = 16;
constexpr int kRequestsPerProducer = 400;

std::atomic<long> ok{0}, timeouts{0}, failed{0}, rejected{0}, closed{0},
    mismatches{0};

void producer(void* q, int id) {
  uint8_t img[kItemBytes];
  float out[kOutFloats];
  for (int i = 0; i < kRequestsPerProducer; ++i) {
    const uint8_t tag = static_cast<uint8_t>((id * 31 + i) % 251);
    std::memset(img, tag, sizeof(img));
    int64_t t = kdlt_bq_submit(q, img);
    if (t == -1) {
      rejected.fetch_add(1);
      continue;
    }
    if (t == -2) {
      closed.fetch_add(1);
      return;  // queue closed under us; expected near the end
    }
    // Every 7th request uses a near-zero deadline to exercise abandonment.
    const double timeout = (i % 7 == 6) ? 1e-4 : 5.0;
    int rc = kdlt_bq_wait(q, t, out, timeout);
    if (rc == 0) {
      // Result integrity: the dispatcher echoes sum(img) = tag * kItemBytes.
      if (out[0] != static_cast<float>(tag) * kItemBytes) mismatches.fetch_add(1);
      ok.fetch_add(1);
    } else if (rc == 1) {
      timeouts.fetch_add(1);
    } else {
      failed.fetch_add(1);
    }
  }
}

void dispatcher(void* q) {
  std::vector<uint8_t> buf(static_cast<size_t>(kMaxBatch) * kItemBytes);
  int64_t tickets[kMaxBatch];
  float logits[kMaxBatch * kOutFloats];
  long batches = 0;
  for (;;) {
    int n = kdlt_bq_take(q, buf.data(), kMaxBatch, 0.0005, -1.0, tickets);
    if (n == 0) return;  // closed and drained
    ++batches;
    if (batches % 97 == 0) {  // injected engine failure
      kdlt_bq_fail(q, tickets, n);
      continue;
    }
    for (int i = 0; i < n; ++i) {
      long sum = 0;
      for (int b = 0; b < kItemBytes; ++b) sum += buf[i * kItemBytes + b];
      logits[i * kOutFloats] = static_cast<float>(sum);
      logits[i * kOutFloats + 1] = static_cast<float>(2 * sum);
    }
    kdlt_bq_complete(q, tickets, n, logits, kOutFloats);
  }
}

}  // namespace

// abort_mid_load=false: drain-close while producers still submit (late
// submits must see -2, queued work must still be served).  true: abort
// while the dispatcher is mid-take/mid-complete -- this is the race the
// advisor flagged (take's gather vs abort-triggered slot frees); waiters
// must resolve with rc=2, never a torn ticket.
int run_scenario(bool abort_mid_load) {
  void* q = kdlt_bq_create(kCapacity, kItemBytes, kOutFloats);
  if (!q) {
    std::fprintf(stderr, "create failed\n");
    return 1;
  }
  std::thread disp(dispatcher, q);
  std::vector<std::thread> prods;
  for (int i = 0; i < kProducers; ++i) prods.emplace_back(producer, q, i);
  if (abort_mid_load) {
    // Abort as early as possible while traffic is at full blast: no join
    // first, just a tiny sleep so slots are pending AND inflight.
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    kdlt_bq_abort(q);
    for (auto& p : prods) p.join();
  } else {
    prods[0].join();
    kdlt_bq_close(q);
    for (size_t i = 1; i < prods.size(); ++i) prods[i].join();
  }
  disp.join();
  kdlt_bq_destroy(q);

  std::printf(
      "%s: ok=%ld timeouts=%ld failed=%ld rejected=%ld closed=%ld "
      "mismatches=%ld\n",
      abort_mid_load ? "abort" : "drain", ok.load(), timeouts.load(),
      failed.load(), rejected.load(), closed.load(), mismatches.load());
  if (mismatches.load() != 0) return 1;
  // The drain scenario must exercise the happy path; the abort scenario may
  // legitimately kill everything before any completion lands.
  if (!abort_mid_load && ok.load() == 0) return 1;
  return 0;
}

int main() {
  if (int rc = run_scenario(false)) return rc;
  ok = timeouts = failed = rejected = closed = mismatches = 0;
  if (int rc = run_scenario(true)) return rc;
  return 0;
}
