"""The fault-injection framework (serving.faults): parsing, determinism,
inertness when unset, each fault kind's behavior, and the kinds wired
through the real model server handler (error -> 500, disconnect -> dropped
connection, corrupt -> undecodable response, all counted in
kdlt_fault_injected_total)."""

from __future__ import annotations

import time

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
from kubernetes_deep_learning_tpu.serving import faults, protocol
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer


# --- parsing ----------------------------------------------------------------


def test_parse_rules_full_syntax():
    rules = faults.parse_rules(
        "gateway.upstream:error:0.5,server.predict:latency:1.0:25"
    )
    assert rules == (
        faults.FaultRule("gateway.upstream", "error", 0.5, None),
        faults.FaultRule("server.predict", "latency", 1.0, 25.0),
    )


@pytest.mark.parametrize(
    "bad",
    [
        "point:explode:1.0",     # unknown kind
        "point:error:1.5",       # rate out of range
        "point:error",           # missing rate
        ":error:1.0",            # empty point
        "point:error:notafloat",
    ],
)
def test_parse_rules_rejects_garbage(bad):
    # A typo'd chaos experiment must fail loudly, not silently run healthy.
    with pytest.raises(ValueError):
        faults.parse_rules(bad)


def test_from_env_inert_when_unset(monkeypatch):
    monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
    assert faults.from_env() is None
    monkeypatch.setenv(faults.FAULTS_ENV, "   ")
    assert faults.from_env() is None
    monkeypatch.setenv(faults.FAULTS_ENV, "p:error:1.0")
    assert faults.from_env() is not None


# --- determinism ------------------------------------------------------------


def _fire_pattern(injector, point, n=64):
    out = []
    for _ in range(n):
        try:
            injector.fire(point)
            out.append(0)
        except faults.InjectedFault:
            out.append(1)
    return out


def test_same_seed_same_fault_sequence():
    rules = faults.parse_rules("p:error:0.3")
    a = faults.FaultInjector(rules, seed=7)
    b = faults.FaultInjector(rules, seed=7)
    pattern = _fire_pattern(a, "p")
    assert pattern == _fire_pattern(b, "p")
    assert 0 < sum(pattern) < len(pattern)  # rate 0.3 actually samples


def test_per_point_streams_independent_of_interleaving():
    # Firing point q between p's arrivals must not change p's pattern.
    rules = faults.parse_rules("p:error:0.3,q:error:0.3")
    a = faults.FaultInjector(rules, seed=1)
    b = faults.FaultInjector(rules, seed=1)
    pattern_a = _fire_pattern(a, "p")
    pattern_b = []
    for _ in range(64):
        _fire_pattern(b, "q", n=3)  # interleaved q arrivals
        pattern_b.extend(_fire_pattern(b, "p", n=1))
    assert pattern_a == pattern_b


def test_rate_bounds():
    always = faults.FaultInjector(faults.parse_rules("p:error:1.0"))
    with pytest.raises(faults.InjectedFault):
        always.fire("p")
    never = faults.FaultInjector(faults.parse_rules("p:error:0.0"))
    for _ in range(100):
        never.fire("p")
    assert never.counts[("p", "error")] == 0
    # Unconfigured points are free.
    always.fire("other.point")


# --- each kind --------------------------------------------------------------


def test_kind_latency_sleeps():
    inj = faults.FaultInjector(faults.parse_rules("p:latency:1.0:30"))
    t0 = time.perf_counter()
    inj.fire("p")
    assert time.perf_counter() - t0 >= 0.025


def test_kind_hang_sleeps_arg_seconds():
    inj = faults.FaultInjector(faults.parse_rules("p:hang:1.0:0.05"))
    t0 = time.perf_counter()
    inj.fire("p")
    assert time.perf_counter() - t0 >= 0.045


def test_kind_disconnect_raises_connection_error():
    inj = faults.FaultInjector(faults.parse_rules("p:disconnect:1.0"))
    with pytest.raises(faults.InjectedDisconnect):
        inj.fire("p")
    assert issubclass(faults.InjectedDisconnect, ConnectionError)


def test_kind_corrupt_garbles_payload_only_when_firing():
    data = bytes(range(200))
    inj = faults.FaultInjector(faults.parse_rules("p:corrupt:1.0"))
    garbled = inj.corrupt("p", data)
    assert garbled != data and len(garbled) == len(data)
    # fire() ignores corrupt rules (they only apply to payloads).
    inj.fire("p")
    off = faults.FaultInjector(faults.parse_rules("p:corrupt:0.0"))
    assert off.corrupt("p", data) == data


# --- wired through the real model server ------------------------------------


def _stub_server(name, tmp_path, **kw):
    spec = register_spec(
        ModelSpec(
            name=name,
            family="xception",  # never instantiated by StubEngine
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
        )
    )
    root = tmp_path / "models"
    art.save_artifact(
        art.version_dir(str(root), spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        str(root), port=0, buckets=(1, 2), max_delay_ms=1.0, host="127.0.0.1",
        engine_factory=StubEngine, **kw,
    )
    server.warmup()
    server.start()
    return spec, server


def _post(spec, server, n=1, timeout=10.0):
    import requests

    img = np.zeros((n, *spec.input_shape), np.uint8)
    return requests.post(
        f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict",
        data=protocol.encode_predict_request(img),
        headers={"Content-Type": protocol.MSGPACK_CONTENT_TYPE},
        timeout=timeout,
    )


def test_server_without_faults_env_is_inert(tmp_path, monkeypatch):
    monkeypatch.delenv(faults.FAULTS_ENV, raising=False)
    spec, server = _stub_server("faults-inert", tmp_path)
    try:
        assert server._faults is None
        assert _post(spec, server).status_code == 200
    finally:
        server.shutdown()


def test_server_error_fault_becomes_500_and_is_counted(tmp_path, monkeypatch):
    import requests

    monkeypatch.setenv(faults.FAULTS_ENV, "server.predict:error:1.0")
    spec, server = _stub_server("faults-error", tmp_path)
    try:
        r = _post(spec, server)
        assert r.status_code == 500
        assert "injected fault" in r.json()["error"]
        metrics = requests.get(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5
        ).text
        assert (
            'kdlt_fault_injected_total{point="server.predict",kind="error"} 1'
            in metrics
        )
    finally:
        server.shutdown()


def test_server_disconnect_fault_drops_connection(tmp_path, monkeypatch):
    import requests

    monkeypatch.setenv(faults.FAULTS_ENV, "server.predict:disconnect:1.0")
    spec, server = _stub_server("faults-disc", tmp_path)
    try:
        with pytest.raises(requests.RequestException):
            _post(spec, server)
    finally:
        server.shutdown()


def test_server_corrupt_fault_makes_response_undecodable(tmp_path, monkeypatch):
    monkeypatch.setenv(faults.FAULTS_ENV, "server.predict:corrupt:1.0")
    spec, server = _stub_server("faults-corrupt", tmp_path)
    try:
        r = _post(spec, server)
        # The status is still 200 -- corruption is a payload fault, which is
        # exactly why the gateway must decode defensively (502, not silence).
        assert r.status_code == 200
        with pytest.raises(Exception):
            protocol.decode_predict_response(
                r.content, r.headers.get("Content-Type", "")
            )
    finally:
        server.shutdown()


def test_server_latency_fault_delays_requests(tmp_path, monkeypatch):
    monkeypatch.setenv(faults.FAULTS_ENV, "server.predict:latency:1.0:80")
    spec, server = _stub_server("faults-lat", tmp_path)
    try:
        t0 = time.perf_counter()
        assert _post(spec, server).status_code == 200
        assert time.perf_counter() - t0 >= 0.07
    finally:
        server.shutdown()


# --- cache x fault-injection (ISSUE 8 satellite) ----------------------------
# The real gateway.upstream fault point firing under the cache+singleflight
# front door: an injected upstream failure must surface to the client AND
# never be served back from the response cache once the fault clears.


def _gateway_stack(tmp_path, name):
    import os
    import threading
    from functools import partial
    from http.server import HTTPServer, SimpleHTTPRequestHandler

    from PIL import Image

    from kubernetes_deep_learning_tpu.serving.gateway import Gateway

    spec, server = _stub_server(name, tmp_path)
    gw = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name,
        port=0, host="127.0.0.1",
    )
    gw.start()

    class Quiet(SimpleHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

    img_dir = tmp_path / "img"
    img_dir.mkdir()
    rng = np.random.default_rng(0)
    Image.fromarray(
        rng.integers(0, 256, size=(48, 48, 3), dtype=np.uint8)
    ).save(os.path.join(str(img_dir), "img.png"))
    httpd = HTTPServer(
        ("127.0.0.1", 0), partial(Quiet, directory=str(img_dir))
    )
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    img_url = f"http://127.0.0.1:{httpd.server_address[1]}/img.png"
    return spec, server, gw, httpd, img_url


def test_gateway_injected_upstream_error_is_never_cached(
    tmp_path, monkeypatch
):
    import requests

    # The gateway's own injector fires at gateway.upstream (rate 1.0, no
    # failover so the injected failure surfaces instead of retrying).
    monkeypatch.setenv(faults.FAULTS_ENV, "gateway.upstream:error:1.0")
    monkeypatch.setenv("KDLT_FAILOVER", "0")
    spec, server, gw, httpd, img_url = _gateway_stack(
        tmp_path, "faults-cache-gw"
    )
    try:
        url = f"http://127.0.0.1:{gw.port}/predict"
        r1 = requests.post(url, json={"url": img_url}, timeout=10)
        assert r1.status_code in (502, 503)
        assert r1.headers.get(protocol.CACHE_STATUS_HEADER) == "miss"
        # The failure was NOT stored: the cache holds nothing.
        dbg = requests.get(
            f"http://127.0.0.1:{gw.port}/debug/cache", timeout=5
        ).json()
        assert dbg["entries"] == 0
        # Fault cleared: the same URL re-dispatches upstream and succeeds
        # -- a cached error here would be a silent availability bug.
        gw._faults = None
        r2 = requests.post(url, json={"url": img_url}, timeout=10)
        assert r2.status_code == 200
        assert r2.headers.get(protocol.CACHE_STATUS_HEADER) == "miss"
        r3 = requests.post(url, json={"url": img_url}, timeout=10)
        assert r3.status_code == 200
        assert r3.headers.get(protocol.CACHE_STATUS_HEADER) == "hit"
        assert r3.json() == r2.json()
    finally:
        gw.shutdown()
        server.shutdown()
        httpd.shutdown()
