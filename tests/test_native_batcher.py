"""NativeBatcher (C++ batchqueue.cc) tests: mirrors test_batcher.py's
scenarios so both implementations provably share policy and surface."""

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from types import SimpleNamespace

import numpy as np
import pytest

pytest.importorskip(
    "kubernetes_deep_learning_tpu.ops._native",
    reason="native library unavailable (no toolchain)",
)

from kubernetes_deep_learning_tpu.runtime import create_batcher
from kubernetes_deep_learning_tpu.runtime.batcher import BatcherClosed, QueueFull
from kubernetes_deep_learning_tpu.runtime.native_batcher import NativeBatcher


class FakeEngine:
    """Deterministic stand-in: logit row = [sum(image), 2*sum(image)]."""

    max_batch = 8
    spec = SimpleNamespace(input_shape=(2, 2, 3), num_classes=2)

    def __init__(self, delay_s=0.0, fail=False):
        self.delay_s = delay_s
        self.fail = fail
        self.batch_sizes = []
        self._lock = threading.Lock()

    def predict(self, images: np.ndarray) -> np.ndarray:
        with self._lock:
            self.batch_sizes.append(images.shape[0])
        if self.fail:
            raise RuntimeError("boom")
        if self.delay_s:
            time.sleep(self.delay_s)
        sums = images.reshape(images.shape[0], -1).sum(axis=1).astype(np.float32)
        return np.stack([sums, sums * 2], axis=1)


def _img(value: int) -> np.ndarray:
    return np.full((2, 2, 3), value, np.uint8)


def test_create_batcher_auto_respects_core_count(monkeypatch):
    import os

    from kubernetes_deep_learning_tpu.runtime import DynamicBatcher

    # With a core to overlap with, auto picks the C++ queue.  (The check is
    # affinity-aware, so patch sched_getaffinity where it exists.)
    monkeypatch.setattr(os, "cpu_count", lambda: 4)
    if hasattr(os, "sched_getaffinity"):
        monkeypatch.setattr(os, "sched_getaffinity", lambda pid: {0, 1, 2, 3})
    b = create_batcher(FakeEngine(), impl="auto", max_delay_ms=1)
    try:
        assert isinstance(b, NativeBatcher)
    finally:
        b.close()
    # On a single-core host the GIL convoys the native pipeline's
    # cross-thread handoffs (measured: bench.py --batcher-sweep, BENCH.md
    # round 3), so auto degrades to the one-thread Python dispatcher.
    monkeypatch.setattr(os, "cpu_count", lambda: 1)
    if hasattr(os, "sched_getaffinity"):
        monkeypatch.setattr(os, "sched_getaffinity", lambda pid: {0})
    b = create_batcher(FakeEngine(), impl="auto", max_delay_ms=1)
    try:
        assert isinstance(b, DynamicBatcher)
    finally:
        b.close()


def test_native_batcher_async_stub_correctness():
    """The depth-2 pipeline against the async serial-device stub
    (runtime.stub async_device): concurrent requests must map back to
    their own checksum rows even with a batch in flight during assembly
    -- the aliasing/ping-pong contract under real overlap."""
    import tempfile

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.runtime.stub import StubEngine, stub_logits

    spec = register_spec(
        ModelSpec(
            name="nb-async-stub",
            family="xception",
            input_shape=(8, 8, 3),
            labels=("a", "b"),
            preprocessing="tf",
        )
    )
    root = tempfile.mkdtemp()
    art.save_artifact(art.version_dir(root, spec.name, 1), spec, {"params": {}}, None, {})
    artifact = art.load_artifact(art.version_dir(root, spec.name, 1))
    eng = StubEngine(artifact, device_ms_per_batch=1.0, async_device=True)
    eng.warmup()
    assert hasattr(eng, "predict_async")
    b = NativeBatcher(eng, max_delay_ms=1)
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (24, *spec.input_shape), np.uint8)
    try:
        with ThreadPoolExecutor(max_workers=12) as pool:
            outs = list(pool.map(b.predict, imgs))
        want = stub_logits(imgs, spec.num_classes)
        np.testing.assert_allclose(np.stack(outs), want)
    finally:
        b.close()
        eng.close()


def test_single_request_roundtrip():
    b = NativeBatcher(FakeEngine(), max_delay_ms=1)
    try:
        out = b.predict(_img(3))
        assert out.tolist() == [36.0, 72.0]
    finally:
        b.close()


def test_concurrent_requests_batch_and_map_correctly():
    eng = FakeEngine(delay_s=0.02)
    b = NativeBatcher(eng, max_delay_ms=5)
    results: dict[int, np.ndarray] = {}
    errors = []

    def worker(v):
        try:
            results[v] = b.predict(_img(v))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(v,)) for v in range(40)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        for v in range(40):
            assert results[v].tolist() == [v * 12.0, v * 24.0], v
        # while the engine sleeps, the queue must coalesce into real batches
        assert max(eng.batch_sizes) > 1
        assert all(s <= eng.max_batch for s in eng.batch_sizes)
    finally:
        b.close()


def test_engine_error_propagates_and_batcher_survives():
    eng = FakeEngine(fail=True)
    b = NativeBatcher(eng, max_delay_ms=1)
    try:
        with pytest.raises(RuntimeError, match="boom"):
            b.predict(_img(1))
        eng.fail = False
        assert b.predict(_img(2)).tolist() == [24.0, 48.0]
    finally:
        b.close()


def test_queue_cap_rejects():
    # Capacity 2, engine busy 300 ms per batch: 8 concurrent requests cannot
    # all fit, so at least one must be rejected with the retryable QueueFull
    # (and the accepted ones must all succeed).
    eng = FakeEngine(delay_s=0.3)
    b = NativeBatcher(eng, max_delay_ms=0, queue_cap=2)
    ok, rejected, other = [], [], []

    def worker(v):
        try:
            ok.append(b.predict(_img(v)))
        except QueueFull:
            rejected.append(v)
        except Exception as e:  # pragma: no cover
            other.append(e)

    threads = [threading.Thread(target=worker, args=(v,)) for v in range(8)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not other
        assert rejected, "capacity-2 queue accepted 8 concurrent requests"
        assert len(ok) == 8 - len(rejected)
    finally:
        b.close()


def test_timeout_reclaims_capacity():
    eng = FakeEngine(delay_s=0.2)
    b = NativeBatcher(eng, max_delay_ms=0, queue_cap=2)
    try:
        with pytest.raises(FuturesTimeout):
            b.predict(_img(1), timeout=0.01)
        # The timed-out slot must be reclaimed: capacity-2 queue still
        # accepts and serves 2 concurrent requests afterwards.
        time.sleep(0.3)
        outs = []
        pool = [
            threading.Thread(target=lambda v=v: outs.append(b.predict(_img(v))))
            for v in (2, 3)
        ]
        for t in pool:
            t.start()
        for t in pool:
            t.join()
        assert len(outs) == 2
    finally:
        b.close()


def test_dispatcher_survives_all_abandoned_round():
    # Regression: while the engine is stuck on batch 1, every queued waiter
    # times out (slots abandoned).  The dispatcher's next take() pops only
    # abandoned slots -- it must go back to waiting, NOT exit as if closed;
    # the batcher has to keep serving afterwards.
    eng = FakeEngine(delay_s=0.3)
    b = NativeBatcher(eng, max_delay_ms=0, queue_cap=8)
    try:
        first = threading.Thread(target=lambda: b.predict(_img(0)))
        first.start()
        time.sleep(0.05)  # batch 1 in flight; engine busy 300 ms
        for v in (1, 2):
            with pytest.raises(FuturesTimeout):
                b.predict(_img(v), timeout=0.01)  # queued, then abandoned
        first.join()
        time.sleep(0.2)  # let the dispatcher churn through the abandoned round
        eng.delay_s = 0.0
        assert b.predict(_img(5)).tolist() == [60.0, 120.0]
    finally:
        b.close()


def test_close_without_drain_rejects_new_requests():
    b = NativeBatcher(FakeEngine(), max_delay_ms=0)
    b.close(drain=False)
    with pytest.raises(BatcherClosed):
        b.predict(_img(1))


def test_close_with_drain_serves_queued_work():
    # Parity with DynamicBatcher.close(drain=True): requests queued at close
    # time must be SERVED, not failed with BatcherClosed.
    eng = FakeEngine(delay_s=0.1)
    b = NativeBatcher(eng, max_delay_ms=0, queue_cap=8)
    outs, errs = [], []

    def worker(v):
        try:
            outs.append(b.predict(_img(v)))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(v,)) for v in range(4)]
    for t in threads:
        t.start()
    # Positive handshake, not a sleep: close only once all 4 requests are
    # observably submitted (taken into a batch or still pending in C++) --
    # a fixed delay races thread startup on a loaded machine.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        submitted = sum(eng.batch_sizes) + b._lib.kdlt_bq_pending(b._q)
        if submitted >= 4:
            break
        time.sleep(0.005)
    else:  # pragma: no cover
        pytest.fail("requests never queued")
    b.close(drain=True)
    for t in threads:
        t.join()
    assert not errs
    assert len(outs) == 4


def test_served_through_model_server(tmp_path):
    # End to end: a real artifact served with batcher_impl="native".
    import requests

    from kubernetes_deep_learning_tpu.export.exporter import export_model
    from kubernetes_deep_learning_tpu.models import init_variables
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    spec = register_spec(
        ModelSpec(
            name="native-bq-model",
            family="vit-tiny",
            input_shape=(16, 16, 3),
            labels=("a", "b"),
            preprocessing="tf",
        )
    )
    export_model(spec, init_variables(spec, seed=0), str(tmp_path))
    server = ModelServer(
        str(tmp_path), port=0, buckets=(1, 2), batcher_impl="native"
    )
    try:
        assert isinstance(server.models["native-bq-model"].batcher, NativeBatcher)
        server.warmup()
        server.start()
        r = requests.post(
            f"http://localhost:{server.port}/v1/models/native-bq-model:predict",
            json={"instances": np.zeros((1, 16, 16, 3), np.uint8).tolist()},
            timeout=30,
        )
        assert r.status_code == 200
        assert set(r.json()["predictions"][0]) == {"a", "b"}
    finally:
        server.shutdown()


class FakeAsyncEngine(FakeEngine):
    """Engine exposing the predict_async pipelining hook."""

    def predict_async(self, images):
        return self.predict(np.array(images)), images.shape[0]


class LazyFailure:
    """predict_async result whose materialization (device sync) fails."""

    def __array__(self, dtype=None, copy=None):
        raise RuntimeError("device exploded at sync")


def test_async_pipeline_roundtrip_and_mapping():
    eng = FakeAsyncEngine(delay_s=0.01)
    b = NativeBatcher(eng, max_delay_ms=2)
    results, errors = {}, []

    def worker(v):
        try:
            results[v] = b.predict(_img(v))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(v,)) for v in range(30)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        for v in range(30):
            assert results[v].tolist() == [v * 12.0, v * 24.0], v
        assert max(eng.batch_sizes) > 1  # pipelined batches still coalesce
    finally:
        b.close()


def test_async_sync_failure_fails_only_its_batch():
    eng = FakeAsyncEngine()
    b = NativeBatcher(eng, max_delay_ms=1)
    try:
        real = eng.predict_async
        eng.predict_async = lambda images: (LazyFailure(), images.shape[0])
        with pytest.raises(RuntimeError, match="device exploded"):
            b.predict(_img(1))
        eng.predict_async = real
        assert b.predict(_img(2)).tolist() == [24.0, 48.0]
    finally:
        b.close()
