"""Version hot-reload: the TF-Serving version-watching convention, in-tree.

The reference bakes exactly one version into the image and redeploys to
update (reference tf-serving.dockerfile:5); the underlying TF-Serving binary
would hot-load a higher-numbered dir.  Our server implements that convention:
poll_versions() scans /models/<name>/ and atomically swaps in new warmed
versions (serving/model_server.py).
"""

from __future__ import annotations

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import export_model
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer


@pytest.fixture(scope="module")
def reload_spec() -> ModelSpec:
    return register_spec(
        ModelSpec(
            name="reload-model",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",
        )
    )


def test_hot_reload_new_version(reload_spec, tmp_path):
    root = str(tmp_path)
    v1_vars = init_variables(reload_spec, seed=1)
    export_model(reload_spec, v1_vars, root, dtype=np.float32)

    server = ModelServer(root, port=0, buckets=(1, 2), max_delay_ms=1.0)
    try:
        server.warmup()
        assert server.models[reload_spec.name].version == 1

        x = np.zeros((1, 96, 96, 3), np.uint8)
        logits_v1 = server.models[reload_spec.name].predict(x)

        # Nothing new on disk -> no-op poll.
        assert server.poll_versions() == []

        # Drop version 2 with different weights; poll must swap it in warmed.
        v2_vars = init_variables(reload_spec, seed=2)
        export_model(reload_spec, v2_vars, root, dtype=np.float32)
        assert server.poll_versions() == [f"{reload_spec.name} v2"]
        served = server.models[reload_spec.name]
        assert served.version == 2
        assert served.engine.ready  # warmed before the swap
        assert server.ready

        logits_v2 = served.predict(x)
        assert not np.allclose(logits_v1, logits_v2)  # weights actually changed

        # Old version's metric series dropped, new version's present.
        page = server.registry.render()
        assert 'version="2"' in page
        assert 'version="1"' not in page
    finally:
        server.shutdown()


def test_reload_one_model_leaves_other_models_untouched(tmp_path):
    """Registry hot-reload isolation: dropping /models/<name>/<n+1>
    reloads ONLY that model -- another model's ServedModel object, engine,
    and IN-FLIGHT requests are unaffected (the scheduling lane survives
    engine swaps, and swaps happen per model)."""
    import threading
    import time

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.runtime.stub import StubEngine, stub_logits

    specs = {}
    for name in ("rl-a", "rl-b"):
        specs[name] = register_spec(ModelSpec(
            name=name, family="xception", input_shape=(32, 32, 3),
            labels=("x", "y"),
        ))
        art.save_artifact(
            art.version_dir(str(tmp_path), name, 1), specs[name],
            {"params": {}}, None, {},
        )
    # rl-b's simulated device is slow, so a request on it is reliably
    # IN FLIGHT while rl-a reloads.
    device_ms = {"rl-a": 1.0, "rl-b": 400.0}
    server = ModelServer(
        str(tmp_path), port=0, buckets=(1, 2), max_delay_ms=1.0,
        host="127.0.0.1",
        engine_factory=lambda a, **kw: StubEngine(
            a, async_device=True,
            device_ms_per_batch=device_ms[a.spec.name], **kw,
        ),
    )
    try:
        server.warmup()
        b_before = server.models["rl-b"]
        img = np.full((1, 32, 32, 3), 7, np.uint8)
        result: dict = {}

        def inflight_b():
            result["logits"] = b_before.predict(img)

        t = threading.Thread(target=inflight_b)
        t.start()
        time.sleep(0.05)  # the rl-b batch is now on its slow device
        # Drop rl-a v2 and reload while rl-b's request is in flight.
        art.save_artifact(
            art.version_dir(str(tmp_path), "rl-a", 2), specs["rl-a"],
            {"params": {"v": np.ones(1, np.float32)}}, None, {},
        )
        assert server.poll_versions() == ["rl-a v2"]
        assert server.models["rl-a"].version == 2
        # rl-b: same ServedModel object, same engine, request completes.
        assert server.models["rl-b"] is b_before
        t.join(timeout=10)
        assert not t.is_alive()
        np.testing.assert_array_equal(
            result["logits"], stub_logits(img, 2)
        )
        # Metrics: rl-a's v1 series dropped, v2 present; rl-b's v1 intact.
        page = server.registry.render()
        assert 'model="rl-a",version="2"' in page
        assert 'model="rl-a",version="1"' not in page
        assert 'model="rl-b",version="1"' in page
    finally:
        server.shutdown()


def test_broken_version_dir_is_skipped(reload_spec, tmp_path):
    root = str(tmp_path)
    export_model(reload_spec, init_variables(reload_spec, seed=1), root, dtype=np.float32)
    server = ModelServer(root, port=0, buckets=(1,), max_delay_ms=1.0)
    try:
        server.warmup()
        # A half-written version dir (no artifact files) must not take down
        # the serving version.
        (tmp_path / reload_spec.name / "2").mkdir()
        assert server.poll_versions() == []
        assert server.models[reload_spec.name].version == 1
        assert server.ready
    finally:
        server.shutdown()
