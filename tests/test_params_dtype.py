"""bfloat16 param storage: export roundtrip + logit tolerance vs float32."""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from kubernetes_deep_learning_tpu.export import export_model, load_artifact
from kubernetes_deep_learning_tpu.export.exporter import cast_params
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.runtime import InferenceEngine


def test_cast_params_halves_float_leaves(tiny_spec):
    variables = init_variables(tiny_spec, seed=0)
    cast = cast_params(variables, jnp.bfloat16)
    import jax

    for a, b in zip(jax.tree.leaves(variables), jax.tree.leaves(cast)):
        if a.dtype == jnp.float32:
            assert b.dtype == jnp.bfloat16
        else:
            assert b.dtype == a.dtype


def test_bf16_export_serves_and_matches_f32(tiny_spec, tmp_path):
    variables = init_variables(tiny_spec, seed=0)
    d32 = export_model(tiny_spec, variables, str(tmp_path / "f32"))
    d16 = export_model(
        tiny_spec, variables, str(tmp_path / "bf16"), params_dtype=jnp.bfloat16
    )

    # bf16 artifact params are about half the size on disk.
    s32 = os.path.getsize(os.path.join(d32, "params.msgpack"))
    s16 = os.path.getsize(os.path.join(d16, "params.msgpack"))
    assert s16 < 0.6 * s32

    a16 = load_artifact(d16)
    assert a16.metadata["params_dtype"] == "bfloat16"

    e32 = InferenceEngine(load_artifact(d32), buckets=(2,))
    e16 = InferenceEngine(a16, buckets=(2,))
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(2, *tiny_spec.input_shape), dtype=np.uint8)
    l32 = e32.predict(x)
    l16 = e16.predict(x)
    # bf16 weight rounding shifts logits slightly; they must stay close in
    # absolute terms (logit scale here is O(1)).
    np.testing.assert_allclose(l16, l32, atol=0.05)
