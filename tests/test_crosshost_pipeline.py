"""Pipelined cross-host dispatch (ISSUE 5): in-flight budget, stall
detection, lockstep equivalence, and the serving-path wiring.

Two tiers of tests:

- single-process (a "fleet" of one -- jax.process_count() == 1 skips the
  control channel but exercises the whole pipelined round path: in-flight
  ledger, budget semaphore, watch wiring, handle materialization);
- a real 2-process fleet (same env-triplet bring-up as test_crosshost.py)
  proving pipelined logits are BIT-IDENTICAL to lockstep across bucket
  changes and a mid-stream RELOAD, and that a follower whose round wedges
  exits 70 (its own stall detection, not the leader's).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tests.test_crosshost import _run_fleet, _run_fleet_raw


@pytest.fixture(scope="module")
def xh_pair():
    """One CrossHostForward (depth 4 -- the deepest the tests drive) plus
    its reference forward, shared across this module's single-process
    tests (construction compiles the SPMD program: seconds on CPU)."""
    import jax
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import build_forward, init_variables
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.parallel.crosshost import CrossHostForward
    from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh

    spec = register_spec(
        ModelSpec(
            name="xh-pipe-test",
            family="vit-tiny",
            input_shape=(16, 16, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",
        )
    )
    variables = init_variables(spec, seed=11)
    mesh = make_mesh(8, devices=jax.devices())
    xh = CrossHostForward(spec, mesh, variables, buckets=(4, 8), pipeline_depth=4)
    ref = jax.jit(build_forward(spec, dtype=jnp.bfloat16, fast=False))
    return xh, ref, variables


class _GatedArray:
    """Stands in for a dispatched device array whose completion the test
    controls: block_until_ready() blocks until released."""

    def __init__(self, value: np.ndarray):
        self._value = value
        self._event = threading.Event()

    def release(self):
        self._event.set()

    def block_until_ready(self):
        assert self._event.wait(timeout=30.0), "gated round never released"
        return self

    def __array__(self, dtype=None):
        return np.asarray(self._value, dtype=dtype)


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_inflight_budget_respected(xh_pair, monkeypatch, depth):
    """predict_async admits at most ``depth`` unmaterialized rounds; the
    next submit blocks until one completes (the backpressure contract),
    at depth 1/2/4."""
    xh, _ref, _v = xh_pair
    monkeypatch.setattr(xh, "pipeline_depth", depth)
    monkeypatch.setattr(xh, "_slots", threading.Semaphore(depth))
    gates = []
    logits = np.zeros((8, 3), np.float32)
    monkeypatch.setattr(
        xh, "_dispatch_round",
        lambda batch, fast=False: gates.append(_GatedArray(logits)) or gates[-1],
    )
    images = np.zeros((8, 16, 16, 3), np.uint8)

    handles = [xh.predict_async(images) for _ in range(depth)]
    assert xh.inflight_rounds == depth

    blocked_result = []

    def over_budget():
        blocked_result.append(xh.predict_async(images))

    t = threading.Thread(target=over_budget, daemon=True)
    t.start()
    time.sleep(0.15)
    # The over-budget submit must be parked on the semaphore, not admitted.
    assert not blocked_result and len(gates) == depth

    # Completing the OLDEST round frees exactly one slot.
    gates[0].release()
    np.asarray(handles[0][0])
    t.join(timeout=10.0)
    assert not t.is_alive() and len(blocked_result) == 1
    assert len(gates) == depth + 1

    for g in gates[1:]:
        g.release()
    for h, n in handles[1:] + blocked_result:
        assert np.asarray(h).shape == (8, 3)
    assert xh.inflight_rounds == 0


def test_depth1_is_lockstep(xh_pair, monkeypatch):
    """Depth 1 reproduces lockstep dispatch exactly: a second submit is
    not even BROADCAST until the first round materialized (safe fallback,
    acceptance criterion)."""
    xh, _ref, _v = xh_pair
    monkeypatch.setattr(xh, "pipeline_depth", 1)
    monkeypatch.setattr(xh, "_slots", threading.Semaphore(1))
    order = []
    real_send = xh._send_round

    def logged_send(flag, aux, payload=b""):
        order.append(("send", flag))
        return real_send(flag, aux, payload)

    monkeypatch.setattr(xh, "_send_round", logged_send)
    images = np.zeros((4, 16, 16, 3), np.uint8)
    h1, n1 = xh.predict_async(images)
    t = threading.Thread(
        target=lambda: order.append(("done2", xh.predict(images).shape)),
        daemon=True,
    )
    t.start()
    time.sleep(0.15)
    assert len([e for e in order if e[0] == "send"]) == 1  # second not sent
    np.asarray(h1)  # materialize round 1 -> slot frees -> round 2 proceeds
    t.join(timeout=30.0)
    assert not t.is_alive()
    assert [e[0] for e in order] == ["send", "send", "done2"]


def test_pipelined_matches_lockstep_single_process(xh_pair):
    """Bit-identical logits: the same request sequence (bucket changes
    included) served sync (lockstep) then pipelined at depth 4.

    The pipelined arm drives the raw API the way a real caller must:
    materialize the oldest round once ``depth`` are in flight (submitting
    past the budget without materializing anything would just park on the
    backpressure semaphore -- the contract test_inflight_budget_respected
    proves -- since only the serving dispatcher has a completion thread)."""
    from collections import deque

    xh, _ref, _v = xh_pair
    rng = np.random.default_rng(3)
    batches = [
        rng.integers(0, 256, (n, 16, 16, 3), np.uint8)
        for n in (8, 3, 4, 7, 2, 8)
    ]
    lockstep = [xh.predict(b) for b in batches]
    pipelined = []
    pending = deque()
    for b in batches:  # budget 4: up to 4 rounds overlap
        pending.append(xh.predict_async(b))
        while len(pending) >= xh.pipeline_depth:
            h, n = pending.popleft()
            pipelined.append(np.asarray(h)[:n])
    while pending:
        h, n = pending.popleft()
        pipelined.append(np.asarray(h)[:n])
    for a, b in zip(lockstep, pipelined):
        assert np.array_equal(a, b), "pipelined logits diverge from lockstep"


def test_predict_async_failure_releases_slot(xh_pair, monkeypatch):
    """A broadcast/dispatch failure must not leak an in-flight slot (the
    budget would shrink forever under transient errors)."""
    xh, _ref, _v = xh_pair

    def boom(batch, fast=False):
        raise RuntimeError("injected dispatch failure")

    monkeypatch.setattr(xh, "_dispatch_round", boom)
    images = np.zeros((4, 16, 16, 3), np.uint8)
    before = xh.inflight_rounds
    with pytest.raises(RuntimeError, match="injected"):
        xh.predict_async(images)
    assert xh.inflight_rounds == before


def test_round_stall_watch_arming_and_ewma():
    """The leader/follower stall watch: unarmed while a (mode, bucket) has
    no completed sample (compile round), EWMA-bounded after; on_stall is
    injectable so the exit(70) path is assertable in-process."""
    from kubernetes_deep_learning_tpu.parallel.crosshost import RoundStallWatch

    fired = []
    watch = RoundStallWatch(
        floor_s=0.1, multiple=2.0, label="test", on_stall=fired.append
    )
    key = ("exact", 8)
    # Compile round: in flight way past the floor with no sample -> silent.
    watch.begin(0, key)
    time.sleep(0.4)
    assert not fired
    watch.complete(0, 0.01)  # seeds the EWMA
    # Steady-state round past max(floor, multiple x EWMA) -> stall fires.
    watch.begin(1, key)
    deadline = time.monotonic() + 5.0
    while not fired and time.monotonic() < deadline:
        time.sleep(0.02)
    assert fired and "stall bound" in fired[0]
    watch.stop()


def test_follower_stall_detection_exits_70():
    """The follower-side watch's REAL stall action: a subprocess whose
    steady-state round never completes must exit 70 (the gang-restart
    contract), driven through the exact RoundStallWatch defaults the
    follower loop uses."""
    src = (
        "import time\n"
        "from kubernetes_deep_learning_tpu.parallel.crosshost import "
        "RoundStallWatch\n"
        "w = RoundStallWatch(floor_s=0.2, multiple=2.0, label='follower')\n"
        "w.begin(0, ('exact', 8)); w.complete(0, 0.01)\n"
        "w.begin(1, ('exact', 8))  # never completes: a wedged collective\n"
        "time.sleep(30)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", src],
        capture_output=True, text=True, timeout=60,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 70, (proc.returncode, proc.stdout, proc.stderr)
    assert "exiting 70" in proc.stdout


def test_dispatcher_uses_engine_depth_and_label(xh_pair):
    """The serving wiring: ServedModel's InFlightDispatcher takes the
    engine's preferred depth (the fleet budget, not KDLT_PIPELINE_DEPTH)
    and labels the kdlt_pipeline_* series with engine="crosshost"."""
    from kubernetes_deep_learning_tpu.runtime.engine import InFlightDispatcher
    from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

    xh, _ref, _v = xh_pair

    class _Artifact:
        spec = xh.spec
        path = "/models/xh-pipe-test/1"
        variables = None
        metadata = {}

    from kubernetes_deep_learning_tpu.parallel.crosshost import CrossHostEngine

    registry = metrics_lib.Registry()
    engine = CrossHostEngine(_Artifact(), xh, registry=registry)
    assert engine.preferred_pipeline_depth == xh.pipeline_depth
    assert engine.pipeline_engine_label == "crosshost"

    disp = InFlightDispatcher(
        engine, depth=engine.preferred_pipeline_depth, registry=registry
    )
    try:
        rng = np.random.default_rng(5)
        images = rng.integers(0, 256, (4, 16, 16, 3), np.uint8)
        futs = [disp.submit(images) for _ in range(3)]
        outs = [f.result(timeout=60) for f in futs]
        want = xh.predict(images)
        for o in outs:
            assert np.array_equal(o, want)
    finally:
        disp.close()
    page = registry.render()
    assert 'kdlt_pipeline_execute_seconds_count{engine="crosshost"}' in page
    assert "kdlt_crosshost_rounds_total" in page
    assert "kdlt_crosshost_pipeline_depth" in page


_EQUIVALENCE_WORKER = r"""
import os, sys, tempfile
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize()
import jax
import numpy as np
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import CrossHostForward
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.export import artifact as art

spec = register_spec(ModelSpec(
    name="xh-equiv", family="vit-tiny", input_shape=(16, 16, 3),
    labels=("a", "b", "c"), preprocessing="tf",
))
root = sys.argv[2]
v1 = init_variables(spec, seed=9)
v2 = init_variables(spec, seed=23)
if jax.process_index() == 0:
    art.save_artifact(art.version_dir(root, spec.name, 1), spec, v1, None, {})
    art.save_artifact(art.version_dir(root, spec.name, 2), spec, v2, None, {})
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("artifacts-written")

mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(
    spec, mesh, v1, buckets=(4, 8), model_root=root, model_name=spec.name,
    pipeline_depth=2,
)
xh.version = 1

if sys.argv[1] == "follower":
    rounds = xh.follower_loop()
    # 6 predict rounds per arm x 2 arms (RELOAD rounds are not predicts).
    assert rounds == 12, f"expected 12 predict rounds, served {rounds}"
    print("FOLLOWER-OK", flush=True)
    sys.exit(0)

rng = np.random.default_rng(0)
# Bucket changes (4 and 8) plus partial batches, same sequence both arms.
batches = [
    rng.integers(0, 256, (n, *spec.input_shape), np.uint8)
    for n in (8, 3, 4, 7, 2, 8)
]

def arm(pipelined):
    # Rounds 1-3 on v1, mid-stream RELOAD to v2, rounds 4-6 on v2.
    outs = []
    def run(seq):
        if pipelined:
            # Sliding window at the budget: materialize the oldest once
            # depth rounds are in flight (submitting past the budget
            # without materializing would park on the backpressure
            # semaphore forever -- there is no completion thread here).
            from collections import deque
            pending = deque()
            for b in seq:
                pending.append(xh.predict_async(b))  # depth-2 overlap
                while len(pending) >= xh.pipeline_depth:
                    h, n = pending.popleft()
                    outs.append(np.asarray(h)[:n])
            while pending:
                h, n = pending.popleft()
                outs.append(np.asarray(h)[:n])
        else:
            outs.extend(xh.predict(b) for b in seq)
    run(batches[:3])
    xh.reload(2)
    run(batches[3:])
    xh.reload(1)  # reset for the next arm
    return outs

lockstep = arm(pipelined=False)
pipelined = arm(pipelined=True)
for i, (a, b) in enumerate(zip(lockstep, pipelined)):
    assert np.array_equal(a, b), f"round {i}: pipelined logits diverge"
xh.shutdown()
print("LEADER-OK", flush=True)
"""


def test_multiprocess_pipelined_bit_identical_to_lockstep():
    """The tentpole's equivalence bar on a REAL 2-process fleet: the same
    round sequence -- bucket changes and a mid-stream RELOAD included --
    produces bit-identical logits lockstep vs pipelined (depth 2)."""
    import tempfile

    root = tempfile.mkdtemp(prefix="kdlt-xh-equiv-")
    leader_out, follower_out = _run_fleet(_EQUIVALENCE_WORKER, extra_args=[root])
    assert "LEADER-OK" in leader_out, leader_out[-2000:]
    assert "FOLLOWER-OK" in follower_out, follower_out[-2000:]


_FOLLOWER_STALL_WORKER = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
if sys.argv[1] == "follower":
    # Tight stall bound so the wedged round is declared quickly; the
    # leader keeps the default (it must NOT be the one exiting 70 here).
    os.environ["KDLT_XH_STALL_FLOOR_S"] = "1.0"
    os.environ["KDLT_XH_STALL_MULTIPLE"] = "2.0"
from kubernetes_deep_learning_tpu.utils.platform import force_platform
force_platform("cpu")
from kubernetes_deep_learning_tpu.utils.distributed import initialize
assert initialize()
import jax
import numpy as np
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.parallel.crosshost import (
    CrossHostForward, _PREDICT,
)
from kubernetes_deep_learning_tpu.models import init_variables

spec = register_spec(ModelSpec(
    name="xh-stall", family="vit-tiny", input_shape=(16, 16, 3),
    labels=("a", "b", "c"), preprocessing="tf",
))
variables = init_variables(spec, seed=3)
mesh = make_mesh(8, devices=jax.devices())
xh = CrossHostForward(spec, mesh, variables, buckets=(8,), pipeline_depth=2)

if sys.argv[1] == "follower":
    xh.follower_loop()  # the stall watch must exit(70) from inside
    print("FOLLOWER-UNEXPECTED-RETURN", flush=True)
    os._exit(1)

rng = np.random.default_rng(0)
batch = rng.integers(0, 256, (8, *spec.input_shape), np.uint8)
xh.predict(batch)  # warm round: compiles AND seeds the follower's EWMA
# Now wedge the fleet mid-round: send the control+payload for a round the
# leader never dispatches its own collective half of.  The follower
# dispatches, its collective blocks on the absent leader, and ITS stall
# watch -- not the leader's -- must end the process with exit 70.
xh._send_round(_PREDICT, 8, batch.tobytes())
time.sleep(12)
os._exit(0)
"""


def test_follower_stall_exits_70_in_fleet():
    """End to end on a real fleet: a round wedged by a vanished leader
    half trips the FOLLOWER's own EWMA stall detection -> exit 70 (the
    satellite's follower-side completion protocol)."""
    leader, follower = _run_fleet_raw(_FOLLOWER_STALL_WORKER, timeout=240)
    (l_rc, l_out), (f_rc, f_out) = leader, follower
    assert f_rc == 70, f"follower rc {f_rc}:\n{f_out[-2000:]}"
    assert "exiting 70" in f_out, f_out[-2000:]
    assert l_rc == 0, f"leader rc {l_rc}:\n{l_out[-2000:]}"
