"""The decode subsystem's device half (runtime/decode.py): paged
KV-cache bookkeeping, the continuous-batching scheduler, and the lane's
load-bearing invariant -- token streams from a shifting continuous batch
are bit-identical to solo decode.

The engine under test is the lane's real engine (tiny byte-level
transformer, real jitted prefill/step programs on CPU), sized small
(2 slots, 8-token pages) so the whole file compiles two prefill buckets
plus one step program once, module-scoped.  Pure token/SSE plumbing
tests run first and need no jax at all.
"""

from __future__ import annotations

import threading
import time

import pytest

from kubernetes_deep_learning_tpu.runtime import decode as decode_lib
from kubernetes_deep_learning_tpu.runtime.batcher import QueueFull
from kubernetes_deep_learning_tpu.serving import protocol
from kubernetes_deep_learning_tpu.serving.admission import Deadline


# --- token + SSE plumbing (no device, no jax) --------------------------------


def test_encode_decode_prompt_round_trip():
    tokens = decode_lib.encode_prompt("hello, tpu!")
    assert tokens[0] == decode_lib.BOS_TOKEN
    assert decode_lib.decode_tokens(tokens[1:]) == "hello, tpu!"
    # Specials never decode into text.
    assert decode_lib.decode_tokens(
        [decode_lib.BOS_TOKEN, 104, 105, decode_lib.EOS_TOKEN]
    ) == "hi"


def test_prompt_bucket_picks_smallest_fit_and_raises_on_overflow():
    buckets = (16, 32, 64)
    assert decode_lib.prompt_bucket(1, buckets) == 16
    assert decode_lib.prompt_bucket(16, buckets) == 16
    assert decode_lib.prompt_bucket(17, buckets) == 32
    assert decode_lib.prompt_bucket(64, buckets) == 64
    with pytest.raises(ValueError):
        decode_lib.prompt_bucket(65, buckets)


def test_generation_ttft_tpot_math():
    gen = decode_lib.Generation(rid="r", prompt_tokens=[256], max_new_tokens=4)
    assert gen.ttft_s() is None and gen.tpot_s() is None
    gen.t_first = gen.t_submit + 0.5
    gen.t_last = gen.t_first + 0.3
    gen.tokens = [1, 2, 3, 4]
    assert gen.ttft_s() == pytest.approx(0.5)
    # TPOT is the inter-token mean EXCLUDING the first token (that one is
    # TTFT's): 0.3s over 3 gaps.
    assert gen.tpot_s() == pytest.approx(0.1)
    # A single-token generation has no inter-token gap to average.
    gen.tokens = [1]
    assert gen.tpot_s() is None


def test_sse_events_round_trip_through_the_parser():
    frames = (
        protocol.sse_token_event(0, 104, "h")
        + protocol.sse_token_event(1, 105, "i")
        + protocol.sse_done_event(
            tokens=2, ttft_ms=1.5, tpot_ms=0.5,
            finish_reason="length", text="hi",
        )
    )
    events = protocol.parse_sse_events(frames)
    assert [e.get("token") for e in events[:-1]] == [104, 105]
    done = events[-1]
    assert done["done"] is True
    assert done["finish_reason"] == "length"
    assert done["text"] == "hi"
    assert done["tokens"] == 2


def test_decode_generate_request_validation():
    ok = protocol.decode_generate_request(b'{"prompt": "hi"}')
    assert ok == {"prompt": "hi", "max_new_tokens": 16, "stream": True}
    ok = protocol.decode_generate_request(
        b'{"prompt": "hi", "max_new_tokens": 3, "stream": false}'
    )
    assert ok["max_new_tokens"] == 3 and ok["stream"] is False
    for bad in (
        b"notjson",
        b'["prompt"]',
        b'{"nope": 1}',
        b'{"prompt": ""}',
        b'{"prompt": 3}',
        b'{"prompt": "x", "max_new_tokens": 0}',
        b'{"prompt": "x", "max_new_tokens": "many"}',
        (
            '{"prompt": "x", "max_new_tokens": %d}'
            % (protocol.GENERATE_MAX_NEW_TOKENS_CAP + 1)
        ).encode(),
    ):
        with pytest.raises(ValueError):
            protocol.decode_generate_request(bad)


# --- the paged engine (real jitted programs, CPU) ----------------------------


@pytest.fixture(scope="module")
def engine():
    # 2 slots x 4 pages of 8 tokens = 32-token context -> two prefill
    # buckets (16, 32); one compile of each + the step program serves the
    # whole module.
    return decode_lib.DecodeEngine(
        "gen-test", max_slots=2, page_size=8, max_pages_per_seq=4,
    )


def test_paged_allocation_frees_on_release(engine):
    assert engine.pages_in_use == 0
    slot = engine.acquire_slot(20)  # 20 tokens -> 3 pages of 8
    try:
        assert slot is not None
        assert engine.pages_in_use == 3
        # active_slots tracks the step mask, which flips at prefill --
        # an acquired-but-unprefilled slot holds pages but is not active.
        assert engine.active_slots == 0
        # Page 0 is the trash page: never handed to a sequence.
        assert 0 not in engine._slot_pages[slot]
    finally:
        engine.release_slot(slot)
    assert engine.pages_in_use == 0
    assert engine.active_slots == 0


def test_slot_exhaustion_returns_none_not_error(engine):
    slots = [engine.acquire_slot(8) for _ in range(engine.max_slots)]
    try:
        assert all(s is not None for s in slots)
        assert engine.acquire_slot(8) is None  # full: admission queues
    finally:
        for s in slots:
            engine.release_slot(s)


def test_solo_decode_is_deterministic(engine):
    a = engine.decode_solo("abc", 6)
    b = engine.decode_solo("abc", 6)
    assert a == b and len(a) <= 6


def test_continuous_batch_streams_bit_identical_to_solo(engine):
    """The lane's load-bearing invariant: a request decoded in a
    SHIFTING continuous batch (members joining and retiring around it)
    yields exactly the tokens of the same request decoded alone.  Mixed
    prompt lengths cover both prefill buckets; mixed budgets force slot
    churn mid-flight."""
    requests = [
        ("short", 10),
        ("a much longer prompt string", 4),
        ("mid-size prompt", 8),
        ("x", 12),
        ("long-ish prompt here", 6),
    ]
    sched = decode_lib.DecodeScheduler(engine, continuous=True)
    sched.start()
    streamed: dict[int, list[int]] = {}

    def drive(i, prompt, mnt):
        gen = sched.submit(prompt, mnt, rid=f"r{i}")
        toks = [ev[2] for ev in gen.iter_events(timeout_s=60.0)
                if ev[0] == "token"]
        streamed[i] = toks

    threads = [
        threading.Thread(target=drive, args=(i, p, n))
        for i, (p, n) in enumerate(requests)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120.0)
    sched.close()
    assert sorted(streamed) == list(range(len(requests)))
    for i, (prompt, mnt) in enumerate(requests):
        solo = engine.decode_solo(prompt, mnt)
        assert streamed[i] == solo, (
            f"req {i}: continuous batch diverged from solo decode"
        )


def test_scheduler_submit_rejects_oversize_prompts(engine):
    sched = decode_lib.DecodeScheduler(engine, continuous=True)
    # 40 chars + budget 10 > the 32-token context (with BOS): a 400, not
    # an admission.
    with pytest.raises(ValueError):
        sched.submit("x" * 40, 10)
    sched.close()


def test_scheduler_queue_cap_sheds_with_queuefull(engine):
    sched = decode_lib.DecodeScheduler(engine, continuous=True, queue_cap=1)
    # Loop NOT started: the first admission sits in the queue, the second
    # hits the cap.
    sched.submit("a", 2)
    with pytest.raises(QueueFull):
        sched.submit("b", 2)
    sched.close()


def test_expired_deadline_finishes_as_deadline_without_tokens(engine):
    sched = decode_lib.DecodeScheduler(engine, continuous=True)
    sched.start()
    gen = sched.submit("abc", 4, deadline=Deadline(0.0))
    events = list(gen.iter_events(timeout_s=30.0))
    sched.close()
    assert events == [("done", decode_lib.FINISH_DEADLINE)]
    assert gen.tokens == []


def test_cancel_stops_a_queued_generation(engine):
    sched = decode_lib.DecodeScheduler(engine, continuous=True)
    gen = sched.submit("abc", 4)
    gen.cancel()
    sched.start()
    deadline = time.monotonic() + 30.0
    while not gen.done and time.monotonic() < deadline:
        time.sleep(0.01)
    sched.close()
    assert gen.finish_reason == decode_lib.FINISH_CANCELLED
