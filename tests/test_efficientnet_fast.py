"""EfficientNet fast path vs the stock flax graph, on CPU interpret mode.

End-to-end logits parity on a small B0 spec whose stages exercise BOTH
paths at trace time: XLA segments (stem, expand-ratio-1 stage 1, stride-2
openers) and fused runs (stride-1 repeats AND the stride-1 stage-5/7
openers fused with residual=False).  Real-TPU speed is
exp/mbconv_variants.py + BENCH.md's job.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.models.efficientnet_fast import (
    block_plan,
    build_fast_forward,
)
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

_SPEC = register_spec(
    ModelSpec(
        name="effnet-fast-test",
        family="efficientnet-b0",
        input_shape=(64, 64, 3),
        labels=("a", "b", "c"),
        preprocessing="tf",
        description="test-only fast-path EfficientNet",
    )
)


def test_block_plan_b3_structure():
    """The static plan must reproduce the flax module's block layout (same
    round_filters/round_repeats math): B3 = 26 blocks, stage channel
    ladder 24/32/48/96/136/232/384."""
    plan = block_plan(1.2, 1.4)
    assert len(plan) == 26
    feats = sorted({f for _, _, _, f, _ in plan})
    assert feats == [24, 32, 48, 96, 136, 232, 384]
    # Stage openers carry the stage stride; repeats are stride 1.
    assert plan[0] == ("block0", 1, 3, 24, 1)
    strides = [st for _, st, _, _, _ in plan]
    assert strides.count(2) == 4  # stages 2, 3, 4, 6


def test_fast_forward_matches_flax():
    variables = init_variables(_SPEC, seed=3)
    rng = np.random.default_rng(0)
    # 5 images: exercises the sublane batch padding (5 -> 8) end to end.
    images = rng.integers(0, 256, size=(5, *_SPEC.input_shape), dtype=np.uint8)

    want = np.asarray(
        jax.jit(build_forward(_SPEC, dtype=jnp.bfloat16, fast=False))(
            variables, images
        )
    )

    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    inner = build_fast_forward(_SPEC, dtype=jnp.bfloat16, interpret=True)
    got = np.asarray(
        jax.jit(
            lambda v, im: inner(v, normalize(im, _SPEC.preprocessing)).astype(
                jnp.float32
            )
        )(variables, images)
    )
    assert got.shape == want.shape == (5, 3)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 2e-2, f"fast path diverges from flax: {rel:.2e}"
