import threading
import time

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.runtime.batcher import (
    BatcherClosed,
    DynamicBatcher,
    QueueFull,
)


class FakeEngine:
    """Deterministic stand-in: logit = [sum(image), batch_index_invariant]."""

    max_batch = 8

    def __init__(self, delay_s=0.0, fail=False):
        self.delay_s = delay_s
        self.fail = fail
        self.batch_sizes = []
        self._lock = threading.Lock()

    def predict(self, images: np.ndarray) -> np.ndarray:
        with self._lock:
            self.batch_sizes.append(images.shape[0])
        if self.fail:
            raise RuntimeError("boom")
        if self.delay_s:
            time.sleep(self.delay_s)
        sums = images.reshape(images.shape[0], -1).sum(axis=1).astype(np.float32)
        return np.stack([sums, sums * 2], axis=1)


def _img(value: int) -> np.ndarray:
    return np.full((2, 2, 3), value, np.uint8)


def test_single_request_roundtrip():
    b = DynamicBatcher(FakeEngine(), max_delay_ms=1)
    try:
        out = b.predict(_img(3))
        assert out.tolist() == [36.0, 72.0]
    finally:
        b.close()


def test_concurrent_requests_batch_and_map_correctly():
    eng = FakeEngine(delay_s=0.02)
    b = DynamicBatcher(eng, max_delay_ms=5)
    results: dict[int, np.ndarray] = {}
    errors = []

    def worker(v):
        try:
            results[v] = b.predict(_img(v))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(v,)) for v in range(40)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        for v in range(40):
            assert results[v].tolist() == [v * 12.0, v * 24.0], v
        # while the engine sleeps, the queue must coalesce into real batches
        assert max(eng.batch_sizes) > 1
        assert all(s <= eng.max_batch for s in eng.batch_sizes)
    finally:
        b.close()


def test_engine_error_propagates_and_batcher_survives():
    eng = FakeEngine(fail=True)
    b = DynamicBatcher(eng, max_delay_ms=1)
    try:
        with pytest.raises(RuntimeError, match="boom"):
            b.predict(_img(1))
        eng.fail = False
        assert b.predict(_img(2)).tolist() == [24.0, 48.0]
    finally:
        b.close()


def test_queue_cap_rejects():
    eng = FakeEngine(delay_s=0.2)
    b = DynamicBatcher(eng, max_delay_ms=0, queue_cap=2)
    try:
        b.submit(_img(0))  # dispatcher takes this
        time.sleep(0.05)   # let dispatch start, engine now busy 200ms
        b.submit(_img(1))
        b.submit(_img(2))
        with pytest.raises(QueueFull):
            for _ in range(3):
                b.submit(_img(3))
    finally:
        b.close()


def test_close_rejects_new_and_drains():
    b = DynamicBatcher(FakeEngine(), max_delay_ms=1)
    fut = b.submit(_img(1))
    b.close()
    assert fut.result(timeout=5).tolist() == [12.0, 24.0]
    with pytest.raises(BatcherClosed):
        b.submit(_img(1))
