"""kdlt-lint wired into tier-1: every rule in the unified suite has a
known-bad fixture it flags and a suppression path that silences it, the
donation pass catches a reconstruction of the PR 9 checkpoint bug, and the
production tree itself lints clean (zero unsuppressed findings) inside the
<10 s budget the pre-commit posture depends on."""

from __future__ import annotations

import json
import os
import sys
import textwrap
import time

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

from kdlt_lint import cli  # noqa: E402
from kdlt_lint.core import PACKAGE, REPO, default_passes, run_lint  # noqa: E402
from kdlt_lint.passes.closed_vocab import ClosedVocabPass  # noqa: E402
from kdlt_lint.passes.donation import DonationSafetyPass  # noqa: E402
from kdlt_lint.passes.env_knobs import EnvKnobsPass  # noqa: E402
from kdlt_lint.passes.hotpath import HotPathSyncPass  # noqa: E402
from kdlt_lint.passes.locks import LockDisciplinePass  # noqa: E402
from kdlt_lint.passes.metrics_names import MetricsNamingPass  # noqa: E402

ENGINE_REL = f"{PACKAGE}/runtime/engine.py"
TRACE_REL = f"{PACKAGE}/utils/trace.py"
FAULTS_REL = f"{PACKAGE}/serving/faults.py"
RECORDER_REL = f"{PACKAGE}/utils/flightrecorder.py"


def lint_fixture(tmp_path, sources, passes, copy_real=()):
    """Write fixture modules into a scratch repo and lint just them.

    ``sources`` maps repo-relative paths to source text; ``copy_real``
    names real production files to copy in verbatim (registry modules the
    closed-vocab pass reads its vocabularies from)."""
    merged = dict(sources)
    for rel in copy_real:
        with open(os.path.join(REPO, rel)) as f:
            merged[rel] = f.read()
    paths = []
    for rel, src in merged.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        paths.append(str(p))
    return run_lint(passes, repo=str(tmp_path), files=paths)


def active(findings, rule=None):
    return [
        f for f in findings
        if not f.suppressed and (rule is None or f.rule == rule)
    ]


# --- lock-discipline ---------------------------------------------------------

GUARDED_BAD = """\
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0  # guarded-by: _lock

        def bump(self):
            self._n += 1
"""


def test_guarded_by_flags_unlocked_access(tmp_path):
    findings = lint_fixture(
        tmp_path, {"box.py": GUARDED_BAD}, [LockDisciplinePass()])
    hits = active(findings, "guarded-by")
    assert len(hits) == 1
    assert "Box.bump" in hits[0].message
    assert "_lock" in hits[0].message


def test_guarded_by_accepts_locked_access_and_locked_suffix(tmp_path):
    src = """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._n = 0  # guarded-by: _lock

            def bump(self):
                with self._lock:
                    self._n += 1

            def wait_bump(self):
                with self._cond:
                    self._n += 1

            def _bump_locked(self):
                self._n += 1
    """
    findings = lint_fixture(tmp_path, {"box.py": src}, [LockDisciplinePass()])
    assert active(findings) == []


def test_lock_order_cycle_flagged(tmp_path):
    src = """\
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """
    findings = lint_fixture(tmp_path, {"ab.py": src}, [LockDisciplinePass()])
    hits = active(findings, "lock-order")
    assert len(hits) == 1
    assert "AB._a" in hits[0].message and "AB._b" in hits[0].message


def test_blocking_under_lock_flagged(tmp_path):
    src = """\
        import threading
        import time
        import requests

        class Fetcher:
            def __init__(self):
                self._lock = threading.Lock()

            def fetch(self):
                with self._lock:
                    time.sleep(0.1)
                    return requests.get("http://upstream/healthz")
    """
    findings = lint_fixture(tmp_path, {"f.py": src}, [LockDisciplinePass()])
    hits = active(findings, "blocking-under-lock")
    messages = " | ".join(f.message for f in hits)
    assert len(hits) == 2
    assert "time.sleep" in messages and "requests.get" in messages


# --- hot-path-sync / lock-around-jit ----------------------------------------

def test_hot_path_sync_flags_asarray_on_dispatch_path(tmp_path):
    src = """\
        import numpy as np

        class InFlightDispatcher:
            def submit(self, x):
                return self._pack(x)

            def _pack(self, x):
                return np.asarray(x)
    """
    findings = lint_fixture(
        tmp_path, {ENGINE_REL: src}, [HotPathSyncPass()])
    hits = active(findings, "hot-path-sync")
    assert len(hits) == 1
    assert "numpy.asarray" in hits[0].message
    assert "InFlightDispatcher.submit" in hits[0].message


def test_lock_around_jit_flagged_on_hot_path(tmp_path):
    src = """\
        import threading
        import jax

        class InFlightDispatcher:
            def __init__(self, fn):
                self._lock = threading.Lock()
                self._jitted = jax.jit(fn)

            def submit(self, x):
                with self._lock:
                    return self._jitted(x)
    """
    findings = lint_fixture(
        tmp_path, {ENGINE_REL: src}, [HotPathSyncPass()])
    hits = active(findings, "lock-around-jit")
    assert len(hits) == 1


def test_cold_path_sync_not_flagged(tmp_path):
    # The same np.asarray in a function unreachable from the roots is fine.
    src = """\
        import numpy as np

        def offline_eval(x):
            return np.asarray(x)
    """
    findings = lint_fixture(
        tmp_path, {ENGINE_REL: src}, [HotPathSyncPass()])
    assert active(findings) == []


# --- donation-safety ---------------------------------------------------------

def test_donation_use_after_donate_flagged(tmp_path):
    src = """\
        import jax

        class Trainer:
            def __init__(self, fn):
                self._step = jax.jit(fn, donate_argnums=(0,))

            def train(self, state, batch):
                new_state = self._step(state, batch)
                self._log(state)
                return new_state
    """
    findings = lint_fixture(
        tmp_path, {"t.py": src}, [DonationSafetyPass()])
    hits = active(findings, "donation-safety")
    assert len(hits) == 1
    assert "state was donated" in hits[0].message


def test_donation_pr9_checkpoint_bug_reconstruction(tmp_path):
    # The PR 9 training/checkpoint.py bug class: the loop donates ``state``
    # into the next step, then hands the SAME array to the checkpointer
    # whose background serializer reads the already-recycled device buffer.
    src = """\
        import jax

        def train_step(state, batch):
            return state

        step = jax.jit(train_step, donate_argnums=(0,))

        def train_loop(state, batches, checkpointer):
            for batch in batches:
                new_state = step(state, batch)
                checkpointer.save(state)
                state = new_state
            return state
    """
    findings = lint_fixture(
        tmp_path, {"loop.py": src}, [DonationSafetyPass()])
    hits = active(findings, "donation-safety")
    assert len(hits) == 1
    assert "use-after-donate" in hits[0].message


def test_donation_rebind_is_clean(tmp_path):
    # The canonical safe idiom: the donated name is rebound by the call.
    src = """\
        import jax

        def train_step(state, batch):
            return state

        step = jax.jit(train_step, donate_argnums=(0,))

        def train_loop(state, batches):
            for batch in batches:
                state = step(state, batch)
            return state
    """
    findings = lint_fixture(
        tmp_path, {"loop.py": src}, [DonationSafetyPass()])
    assert active(findings) == []


# --- closed-vocab ------------------------------------------------------------

def test_closed_vocab_flags_unknown_span_and_fault_point(tmp_path):
    src = """\
        def handle(tr, faults):
            faults.fire("gateway.upstrem")
            with tr.span("gateway.requset"):
                pass
    """
    findings = lint_fixture(
        tmp_path, {"h.py": src}, [ClosedVocabPass()],
        copy_real=(TRACE_REL, FAULTS_REL, RECORDER_REL))
    hits = active(findings, "closed-vocab")
    messages = " | ".join(f.message for f in hits)
    assert len(hits) == 2
    assert "gateway.requset" in messages and "gateway.upstrem" in messages


def test_closed_vocab_accepts_registry_members(tmp_path):
    src = """\
        def handle(tr, faults, recorder):
            faults.fire("gateway.upstream")
            recorder.record("pool.drain", model="m")
            with tr.span("gateway.request"):
                pass
    """
    findings = lint_fixture(
        tmp_path, {"h.py": src}, [ClosedVocabPass()],
        copy_real=(TRACE_REL, FAULTS_REL, RECORDER_REL))
    assert active(findings) == []


# --- metrics-naming / env-knobs ---------------------------------------------

def test_metrics_naming_flags_unprefixed_name(tmp_path):
    src = """\
        def build(reg):
            return reg.counter("requests_total", "help text")
    """
    findings = lint_fixture(
        tmp_path, {"m.py": src}, [MetricsNamingPass()])
    hits = active(findings, "metrics-naming")
    assert len(hits) == 1
    assert "kdlt_-prefixed" in hits[0].message


def test_env_knobs_flags_undocumented_knob(tmp_path):
    # Run the env pass with the real repo's GUIDE/manifests but only this
    # fixture contributing code literals: its bogus knob is undocumented.
    src = 'KNOB = "KDLT_DEFINITELY_NOT_DOCUMENTED"\n'
    p = tmp_path / "fixture.py"
    p.write_text(src)
    findings = run_lint([EnvKnobsPass()], repo=REPO, files=[str(p)])
    hits = [
        f for f in active(findings, "env-knobs")
        if "KDLT_DEFINITELY_NOT_DOCUMENTED" in f.message
    ]
    assert len(hits) == 1
    assert "never mentioned in GUIDE.md" in hits[0].message


# --- suppression grammar -----------------------------------------------------

def test_suppression_silences_finding(tmp_path):
    src = """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded-by: _lock

            def bump(self):
                # kdlt-lint: disable=guarded-by -- benign monotonic counter
                self._n += 1
    """
    findings = lint_fixture(tmp_path, {"box.py": src}, [LockDisciplinePass()])
    assert active(findings) == []
    suppressed = [f for f in findings if f.suppressed]
    assert len(suppressed) == 1 and suppressed[0].rule == "guarded-by"


def test_unused_suppression_is_itself_flagged(tmp_path):
    src = """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                # kdlt-lint: disable=guarded-by -- nothing to silence here
                self._n += 1
    """
    findings = lint_fixture(tmp_path, {"box.py": src}, [LockDisciplinePass()])
    hits = active(findings, "unused-suppression")
    assert len(hits) == 1
    assert "matched no finding" in hits[0].message


# --- the production tree itself ----------------------------------------------

def test_production_tree_lints_clean_within_budget(capsys):
    t0 = time.monotonic()
    findings = run_lint(default_passes(), repo=REPO)
    elapsed = time.monotonic() - t0
    bad = active(findings)
    assert bad == [], "\n".join(f.format() for f in bad)
    # Every suppression that survives review carries a justification; the
    # count is asserted loosely so adding one is a conscious test edit.
    assert len([f for f in findings if f.suppressed]) <= 8
    assert elapsed < 10.0, f"full-tree lint took {elapsed:.1f}s (budget 10s)"


def test_cli_clean_run_and_stable_json(capsys):
    assert cli.main([]) == 0
    out = capsys.readouterr().out
    assert "kdlt-lint: clean" in out

    assert cli.main(["--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1
    assert doc["summary"]["active"] == 0
    for f in doc["findings"]:
        assert set(f) >= {"rule", "file", "line", "message", "suppressed"}


def test_cli_lists_every_rule(capsys):
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in (
        "guarded-by", "lock-order", "blocking-under-lock", "hot-path-sync",
        "lock-around-jit", "donation-safety", "closed-vocab",
        "metrics-naming", "env-knobs", "unused-suppression",
    ):
        assert rule in out, rule
