"""Span tracing end to end: the Dapper-style waterfall across both tiers.

Acceptance surface of the tracing layer (utils/trace.py):

- one traced request through gateway -> model tier yields a MERGED
  waterfall (the gateway's /debug/trace/<rid> pulls the model tier's spans
  in) with >= 8 spans, correct parent/child nesting, and monotonic
  non-overlapping pipeline-stage intervals;
- a hedged request's trace shows BOTH upstream attempt spans with the
  winner marked;
- bench.py --trace-breakdown attributes >= 95% of measured request wall
  time to named spans on a stub run.

Everything runs on stub engines (async device: the in-flight dispatch
pipeline and its stage spans engage) -- no compiles, CPU-only.
"""

from __future__ import annotations

import json
import tempfile
import threading
import time
from functools import partial
from http.server import HTTPServer, SimpleHTTPRequestHandler

import numpy as np
import pytest
import requests

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
from kubernetes_deep_learning_tpu.serving.gateway import Gateway
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
from kubernetes_deep_learning_tpu.serving.tracing import (
    PARENT_SPAN_HEADER,
    REQUEST_ID_HEADER,
    TRACE_HEADER,
)
from kubernetes_deep_learning_tpu.utils import trace as trace_lib


# --- unit: the tracer core -------------------------------------------------


def test_tracer_ring_buffer_evicts_oldest_trace():
    t = trace_lib.Tracer("test", max_traces=3, max_spans=4)
    for i in range(5):
        t.record(f"trace-{i}", "root", trace_lib.now_s(), 0.001)
    assert t.spans("trace-0") is None and t.spans("trace-1") is None
    assert t.spans("trace-4") is not None


def test_tracer_caps_spans_per_trace():
    t = trace_lib.Tracer("test", max_spans=4)
    for _ in range(10):
        t.record("rid", "s", trace_lib.now_s(), 0.001)
    assert len(t.spans("rid")) == 4


def test_request_trace_span_nesting_and_tags():
    t = trace_lib.Tracer("test")
    rt = t.request_trace("rid")
    with rt.span("outer") as outer:
        with outer.span("inner") as inner:
            inner.tags["k"] = "v"
    spans = {s["name"]: s for s in t.spans("rid")}
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["outer"]["parent_id"] == rt.span_id
    assert spans["inner"]["tags"]["k"] == "v"
    # summary header: Server-Timing style, record order (inner closed first)
    assert t.summary("rid").startswith("inner;dur=")


def test_span_recorded_even_when_block_raises():
    t = trace_lib.Tracer("test")
    rt = t.request_trace("rid")
    with pytest.raises(RuntimeError):
        with rt.span("failing"):
            raise RuntimeError("boom")
    assert [s["name"] for s in t.spans("rid")] == ["failing"]


def test_tracer_counts_dropped_spans_instead_of_silently_evicting():
    # The pre-PR-7 bug: spans past the cap vanished without a trace, so a
    # truncated waterfall read as missing instrumentation.
    t = trace_lib.Tracer("test", max_spans=4)
    for _ in range(10):
        t.record("rid", "s", trace_lib.now_s(), 0.001)
    info = t.trace_info("rid")
    assert len(info["spans"]) == 4
    assert info["spans_dropped"] == 6
    assert t.stats()["spans_dropped_total"] == 6


def test_tail_based_retention_protects_interesting_traces():
    t = trace_lib.Tracer("test", max_traces=4)
    for i in range(4):
        t.record(f"t{i}", "root", trace_lib.now_s(), 0.001)
    t.classify("t0", "error")   # oldest, but protected
    t.classify("t1", "shed")
    # Two new traces force two evictions: the ROUTINE t2/t3 go first even
    # though t0/t1 are older.
    t.record("t4", "root", trace_lib.now_s(), 0.001)
    t.record("t5", "root", trace_lib.now_s(), 0.001)
    assert t.spans("t0") is not None and t.spans("t1") is not None
    assert t.spans("t2") is None and t.spans("t3") is None
    assert t.evicted_traces == 2
    # All protected: the ring still stays bounded (oldest protected goes).
    t.classify("t4", "deadline")
    t.classify("t5", "slow")
    t.record("t6", "root", trace_lib.now_s(), 0.001)
    assert t.spans("t0") is None  # oldest protected was the fallback victim


def test_classify_upgrades_only():
    t = trace_lib.Tracer("test")
    t.record("rid", "root", trace_lib.now_s(), 0.001)
    t.classify("rid", "error")
    t.classify("rid", "slow")  # must not downgrade
    assert t.trace_info("rid")["retention_class"] == "error"
    t.classify("missing", "error")  # unknown trace: a no-op, not a KeyError


def test_retention_metrics_count_retained_and_dropped():
    from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

    r = metrics_lib.Registry()
    t = trace_lib.Tracer("test", max_traces=2, registry=r)
    t.record("a", "root", trace_lib.now_s(), 0.001)
    t.classify("a", "error")
    t.record("b", "root", trace_lib.now_s(), 0.001)
    t.classify("b", "routine")
    t.record("c", "root", trace_lib.now_s(), 0.001)  # evicts routine b
    page = r.render()
    assert 'kdlt_trace_retained_total{class="error"} 1' in page
    assert 'kdlt_trace_dropped_total{class="routine"} 1' in page
    assert t.spans("a") is not None


def test_retention_class_mapping():
    rc = trace_lib.retention_class
    assert rc(503) == "shed" and rc(504) == "shed"
    assert rc(500) == "error" and rc(-1) == "error"
    assert rc(200, deadline_exceeded=True) == "deadline"
    assert rc(200, slow=True) == "slow"
    assert rc(200) == "routine"
    assert rc(400) == "routine"  # the caller's fault is not worth retaining


def test_ensure_span_id_sanitizes():
    assert trace_lib.ensure_span_id(None) is None
    assert trace_lib.ensure_span_id("abc\r\nX: 1") == "abcX1"
    assert trace_lib.ensure_span_id("!!!") is None


def test_render_waterfall_smoke():
    t = trace_lib.Tracer("tier")
    rt = t.request_trace("rid")
    with rt.span("child"):
        pass
    t.record("rid", "root", trace_lib.now_s() - 0.01, 0.01, span_id=rt.span_id)
    out = trace_lib.render_waterfall(t.spans("rid"))
    assert "root" in out and "child" in out and "ms" in out


# --- e2e: the merged cross-tier waterfall ----------------------------------


def _make_stack(tmp, name, device_ms=5.0):
    spec = register_spec(
        ModelSpec(
            name=name,
            family="xception",  # never instantiated by StubEngine
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
        )
    )
    root = tempfile.mkdtemp(prefix=f"kdlt-{name}-", dir=tmp)
    art.save_artifact(
        art.version_dir(root, spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        root, port=0, buckets=(1, 2), max_delay_ms=1.0, host="127.0.0.1",
        batcher_impl="python",
        engine_factory=lambda a, **kw: StubEngine(
            a, device_ms_per_batch=device_ms, async_device=True, **kw
        ),
    )
    server.warmup()
    server.start()
    return spec, server


@pytest.fixture(scope="module")
def traced_stack(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp("trace-e2e"))
    spec, server = _make_stack(tmp, "trace-e2e-stub")
    gateway = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name, port=0,
        host="127.0.0.1",
        # The response cache would serve repeat fixture URLs without an
        # upstream hop at all; these tests trace the FULL path (the cached
        # path's gateway.cache span is covered by test_cache.py).
        cache=False,
    )
    gateway.start()

    img_dir = tmp_path_factory.mktemp("trace-img")
    from PIL import Image

    rng = np.random.default_rng(0)
    Image.fromarray(
        rng.integers(0, 256, size=(48, 48, 3), dtype=np.uint8)
    ).save(img_dir / "img.png")

    class Quiet(SimpleHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

    img_httpd = HTTPServer(
        ("127.0.0.1", 0), partial(Quiet, directory=str(img_dir))
    )
    threading.Thread(target=img_httpd.serve_forever, daemon=True).start()
    img_url = f"http://127.0.0.1:{img_httpd.server_address[1]}/img.png"

    yield spec, server, gateway, img_url

    gateway.shutdown()
    server.shutdown()
    img_httpd.shutdown()


def _merged_trace(gateway, rid, want_names=(), timeout_s=3.0):
    """Poll the gateway's merged /debug/trace/<rid> until the expected span
    names appear (the model tier's root span records microseconds after its
    response is sent, so an immediate fetch can race it)."""
    base = f"http://127.0.0.1:{gateway.port}"
    deadline = time.monotonic() + timeout_s
    spans: list = []
    while time.monotonic() < deadline:
        r = requests.get(f"{base}/debug/trace/{rid}", timeout=5)
        if r.status_code == 200:
            spans = r.json()["spans"]
            names = [s["name"] for s in spans]
            if all(w in names for w in want_names):
                return spans
        time.sleep(0.02)
    return spans


def test_single_request_merged_waterfall(traced_stack):
    """The tentpole acceptance: >= 8 spans, correct nesting, monotonic
    non-overlapping pipeline-stage intervals, trace headers on the wire."""
    _, _, gateway, img_url = traced_stack
    rid = "waterfall-req-1"
    r = requests.post(
        f"http://127.0.0.1:{gateway.port}/predict",
        json={"url": img_url},
        headers={REQUEST_ID_HEADER: rid},
        timeout=30,
    )
    assert r.status_code == 200, r.text
    assert r.headers[REQUEST_ID_HEADER] == rid
    # Server-Timing-style summary on the response, root span included
    # (the transports build it after handle_predict records the root).
    assert "gateway.request;dur=" in r.headers[TRACE_HEADER]

    spans = _merged_trace(
        gateway, rid, want_names=("server.request", "gateway.request")
    )
    assert len(spans) >= 8, [s["name"] for s in spans]
    by_name = {s["name"]: s for s in spans}
    by_id = {s["span_id"]: s for s in spans}

    # Exactly one root: the gateway's request span.
    roots = [s for s in spans if s.get("parent_id") not in by_id]
    assert [s["name"] for s in roots] == ["gateway.request"]

    # Cross-tier nesting: the model tier's root hangs off the exact
    # gateway upstream attempt that carried it.
    up = by_name["gateway.upstream"]
    assert by_name["server.request"]["parent_id"] == up["span_id"]
    assert up["parent_id"] == by_name["gateway.request"]["span_id"]
    assert up["tags"]["winner"] is True
    assert up["tags"]["status"] == 200

    # The model tier's own nesting: admission/decode/predict under the
    # request root, batcher + pipeline stages under the predict span.
    srv_root = by_name["server.request"]["span_id"]
    predict = by_name["server.predict"]
    assert predict["parent_id"] == srv_root
    assert by_name["server.admission"]["parent_id"] == srv_root
    assert by_name["batcher.queue_wait"]["parent_id"] == predict["span_id"]

    stages = [
        by_name[f"pipeline.{s}"]
        for s in ("enqueue_wait", "dispatch", "execute", "readback")
    ]
    for st in stages:
        assert st["parent_id"] == predict["span_id"]
        assert st["tier"] == "model-server"
    # Monotonic, non-overlapping, contiguous-in-order intervals: each
    # stage starts exactly where its predecessor ended (shared perf-counter
    # boundaries), and all sit inside the predict span's window.  Slack:
    # start_s rounds to 1e-6 s and dur_ms to 1e-6 s (trace.py to_dict), so
    # end_a vs start_b carries up to three half-ulp roundings -- 1e-6 was
    # exactly reachable and flaked (~1/500 runs).
    for a, b in zip(stages, stages[1:]):
        end_a = a["start_s"] + a["dur_ms"] / 1e3
        assert b["start_s"] >= end_a - 2e-6, (a["name"], b["name"])
    assert stages[0]["start_s"] >= predict["start_s"] - 2e-6
    # Sibling gateway spans are sequential too (admission, preprocess,
    # then the upstream hop).
    gw_seq = [by_name["gateway.admission"], by_name["gateway.preprocess"], up]
    for a, b in zip(gw_seq, gw_seq[1:]):
        assert b["start_s"] >= a["start_s"] + a["dur_ms"] / 1e3 - 2e-6


def test_trace_endpoint_unknown_rid_404(traced_stack):
    _, server, gateway, _ = traced_stack
    for port in (gateway.port, server.port):
        r = requests.get(
            f"http://127.0.0.1:{port}/debug/trace/never-seen-rid", timeout=5
        )
        assert r.status_code == 404


def test_client_fetch_trace_and_render(traced_stack):
    from kubernetes_deep_learning_tpu.serving.client import (
        fetch_trace,
        predict_url,
    )

    _, _, gateway, img_url = traced_stack
    base = f"http://127.0.0.1:{gateway.port}"
    stats: dict = {}
    predict_url(base, img_url, stats=stats)
    assert stats["request_id"]
    assert "gateway.request;dur=" in stats["trace_summary"]
    spans = _merged_trace(gateway, stats["request_id"],
                          want_names=("server.request",))
    out = trace_lib.render_waterfall(spans)
    assert "gateway.request" in out and "[model-server]" in out


def test_model_tier_response_carries_trace_header(traced_stack):
    from kubernetes_deep_learning_tpu.serving import protocol

    spec, server, _, _ = traced_stack
    img = np.zeros((1, 32, 32, 3), np.uint8)
    r = requests.post(
        f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict",
        data=protocol.encode_predict_request(img),
        headers={
            "Content-Type": protocol.MSGPACK_CONTENT_TYPE,
            REQUEST_ID_HEADER: "direct-model-req",
            PARENT_SPAN_HEADER: "cafe0123",
        },
        timeout=30,
    )
    assert r.status_code == 200
    assert "server.predict;dur=" in r.headers[TRACE_HEADER]
    # The propagated parent became the model-tier root's parent.
    spans = requests.get(
        f"http://127.0.0.1:{server.port}/debug/trace/direct-model-req",
        timeout=5,
    ).json()["spans"]
    root = next(s for s in spans if s["name"] == "server.request")
    assert root["parent_id"] == "cafe0123"


def test_hedged_request_trace_shows_both_attempts_with_winner(
    tmp_path_factory,
):
    """Replica A is slow (400 ms device), B fast; with a 60 ms hedge delay
    the hedge fires, B answers first, and the trace must show BOTH
    gateway.upstream attempt spans -- the hedge marked winner."""
    tmp = str(tmp_path_factory.mktemp("trace-hedge"))
    spec, slow = _make_stack(tmp, "trace-hedge-stub", device_ms=400.0)
    _, fast = _make_stack(tmp, "trace-hedge-stub", device_ms=5.0)
    gateway = Gateway(
        serving_host=f"127.0.0.1:{slow.port},127.0.0.1:{fast.port}",
        model=spec.name, port=0, host="127.0.0.1",
        hedge_delay_ms=60.0, probe_interval_s=0.0,
    )
    gateway.start()
    try:
        from kubernetes_deep_learning_tpu.serving import protocol

        rid = "hedged-req-1"
        img = np.zeros((1, 32, 32, 3), np.uint8)
        body = protocol.encode_predict_request(img)
        rt = gateway.tracer.request_trace(rid)
        t0 = time.monotonic()
        logits, labels = gateway._predict_batch(img, rid, trace=rt)
        took = time.monotonic() - t0
        assert len(logits) == 1 and list(labels) == list(spec.labels)
        del body
        # The hedge won: the request finished far below the slow replica's
        # 400 ms device time.
        assert took < 0.35, took

        # The losing primary's span records when its (abandoned) response
        # eventually lands; poll for both attempts.
        deadline = time.monotonic() + 3.0
        attempts = []
        while time.monotonic() < deadline:
            spans = gateway.tracer.spans(rid) or []
            attempts = [s for s in spans if s["name"] == "gateway.upstream"]
            if len(attempts) == 2:
                break
            time.sleep(0.02)
        assert len(attempts) == 2, attempts
        by_role = {s["tags"]["role"]: s for s in attempts}
        assert set(by_role) == {"primary", "hedge"}
        assert by_role["hedge"]["tags"].get("winner") is True
        assert "winner" not in by_role["primary"]["tags"]
        assert by_role["primary"]["tags"]["replica"].endswith(str(slow.port))
        assert by_role["hedge"]["tags"]["replica"].endswith(str(fast.port))
    finally:
        gateway.shutdown()
        slow.shutdown()
        fast.shutdown()


# --- /debug/profile --------------------------------------------------------


def test_debug_profile_get_captures_into_profile_dir(
    tmp_path, monkeypatch, traced_stack
):
    """GET /debug/profile?seconds=N captures a jax.profiler trace into a
    fresh dir under $KDLT_PROFILE_DIR (wired here via profile_base since
    the fixture server predates the monkeypatch)."""
    import os

    _, server, _, _ = traced_stack
    profile_dir = str(tmp_path / "profiles")
    monkeypatch.setattr(server, "_profile_base", profile_dir)
    r = requests.get(
        f"http://127.0.0.1:{server.port}/debug/profile?seconds=0.05",
        timeout=30,
    )
    assert r.status_code == 200, r.text
    out = r.json()
    assert out["seconds"] == 0.05
    assert out["trace_dir"].startswith(profile_dir)
    assert os.path.isdir(out["trace_dir"])
    # jax.profiler writes its plugin tree into the capture dir.
    assert os.listdir(out["trace_dir"]), "profile capture produced no files"

    # Bad input stays a 400, never a capture.
    r = requests.get(
        f"http://127.0.0.1:{server.port}/debug/profile?seconds=999", timeout=30
    )
    assert r.status_code == 400


def test_profile_dir_env_is_honored(monkeypatch, tmp_path):
    from kubernetes_deep_learning_tpu.serving import model_server as ms

    monkeypatch.setenv(ms.PROFILE_DIR_ENV, str(tmp_path / "via-env"))
    spec = register_spec(
        ModelSpec(
            name="profile-env-stub", family="xception",
            input_shape=(16, 16, 3), labels=("a",),
        )
    )
    root = str(tmp_path / "models")
    art.save_artifact(
        art.version_dir(root, spec.name, 1), spec, {"params": {}}, None, {}
    )
    server = ModelServer(
        root, port=0, buckets=(1,), host="127.0.0.1",
        engine_factory=StubEngine, use_batcher=False,
    )
    try:
        assert server._profile_base == str(tmp_path / "via-env")
    finally:
        server.shutdown()


# --- structured logging (KDLT_LOG_FORMAT=json) -----------------------------


def test_log_request_json_format(monkeypatch, capsys):
    from kubernetes_deep_learning_tpu.serving.tracing import log_request

    monkeypatch.setenv("KDLT_LOG_FORMAT", "json")
    t0 = time.perf_counter()
    log_request(
        "gateway predict", "rid-1", status=200, t0=t0, span_id="abcd1234",
        urls=3,
    )
    line = capsys.readouterr().out.strip()
    rec = json.loads(line)
    assert rec["rid"] == "rid-1" and rec["trace_id"] == "rid-1"
    assert rec["tier"] == "gateway predict"
    assert rec["status"] == 200 and rec["span_id"] == "abcd1234"
    assert rec["urls"] == 3 and isinstance(rec["dur_ms"], float)


def test_log_request_default_format_unchanged(monkeypatch, capsys):
    from kubernetes_deep_learning_tpu.serving.tracing import log_request

    monkeypatch.delenv("KDLT_LOG_FORMAT", raising=False)
    log_request("tier", "rid-2", status=500, t0=time.perf_counter())
    out = capsys.readouterr().out
    assert out.startswith("[rid=rid-2] tier status=500 dur_ms=")


# --- bench --trace-breakdown ----------------------------------------------


def test_bench_trace_breakdown_attributes_wall_time():
    """The bench acceptance bar: >= 95% of measured request wall time
    attributed to named spans on a stub run, >= 8 spans per waterfall."""
    import bench

    out, rc = bench.bench_trace_breakdown(n_requests=12, device_ms=40.0)
    assert rc == 0, out
    assert out["value"] >= 0.95
    assert out["min_spans_per_request"] >= 8
    for stage in ("gateway.request", "server.predict", "pipeline.readback"):
        assert stage in out["stages"]


def test_bench_dry_run_reports_trace_mode():
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "bench.py", "--dry-run", "--trace-breakdown", "7"],
        capture_output=True, text=True, timeout=120,
        cwd=__import__("os").path.dirname(
            __import__("os").path.dirname(__import__("os").path.abspath(__file__))
        ),
    )
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["mode"] == "trace_breakdown"
    assert out["trace"]["requests"] == 7
