"""Data-parallel serving through the full HTTP model server: the engine's
``mesh`` mode (BASELINE.json config 5) on the 8-virtual-device CPU mesh."""

import threading

import numpy as np
import pytest
import requests

from kubernetes_deep_learning_tpu.export.exporter import export_model
from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh
from kubernetes_deep_learning_tpu.runtime import InferenceEngine
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer


@pytest.fixture(scope="module")
def shard_spec() -> ModelSpec:
    return register_spec(
        ModelSpec(
            name="shard-vit",
            family="vit-tiny",
            input_shape=(16, 16, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",
            description="test-only sharded-serving model",
        )
    )


@pytest.fixture(scope="module")
def artifact_root(shard_spec, tmp_path_factory):
    root = tmp_path_factory.mktemp("shard-models")
    export_model(shard_spec, init_variables(shard_spec, seed=0), str(root))
    return str(root)


def test_mesh_engine_buckets_round_to_data_axis(shard_spec, artifact_root):
    from kubernetes_deep_learning_tpu.export import artifact as art

    mesh = make_mesh(8, model_parallel=2)  # data axis = 4
    a = art.load_artifact(art.version_dir(artifact_root, shard_spec.name, 1))
    eng = InferenceEngine(a, buckets=(1, 2, 6, 16), mesh=mesh)
    assert eng.buckets == (4, 8, 16)


def test_mesh_engine_matches_single_device(shard_spec, artifact_root):
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.export import artifact as art

    mesh = make_mesh(8)
    a = art.load_artifact(art.version_dir(artifact_root, shard_spec.name, 1))
    eng = InferenceEngine(a, buckets=(8,), mesh=mesh)
    eng.warmup()
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(5, *shard_spec.input_shape), dtype=np.uint8)
    got = eng.predict(images)
    fwd = build_forward(shard_spec, dtype=jnp.dtype(eng._compute_dtype))
    want = np.asarray(fwd(a.variables, images))
    # bfloat16 compute: differently-fused programs legitimately differ at
    # ~1e-2 on unit-scale logits; the check is placement/mapping, not ulps.
    np.testing.assert_allclose(got, want, atol=5e-2)


def test_profile_endpoint_captures_trace(shard_spec, artifact_root):
    import os

    server = ModelServer(artifact_root, port=0, buckets=(1,), use_batcher=False)
    try:
        server.warmup()
        server.start()
        base = f"http://localhost:{server.port}"
        r = requests.post(base + "/debug/profile", json={"seconds": 0.3}, timeout=30)
        assert r.status_code == 200, r.text
        trace_dir = r.json()["trace_dir"]
        assert any(os.scandir(trace_dir)), "trace dir is empty"
        r = requests.post(
            base + "/debug/profile", json={"seconds": 100}, timeout=30
        )
        assert r.status_code == 400
    finally:
        server.shutdown()


def test_served_data_parallel_over_mesh(shard_spec, artifact_root):
    server = ModelServer(
        artifact_root, port=0, buckets=(1, 2, 8, 16), mesh=make_mesh(8),
        max_delay_ms=5.0,
    )
    try:
        server.warmup()
        server.start()
        url = f"http://localhost:{server.port}/v1/models/{shard_spec.name}:predict"

        # Concurrent single-image requests must coalesce into mesh-sharded
        # batches and map back to the right requester.
        results, errors = {}, []

        def worker(v):
            try:
                body = {"instances": np.full((1, 16, 16, 3), v, np.uint8).tolist()}
                r = requests.post(url, json=body, timeout=60)
                assert r.status_code == 200, r.text
                results[v] = r.json()["predictions"][0]
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(v,)) for v in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(results) == 12
        # Distinct inputs must give distinct logits (mapping not scrambled).
        eng = server.models[shard_spec.name].engine
        direct = eng.predict(
            np.stack([np.full((16, 16, 3), v, np.uint8) for v in range(12)])
        )
        for v in range(12):
            got = [results[v][label] for label in shard_spec.labels]
            # Different bucket shapes fuse differently in bfloat16; the
            # check is that request->row mapping isn't scrambled.
            np.testing.assert_allclose(got, direct[v], atol=5e-2)
    finally:
        server.shutdown()


@pytest.fixture(scope="module")
def xc_spec() -> ModelSpec:
    return register_spec(
        ModelSpec(
            name="shard-xc",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
            description="test-only sharded fused-fast-path model",
        )
    )


def test_shard_map_fast_path_matches_flax(xc_spec, monkeypatch):
    """The fused fast forward under shard_map (each chip runs the fused
    Pallas program on its local batch shard -- what mesh serving runs on
    TPU) vs the flax graph on identical variables.  Interpret mode stands
    in for Mosaic on CPU; real-TPU engagement is covered by
    resolve_sharded_fast + the engine wiring below."""
    import functools

    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.models import xception_fast
    from kubernetes_deep_learning_tpu.parallel.dataparallel import (
        build_sharded_forward,
    )

    monkeypatch.setattr(
        xception_fast,
        "build_fast_forward",
        functools.partial(xception_fast.build_fast_forward, interpret=True),
    )
    mesh = make_mesh(8)
    variables = init_variables(xc_spec, seed=2)
    call = build_sharded_forward(mesh=mesh, spec=xc_spec, dtype=jnp.bfloat16, fast=True)
    rng = np.random.default_rng(3)
    images = rng.integers(0, 256, size=(16, *xc_spec.input_shape), dtype=np.uint8)
    got = np.asarray(call(variables, images))
    want = np.asarray(
        build_forward(xc_spec, dtype=jnp.bfloat16, fast=False)(variables, images)
    )
    # 2e-2: same interpreter bf16-rounding spread across jax versions as
    # test_fused_sepconv (measured 1.02e-2 on 0.4.x, under 1e-2 on current).
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 2e-2, f"shard_map fast path diverges: {rel:.2e}"


def test_mesh_engine_fast_resolution_and_degrade(xc_spec, tmp_path):
    """resolve_sharded_fast gates on platform/model-axis; a mesh engine
    with the fast path FORCED on CPU reproduces a real Mosaic-style compile
    failure under shard_map and must degrade to the flax graph fleet-wide,
    same contract as single-device serving."""
    import jax.numpy as jnp

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.parallel.dataparallel import (
        resolve_sharded_fast,
    )

    mesh = make_mesh(8)
    # auto on a CPU mesh: exact graph (no Pallas on CPU outside interpret)
    assert not resolve_sharded_fast(xc_spec, mesh, jnp.bfloat16, "auto")
    # model axis > 1: exact graph even where fast would otherwise resolve
    assert not resolve_sharded_fast(
        xc_spec, make_mesh(8, model_parallel=2), jnp.bfloat16, True
    )

    export_model(xc_spec, init_variables(xc_spec, seed=1), str(tmp_path))
    a = art.load_artifact(art.version_dir(str(tmp_path), xc_spec.name, 1))
    eng = InferenceEngine(a, buckets=(8,), mesh=mesh, fast=True)
    assert eng._fast_engaged
    eng.warmup()
    assert eng.ready and eng.fast_degraded
    out = eng.predict(np.zeros((3, *xc_spec.input_shape), np.uint8))
    assert out.shape == (3, 4)
