import io

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.ops import preprocess


def _png_bytes(h=40, w=60):
    from PIL import Image

    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, size=(h, w, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    return buf.getvalue(), img


def test_decode_roundtrip():
    data, img = _png_bytes()
    out = preprocess.decode_image(data)
    np.testing.assert_array_equal(out, img)


def test_resize_shapes():
    _, img = _png_bytes()
    out = preprocess.resize_uint8(img, (299, 299))
    assert out.shape == (299, 299, 3) and out.dtype == np.uint8
    same = preprocess.resize_uint8(img, img.shape[:2])
    np.testing.assert_array_equal(same, img)


def test_preprocess_bytes_pipeline():
    data, _ = _png_bytes()
    out = preprocess.preprocess_bytes(data, (128, 128))
    assert out.shape == (128, 128, 3) and out.dtype == np.uint8


def test_normalize_tf_mode_matches_reference():
    # Xception "tf" mode: x/127.5 - 1, the keras-image-helper behavior the
    # reference gateway applies (reference model_server.py:18).
    x = np.array([[0, 127.5, 255]], np.float32)
    out = preprocess.normalize(x, "tf")
    np.testing.assert_allclose(out, [[-1.0, 0.0, 1.0]], atol=1e-6)


def test_normalize_caffe_bgr_and_means():
    x = np.zeros((1, 1, 3), np.float32)
    out = preprocess.normalize(x, "caffe")
    np.testing.assert_allclose(out[0, 0], -preprocess._CAFFE_MEAN_BGR)


def test_normalize_torch():
    x = np.full((1, 1, 3), 255.0, np.float32)
    out = preprocess.normalize(x, "torch")
    np.testing.assert_allclose(
        out[0, 0], (1.0 - preprocess._TORCH_MEAN) / preprocess._TORCH_STD, rtol=1e-5
    )


def test_normalize_jax_matches_numpy():
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    x = rng.integers(0, 256, size=(2, 4, 4, 3), dtype=np.uint8)
    for mode in ("tf", "caffe", "torch"):
        a = preprocess.normalize(x.astype(np.float32), mode)
        b = np.asarray(preprocess.normalize(jnp.asarray(x, jnp.float32), mode))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_normalize_unknown_mode():
    with pytest.raises(ValueError):
        preprocess.normalize(np.zeros((1,), np.float32), "bogus")
