import jax
import numpy as np
import optax
import pytest

from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.training import (
    Checkpointer,
    PrefetchIterator,
    abstract_like,
    create_train_state,
    fit,
    fit_and_export,
    synthetic_batches,
)


@pytest.fixture(scope="module")
def ckpt_spec() -> ModelSpec:
    return register_spec(
        ModelSpec(
            name="ckpt-vit",
            family="vit-tiny",
            input_shape=(16, 16, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",
            description="test-only checkpointing model",
        )
    )


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(ckpt_spec, tmp_path):
    tx = optax.adam(1e-3)
    state, _ = fit(ckpt_spec, tx, synthetic_batches(ckpt_spec, 2), steps=2)
    ckpt = Checkpointer(str(tmp_path), max_to_keep=2)
    ckpt.save(state)
    ckpt.wait()
    assert ckpt.latest_step() == 2
    fresh = create_train_state(ckpt_spec, tx, seed=1)
    restored = ckpt.restore(abstract_like(fresh))
    ckpt.close()
    assert int(restored.step) == 2
    _trees_equal(restored.params, state.params)
    _trees_equal(restored.opt_state, state.opt_state)


def test_fit_resumes_from_checkpoint(ckpt_spec, tmp_path):
    tx = optax.sgd(1e-3)
    d = str(tmp_path / "run")
    logs: list[str] = []
    state1, _ = fit(
        ckpt_spec, tx, synthetic_batches(ckpt_spec, 2), steps=2,
        ckpt_dir=d, ckpt_every=1, log_fn=logs.append,
    )
    # Second invocation restores step 2 and trains only 2 more steps.
    state2, hist = fit(
        ckpt_spec, tx, synthetic_batches(ckpt_spec, 2), steps=4,
        ckpt_dir=d, ckpt_every=1, log_fn=logs.append,
    )
    assert any("resumed" in line and "step 2" in line for line in logs)
    assert int(state2.step) == 4
    assert hist[-1][0] == 4


def test_retention_prunes_old_steps(ckpt_spec, tmp_path):
    tx = optax.sgd(1e-3)
    state = create_train_state(ckpt_spec, tx, seed=0)
    ckpt = Checkpointer(str(tmp_path), max_to_keep=2)
    for step in (1, 2, 3):
        state = type(state)(
            state.step * 0 + step, state.params, state.batch_stats, state.opt_state
        )
        ckpt.save(state)
    ckpt.wait()
    steps = ckpt._mngr.all_steps()
    ckpt.close()
    assert max(steps) == 3
    assert len(steps) <= 2


def test_sharded_roundtrip_trains_after_restore(ckpt_spec, tmp_path):
    # Regression: a restored state's scalar leaves (step, adam's count) come
    # back COMMITTED to whatever sharding the abstract target carried; if
    # create_train_state leaves them single-device while params are
    # mesh-wide, the first post-restore train step fails with "incompatible
    # devices".  So restore must be followed by a working sharded step.
    from kubernetes_deep_learning_tpu.parallel.mesh import batch_sharding, make_mesh
    from kubernetes_deep_learning_tpu.training import build_train_step

    tx = optax.adam(1e-3)
    mesh = make_mesh(8, model_parallel=2)
    state = create_train_state(ckpt_spec, tx, seed=0, mesh=mesh)
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(state, force=True)
    ckpt.wait()
    restored = ckpt.restore(abstract_like(state))
    ckpt.close()
    _trees_equal(restored.params, state.params)

    step_fn = build_train_step(ckpt_spec, tx, mesh=mesh)
    images, labels = next(synthetic_batches(ckpt_spec, 8))
    sharding = batch_sharding(mesh)
    out, metrics = step_fn(
        restored, jax.device_put(images, sharding), jax.device_put(labels, sharding)
    )
    assert int(out.step) == 1
    assert np.isfinite(float(metrics["loss"]))


def test_prefetch_iterator_matches_source(ckpt_spec):
    src = list(synthetic_batches(ckpt_spec, 2, steps=3, seed=7))
    out = list(PrefetchIterator(iter(src)))
    assert len(out) == 3
    for (si, sl), (oi, ol) in zip(src, out):
        np.testing.assert_array_equal(si, np.asarray(oi))
        np.testing.assert_array_equal(sl, np.asarray(ol))


def test_prefetch_close_stops_producer(ckpt_spec):
    import threading

    # Endless source + abandoned consumer: close() must unblock and join
    # the producer thread instead of leaking it (and its staged batches).
    it = PrefetchIterator(synthetic_batches(ckpt_spec, 2), depth=1)
    next(it)
    it.close()
    assert not it._thread.is_alive()
    assert sum(t.name == "kdlt-prefetch" for t in threading.enumerate()) == 0


def test_fit_history_records_final_step_on_exhaustion(ckpt_spec):
    import optax as _optax

    # 2-batch source, 10 requested steps: history[-1] must be the step where
    # training actually stopped, not the last log_every multiple.
    state, hist = fit(
        ckpt_spec, _optax.sgd(1e-3),
        synthetic_batches(ckpt_spec, 2, steps=2), steps=10, log_fn=lambda s: None,
    )
    assert int(state.step) == 2
    assert hist[-1][0] == 2


def test_prefetch_propagates_source_error(ckpt_spec):
    def bad():
        yield next(synthetic_batches(ckpt_spec, 2))
        raise RuntimeError("boom")

    it = PrefetchIterator(bad())
    next(it)
    with pytest.raises(RuntimeError, match="boom"):
        for _ in it:
            pass


def test_fit_and_export_lands_served_artifact(ckpt_spec, tmp_path):
    from kubernetes_deep_learning_tpu.export import artifact as art

    tx = optax.sgd(1e-3)
    directory = fit_and_export(
        ckpt_spec, tx, synthetic_batches(ckpt_spec, 2), steps=1,
        artifact_root=str(tmp_path),
    )
    a = art.load_artifact(directory)
    assert a.spec.name == "ckpt-vit"
    assert art.latest_version(str(tmp_path), "ckpt-vit") == 1


def test_image_folder_batches(tmp_path, ckpt_spec):
    from PIL import Image

    from kubernetes_deep_learning_tpu.training.data import image_folder_batches

    rng = np.random.default_rng(0)
    counts = {"a": 5, "b": 3, "c": 4}
    for label, count in counts.items():
        d = tmp_path / label
        d.mkdir()
        for i in range(count):
            Image.fromarray(
                rng.integers(0, 255, (20, 24, 3), dtype=np.uint8), "RGB"
            ).save(d / f"{i}.png")

    batches = list(
        image_folder_batches(str(tmp_path), ckpt_spec, batch=4, epochs=1)
    )
    # 12 samples, batch 4, drop_remainder -> 3 batches.
    assert len(batches) == 3
    seen_labels = np.concatenate([lbl for _, lbl in batches])
    assert set(seen_labels.tolist()) <= {0, 1, 2}
    for imgs, lbls in batches:
        assert imgs.shape == (4, *ckpt_spec.input_shape) and imgs.dtype == np.uint8
        assert lbls.shape == (4,) and lbls.dtype == np.int32

    # Trains end to end: the folder pipeline feeds fit() directly.
    import optax

    state, hist = fit(
        ckpt_spec, optax.sgd(1e-3),
        image_folder_batches(str(tmp_path), ckpt_spec, batch=4),
        steps=2, log_fn=lambda s: None,
    )
    assert int(state.step) == 2


def test_image_folder_too_few_samples_fails_loudly(tmp_path, ckpt_spec):
    """drop_remainder with fewer samples than one batch must raise, not
    busy-spin forever inside fit()'s next() (ADVICE r1)."""
    from PIL import Image

    from kubernetes_deep_learning_tpu.training.data import image_folder_batches

    d = tmp_path / "a"
    d.mkdir()
    Image.fromarray(np.zeros((8, 8, 3), np.uint8), "RGB").save(d / "x.png")
    with pytest.raises(ValueError, match="zero batches"):
        next(image_folder_batches(str(tmp_path), ckpt_spec, batch=8))


def test_image_folder_rejects_unknown_label(tmp_path, ckpt_spec):
    from PIL import Image

    from kubernetes_deep_learning_tpu.training.data import image_folder_batches

    d = tmp_path / "not-a-label"
    d.mkdir()
    Image.fromarray(np.zeros((8, 8, 3), np.uint8), "RGB").save(d / "x.png")
    with pytest.raises(ValueError, match="not a spec label"):
        next(image_folder_batches(str(tmp_path), ckpt_spec, batch=2))
