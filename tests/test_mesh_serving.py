"""Model-parallel serving on the 2-D mesh (ISSUE 16): the GSPMD path.

test_sharded_serving covers the data axis; this file covers what the
model axis adds -- identical logits at a smaller per-device parameter
footprint, the sharding status surface (GET /v1/models, kdlt_mesh_*),
the partition rules' composition with quantized subtrees, hot reload
keeping the layout, and the bucket-shape audit that rides along
(/debug/profile?audit=buckets at both tiers + the client rendering).
All on the 8-virtual-device CPU mesh from conftest.
"""

from __future__ import annotations

import numpy as np
import pytest
import requests

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.export.exporter import export_model
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel import mesh as mesh_lib
from kubernetes_deep_learning_tpu.parallel.mesh import MODEL_AXIS, P, make_mesh
from kubernetes_deep_learning_tpu.runtime import InferenceEngine
from kubernetes_deep_learning_tpu.serving.gateway import Gateway
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer


@pytest.fixture(scope="module")
def mp_spec() -> ModelSpec:
    return register_spec(
        ModelSpec(
            name="mp-vit",
            family="vit-tiny",
            input_shape=(16, 16, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",
            description="test-only model-parallel serving model",
        )
    )


@pytest.fixture(scope="module")
def artifact_root(mp_spec, tmp_path_factory):
    root = tmp_path_factory.mktemp("mp-models")
    export_model(mp_spec, init_variables(mp_spec, seed=0), str(root))
    return str(root)


@pytest.fixture(scope="module")
def mp_server(artifact_root):
    server = ModelServer(
        artifact_root, port=0, buckets=(1, 8), use_batcher=False,
        mesh=make_mesh(8, model_parallel=2),
    )
    server.warmup()
    server.start()
    try:
        yield server
    finally:
        server.shutdown()


def test_model_parallel_matches_data_parallel_at_smaller_footprint(
    mp_spec, artifact_root
):
    """The whole point of the model axis: same logits, ~1/mp of the wide
    kernels resident per device."""
    a = art.load_artifact(art.version_dir(artifact_root, mp_spec.name, 1))
    eng_dp = InferenceEngine(a, buckets=(8,), mesh=make_mesh(8))
    eng_mp = InferenceEngine(a, buckets=(8,), mesh=make_mesh(8, model_parallel=2))
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(5, *mp_spec.input_shape), dtype=np.uint8)
    want = eng_dp.predict(images)
    got = eng_mp.predict(images)
    # Same compute dtype, different partitioning: GSPMD's reduction order
    # may differ, the math must not.
    np.testing.assert_allclose(got, want, atol=5e-2)

    dp, mp = eng_dp.sharding_info(), eng_mp.sharding_info()
    assert mp["sharding"] == "mesh-data"
    assert mp["model_parallel"] == 2
    assert mp["mesh_shape"] == {"data": 4, "model": 2}
    assert dp["model_parallel"] == 1
    # vit-tiny's mlp_in kernels (64 -> 256) clear the vit min_features
    # floor and shard; the footprint must strictly shrink.
    assert 0 < mp["param_bytes_per_device"] < dp["param_bytes_per_device"]


def test_quantized_subtree_shards_with_its_kernel():
    """w8a8 x mesh composition (the partition rules' quantize contract):
    the _q8 int8 payload shards exactly like the float kernel it
    replaced; scale vectors and scalars replicate."""
    variables = {"params": {
        "mlp": {"kernel": {
            "_q8": np.zeros((64, 256), np.int8),
            "_q8_scale": np.zeros((256,), np.float32),
            "_q8_act_scale": np.float32(1.0),
        }},
        "head": {"kernel": np.zeros((64, 8), np.float32)},
        "query": {"kernel": np.zeros((64, 4, 32), np.float32)},
    }}
    specs = mesh_lib.partition_spec("vit-s16", variables, 2)
    p = specs["params"]
    assert p["mlp"]["kernel"]["_q8"] == P(None, MODEL_AXIS)
    assert p["mlp"]["kernel"]["_q8_scale"] == P()
    assert p["mlp"]["kernel"]["_q8_act_scale"] == P()
    # Narrow head stays replicated; qkv shards its heads axis.
    assert p["head"]["kernel"] == P()
    assert p["query"]["kernel"] == P(None, MODEL_AXIS, None)


def test_served_status_metrics_and_audit(mp_spec, mp_server):
    base = f"http://localhost:{mp_server.port}"
    name = mp_spec.name

    # Status surface: GET /v1/models/<name>:status carries the layout.
    status = requests.get(f"{base}/v1/models/{name}:status", timeout=10).json()
    assert status["sharding"] == "mesh-data"
    assert status["model_parallel"] == 2
    assert status["mesh_shape"] == {"data": 4, "model": 2}

    # One real predict so the audit window has a row.
    body = {"instances": np.zeros((3, 16, 16, 3), np.uint8).tolist()}
    r = requests.post(f"{base}/v1/models/{name}:predict", json=body, timeout=60)
    assert r.status_code == 200, r.text

    # kdlt_mesh_* series on the metrics page.
    page = requests.get(f"{base}/metrics", timeout=10).text
    assert "kdlt_mesh_model_parallel" in page
    assert 'kdlt_mesh_axis_devices{' in page
    assert "kdlt_mesh_param_bytes_per_device" in page

    # The bucket-shape audit: 3 admitted into the 4-bucket (buckets round
    # up to the data axis) -> padding waste 1/4 on that bucket.
    audit = requests.get(f"{base}/debug/profile?audit=buckets", timeout=10).json()
    assert audit["tier"] == "model-server"
    buckets = audit["models"][name]["buckets"]
    row = buckets["4"]
    assert row["batches"] >= 1
    assert row["mean_admitted"] == pytest.approx(3.0)
    assert row["padding_waste_ratio"] == pytest.approx(0.25)
    # Never-admitted buckets report null, not garbage.
    assert buckets["8"]["mean_admitted"] is None


def test_gateway_merges_the_bucket_audit(mp_spec, mp_server):
    from kubernetes_deep_learning_tpu.serving.client import render_bucket_audit

    gateway = Gateway(
        serving_host=f"localhost:{mp_server.port}", model=mp_spec.name, port=0,
    )
    gateway.start()
    try:
        r = requests.get(
            f"http://localhost:{gateway.port}/debug/profile", timeout=10
        )
        assert r.status_code == 200, r.text
        merged = r.json()
        assert merged["tier"] == "gateway"
        (body,) = merged["replicas"].values()
        assert mp_spec.name in body["models"]
        # The client rendering handles both live rows and never-admitted
        # buckets (None mean/waste) without crashing.
        text = render_bucket_audit(merged)
        assert mp_spec.name in text
        assert "bucket audit" in text
    finally:
        gateway.shutdown()


def test_render_bucket_audit_marks_unreachable_replicas():
    from kubernetes_deep_learning_tpu.serving.client import render_bucket_audit

    text = render_bucket_audit({
        "tier": "gateway",
        "replicas": {
            "a:8500": {"tier": "model-server", "models": {"m": {
                "window": 0,
                "buckets": {"8": {
                    "batches": 0, "mean_admitted": None,
                    "padding_waste_ratio": None, "flops_per_image": None,
                }},
            }}},
            "b:8500": {"error": "status 503"},
        },
    })
    assert "# unreachable: status 503" in text
    assert " m " in text  # the reachable replica's model row rendered


def test_hot_reload_preserves_the_mesh_layout(mp_spec, mp_server, artifact_root):
    """Dropping a new version must come back warmed on the SAME mesh --
    a reload silently falling back to single-device would undo the
    footprint the model axis bought."""
    export_model(mp_spec, init_variables(mp_spec, seed=2), artifact_root)
    assert mp_server.poll_versions() == [f"{mp_spec.name} v2"]
    served = mp_server.models[mp_spec.name]
    assert served.version == 2
    info = served.engine.sharding_info()
    assert info["sharding"] == "mesh-data"
    assert info["model_parallel"] == 2
    status = mp_server.model_registry.model_status(mp_spec.name)
    assert status["model_parallel"] == 2
