import pytest

from kubernetes_deep_learning_tpu.utils.metrics import Histogram, Registry


def test_counter_gauge_histogram_render():
    r = Registry()
    c = r.counter("c_total", "a counter")
    g = r.gauge("g", "a gauge")
    h = r.histogram("h_seconds", "a histogram", buckets=(0.1, 1.0))
    c.inc()
    c.inc(2)
    g.set(5)
    h.observe(0.05)
    h.observe(0.5)
    h.observe(10)
    text = r.render()
    assert "c_total 3.0" in text
    assert "g 5" in text
    assert 'h_seconds_bucket{le="0.1"} 1' in text
    assert 'h_seconds_bucket{le="+Inf"} 3' in text
    assert "h_seconds_count 3" in text


def test_histogram_percentile():
    h = Histogram("x", buckets=(0.01, 0.1, 1.0))
    for _ in range(99):
        h.observe(0.05)
    h.observe(5.0)
    assert h.percentile(0.5) == 0.1
    assert h.percentile(0.99) == 0.1
    assert h.percentile(1.0) == float("inf")


def test_duplicate_metric_rejected():
    r = Registry()
    r.counter("dup_total")
    with pytest.raises(ValueError, match="duplicate"):
        r.counter("dup_total")


def test_labeled_child_registries_do_not_collide():
    r = Registry()
    a = r.with_labels(model="a")
    b = r.with_labels(model="b")
    a.counter("kdlt_engine_images_total").inc(1)
    b.counter("kdlt_engine_images_total").inc(2)
    text = r.render()
    assert 'kdlt_engine_images_total{model="a"} 1.0' in text
    assert 'kdlt_engine_images_total{model="b"} 2.0' in text
    # labels flow into histogram series too
    a.histogram("lat_seconds", buckets=(1.0,)).observe(0.5)
    assert 'lat_seconds_bucket{model="a",le="1.0"} 1' in r.render()
