"""SLO engine (utils/slo.py): window/burn-rate math on synthetic request
streams, gauge refresh, and the /debug/slo surfaces of both live tiers
(the gateway merging the model tier's view), plus the exemplar link from a
burning histogram back to its traces.
"""

from __future__ import annotations

import json
import tempfile

import numpy as np
import pytest
import requests

from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib
from kubernetes_deep_learning_tpu.utils import slo as slo_lib


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_engine(target=0.9, **kw):
    clock = FakeClock()
    eng = slo_lib.SloEngine(
        metrics_lib.Registry(), tier="test", enabled=True, target=target,
        clock=clock, **kw,
    )
    return eng, clock


# --- the window / burn-rate math -------------------------------------------


def test_burn_rate_math_against_synthetic_stream():
    eng, clock = make_engine(target=0.9)  # error budget = 10%
    for _ in range(80):
        eng.record("m", 200, 0.01)
    for _ in range(10):
        eng.record("m", 503, 0.0)
    for _ in range(10):
        eng.record("m", 500, 0.0)
    row = eng.model_windows()["m"]["5m"]
    assert row["total"] == 100
    assert row["good"] == 80
    assert row["goodput_ratio"] == pytest.approx(0.8)
    # bad fraction 0.2 over a 0.1 budget: burning 2x the sustainable rate.
    assert row["burn_rate"] == pytest.approx(2.0)
    assert row["shed_ratio"] == pytest.approx(0.1)
    assert row["error_ratio"] == pytest.approx(0.1)


def test_windows_age_out_independently():
    eng, clock = make_engine(target=0.99)
    for _ in range(10):
        eng.record("m", 500, 0.0)  # a burst of errors
    clock.advance(400)  # past 5m, inside 1h
    for _ in range(10):
        eng.record("m", 200, 0.01)
    rows = eng.model_windows()["m"]
    # 5m: only the recent good traffic; the burst aged out.
    assert rows["5m"]["total"] == 10
    assert rows["5m"]["burn_rate"] == 0.0
    # 1h: burst still visible -- 10 bad of 20 -> burn 0.5/0.01 = 50x.
    assert rows["1h"]["total"] == 20
    assert rows["1h"]["burn_rate"] == pytest.approx(50.0)
    clock.advance(3700)  # everything aged out
    rows = eng.model_windows()["m"]
    assert rows["1h"]["total"] == 0
    assert rows["1h"]["burn_rate"] == 0.0
    assert rows["1h"]["goodput_ratio"] == 1.0  # quiet != burning


def test_client_errors_excluded_from_the_slo():
    eng, _ = make_engine(target=0.9)
    for _ in range(10):
        eng.record("m", 200, 0.01)
    for _ in range(90):
        eng.record("m", 400, 0.0)  # the callers' fault
    row = eng.model_windows()["m"]["5m"]
    assert row["client"] == 90
    assert row["goodput_ratio"] == 1.0  # 10/10 eligible
    assert row["burn_rate"] == 0.0


def test_deadline_and_latency_objective_violations_are_late():
    eng, _ = make_engine(target=0.9, latency_objective_ms=100.0)
    eng.record("m", 200, 0.01)                            # good
    eng.record("m", 200, 0.01, deadline_exceeded=True)    # late via deadline
    eng.record("m", 200, 0.5)                             # late via objective
    row = eng.model_windows()["m"]["5m"]
    assert row["good"] == 1 and row["late"] == 2
    assert row["goodput_ratio"] == pytest.approx(1 / 3)


def test_refresh_sets_gauges_and_metrics_page_is_bounded():
    eng, _ = make_engine(target=0.9)
    registry = eng._registry
    for _ in range(8):
        eng.record("heavy", 200, 0.01)
    eng.record("heavy", 503, 0.0)
    eng.refresh()
    page = registry.render()
    assert 'kdlt_slo_burn_rate{tier="test",model="heavy",window="5m"}' in page
    assert 'window="1h"' in page
    # Refreshing twice must not re-mint (the registry dedupes by design).
    eng.refresh()


def test_merge_model_views_sums_counts_and_rederives():
    a = {"m": {"5m": {"total": 10, "good": 9, "late": 0, "shed": 1,
                      "error": 0, "client": 0}}}
    b = {"m": {"5m": {"total": 10, "good": 7, "late": 0, "shed": 0,
                      "error": 3, "client": 0}}}
    merged = slo_lib.merge_model_views([a, b], target=0.9)
    row = merged["m"]["5m"]
    assert row["total"] == 20 and row["good"] == 16
    assert row["goodput_ratio"] == pytest.approx(0.8)
    assert row["burn_rate"] == pytest.approx(2.0)


def test_resolve_target_clamps_and_survives_garbage(monkeypatch):
    monkeypatch.setenv(slo_lib.SLO_TARGET_ENV, "0.999")
    assert slo_lib.resolve_target() == pytest.approx(0.999)
    monkeypatch.setenv(slo_lib.SLO_TARGET_ENV, "bogus")
    assert slo_lib.resolve_target() == slo_lib.DEFAULT_SLO_TARGET
    # 1.0 would make every burn rate infinite; clamp below it.
    assert slo_lib.resolve_target(1.0) < 1.0


def test_disabled_engine_is_inert():
    eng = slo_lib.SloEngine(
        metrics_lib.Registry(), tier="test", enabled=False
    )
    eng.record("m", 500, 0.0)
    assert eng.refresh() == {}
    assert eng.debug_payload()["enabled"] is False


# --- both live tiers' /debug/slo + the exemplar link -----------------------


@pytest.fixture(scope="module")
def slo_stack():
    import os

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
    from kubernetes_deep_learning_tpu.runtime.stub import StubEngine
    from kubernetes_deep_learning_tpu.serving.gateway import Gateway
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    spec = register_spec(
        ModelSpec(
            name="slo-stub", family="xception",
            input_shape=(16, 16, 3), labels=("a", "b"),
        )
    )
    root = tempfile.mkdtemp(prefix="kdlt-slo-")
    art.save_artifact(
        art.version_dir(root, spec.name, 1), spec, {"params": {}}, None, {}
    )
    prev = os.environ.get(metrics_lib.EXEMPLARS_ENV)
    os.environ[metrics_lib.EXEMPLARS_ENV] = "1"
    server = ModelServer(
        root, port=0, buckets=(1, 2), host="127.0.0.1", batcher_impl="python",
        engine_factory=lambda a, **kw: StubEngine(a, async_device=True, **kw),
    )
    server.warmup()
    server.start()
    gateway = Gateway(
        serving_host=f"127.0.0.1:{server.port}", model=spec.name, port=0,
        host="127.0.0.1",
    )
    gateway.start()
    yield server, gateway, spec
    if prev is None:
        os.environ.pop(metrics_lib.EXEMPLARS_ENV, None)
    else:
        os.environ[metrics_lib.EXEMPLARS_ENV] = prev
    gateway.shutdown()
    server.shutdown()


def _predict_ok(server, spec, n=1):
    from kubernetes_deep_learning_tpu.serving import protocol
    from kubernetes_deep_learning_tpu.serving.admission import DEADLINE_HEADER

    img = np.zeros((1, 16, 16, 3), np.uint8)
    for _ in range(n):
        requests.post(
            f"http://127.0.0.1:{server.port}/v1/models/{spec.name}:predict",
            data=protocol.encode_predict_request(img),
            headers={
                "Content-Type": protocol.MSGPACK_CONTENT_TYPE,
                DEADLINE_HEADER: "5000",
            },
            timeout=30,
        ).raise_for_status()


def test_model_server_debug_slo_counts_agree_with_traffic(slo_stack):
    server, _, spec = slo_stack
    before = requests.get(
        f"http://127.0.0.1:{server.port}/debug/slo", timeout=5
    ).json()
    seen = (
        before.get("models", {}).get(spec.name, {}).get("5m", {})
        .get("total", 0)
    )
    _predict_ok(server, spec, n=5)
    body = requests.get(
        f"http://127.0.0.1:{server.port}/debug/slo", timeout=5
    ).json()
    assert body["tier"] == "model-server" and body["enabled"] is True
    row = body["models"][spec.name]["5m"]
    # The engine's count must agree exactly with the traffic sent (the
    # acceptance criterion's +-1-request bar, at unit scale).
    assert row["total"] == seen + 5
    assert row["good"] >= 5
    assert row["burn_rate"] == 0.0


def test_gateway_debug_slo_merges_replica_views(slo_stack):
    server, gateway, spec = slo_stack
    _predict_ok(server, spec, n=2)
    r = requests.post(
        f"http://127.0.0.1:{gateway.port}/predict",
        json={"url": "not-a-url"},
        timeout=30,
    )
    assert r.status_code == 400  # unfetchable URL: a client-class outcome
    body = requests.get(
        f"http://127.0.0.1:{gateway.port}/debug/slo", timeout=5
    ).json()
    assert body["tier"] == "gateway"
    # The gateway's own (client-observed) view saw the /predict attempt...
    gw_row = body["gateway"][spec.name]["5m"]
    assert gw_row["client"] >= 1
    # ...and the merged view carries the model tier's counts per replica.
    host = f"127.0.0.1:{server.port}"
    assert host in body["replicas"]
    merged = body["merged"][spec.name]["5m"]
    direct = body["replicas"][host]["models"][spec.name]["5m"]
    assert merged["total"] == direct["total"] >= 2


def test_slo_gauges_and_exemplars_on_live_metrics_page(slo_stack):
    from test_exposition import parse_exposition

    server, _, spec = slo_stack
    _predict_ok(server, spec, n=3)
    text = requests.get(
        f"http://127.0.0.1:{server.port}/metrics", timeout=5
    ).text
    fams = parse_exposition(text)
    assert "kdlt_slo_burn_rate" in fams
    assert "kdlt_slo_goodput_ratio" in fams
    # The burn-rate gauge carries the bounded (model, window) matrix.
    windows = {
        labels.get("window")
        for _, labels, _ in fams["kdlt_slo_burn_rate"]["samples"]
    }
    assert windows == {"5m", "1h"}
    # Exemplars (KDLT_METRICS_EXEMPLARS=1 in this stack): the request
    # latency histogram links a bucket to a trace id, and the annotated
    # page still parses strictly.
    exemplars = fams["kdlt_server_request_seconds"].get("exemplars", [])
    assert exemplars, "latency histogram should carry a trace exemplar"
    trace_id = exemplars[0][2]["trace_id"]
    # The exemplar links to a real retained trace on /debug/trace.
    r = requests.get(
        f"http://127.0.0.1:{server.port}/debug/trace/{trace_id}", timeout=5
    )
    assert r.status_code == 200
    assert r.json()["spans"]


def test_trace_retention_counters_on_live_page(slo_stack):
    server, _, spec = slo_stack
    _predict_ok(server, spec, n=1)
    text = requests.get(
        f"http://127.0.0.1:{server.port}/metrics", timeout=5
    ).text
    assert 'kdlt_trace_retained_total{class="routine"' in text


def test_client_renders_slo_table(slo_stack):
    from kubernetes_deep_learning_tpu.serving import client as client_lib

    server, gateway, spec = slo_stack
    _predict_ok(server, spec, n=1)
    payload = client_lib.fetch_slo(f"http://127.0.0.1:{gateway.port}")
    out = client_lib.render_slo(payload)
    assert "burn" in out and spec.name in out
    assert "merged" in out
    # And the CLI flag drives the same path end to end.
    rc = client_lib.main([
        "--gateway", f"http://127.0.0.1:{gateway.port}", "--slo",
    ])
    assert rc == 0
