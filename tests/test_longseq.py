"""Sequence-parallel ViT (parallel/longseq.py): the context-parallel serving
schedule must be numerically the SAME MODEL as the single-device flax module."""

import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.parallel.longseq import build_sequence_parallel_forward
from kubernetes_deep_learning_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def ls_spec() -> ModelSpec:
    # 32x32 / patch 8 -> 16 tokens, sharded 4 ways over the mesh.
    return register_spec(
        ModelSpec(
            name="longseq-vit",
            family="vit-tiny",
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",
            description="test-only sequence-parallel vit",
        )
    )


def test_matches_single_device_module(ls_spec):
    variables = init_variables(ls_spec, seed=0)
    mesh = make_mesh(4)
    fwd_sp = build_sequence_parallel_forward(ls_spec, mesh, dtype=jnp.float32)
    fwd_ref = build_forward(ls_spec, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(3, *ls_spec.input_shape), dtype=np.uint8)
    got = np.asarray(fwd_sp(variables, images))
    want = np.asarray(fwd_ref(variables, images))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_float32_prenormalized_path(ls_spec):
    variables = init_variables(ls_spec, seed=0)
    mesh = make_mesh(4)
    fwd_sp = build_sequence_parallel_forward(ls_spec, mesh, dtype=jnp.float32)
    fwd_ref = build_forward(ls_spec, dtype=jnp.float32)
    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, *ls_spec.input_shape)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(fwd_sp(variables, x)), np.asarray(fwd_ref(variables, x)), atol=1e-4
    )


def test_served_sequence_parallel(ls_spec, tmp_path):
    # The engine's mesh_mode="sequence" through the full HTTP server.
    import requests

    from kubernetes_deep_learning_tpu.export.exporter import export_model
    from kubernetes_deep_learning_tpu.serving.model_server import ModelServer

    export_model(ls_spec, init_variables(ls_spec, seed=0), str(tmp_path))
    server = ModelServer(
        str(tmp_path), port=0, buckets=(1, 4), mesh=make_mesh(4),
        mesh_mode="sequence",
    )
    try:
        server.warmup()
        server.start()
        r = requests.post(
            f"http://localhost:{server.port}/v1/models/{ls_spec.name}:predict",
            json={"instances": np.zeros((2, *ls_spec.input_shape), np.uint8).tolist()},
            timeout=60,
        )
        assert r.status_code == 200, r.text
        assert len(r.json()["predictions"]) == 2
    finally:
        server.shutdown()


def test_differentiable_grads_match_single_device(ls_spec):
    import jax

    variables = init_variables(ls_spec, seed=0)
    mesh = make_mesh(4)
    fwd_sp = build_sequence_parallel_forward(
        ls_spec, mesh, dtype=jnp.float32, differentiable=True
    )
    fwd_ref = build_forward(ls_spec, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(2, *ls_spec.input_shape), dtype=np.uint8)
    cot = jnp.asarray(
        rng.standard_normal((2, ls_spec.num_classes)), jnp.float32
    )

    def loss(fwd):
        return lambda v: jnp.sum(fwd(v, images) * cot)

    g_sp = jax.grad(loss(fwd_sp))(variables)
    g_ref = jax.grad(loss(fwd_ref))(variables)
    flat_sp, tree_sp = jax.tree.flatten(g_sp)
    flat_ref, tree_ref = jax.tree.flatten(g_ref)
    assert tree_sp == tree_ref
    for a, r in zip(flat_sp, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=1e-3)


def test_sequence_parallel_train_step(ls_spec):
    import optax

    from kubernetes_deep_learning_tpu.parallel.longseq import (
        build_sequence_parallel_train_step,
    )
    from kubernetes_deep_learning_tpu.training import create_train_state

    mesh = make_mesh(4)
    tx = optax.sgd(1e-3)
    state = create_train_state(ls_spec, tx, seed=0)
    step = build_sequence_parallel_train_step(ls_spec, tx, mesh, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(4, *ls_spec.input_shape), dtype=np.uint8)
    labels = rng.integers(0, ls_spec.num_classes, size=(4,), dtype=np.int32)
    state, metrics = step(state, images, labels)
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    state, metrics2 = step(state, images, labels)
    assert int(state.step) == 2
    assert float(metrics2["loss"]) < float(metrics["loss"]) + 1e-6


def test_rejects_non_vit_and_indivisible(ls_spec):
    mesh = make_mesh(8)
    cnn = register_spec(
        ModelSpec(
            name="longseq-cnn",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b"),
            preprocessing="tf",
        )
    )
    with pytest.raises(ValueError, match="vit family"):
        build_sequence_parallel_forward(cnn, mesh)
    odd = register_spec(
        ModelSpec(
            name="longseq-odd",
            family="vit-tiny",
            input_shape=(24, 32, 3),  # 3x4 = 12 tokens, not divisible by 8
            labels=("a", "b"),
            preprocessing="tf",
        )
    )
    with pytest.raises(ValueError, match="not divisible"):
        build_sequence_parallel_forward(odd, mesh)
    with pytest.raises(ValueError, match="model_parallel=1"):
        build_sequence_parallel_forward(ls_spec, make_mesh(8, model_parallel=2))
