import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.models import build_forward, create_model, init_variables
from kubernetes_deep_learning_tpu.models.efficientnet import (
    round_filters,
    round_repeats,
)
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec


@pytest.fixture(scope="module")
def tiny_effnet_spec() -> ModelSpec:
    return register_spec(
        ModelSpec(
            name="tiny-effnet",
            family="efficientnet-b3",
            input_shape=(64, 64, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="torch",
            description="test-only small-input efficientnet-b3",
        )
    )


def test_compound_scaling_b3():
    # B3: width 1.2 -> stem 40, top 1536; depth 1.4 -> repeats (2 -> 3).
    assert round_filters(32, 1.2) == 40
    assert round_filters(1280, 1.2) == 1536
    assert round_repeats(2, 1.4) == 3
    assert round_repeats(3, 1.4) == 5


def test_forward_shape_and_dtype(tiny_effnet_spec):
    variables = init_variables(tiny_effnet_spec, seed=0)
    fwd = build_forward(tiny_effnet_spec, dtype=None)
    x = np.zeros((2, *tiny_effnet_spec.input_shape), np.uint8)
    logits = jax.jit(fwd)(variables, x)
    assert logits.shape == (2, tiny_effnet_spec.num_classes)
    assert logits.dtype == jnp.float32


def test_param_count_matches_b3():
    # EfficientNet-B3 (include_top, 1000 classes) is 12,233,232 params in the
    # canonical implementations (stochastic depth adds none); require our
    # count to land in a tight band around it.
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    spec = get_spec("efficientnet-b3-imagenet")
    model = create_model(spec)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 300, 300, 3)))
    )
    total = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(variables))
    assert 11_900_000 < total < 12_600_000, total


def test_residual_only_on_matching_shapes(tiny_effnet_spec):
    # Smoke the block wiring: deterministic inference, two calls agree.
    variables = init_variables(tiny_effnet_spec, seed=0)
    fwd = build_forward(tiny_effnet_spec, dtype=None)
    x = np.zeros((1, *tiny_effnet_spec.input_shape), np.uint8)
    a = jax.jit(fwd)(variables, x)
    b = jax.jit(fwd)(variables, x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
