"""Test env: force JAX onto a virtual 8-device CPU mesh.

Must run before jax is first imported anywhere, which pytest guarantees by
importing conftest first.  All multi-chip sharding tests run against these
virtual devices; real-TPU behavior is exercised by bench.py, not tests
(SURVEY.md section 4: fake/CPU backend so the serving path is testable
without TPUs).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The host environment force-registers a real-TPU PJRT plugin ("axon") into
# every interpreter via sitecustomize, which imports jax at interpreter
# startup with JAX_PLATFORMS=axon -- so the env vars above are latched too
# late and tests would silently run on (and wedge) the single-client TPU
# tunnel.  Make tests hermetic CPU-only before the first backend lookup.
from kubernetes_deep_learning_tpu.utils.platform import force_platform  # noqa: E402

force_platform("cpu")

# NOTE on the persistent XLA compile cache: pointing the suite at
# utils/compilecache's mechanism (JAX_COMPILATION_CACHE_DIR) cuts ~90 s of
# warm-rerun wall clock, but on this jaxlib (0.4.37, CPU) suite runs with a
# WARM cache intermittently die of heap corruption around the orbax async-
# checkpoint tests -- cache-deserialized executables with real input/output
# aliasing (the donated train state) are implicated; the crash survives
# scoping the cache away from the checkpoint module itself, so the
# corruption source is nonlocal.  Do not re-enable wholesale; opt in per
# developer run via KDLT_COMPILE_CACHE_DIR at your own risk.

import pytest  # noqa: E402

from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec  # noqa: E402


@pytest.fixture(scope="session")
def tiny_spec() -> ModelSpec:
    """A small Xception spec so CPU tests stay fast."""
    return register_spec(
        ModelSpec(
            name="tiny-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
            head_hidden=(16,),
            description="test-only small-input xception",
        )
    )
