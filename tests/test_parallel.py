"""Multi-chip tests on the virtual 8-device CPU mesh (see conftest)."""

import jax
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    ShardedEngine,
    make_mesh,
)
from kubernetes_deep_learning_tpu.parallel.dataparallel import (
    build_sharded_forward,
    shard_variables,
)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_make_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.shape == {DATA_AXIS: 8, MODEL_AXIS: 1}
    mesh = make_mesh(8, model_parallel=2)
    assert mesh.shape == {DATA_AXIS: 4, MODEL_AXIS: 2}
    with pytest.raises(ValueError, match="divisible"):
        make_mesh(6, model_parallel=4)


def test_dataparallel_matches_single_device(tiny_spec):
    variables = init_variables(tiny_spec, seed=0)
    mesh = make_mesh(8)
    call = build_sharded_forward(tiny_spec, mesh, dtype=None)
    sharded_vars = shard_variables(variables, mesh)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(16, *tiny_spec.input_shape), dtype=np.uint8)
    got = np.asarray(call(sharded_vars, x))

    fwd = jax.jit(build_forward(tiny_spec, dtype=None))
    want = np.asarray(fwd(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-3)


def test_tensor_parallel_sharding_applied(tiny_spec):
    variables = init_variables(tiny_spec, seed=0)
    mesh = make_mesh(8, model_parallel=2)
    sharded = shard_variables(variables, mesh)
    # A wide pointwise kernel (728+ features) must be sharded on its out dim.
    wide = sharded["params"]["block13_sepconv2"]["pointwise"]["kernel"]
    spec = wide.sharding.spec
    assert spec[-1] == MODEL_AXIS
    # Small kernels stay replicated.
    small = sharded["params"]["block1_conv1"]["kernel"]
    assert all(s is None for s in small.sharding.spec)


def test_tensor_parallel_forward_matches(tiny_spec):
    variables = init_variables(tiny_spec, seed=0)
    mesh = make_mesh(8, model_parallel=2)
    call = build_sharded_forward(tiny_spec, mesh, dtype=None)
    sharded_vars = shard_variables(variables, mesh)
    x = np.zeros((8, *tiny_spec.input_shape), np.uint8)
    got = np.asarray(call(sharded_vars, x))
    fwd = jax.jit(build_forward(tiny_spec, dtype=None))
    want = np.asarray(fwd(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-3)


def test_sharded_engine_bucket_roundup_and_predict(tiny_spec):
    variables = init_variables(tiny_spec, seed=0)
    mesh = make_mesh(8)
    eng = ShardedEngine(tiny_spec, variables, mesh, buckets=(4, 20), dtype=None)
    # 4 -> 8 (round UP to multiple of 8), 20 -> 24
    assert eng.buckets == (8, 24)
    assert eng.max_batch == 24
    out = eng.predict(np.zeros((5, *tiny_spec.input_shape), np.uint8))
    assert out.shape == (5, tiny_spec.num_classes)
    with pytest.raises(ValueError, match="exceeds"):
        eng.predict(np.zeros((25, *tiny_spec.input_shape), np.uint8))
