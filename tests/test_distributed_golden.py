"""utils.distributed env parsing + golden-logit checker logic."""

import pytest

from kubernetes_deep_learning_tpu.golden import GOLDEN_LOGITS, check_scores
from kubernetes_deep_learning_tpu.utils import distributed as dist


def test_env_spec_absent():
    assert dist.env_spec({}) is None


def test_env_spec_complete():
    spec = dist.env_spec({
        dist.COORDINATOR_ENV: "10.0.0.1:1234",
        dist.NUM_PROCESSES_ENV: "4",
        dist.PROCESS_ID_ENV: "2",
    })
    assert spec == {
        "coordinator_address": "10.0.0.1:1234",
        "num_processes": 4,
        "process_id": 2,
    }


def test_env_spec_init_timeout_override():
    spec = dist.env_spec({
        dist.COORDINATOR_ENV: "10.0.0.1:1234",
        dist.NUM_PROCESSES_ENV: "4",
        dist.PROCESS_ID_ENV: "2",
        dist.INIT_TIMEOUT_ENV: "120",
    })
    assert spec["initialization_timeout"] == 120


def test_env_spec_partial_is_loud():
    with pytest.raises(ValueError, match="missing"):
        dist.env_spec({dist.COORDINATOR_ENV: "10.0.0.1:1234"})


@pytest.mark.parametrize("num,pid", [("0", "0"), ("4", "4"), ("4", "-1")])
def test_env_spec_invalid_ranges(num, pid):
    with pytest.raises(ValueError, match="invalid"):
        dist.env_spec({
            dist.COORDINATOR_ENV: "a:1",
            dist.NUM_PROCESSES_ENV: num,
            dist.PROCESS_ID_ENV: pid,
        })


def test_initialize_noop_without_env():
    assert dist.initialize({}) is False


def test_golden_check_passes_on_exact():
    assert check_scores(dict(GOLDEN_LOGITS), atol=0.01) == []


def test_golden_check_flags_drift_and_top1():
    scores = dict(GOLDEN_LOGITS)
    scores["pants"] = -10.0  # drifted AND no longer top-1
    failures = check_scores(scores, atol=0.05)
    assert any("pants: got" in f for f in failures)
    assert any("top-1" in f for f in failures)


def test_golden_check_flags_missing_label():
    scores = dict(GOLDEN_LOGITS)
    del scores["hat"]
    assert any("hat: missing" in f for f in check_scores(scores, atol=0.05))
