"""Native C++ host ops: bit-exact parity with the PIL fallback.

The native resize (native/hostops.cc) replaces PIL on the gateway hot path;
both filters must agree with PIL **exactly** -- the clothing model's golden
logits depend on nearest-resize pixel identity (modelspec.py, BASELINE.md),
so "close" is not good enough.
"""

from __future__ import annotations

import numpy as np
import pytest
from PIL import Image

_native = pytest.importorskip(
    "kubernetes_deep_learning_tpu.ops._native",
    reason="native lib unavailable (no g++?)",
)

SIZES = [
    ((120, 80), (96, 96)),     # down
    ((50, 60), (299, 299)),    # up (exercises PIL's incremental-accumulation quirk)
    ((500, 400), (299, 299)),  # down to flagship resolution
    ((299, 299), (150, 100)),  # non-square down
    ((3, 5), (7, 2)),          # degenerate tiny
]


@pytest.mark.parametrize("src_size,dst_size", SIZES)
@pytest.mark.parametrize("filt", ["nearest", "bilinear"])
def test_resize_matches_pil_exactly(src_size, dst_size, filt):
    rng = np.random.default_rng(hash((src_size, dst_size)) % 2**32)
    img = rng.integers(0, 256, (*src_size, 3), dtype=np.uint8)
    (dh, dw) = dst_size
    pil_filter = Image.NEAREST if filt == "nearest" else Image.BILINEAR
    want = np.asarray(Image.fromarray(img).resize((dw, dh), pil_filter), np.uint8)
    fn = _native.resize_nearest if filt == "nearest" else _native.resize_bilinear
    np.testing.assert_array_equal(fn(img, dh, dw), want)


@pytest.mark.parametrize("filt", ["nearest", "bilinear"])
def test_resize_batch_matches_single(filt):
    rng = np.random.default_rng(7)
    imgs = rng.integers(0, 256, (5, 120, 80, 3), dtype=np.uint8)
    batch = _native.resize_batch(imgs, 64, 48, filter=filt, num_threads=3)
    single = _native.resize_nearest if filt == "nearest" else _native.resize_bilinear
    for i in range(imgs.shape[0]):
        np.testing.assert_array_equal(batch[i], single(imgs[i], 64, 48))


def test_preprocess_uses_native_and_matches_pil():
    from kubernetes_deep_learning_tpu.ops import preprocess

    assert preprocess._native is not None
    rng = np.random.default_rng(3)
    img = rng.integers(0, 256, (200, 150, 3), dtype=np.uint8)
    for filt, pil_filter in (("nearest", Image.NEAREST), ("bilinear", Image.BILINEAR)):
        got = preprocess.resize_uint8(img, (96, 96), filt)
        want = np.asarray(Image.fromarray(img).resize((96, 96), pil_filter), np.uint8)
        np.testing.assert_array_equal(got, want)


def test_input_validation():
    with pytest.raises(ValueError):
        _native.resize_bilinear(np.zeros((4, 4), np.uint8), 2, 2)  # not HWC
    with pytest.raises(ValueError):
        _native.resize_nearest(np.zeros((4, 4, 3), np.float32), 2, 2)  # not uint8
