import jax
import numpy as np
import optax
import pytest

from kubernetes_deep_learning_tpu.parallel import make_mesh
from kubernetes_deep_learning_tpu.training import build_train_step, create_train_state


@pytest.fixture(scope="module")
def train_setup():
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

    spec = register_spec(
        ModelSpec(
            name="train-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
        )
    )
    tx = optax.sgd(1e-3)
    return spec, tx


def _batch(spec, n=8, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(n, *spec.input_shape), dtype=np.uint8)
    labels = rng.integers(0, spec.num_classes, size=(n,), dtype=np.int32)
    return images, labels


def test_train_step_reduces_loss_single_device(train_setup):
    spec, tx = train_setup
    state = create_train_state(spec, tx, seed=0)
    step = build_train_step(spec, tx)
    images, labels = _batch(spec)
    losses = []
    for _ in range(5):
        state, metrics = step(state, images, labels)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 5


def test_train_step_sharded_over_mesh(train_setup):
    spec, tx = train_setup
    mesh = make_mesh(8)
    state = create_train_state(spec, tx, seed=0, mesh=mesh)
    step = build_train_step(spec, tx, mesh=mesh)
    images, labels = _batch(spec, n=16)
    state, metrics = step(state, images, labels)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 1
    # Params remain replicated (or model-sharded), not batch-sharded.
    kernel = state.params["block1_conv1"]["kernel"]
    assert kernel.sharding.is_fully_replicated


def test_sharded_and_single_device_grads_agree(train_setup):
    spec, tx = train_setup
    images, labels = _batch(spec, n=8, seed=3)

    state1 = create_train_state(spec, tx, seed=0)
    step1 = build_train_step(spec, tx)
    state1, m1 = step1(state1, images, labels)

    mesh = make_mesh(8)
    state2 = create_train_state(spec, tx, seed=0, mesh=mesh)
    step2 = build_train_step(spec, tx, mesh=mesh)
    state2, m2 = step2(state2, images, labels)

    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-3
    a = np.asarray(state1.params["head"]["logits"]["kernel"])
    b = np.asarray(state2.params["head"]["logits"]["kernel"])
    np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)
