import jax
import numpy as np
import optax
import pytest

from kubernetes_deep_learning_tpu.parallel import make_mesh
from kubernetes_deep_learning_tpu.training import build_train_step, create_train_state


@pytest.fixture(scope="module")
def train_setup():
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

    spec = register_spec(
        ModelSpec(
            name="train-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
        )
    )
    tx = optax.sgd(1e-3)
    return spec, tx


@pytest.fixture(scope="module")
def tiny_train_spec():
    from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

    return register_spec(
        ModelSpec(
            name="eval-vit",
            family="vit-tiny",
            input_shape=(16, 16, 3),
            labels=("a", "b", "c"),
            preprocessing="tf",
            description="test-only eval-path model",
        )
    )


def _batch(spec, n=8, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(n, *spec.input_shape), dtype=np.uint8)
    labels = rng.integers(0, spec.num_classes, size=(n,), dtype=np.int32)
    return images, labels


def test_train_step_reduces_loss_single_device(train_setup):
    spec, tx = train_setup
    state = create_train_state(spec, tx, seed=0)
    step = build_train_step(spec, tx)
    images, labels = _batch(spec)
    losses = []
    for _ in range(5):
        state, metrics = step(state, images, labels)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 5


def test_train_step_sharded_over_mesh(train_setup):
    spec, tx = train_setup
    mesh = make_mesh(8)
    state = create_train_state(spec, tx, seed=0, mesh=mesh)
    step = build_train_step(spec, tx, mesh=mesh)
    images, labels = _batch(spec, n=16)
    state, metrics = step(state, images, labels)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 1
    # Params remain replicated (or model-sharded), not batch-sharded.
    kernel = state.params["block1_conv1"]["kernel"]
    assert kernel.sharding.is_fully_replicated


def test_sharded_and_single_device_grads_agree(train_setup):
    spec, tx = train_setup
    images, labels = _batch(spec, n=8, seed=3)

    state1 = create_train_state(spec, tx, seed=0)
    step1 = build_train_step(spec, tx)
    state1, m1 = step1(state1, images, labels)

    mesh = make_mesh(8)
    state2 = create_train_state(spec, tx, seed=0, mesh=mesh)
    step2 = build_train_step(spec, tx, mesh=mesh)
    state2, m2 = step2(state2, images, labels)

    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-3
    a = np.asarray(state1.params["head"]["logits"]["kernel"])
    b = np.asarray(state2.params["head"]["logits"]["kernel"])
    np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4)


def test_eval_step_and_evaluate(tiny_train_spec):
    """build_eval_step sums are exact; evaluate() aggregates uneven batches."""
    import optax

    from kubernetes_deep_learning_tpu.training.loop import evaluate
    from kubernetes_deep_learning_tpu.training.trainer import (
        build_eval_step,
        create_train_state,
    )

    spec = tiny_train_spec
    state = create_train_state(spec, optax.sgd(1e-3), seed=0)
    rng = np.random.default_rng(0)

    def batches():
        for n in (3, 5):  # uneven on purpose
            yield (
                rng.integers(0, 256, (n, *spec.input_shape), np.uint8),
                rng.integers(0, spec.num_classes, (n,), np.int32),
            )

    m = evaluate(spec, state, batches())
    assert m["count"] == 8
    assert 0.0 <= m["val_top1"] <= m["val_topk"] <= 1.0
    assert np.isfinite(m["val_loss"])
    # topk capped at num_classes => every example is in the top-k
    if spec.num_classes <= 5:
        assert m["val_topk"] == 1.0

    step = build_eval_step(spec)
    imgs = rng.integers(0, 256, (4, *spec.input_shape), np.uint8)
    lbls = rng.integers(0, spec.num_classes, (4,), np.int32)
    out = step(state, imgs, lbls)
    assert int(out["count"]) == 4
    assert 0 <= int(out["top1_sum"]) <= 4


def test_fit_runs_periodic_and_final_eval(tiny_train_spec):
    import optax

    from kubernetes_deep_learning_tpu.training import fit, synthetic_batches

    spec = tiny_train_spec
    logs: list[str] = []
    eval_hist: list = []

    def eval_batches():
        return synthetic_batches(spec, 4, steps=2, seed=9)

    state, hist = fit(
        spec,
        optax.sgd(1e-3),
        synthetic_batches(spec, 4, steps=4),
        steps=4,
        log_fn=logs.append,
        eval_batches=eval_batches,
        eval_every=2,
        eval_history=eval_hist,
    )
    assert int(state.step) == 4
    assert hist[-1][0] == 4  # train history shape unchanged
    # periodic eval at step 2 + final eval at step 4
    steps_evaled = [s for s, _ in eval_hist]
    assert steps_evaled == [2, 4]
    for _, m in eval_hist:
        assert set(m) >= {"val_loss", "val_top1", "val_topk", "count"}
        assert m["count"] == 8
    assert sum("eval step" in line for line in logs) == 2
