import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_deep_learning_tpu.models import build_forward, create_model, init_variables


def test_forward_shape_and_dtype(tiny_spec):
    variables = init_variables(tiny_spec, seed=0)
    fwd = build_forward(tiny_spec, dtype=None)
    x = np.zeros((2, *tiny_spec.input_shape), np.uint8)
    logits = jax.jit(fwd)(variables, x)
    assert logits.shape == (2, tiny_spec.num_classes)
    assert logits.dtype == jnp.float32


def test_uint8_and_prenormalized_paths_agree(tiny_spec):
    variables = init_variables(tiny_spec, seed=0)
    fwd = build_forward(tiny_spec, dtype=None)
    rng = np.random.default_rng(0)
    u8 = rng.integers(0, 256, size=(1, *tiny_spec.input_shape), dtype=np.uint8)
    f32 = u8.astype(np.float32) / 127.5 - 1.0
    a = jax.jit(fwd)(variables, u8)
    b = jax.jit(fwd)(variables, f32)
    # The two entry dtypes compile separately; XLA fuses the normalize into
    # downstream convs differently, so allow fusion-level f32 rounding drift.
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=5e-3)


def test_param_count_matches_keras_xception():
    # keras.applications.Xception base (include_top=False) has 20,861,480
    # params; our backbone must match it weight-for-weight for .h5 import.
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    spec = get_spec("clothing-model")
    model = create_model(spec)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 299, 299, 3)))
    )
    total = sum(
        int(np.prod(a.shape))
        for a in jax.tree.leaves(variables)
    )
    head = 2048 * 100 + 100 + 100 * 10 + 10  # hidden_0 + logits
    assert total == 20_861_480 + head


def test_batchnorm_inference_uses_running_stats(tiny_spec):
    variables = init_variables(tiny_spec, seed=0)
    fwd = build_forward(tiny_spec, dtype=None)
    x = np.zeros((1, *tiny_spec.input_shape), np.uint8)
    a = jax.jit(fwd)(variables, x)
    b = jax.jit(fwd)(variables, x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
