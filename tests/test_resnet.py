import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.models import build_forward, create_model, init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec


@pytest.fixture(scope="module")
def tiny_resnet_spec() -> ModelSpec:
    return register_spec(
        ModelSpec(
            name="tiny-resnet",
            family="resnet50",
            input_shape=(64, 64, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="caffe",
            description="test-only small-input resnet50",
        )
    )


def test_forward_shape_and_dtype(tiny_resnet_spec):
    variables = init_variables(tiny_resnet_spec, seed=0)
    fwd = build_forward(tiny_resnet_spec, dtype=None)
    x = np.zeros((2, *tiny_resnet_spec.input_shape), np.uint8)
    logits = jax.jit(fwd)(variables, x)
    assert logits.shape == (2, tiny_resnet_spec.num_classes)
    assert logits.dtype == jnp.float32


def test_param_count_matches_keras_resnet50():
    # keras.applications.ResNet50 (include_top, 1000 classes) has exactly
    # 25,636,712 parameters; matching it weight-for-weight is the
    # precondition for .h5 import via models.keras_import.
    from kubernetes_deep_learning_tpu.modelspec import get_spec

    spec = get_spec("resnet50-imagenet")
    model = create_model(spec)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))
    )
    total = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(variables))
    assert total == 25_636_712


def test_stage_downsampling(tiny_resnet_spec):
    # 64x64 input: stem /2 -> 32, pool /2 -> 16, stages 3..5 halve -> 2x2
    # before global pool; total stride 32 like every ResNet50.
    model = create_model(tiny_resnet_spec)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    # Grab the pre-pool activation by checking the conv5 output channels: 2048.
    leaves = variables["params"]
    assert leaves["conv5_block3"]["3_conv"]["kernel"].shape[-1] == 2048
