"""ModelRegistry (serving/registry.py): artifact-root scanning, artifact-
hash identity (byte-identical re-exports adopted without reload), status
surfaces, and loader/unloader discipline -- unit level, no server, no jax."""

from __future__ import annotations

import os

import pytest

from kubernetes_deep_learning_tpu.serving.registry import (
    ModelRegistry,
    artifact_hash,
)


class _Served:
    """Minimal ServedModel stand-in: what the registry actually touches."""

    class _Engine:
        ready = True
        buckets = (1, 2)

    class _Spec:
        family = "xception"
        labels = ("a", "b")

    class _Artifact:
        spec = None

    def __init__(self, name, version):
        self.name = name
        self.version = version
        self.artifact_hash = None
        self.engine = self._Engine()
        self.artifact = self._Artifact()
        self.artifact.spec = self._Spec()
        self.closed = False


def _write_version(root, name, version, payload: bytes):
    d = os.path.join(root, name, str(version))
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "spec.json"), "wb") as f:
        f.write(payload)
    with open(os.path.join(d, "params.msgpack"), "wb") as f:
        f.write(b"params:" + payload)
    return d


def _registry(root, log=None):
    log = log if log is not None else []

    def loader(name, version, directory):
        log.append(("load", name, version))
        return _Served(name, version)

    def unloader(served):
        log.append(("unload", served.name, served.version))
        served.closed = True

    return ModelRegistry(str(root), loader, unloader), log


def test_scans_every_model_and_highest_version(tmp_path):
    _write_version(tmp_path, "alpha", 1, b"a1")
    _write_version(tmp_path, "alpha", 3, b"a3")
    _write_version(tmp_path, "beta", 2, b"b2")
    reg, log = _registry(tmp_path)
    assert sorted(reg.poll()) == ["alpha v3", "beta v2"]
    assert reg.models["alpha"].version == 3
    assert reg.models["beta"].version == 2
    assert "alpha" in reg and reg.get("beta") is not None
    # No change on disk -> no-op poll.
    assert reg.poll() == []
    assert [e for e in log if e[0] == "load"] == [
        ("load", "alpha", 3), ("load", "beta", 2),
    ]


def test_artifact_hash_keys_identity(tmp_path):
    d1 = _write_version(tmp_path, "m", 1, b"same-bytes")
    d2 = _write_version(tmp_path, "m", 2, b"same-bytes")
    d3 = _write_version(tmp_path, "m", 3, b"different")
    assert artifact_hash(d1) == artifact_hash(d2)
    assert artifact_hash(d1) != artifact_hash(d3)


def test_byte_identical_reexport_adopts_version_without_reload(tmp_path):
    _write_version(tmp_path, "m", 1, b"weights-v1")
    reg, log = _registry(tmp_path)
    reg.poll()
    served = reg.models["m"]
    # Version 2 is the same bytes: the registry must adopt the number
    # without reload/re-warm (the hash, not the dir name, is identity).
    _write_version(tmp_path, "m", 2, b"weights-v1")
    assert reg.poll() == []
    assert reg.models["m"] is served
    assert served.version == 2  # status reports the adopted version
    assert [e for e in log if e[0] == "load"] == [("load", "m", 1)]
    # Version 3 with NEW bytes is a real reload; the old version unloads.
    _write_version(tmp_path, "m", 3, b"weights-v3")
    assert reg.poll() == ["m v3"]
    assert reg.models["m"] is not served
    assert served.closed
    assert ("unload", "m", 2) in log


def test_broken_loader_keeps_serving_and_retries(tmp_path):
    _write_version(tmp_path, "m", 1, b"v1")
    calls = []

    def loader(name, version, directory):
        calls.append(version)
        if version == 2:
            raise RuntimeError("half-written dir")
        return _Served(name, version)

    reg = ModelRegistry(str(tmp_path), loader)
    reg.poll()
    _write_version(tmp_path, "m", 2, b"v2")
    assert reg.poll() == []  # failed load never takes down the old version
    assert reg.models["m"].version == 1
    assert reg.poll() == []  # ...and is retried on the next scan
    assert calls == [1, 2, 2]


def test_declined_loader_is_skipped(tmp_path):
    _write_version(tmp_path, "mismatch", 1, b"v1")
    reg = ModelRegistry(str(tmp_path), lambda *a: None)
    assert reg.poll() == []
    assert reg.models == {}


def test_status_surfaces(tmp_path):
    _write_version(tmp_path, "m", 1, b"v1")
    reg, _ = _registry(tmp_path)
    reg.poll()
    status = reg.status()
    assert set(status) == {"m"}
    st = status["m"]
    assert st["version"] == 1 and st["ready"] is True
    assert st["artifact_hash"] == artifact_hash(
        os.path.join(str(tmp_path), "m", "1")
    )
    assert st["buckets"] == [1, 2]
    assert st["family"] == "xception"
    assert reg.model_status("m") == st
    assert reg.model_status("nope") is None


def test_single_model_name_errors_are_actionable(tmp_path):
    from kubernetes_deep_learning_tpu.serving.model_server import (
        _single_model_name,
    )

    with pytest.raises(ValueError, match="no versioned model"):
        _single_model_name(str(tmp_path))
    _write_version(tmp_path, "one", 1, b"x")
    assert _single_model_name(str(tmp_path)) == ("one",)
    _write_version(tmp_path, "two", 1, b"y")
    with pytest.raises(ValueError, match="exactly one model.*multi-model"):
        _single_model_name(str(tmp_path))
