"""kdlt-warm (export/warm.py) + warmup provenance accounting: the
zero-cold-start scale-up path.  All device-free: engines are stubbed (the
real cache-hit speedup is a slow-marked/bench concern; see PR 9's note in
tests/conftest.py on why tier-1 never enables a real persistent XLA
cache in-process)."""

from __future__ import annotations

import re

from kubernetes_deep_learning_tpu.export import artifact as art
from kubernetes_deep_learning_tpu.export import warm
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib


def _metric(text: str, name: str, **labels: str) -> float:
    for m in re.finditer(rf"^{re.escape(name)}(\{{[^}}]*\}})? (\S+)$", text, re.M):
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1) or ""))
        if all(got.get(k) == v for k, v in labels.items()):
            return float(m.group(2))
    raise AssertionError(f"no sample {name} with {labels} in:\n{text}")


def _save_model(root, name, version=1):
    spec = register_spec(
        ModelSpec(
            name=name,
            family="xception",  # never instantiated by the stub factory
            input_shape=(32, 32, 3),
            labels=("a", "b", "c"),
        )
    )
    art.save_artifact(
        art.version_dir(str(root), name, version), spec, {"params": {}}, None, {}
    )
    return spec


class _FakeEngine:
    """Records warmup calls and exposes a warm_report like the real engine."""

    calls: list = []

    def __init__(self, directory, buckets):
        self.directory = directory
        self.buckets = tuple(buckets)

    def warmup(self, workers=4):
        _FakeEngine.calls.append((self.directory, self.buckets, workers))
        self.warm_report = {
            "total_seconds": 0.01,
            "buckets": {
                int(b): {"seconds": 0.001, "source": "cache"}
                for b in self.buckets
            },
        }
        return 0.01


def test_warm_models_covers_every_registry_model(tmp_path, monkeypatch):
    monkeypatch.delenv("KDLT_COMPILE_CACHE_DIR", raising=False)
    root = tmp_path / "models"
    _save_model(root, "warm-a")
    _save_model(root, "warm-b", version=1)
    _save_model(root, "warm-b", version=2)  # only the LATEST version warms
    _FakeEngine.calls = []
    report = warm.warm_models(
        str(root),
        buckets=(1, 2),
        cache_dir=str(tmp_path / "cache"),
        engine_factory=_FakeEngine,
    )
    assert sorted(report["models"]) == ["warm-a", "warm-b"]
    assert report["models"]["warm-b"]["version"] == 2
    assert report["buckets"] == [1, 2]
    # The scan rule is the serving registry's: one engine per latest
    # version, full requested ladder each.
    assert len(_FakeEngine.calls) == 2
    assert all(buckets == (1, 2) for _, buckets, _ in _FakeEngine.calls)
    # The engine's own warm_report rides along (per-bucket provenance).
    assert report["models"]["warm-a"]["buckets"][1]["source"] == "cache"


def test_warm_models_fail_soft_warms_the_rest(tmp_path, monkeypatch):
    monkeypatch.delenv("KDLT_COMPILE_CACHE_DIR", raising=False)
    root = tmp_path / "models"
    _save_model(root, "aaa-bad")
    _save_model(root, "bbb-good")

    def factory(directory, buckets):
        if "aaa-bad" in directory:
            raise RuntimeError("compile exploded")
        return _FakeEngine(directory, buckets)

    report = warm.warm_models(
        str(root), buckets=(1,), cache_dir=str(tmp_path / "cache"),
        engine_factory=factory,
    )
    # The failure is reported, not raised -- and the REST of the fleet
    # still warmed (an image bake must not lose every model to one).
    assert report["models"]["aaa-bad"]["error"] == "compile exploded"
    assert "error" not in report["models"]["bbb-good"]
    assert report["models"]["bbb-good"]["seconds"] >= 0


def test_warm_main_rc_and_json(tmp_path, monkeypatch, capsys):
    monkeypatch.delenv("KDLT_COMPILE_CACHE_DIR", raising=False)
    root = tmp_path / "models"
    _save_model(root, "warm-cli")
    monkeypatch.setattr(warm, "_default_factory", _FakeEngine)
    _FakeEngine.calls = []
    rc = warm.main([
        "--models", str(root),
        "--buckets", "2,1,2",
        "--compile-cache-dir", str(tmp_path / "cache"),
        "--json",
    ])
    assert rc == 0
    import json

    report = json.loads(capsys.readouterr().out)
    assert report["buckets"] == [1, 2]  # deduped, sorted
    assert "warm-cli" in report["models"]
    # An empty root is rc=1 loudly: a warm pass that warmed NOTHING must
    # fail the image build rather than bake a cold cache silently.
    assert warm.main(["--models", str(tmp_path / "empty")]) == 1


def test_warm_main_rc_1_when_any_model_fails(tmp_path, monkeypatch):
    monkeypatch.delenv("KDLT_COMPILE_CACHE_DIR", raising=False)
    root = tmp_path / "models"
    _save_model(root, "warm-fail")

    def factory(directory, buckets):
        raise RuntimeError("boom")

    monkeypatch.setattr(warm, "_default_factory", factory)
    assert warm.main([
        "--models", str(root), "--compile-cache-dir", str(tmp_path / "c"),
    ]) == 1


# --- decode bucket ladder (generative lane) ----------------------------------


class _FakeDecodeEngine:
    """Device-free stand-in for runtime.decode.DecodeEngine in warm tests."""

    max_slots = 4

    def __init__(self, model="gen-default"):
        self.model = model

    def warmup(self):
        return {
            "model": self.model,
            "buckets": {"16": 0.01, "32": 0.01, "64": 0.01},
            "step_s": 0.01,
        }


def test_warm_learns_decode_grid_when_lane_enabled(tmp_path, monkeypatch):
    monkeypatch.delenv("KDLT_COMPILE_CACHE_DIR", raising=False)
    monkeypatch.delenv("KDLT_DECODE", raising=False)
    root = tmp_path / "models"
    _save_model(root, "warm-dec")
    report = warm.warm_models(
        str(root), buckets=(1,), cache_dir=str(tmp_path / "cache"),
        engine_factory=_FakeEngine, decode=True,
        decode_engine_factory=_FakeDecodeEngine,
    )
    # The learned ladder is the prompt-length x batch-slot grid: one
    # prefill program per bucket, one fixed-width step for every slot
    # composition.
    grid = report["decode"]["grid"]
    assert grid["prompt_buckets"] == [16, 32, 64]
    assert grid["slots"] == 4
    assert report["decode"]["model"] == "gen-default"
    assert report["decode"]["step_s"] >= 0


def test_warm_decode_follows_kdlt_decode_env(tmp_path, monkeypatch):
    monkeypatch.delenv("KDLT_COMPILE_CACHE_DIR", raising=False)
    root = tmp_path / "models"
    _save_model(root, "warm-nodec")
    # Lane off (default): the image ladder warms alone.
    monkeypatch.delenv("KDLT_DECODE", raising=False)
    report = warm.warm_models(
        str(root), buckets=(1,), cache_dir=str(tmp_path / "cache"),
        engine_factory=_FakeEngine, decode_engine_factory=_FakeDecodeEngine,
    )
    assert "decode" not in report
    # Lane on via the same env switch serving pods read.
    monkeypatch.setenv("KDLT_DECODE", "1")
    report = warm.warm_models(
        str(root), buckets=(1,), cache_dir=str(tmp_path / "cache"),
        engine_factory=_FakeEngine, decode_engine_factory=_FakeDecodeEngine,
    )
    assert report["decode"]["grid"]["slots"] == 4


def test_warm_decode_failure_is_fail_soft_and_reported(tmp_path, monkeypatch):
    monkeypatch.delenv("KDLT_COMPILE_CACHE_DIR", raising=False)
    root = tmp_path / "models"
    _save_model(root, "warm-decfail")

    def exploding_factory(model="gen-default"):
        raise RuntimeError("decode compile exploded")

    report = warm.warm_models(
        str(root), buckets=(1,), cache_dir=str(tmp_path / "cache"),
        engine_factory=_FakeEngine, decode=True,
        decode_engine_factory=exploding_factory,
    )
    # Image models still warmed; the decode failure is an error entry.
    assert "error" not in report["models"]["warm-decfail"]
    assert report["decode"]["error"] == "decode compile exploded"


# --- warmup provenance classification (runtime/engine.py) --------------------


def _provenance_probe(registry, bucket_seconds, cache_dir, monkeypatch):
    """Drive _record_warm_sources on a bare engine shell: the
    classification is pure accounting over (bucket timings, active cache
    dir, threshold) -- no device or artifact needed."""
    from kubernetes_deep_learning_tpu.runtime.engine import InferenceEngine
    from kubernetes_deep_learning_tpu.utils import compilecache

    monkeypatch.setattr(compilecache, "active_cache_dir", lambda: cache_dir)
    eng = object.__new__(InferenceEngine)
    eng.buckets = tuple(sorted(bucket_seconds))
    eng._warm_bucket_seconds = dict(bucket_seconds)
    eng._m_warm_source = metrics_lib.engine_warm_source_metrics(registry)
    eng.warm_report = {}
    eng._record_warm_sources(sum(bucket_seconds.values()))
    return eng


def test_warm_source_classifies_fast_buckets_as_cache_hits(monkeypatch):
    registry = metrics_lib.Registry()
    eng = _provenance_probe(
        registry,
        {1: 0.05, 2: 0.08, 4: 5.0},  # two disk reads, one live compile
        cache_dir="/var/cache/kdlt-xla",
        monkeypatch=monkeypatch,
    )
    text = registry.render()
    assert _metric(text, "kdlt_engine_warm_source", source="cache") == 2.0
    assert _metric(text, "kdlt_engine_warm_source", source="compile") == 1.0
    assert eng.warm_report["buckets"][1]["source"] == "cache"
    assert eng.warm_report["buckets"][4]["source"] == "compile"
    assert eng.warm_report["cache_dir"] == "/var/cache/kdlt-xla"


def test_warm_source_without_cache_is_always_compile(monkeypatch):
    # No active cache: even a fast warm cannot claim a cache hit (the
    # proof metric must never flatter a cold image).
    registry = metrics_lib.Registry()
    eng = _provenance_probe(
        registry, {1: 0.01}, cache_dir=None, monkeypatch=monkeypatch
    )
    text = registry.render()
    assert _metric(text, "kdlt_engine_warm_source", source="compile") == 1.0
    assert _metric(text, "kdlt_engine_warm_source", source="cache") == 0.0
    assert eng.warm_report["buckets"][1]["source"] == "compile"


def test_warm_source_threshold_env_override(monkeypatch):
    monkeypatch.setenv("KDLT_WARM_CACHE_HIT_S", "10.0")
    registry = metrics_lib.Registry()
    eng = _provenance_probe(
        registry, {1: 5.0}, cache_dir="/c", monkeypatch=monkeypatch
    )
    assert eng.warm_report["threshold_s"] == 10.0
    assert eng.warm_report["buckets"][1]["source"] == "cache"
