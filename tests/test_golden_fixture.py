"""Committed golden regression: the importer+engine numeric chain, pinned.

The reference's correctness baseline is a set of expected logits for a known
image against the real trained artifact (reference guide.md:623-625), which
this environment cannot fetch (no egress).  This fixture pins the SAME
numeric chain -- Keras-layout .h5 -> keras_import -> exporter -> artifact ->
InferenceEngine predict -- against logits recorded once and committed
(tests/golden/xception_synthetic.json), so any numeric regression in the
importer, exporter, or engine fails CI even without the real weights
(VERDICT r1 item 5).  ``kdlt-verify-golden`` remains the check for the real
artifact where it is available.

Weights and inputs are generated with numpy's default_rng, whose bit stream
is stable across numpy versions by policy (NEP 19) -- no jax PRNG in the
chain.  Comparison tolerance absorbs XLA CPU codegen variation (fused f32
reductions differ across instruction sets), NOT algorithmic drift.

Regenerate after an INTENTIONAL numeric change:
    python tests/test_golden_fixture.py --regenerate
"""

from __future__ import annotations

import json
import os

import numpy as np

import pytest

from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden", "xception_synthetic.json")

SPEC = ModelSpec(
    name="golden-xception",
    family="xception",
    input_shape=(96, 96, 3),
    labels=("dress", "hat", "pants", "shirt"),
    preprocessing="tf",
    resize_filter="nearest",
    head_hidden=(16,),
)


def _deterministic_variables(spec: ModelSpec):
    """Variables in the module's exact tree, filled by numpy rng in sorted
    path order (independent of jax PRNG internals)."""
    import jax

    from kubernetes_deep_learning_tpu.models import init_variables

    shapes = jax.eval_shape(lambda: init_variables(spec, seed=0))
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    flat = sorted(flat, key=lambda kv: jax.tree_util.keystr(kv[0]))
    rng = np.random.default_rng(20260730)
    leaves = {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if key.endswith("['var']"):
            arr = rng.uniform(0.5, 1.5, leaf.shape)
        elif key.endswith("['scale']"):
            arr = rng.uniform(0.8, 1.2, leaf.shape)
        else:
            arr = rng.normal(0.0, 0.08, leaf.shape)
        leaves[key] = arr.astype(np.float32)
    # Rebuild in original structure order.
    orig_flat, _ = jax.tree_util.tree_flatten_with_path(shapes)
    rebuilt = [leaves[jax.tree_util.keystr(p)] for p, _ in orig_flat]
    return jax.tree_util.tree_unflatten(treedef, rebuilt)


def _golden_inputs(spec: ModelSpec) -> np.ndarray:
    rng = np.random.default_rng(7301)
    return rng.integers(0, 256, size=(2, *spec.input_shape), dtype=np.uint8)


def _compute_chain_logits(tmp_dir: str) -> np.ndarray:
    """The full chain: variables -> keras .h5 -> import -> export -> engine."""
    from test_keras_import import _flax_to_keras_h5

    from kubernetes_deep_learning_tpu.export import artifact as art
    from kubernetes_deep_learning_tpu.export import export_model
    from kubernetes_deep_learning_tpu.models.keras_import import load_keras_h5
    from kubernetes_deep_learning_tpu.runtime import InferenceEngine

    spec = register_spec(SPEC)
    variables = _deterministic_variables(spec)
    h5_path = os.path.join(tmp_dir, "golden.h5")
    _flax_to_keras_h5(h5_path, variables)

    imported = load_keras_h5(spec, h5_path)
    root = os.path.join(tmp_dir, "models")
    # float32 end to end: the golden chain pins algorithmic numerics, and
    # bf16 rounding would drown the signal a regression produces.
    export_model(spec, imported, root, dtype=np.float32)
    engine = InferenceEngine(
        art.load_artifact(art.version_dir(root, spec.name, 1)), buckets=(2,)
    )
    engine.warmup()
    return np.asarray(engine.predict(_golden_inputs(spec)), np.float32)


def test_golden_chain_matches_committed_logits(tmp_path):
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    want = np.asarray(golden["logits"], np.float32)
    got = _compute_chain_logits(str(tmp_path))
    assert got.shape == tuple(golden["shape"])
    # rtol absorbs XLA CPU fused-reduction variation across hosts; a real
    # importer/exporter/engine regression shows up orders of magnitude above.
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


if __name__ == "__main__":
    import argparse
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(__file__))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # sitecustomize latches the real-TPU plugin before env vars apply; force
    # the CPU backend the way tests/conftest.py does.
    from kubernetes_deep_learning_tpu.utils.platform import force_platform

    force_platform("cpu")

    p = argparse.ArgumentParser()
    p.add_argument("--regenerate", action="store_true")
    if not p.parse_args().regenerate:
        p.error("run with --regenerate to rewrite the committed fixture")
    with tempfile.TemporaryDirectory() as td:
        logits = _compute_chain_logits(td)
    os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
    with open(GOLDEN_PATH, "w") as f:
        json.dump(
            {
                "comment": "expected f32 logits of the synthetic golden chain; "
                "see test_golden_fixture.py",
                "shape": list(logits.shape),
                "logits": [[float(v) for v in row] for row in logits],
            },
            f,
            indent=1,
        )
    print(f"wrote {GOLDEN_PATH}\n{logits}")
