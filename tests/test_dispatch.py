"""InFlightDispatcher: the bounded multi-in-flight dispatch pipeline.

Contracts under test (runtime.engine.InFlightDispatcher):

- FIFO ordering + per-future wiring: each Future resolves to ITS batch's
  rows, completions in submit order;
- backpressure: submit blocks once ``depth`` batches are in flight;
- exception propagation: a dispatch failure resolves that submit's Future,
  a sync-side failure resolves the in-flight batch's Future, and neither
  kills the pipeline;
- clean shutdown: close() drains in-flight work (every Future resolves)
  and subsequent submits raise DispatcherClosed.

The engine stand-in exposes the same predict_async surface as the real
engine but with CONTROLLABLE completion: each dispatched batch's handle
materializes only when the test releases it, so overlap is asserted by
construction, not by timing luck.
"""

from __future__ import annotations

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.runtime.engine import (
    DispatcherClosed,
    InFlightDispatcher,
    resolve_pipeline_depth,
)


class _Handle:
    """Device-array stand-in: np.asarray blocks until release()."""

    def __init__(self, out, fail=False):
        self._out = out
        self._fail = fail
        self._ev = threading.Event()

    def release(self):
        self._ev.set()

    def __array__(self, dtype=None, copy=None):
        assert self._ev.wait(timeout=10), "handle never released"
        if self._fail:
            raise RuntimeError("device fault at sync")
        return self._out


class ControlledEngine:
    """predict_async surface with test-controlled completion per batch."""

    def __init__(self, fail_dispatch_at=(), fail_sync_at=()):
        self.handles: list[_Handle] = []
        self.dispatches = 0
        self.completed: list[int] = []
        self._fail_dispatch_at = set(fail_dispatch_at)
        self._fail_sync_at = set(fail_sync_at)
        self._lock = threading.Lock()

    def predict_async(self, images: np.ndarray):
        with self._lock:
            i = self.dispatches
            self.dispatches += 1
        if i in self._fail_dispatch_at:
            raise ValueError(f"dispatch {i} rejected")
        n = images.shape[0]
        # Row r of batch i -> [i, r]: distinct per (batch, row) so wiring
        # mistakes are visible in the values themselves.
        out = np.stack(
            [np.full(2, i, np.float32) + np.array([0, 0.001], np.float32) * r
             for r in range(n)]
        )
        out[:, 1] = np.arange(n)
        out[:, 0] = i
        h = _Handle(out, fail=i in self._fail_sync_at)
        self.handles.append(h)
        return h, n

    def record_completed(self, n: int, seconds: float) -> None:
        self.completed.append(n)


def _imgs(n):
    return np.zeros((n, 2, 2, 3), np.uint8)


def test_resolve_pipeline_depth(monkeypatch):
    monkeypatch.delenv("KDLT_PIPELINE_DEPTH", raising=False)
    assert resolve_pipeline_depth() == 2
    assert resolve_pipeline_depth(4) == 4
    assert resolve_pipeline_depth(0) == 1  # clamped
    monkeypatch.setenv("KDLT_PIPELINE_DEPTH", "3")
    assert resolve_pipeline_depth() == 3
    assert resolve_pipeline_depth(1) == 1  # explicit beats env
    monkeypatch.setenv("KDLT_PIPELINE_DEPTH", "banana")
    assert resolve_pipeline_depth() == 2  # typo degrades to default


def test_ordering_and_future_wiring():
    eng = ControlledEngine()
    d = InFlightDispatcher(eng, depth=2)
    try:
        f0 = d.submit(_imgs(3))
        f1 = d.submit(_imgs(2))
        eng.handles[0].release()
        out0 = f0.result(timeout=5)
        assert out0.shape == (3, 2) and set(out0[:, 0]) == {0.0}
        eng.handles[1].release()
        out1 = f1.result(timeout=5)
        assert out1.shape == (2, 2) and set(out1[:, 0]) == {1.0}
        # async completions were accounted through record_completed
        assert eng.completed == [3, 2]
    finally:
        d.close()


def test_backpressure_blocks_at_depth_limit():
    eng = ControlledEngine()
    d = InFlightDispatcher(eng, depth=2)
    try:
        d.submit(_imgs(1))
        d.submit(_imgs(1))
        third_submitted = threading.Event()
        fut3 = []

        def submit_third():
            fut3.append(d.submit(_imgs(1)))
            third_submitted.set()

        t = threading.Thread(target=submit_third, daemon=True)
        t.start()
        # With 2 batches in flight the third submit must block...
        assert not third_submitted.wait(timeout=0.2)
        assert eng.dispatches == 2
        # ...until a slot frees (batch 0 materializes).
        eng.handles[0].release()
        assert third_submitted.wait(timeout=5)
        eng.handles[1].release()
        eng.handles[2].release()
        assert fut3[0].result(timeout=5)[0, 0] == 2.0
        t.join(timeout=5)
    finally:
        d.close()


def test_sync_failure_lands_on_the_right_future():
    eng = ControlledEngine(fail_sync_at={1})
    d = InFlightDispatcher(eng, depth=3)
    try:
        futs = [d.submit(_imgs(1)) for _ in range(3)]
        for h in eng.handles:
            h.release()
        assert futs[0].result(timeout=5)[0, 0] == 0.0
        with pytest.raises(RuntimeError, match="device fault at sync"):
            futs[1].result(timeout=5)
        # The pipeline survives the failed batch; batch 2 still lands,
        # and the failed batch never inflated the success accounting.
        assert futs[2].result(timeout=5)[0, 0] == 2.0
        assert eng.completed == [1, 1]
    finally:
        d.close()


def test_dispatch_failure_resolves_that_submits_future():
    eng = ControlledEngine(fail_dispatch_at={0})
    d = InFlightDispatcher(eng, depth=2)
    try:
        bad = d.submit(_imgs(1))
        with pytest.raises(ValueError, match="dispatch 0 rejected"):
            bad.result(timeout=5)
        ok = d.submit(_imgs(1))  # the failed dispatch released its slot
        eng.handles[0].release()
        assert ok.result(timeout=5)[0, 0] == 1.0
    finally:
        d.close()


def test_close_drains_inflight_and_rejects_new_submits():
    eng = ControlledEngine()
    d = InFlightDispatcher(eng, depth=2)
    futs = [d.submit(_imgs(1)) for _ in range(2)]

    def release_soon():
        time.sleep(0.1)
        for h in eng.handles:
            h.release()

    threading.Thread(target=release_soon, daemon=True).start()
    d.close()  # must wait out both in-flight batches
    for i, f in enumerate(futs):
        assert f.result(timeout=1)[0, 0] == float(i)  # already resolved
    with pytest.raises(DispatcherClosed):
        d.submit(_imgs(1))
    d.close()  # idempotent


def test_dynamic_batcher_dispatches_next_batch_before_previous_completes():
    """The tentpole behavior at the batcher level: with a pipelined engine
    the dispatch thread must start (assemble AND dispatch) batch N+1 while
    batch N is still executing -- held open here by batch N's unreleased
    handle, so the overlap is structural, not a timing race."""
    from kubernetes_deep_learning_tpu.runtime.batcher import DynamicBatcher

    eng = ControlledEngine()
    eng.spec = SimpleNamespace(input_shape=(2, 2, 3))
    eng.max_batch = 1  # one request per batch -> submit order is batch order
    b = DynamicBatcher(eng, max_delay_ms=0, pipeline_depth=2)
    try:
        img = np.zeros((2, 2, 3), np.uint8)
        f0 = b.submit(img)
        f1 = b.submit(img)
        # Batch 0 has NOT completed (handle unreleased), yet batch 1 must
        # reach the engine: dispatch count hits 2 with zero completions.
        deadline = time.monotonic() + 5
        while eng.dispatches < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.dispatches == 2
        assert eng.completed == []
        eng.handles[0].release()
        eng.handles[1].release()
        assert f0.result(timeout=5)[0] == 0.0
        assert f1.result(timeout=5)[0] == 1.0
    finally:
        b.close()


def test_dynamic_batcher_serial_engine_unchanged():
    """Engines without predict_async keep the dispatch-then-sync loop (no
    dispatcher thread, no behavioral change for plain engines)."""
    from kubernetes_deep_learning_tpu.runtime.batcher import DynamicBatcher

    class Plain:
        max_batch = 4
        spec = SimpleNamespace(input_shape=(2, 2, 3))

        def predict(self, images):
            s = images.reshape(images.shape[0], -1).sum(axis=1)
            return np.stack([s, s * 2], axis=1).astype(np.float32)

    b = DynamicBatcher(Plain(), max_delay_ms=1, pipeline_depth=2)
    try:
        assert b._dispatcher is None
        out = b.predict(np.full((2, 2, 3), 3, np.uint8))
        assert out.tolist() == [36.0, 72.0]
    finally:
        b.close()


def test_dispatcher_emits_stage_metrics():
    from kubernetes_deep_learning_tpu.utils import metrics as metrics_lib

    reg = metrics_lib.Registry()
    eng = ControlledEngine()
    d = InFlightDispatcher(eng, depth=2, registry=reg)
    try:
        f = d.submit(_imgs(1))
        eng.handles[0].release()
        f.result(timeout=5)
        text = reg.render()
        for stage in ("enqueue_wait", "dispatch", "execute", "readback"):
            assert f"kdlt_pipeline_{stage}_seconds" in text
        assert "kdlt_pipeline_depth 2.0" in text
    finally:
        d.close()
