"""serving.wsgi: the gateway under a real WSGI server (gunicorn posture)."""

import json
import threading
import wsgiref.simple_server

import numpy as np
import pytest
import requests

from kubernetes_deep_learning_tpu.export.exporter import export_model
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.serving.gateway import Gateway
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
from kubernetes_deep_learning_tpu.serving.wsgi import GatewayWSGI


@pytest.fixture(scope="module")
def wsgi_stack(tmp_path_factory):
    spec = register_spec(
        ModelSpec(
            name="wsgi-vit",
            family="vit-tiny",
            input_shape=(16, 16, 3),
            labels=("a", "b"),
            preprocessing="tf",
        )
    )
    root = tmp_path_factory.mktemp("wsgi-models")
    export_model(spec, init_variables(spec, seed=0), str(root))
    server = ModelServer(str(root), port=0, buckets=(1, 2))
    server.warmup()
    server.start()

    gw = Gateway(serving_host=f"localhost:{server.port}", model="wsgi-vit", bind=False)
    wsgi = wsgiref.simple_server.make_server(
        "127.0.0.1", 0, GatewayWSGI(gw),
        handler_class=wsgiref.simple_server.WSGIRequestHandler,
    )
    threading.Thread(target=wsgi.serve_forever, daemon=True).start()

    # A local image to fetch (no egress in tests).
    import http.server, functools, io
    from PIL import Image

    webroot = tmp_path_factory.mktemp("wsgi-web")
    img = Image.fromarray(
        np.random.default_rng(0).integers(0, 255, (20, 24, 3), dtype=np.uint8), "RGB"
    )
    img.save(webroot / "x.png")
    fileserver = http.server.ThreadingHTTPServer(
        ("127.0.0.1", 0),
        functools.partial(
            http.server.SimpleHTTPRequestHandler, directory=str(webroot)
        ),
    )
    threading.Thread(target=fileserver.serve_forever, daemon=True).start()

    yield {
        "base": f"http://127.0.0.1:{wsgi.server_address[1]}",
        "image_url": f"http://127.0.0.1:{fileserver.server_address[1]}/x.png",
    }
    wsgi.shutdown()
    fileserver.shutdown()
    server.shutdown()


def test_wsgi_predict_roundtrip(wsgi_stack):
    r = requests.post(
        wsgi_stack["base"] + "/predict",
        json={"url": wsgi_stack["image_url"]},
        timeout=30,
    )
    assert r.status_code == 200, r.text
    scores = r.json()
    assert set(scores) == {"a", "b"}
    assert all(np.isfinite(v) for v in scores.values())


def test_wsgi_health_metrics_and_errors(wsgi_stack):
    base = wsgi_stack["base"]
    assert requests.get(base + "/healthz", timeout=10).status_code == 200
    assert requests.get(base + "/readyz", timeout=10).status_code == 200
    m = requests.get(base + "/metrics", timeout=10)
    assert "kdlt_gateway_requests_total" in m.text
    assert requests.get(base + "/nope", timeout=10).status_code == 404
    r = requests.post(base + "/predict", data=b"not json", timeout=10)
    assert r.status_code == 400
    assert "error" in r.json()


def test_wsgi_cache_dispositions_and_bust_header(wsgi_stack):
    """The response cache's wire surface through the WSGI transport: the
    X-Kdlt-Cache disposition header and the X-Kdlt-Cache-Bust salt behave
    exactly like the threaded transport (both call the same
    Gateway.handle_predict)."""
    from kubernetes_deep_learning_tpu.serving import protocol

    base = wsgi_stack["base"]
    url = wsgi_stack["image_url"] + "?wsgi-cache=1"
    r1 = requests.post(base + "/predict", json={"url": url}, timeout=30)
    assert r1.status_code == 200
    assert r1.headers[protocol.CACHE_STATUS_HEADER] == "miss"
    r2 = requests.post(base + "/predict", json={"url": url}, timeout=30)
    assert r2.status_code == 200
    assert r2.headers[protocol.CACHE_STATUS_HEADER] == "hit"
    assert r1.json() == r2.json()
    r3 = requests.post(
        base + "/predict", json={"url": url},
        headers={protocol.CACHE_BUST_HEADER: "wsgi-salt"}, timeout=30,
    )
    assert r3.status_code == 200
    assert r3.headers[protocol.CACHE_STATUS_HEADER] == "miss"
    assert r3.json() == r2.json()  # the bust path recomputes, same answer


def test_oversize_body_rejected_without_read():
    """A declared multi-GB body is refused at the Content-Length check,
    before any byte of the body is read (ADVICE r1: memory exhaustion)."""
    from kubernetes_deep_learning_tpu.serving.gateway import MAX_PREDICT_BODY_BYTES

    wsgi = GatewayWSGI(Gateway(bind=False))

    class ExplodingInput:
        def read(self, n=-1):
            raise AssertionError("oversize body must not be read")

    statuses = []
    out = wsgi(
        {
            "REQUEST_METHOD": "POST",
            "PATH_INFO": "/predict",
            "CONTENT_LENGTH": str(MAX_PREDICT_BODY_BYTES + 1),
            "wsgi.input": ExplodingInput(),
        },
        lambda status, headers: statuses.append(status),
    )
    assert statuses[0].startswith("413")
    assert b"exceeds" in b"".join(out)
    # At and below the cap is not rejected.
    assert wsgi.gateway.reject_oversize(MAX_PREDICT_BODY_BYTES) is None
    # Negative Content-Length would make rfile.read(-1) buffer until
    # connection close -- must be rejected, not passed through.
    assert wsgi.gateway.reject_oversize(-1) is not None


def test_bind_false_has_no_listener():
    gw = Gateway(bind=False)
    assert gw._httpd is None
    with pytest.raises(RuntimeError, match="bind=False"):
        gw.start()
    gw.shutdown()  # no-op, must not raise
