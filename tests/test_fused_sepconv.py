"""Fused sepconv kernel + Xception fast path, validated on CPU.

The Pallas kernel runs in interpret mode here (tests are hermetic-CPU,
conftest.py); the real-TPU speed claim is bench.py's job.  What IS pinned
here: kernel-vs-reference numerics, BN folding against flax.linen.BatchNorm
(including the Keras-parity epsilon), batch-tile picking rules, and the
full fast-forward's logits against the stock flax graph on the same
variables.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.models import build_forward, init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.ops.fused_sepconv import (
    fold_bn,
    fused_sepconv_block,
    middle_block_weights,
    pick_batch_tile,
    sepconv_block_reference,
)


def _random_block_weights(rng, c):
    dw = jnp.asarray(rng.normal(0, 0.2, (3, 3, 3, c)), jnp.float32)
    pw = jnp.asarray(rng.normal(0, 0.05, (3, c, c)), jnp.bfloat16)
    s = jnp.asarray(rng.uniform(0.8, 1.2, (3, c)), jnp.float32)
    b = jnp.asarray(rng.normal(0, 0.1, (3, c)), jnp.float32)
    return dw, pw, s, b


@pytest.mark.parametrize(
    "shape",
    [
        (4, 6, 6, 256),
        (2, 5, 7, 128),
        # non-8-multiple batches (the serving buckets that killed BENCH_r02)
        # run via sublane padding and must match on the real rows
        (1, 6, 6, 128),
        (3, 6, 6, 128),
        (6, 6, 6, 128),
    ],
)
def test_kernel_matches_reference(shape):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, shape), jnp.bfloat16)
    dw, pw, s, b = _random_block_weights(rng, shape[-1])
    want = np.asarray(sepconv_block_reference(x, dw, pw, s, b), np.float32)
    got = np.asarray(
        jax.jit(lambda *a: fused_sepconv_block(*a, interpret=True))(x, dw, pw, s, b),
        np.float32,
    )
    assert got.shape == shape
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 2e-2, f"kernel diverges from reference: {rel:.2e}"


def test_fold_bn_matches_flax_batchnorm():
    import flax.linen as nn

    from kubernetes_deep_learning_tpu.models.layers import KERAS_BN_EPS, batch_norm

    rng = np.random.default_rng(1)
    c = 32
    x = jnp.asarray(rng.normal(0, 1, (4, c)), jnp.float32)
    p = {
        "scale": jnp.asarray(rng.uniform(0.8, 1.2, c), jnp.float32),
        "bias": jnp.asarray(rng.normal(0, 0.1, c), jnp.float32),
    }
    s = {
        "mean": jnp.asarray(rng.normal(0, 0.5, c), jnp.float32),
        "var": jnp.asarray(rng.uniform(0.5, 1.5, c), jnp.float32),
    }
    mod = batch_norm(False, None, "bn")
    want = mod.apply({"params": p, "batch_stats": s}, x)
    scale, shift = fold_bn(p, s)  # defaults to the Keras epsilon
    got = x * scale + shift
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)
    # and it is the KERAS epsilon, not flax's 1e-5 default
    assert KERAS_BN_EPS == 1e-3
    bad_scale, _ = fold_bn(p, s, eps=1e-5)
    assert not np.allclose(np.asarray(bad_scale), np.asarray(scale))


def test_pick_batch_tile_rules():
    # divisible batches take the largest tile under budget
    assert pick_batch_tile(256, 19, 19, 728) == 16
    assert pick_batch_tile(8, 19, 19, 728) == 8
    # huge spatial extents fall back to the smallest aligned tile
    assert pick_batch_tile(256, 74, 74, 728) == 8
    # NEVER a non-8-multiple: Mosaic rejects the kernel's (H, W, bt) row
    # collapse for unaligned bt (BENCH_r02's batch-1 failure).  Unaligned
    # batches are padded by the kernel wrappers, which then see a multiple
    # of 8 -- but pick_batch_tile itself must stay safe for any input.
    assert pick_batch_tile(6, 19, 19, 728) == 8
    assert pick_batch_tile(12, 19, 19, 728) == 8
    assert pick_batch_tile(1, 19, 19, 728) == 8


def test_fused_entry_kernel_matches_reference():
    """The fused entry kernel (conv2 + block2, ops.fused_entry) vs its
    plain-jnp reference at a small parameterized geometry, interpret mode:
    pins the halo/mask/stride-selection math, including a final partial
    row tile (h_out=11, rt=4) and the batch-pad path (B=2 -> 8)."""
    from kubernetes_deep_learning_tpu.ops.fused_entry import (
        entry_block_reference,
        fused_entry_block_t,
    )

    rng = np.random.default_rng(3)
    h_in, c_in, c_b, c_out = 23, 8, 16, 32  # h_b=21, h_out=11
    w = {
        "conv2": rng.normal(0, 0.2, (3, 3, c_in, c_b)).astype(np.float32),
        "conv2_s": rng.uniform(0.8, 1.2, c_b).astype(np.float32),
        "conv2_b": rng.normal(0, 0.1, c_b).astype(np.float32),
        "res": rng.normal(0, 0.1, (c_b, c_out)).astype(np.float32),
        "res_s": rng.uniform(0.8, 1.2, c_out).astype(np.float32),
        "res_b": rng.normal(0, 0.1, c_out).astype(np.float32),
        "dw1": rng.normal(0, 0.2, (3, 3, c_b)).astype(np.float32),
        "pw1": rng.normal(0, 0.1, (c_b, c_out)).astype(np.float32),
        "bn1_s": rng.uniform(0.8, 1.2, c_out).astype(np.float32),
        "bn1_b": rng.normal(0, 0.1, c_out).astype(np.float32),
        "dw2": rng.normal(0, 0.2, (3, 3, c_out)).astype(np.float32),
        "pw2": rng.normal(0, 0.1, (c_out, c_out)).astype(np.float32),
        "bn2_s": rng.uniform(0.8, 1.2, c_out).astype(np.float32),
        "bn2_b": rng.normal(0, 0.1, c_out).astype(np.float32),
    }
    w = {k: jnp.asarray(v) for k, v in w.items()}
    for batch in (2, 8):  # 2 exercises the pad-to-8 assert path upstream
        a = jnp.asarray(rng.normal(0, 0.5, (8, h_in, h_in, c_in)), jnp.bfloat16)
        a = a[:batch] if batch < 8 else a
        want = np.asarray(entry_block_reference(a, w), np.float32)
        a_t = jnp.pad(a, ((0, 8 - batch), (0, 0), (0, 0), (0, 0))).transpose(
            1, 2, 0, 3
        )
        got_t = jax.jit(
            lambda xt: fused_entry_block_t(xt, w, rt=4, interpret=True)
        )(a_t)
        got = np.asarray(got_t.transpose(2, 0, 1, 3)[:batch], np.float32)
        assert got.shape == want.shape
        rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
        assert rel < 2e-2, f"entry kernel diverges (batch {batch}): {rel:.2e}"


def test_fast_forward_entry_kernel_matches_flax(fast_spec):
    """The EXPERIMENTAL entry_kernel=True fast path end to end (fused entry
    + block3/4 chains + middle + exit, interpret mode) vs the stock flax
    graph -- kept tested even though serving does not enable it."""
    from kubernetes_deep_learning_tpu.models.xception_fast import build_fast_forward
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    rng = np.random.default_rng(5)
    variables = jax.tree_util.tree_map(np.asarray, init_variables(fast_spec, seed=4))
    images = rng.integers(0, 256, (2, *fast_spec.input_shape), np.uint8)
    ref = jax.jit(build_forward(fast_spec, dtype=jnp.bfloat16, fast=False))
    want = np.asarray(ref(variables, images))

    fast = build_fast_forward(
        fast_spec, dtype=jnp.bfloat16, interpret=True, entry_kernel=True
    )
    x = normalize(jnp.asarray(images), fast_spec.preprocessing)
    got = np.asarray(jax.jit(fast)(variables, x), np.float32)

    # 2e-2: the pallas interpreter's bf16 accumulation rounds slightly
    # differently across jax versions (measured 1.09e-2 on 0.4.x, under
    # 1e-2 on current); the real-TPU Mosaic bound stays the strict one.
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 2e-2, f"entry-kernel fast path diverges from flax: {rel:.2e}"

    # conv1_t variant (VERDICT r3 #5): conv1 computed in (H, W, B, C) via
    # HWNC dimension_numbers must be numerically identical layout-math.
    fast_t = build_fast_forward(
        fast_spec, dtype=jnp.bfloat16, interpret=True, entry_kernel=True,
        conv1_t=True,
    )
    got_t = np.asarray(jax.jit(fast_t)(variables, x), np.float32)
    rel = np.abs(got_t - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 2e-2, f"conv1_t fast path diverges from flax: {rel:.2e}"


@pytest.fixture(scope="module")
def fast_spec():
    return register_spec(
        ModelSpec(
            name="fast-xception",
            family="xception",
            input_shape=(96, 96, 3),
            labels=("a", "b", "c", "d"),
            preprocessing="tf",
            head_hidden=(16,),
        )
    )


def test_fast_forward_matches_flax(fast_spec):
    """Full fast path (entry/exit lax ops + fused middle, interpret mode)
    vs the stock flax graph on identical variables with jittered BN stats."""
    from kubernetes_deep_learning_tpu.models.xception_fast import build_fast_forward
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    rng = np.random.default_rng(2)
    variables = jax.tree_util.tree_map(np.asarray, init_variables(fast_spec, seed=3))

    def jitter(tree):
        for k, v in tree.items():
            if isinstance(v, dict):
                jitter(v)
            elif k == "mean":
                tree[k] = rng.normal(0, 0.05, v.shape).astype(np.float32)
            elif k == "var":
                tree[k] = rng.uniform(0.5, 1.5, v.shape).astype(np.float32)

    jitter(variables["batch_stats"])

    images = rng.integers(0, 256, (2, *fast_spec.input_shape), np.uint8)
    ref = jax.jit(build_forward(fast_spec, dtype=jnp.bfloat16, fast=False))
    want = np.asarray(ref(variables, images))

    fast = build_fast_forward(fast_spec, dtype=jnp.bfloat16, interpret=True)
    x = normalize(jnp.asarray(images), fast_spec.preprocessing)
    got = np.asarray(jax.jit(fast)(variables, x), np.float32)

    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-6)
    assert rel < 1e-2, f"fast path diverges from flax graph: {rel:.2e}"


def test_chunk_size_rules():
    """Microbatch chunking engages exactly for 8-multiples in [32, 64]
    (measured win zone, exp/chunked_forward.py); everything else
    monolithic.  Non-16-multiples take a trailing 8-chunk."""
    from kubernetes_deep_learning_tpu.models.xception_fast import _chunk_sizes

    assert _chunk_sizes(32) == [16, 16]
    assert _chunk_sizes(40) == [16, 16, 8]
    assert _chunk_sizes(48) == [16, 16, 16]
    assert _chunk_sizes(56) == [16, 16, 16, 8]
    assert _chunk_sizes(64) == [16, 16, 16, 16]
    for n in (1, 8, 16, 24, 36, 96, 128, 256):
        assert _chunk_sizes(n) is None, n


def test_chunked_fast_forward_matches_monolithic(fast_spec, monkeypatch):
    """The chunk wrapper (slice -> forward_one -> concat) must be a pure
    batching identity.  Scaled down (chunk=1 over batch 2) so interpret-mode
    cost stays test-sized; the production chunk geometry (16 over 32-64) is
    exercised on real TPU by bench.py's sweep."""
    from kubernetes_deep_learning_tpu.models import xception_fast
    from kubernetes_deep_learning_tpu.ops.preprocess import normalize

    monkeypatch.setattr(xception_fast, "_CHUNK", 1)
    monkeypatch.setattr(xception_fast, "_TAIL", 1)
    monkeypatch.setattr(xception_fast, "_CHUNK_MIN", 2)
    monkeypatch.setattr(xception_fast, "_CHUNK_MAX", 2)

    rng = np.random.default_rng(5)
    variables = init_variables(fast_spec, seed=1)
    images = rng.integers(0, 256, (2, *fast_spec.input_shape), np.uint8)
    x = normalize(jnp.asarray(images), fast_spec.preprocessing)

    mono = xception_fast.build_fast_forward(
        fast_spec, dtype=jnp.bfloat16, interpret=True, chunk=False
    )
    chunked = xception_fast.build_fast_forward(
        fast_spec, dtype=jnp.bfloat16, interpret=True, chunk=True
    )
    want = np.asarray(jax.jit(mono)(variables, x), np.float32)
    got = np.asarray(jax.jit(chunked)(variables, x), np.float32)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_middle_block_weights_shapes(fast_spec):
    variables = init_variables(fast_spec, seed=0)
    dw, pw, s, b = middle_block_weights(
        variables["params"], variables["batch_stats"], "block5"
    )
    assert dw.shape == (3, 3, 3, 728) and dw.dtype == jnp.float32
    assert pw.shape == (3, 728, 728) and pw.dtype == jnp.bfloat16
    assert s.shape == (3, 728) and b.shape == (3, 728)


def test_build_forward_fast_flag_dispatch(fast_spec):
    """fast='auto' on the CPU backend must stay on the flax graph (pallas
    TPU kernels cannot lower for CPU outside interpret mode)."""
    fwd = build_forward(fast_spec, dtype=jnp.bfloat16)  # auto
    images = np.zeros((1, *fast_spec.input_shape), np.uint8)
    variables = init_variables(fast_spec, seed=0)
    out = jax.jit(fwd)(variables, images)
    assert out.shape == (1, fast_spec.num_classes)
