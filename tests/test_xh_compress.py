"""The cross-host payload diet (ISSUE 16): codec resolution, payload
round-trips, and the wire contract.

Everything here is device-free: the codec helpers are pure bytes->bytes,
and the wire tests drive the leader/follower framing methods unbound over
a socketpair -- no jax.distributed fleet, no device.  The one contract
that matters most is pinned explicitly: with compression OFF the wire is
byte-identical to the pre-diet protocol, so a mixed fleet mid-rollout
interoperates and the knob cannot regress the default path.
"""

from __future__ import annotations

import socket
import struct

import numpy as np
import pytest

from kubernetes_deep_learning_tpu.parallel.crosshost import (
    _PREDICT,
    _PREDICT_FAST,
    _PREDICT_FAST_Z,
    _PREDICT_Z,
    _XH_CODEC_ZLIB,
    XH_COMPRESS_ENV,
    CrossHostForward,
    _compress_payload,
    _decompress_payload,
    resolve_xh_compress,
)

# --- codec resolution ------------------------------------------------------


@pytest.mark.parametrize("raw", ["", "0", "off", "none", "false", " OFF "])
def test_resolve_off_values_mean_raw_wire(raw):
    assert resolve_xh_compress(raw) is None


@pytest.mark.parametrize("raw", ["1", "on", "true", "zlib", " ZLIB "])
def test_resolve_on_values_mean_zlib(raw):
    assert resolve_xh_compress(raw) == "zlib"


def test_resolve_lz4_degrades_to_zlib_without_the_package():
    try:
        import lz4.frame  # noqa: F401
    except ImportError:
        assert resolve_xh_compress("lz4") == "zlib"
    else:
        assert resolve_xh_compress("lz4") == "lz4"


def test_resolve_unknown_value_fails_loudly():
    # A typo silently serving uncompressed would defeat the knob without a
    # trace; boot must refuse it.
    with pytest.raises(ValueError, match=XH_COMPRESS_ENV):
        resolve_xh_compress("gzip")


def test_resolve_reads_the_env_when_no_explicit_value(monkeypatch):
    monkeypatch.setenv(XH_COMPRESS_ENV, "zlib")
    assert resolve_xh_compress() == "zlib"
    monkeypatch.delenv(XH_COMPRESS_ENV)
    assert resolve_xh_compress() is None


# --- payload round-trips ---------------------------------------------------


def test_zlib_payload_round_trips():
    batch = np.random.default_rng(0).integers(
        0, 255, size=(8, 16, 16, 3), dtype=np.uint8
    )
    raw = batch.tobytes()
    wire = _compress_payload("zlib", raw)
    assert wire[0] == _XH_CODEC_ZLIB
    assert _decompress_payload(wire) == raw


def test_lz4_payload_round_trips_when_importable():
    pytest.importorskip("lz4.frame")
    raw = bytes(range(256)) * 64
    wire = _compress_payload("lz4", raw)
    assert _decompress_payload(wire) == raw


def test_zero_padding_actually_shrinks():
    # The economic case for the diet: a partially filled bucket's pad rows
    # are pure zeros and must compress to (nearly) nothing.
    rng = np.random.default_rng(1)
    batch = np.zeros((16, 96, 96, 3), dtype=np.uint8)
    batch[:2] = rng.integers(0, 255, size=(2, 96, 96, 3), dtype=np.uint8)
    wire = _compress_payload("zlib", batch.tobytes())
    assert len(wire) < batch.nbytes / 4


def test_decompress_rejects_empty_and_unknown_codec():
    with pytest.raises(ValueError, match="empty payload"):
        _decompress_payload(b"")
    with pytest.raises(ValueError, match="codec byte"):
        _decompress_payload(bytes((250,)) + b"junk")


# --- the wire contract over a real socketpair ------------------------------


class _Wire:
    """Leader + follower framing halves bound to a socketpair -- the
    methods under test, none of the fleet bring-up."""

    _send_round = CrossHostForward._send_round
    _recv_round = CrossHostForward._recv_round
    _recv_exact = CrossHostForward._recv_exact

    def __init__(self, leader_sock, follower_sock):
        self._followers = [leader_sock]
        self._ctl_sock = follower_sock


@pytest.fixture()
def wire():
    a, b = socket.socketpair()
    try:
        yield _Wire(a, b)
    finally:
        a.close()
        b.close()


def test_compressed_round_trips_over_the_wire(wire):
    batch = np.random.default_rng(2).integers(
        0, 255, size=(4, 8, 8, 3), dtype=np.uint8
    )
    raw = batch.tobytes()
    wire._send_round(_PREDICT_Z, 4, _compress_payload("zlib", raw))
    flag, aux, payload = wire._recv_round()
    assert (flag, aux) == (_PREDICT_Z, 4)
    got = np.frombuffer(
        _decompress_payload(payload), dtype=np.uint8
    ).reshape(batch.shape)
    np.testing.assert_array_equal(got, batch)


def test_off_mode_wire_is_byte_identical_to_the_legacy_protocol(wire):
    # Pre-diet framing: "<iqq" header (flag, aux, nbytes) + raw batch
    # bytes.  With the knob off the leader must emit EXACTLY that -- a
    # follower from a pre-diet build reads the same rounds.
    batch = np.arange(4 * 6, dtype=np.uint8).reshape(4, 6)
    raw = batch.tobytes()
    wire._send_round(_PREDICT, 4, raw)
    expected = struct.pack("<iqq", _PREDICT, 4, len(raw)) + raw
    got = wire._ctl_sock.recv(len(expected) + 64)
    assert got == expected


def test_flag_pairs_stay_distinct():
    # The flag IS the negotiation; the compressed variants must never
    # collide with the legacy flags a pre-diet follower dispatches on.
    assert len({_PREDICT, _PREDICT_FAST, _PREDICT_Z, _PREDICT_FAST_Z}) == 4
    assert _PREDICT_Z not in (_PREDICT, _PREDICT_FAST)
    assert _PREDICT_FAST_Z not in (_PREDICT, _PREDICT_FAST)


def test_follower_dispatch_decompresses_only_flagged_rounds():
    # The follower-side dispatch rule, as unit arithmetic: _Z flags carry
    # a codec byte, legacy flags carry the raw batch -- a follower must
    # dispatch on the received flag, never its own environment.
    raw = b"\x00" * 128
    for flag, payload in (
        (_PREDICT_Z, _compress_payload("zlib", raw)),
        (_PREDICT_FAST_Z, _compress_payload("zlib", raw)),
    ):
        assert _decompress_payload(payload) == raw, flag
    # And a raw legacy payload would NOT survive the decompressor -- the
    # flag split is load-bearing, not cosmetic.
    with pytest.raises(ValueError):
        _decompress_payload(raw)
