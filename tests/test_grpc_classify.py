"""Classify / Regress / MultiInference wire tests (TF-Serving surface).

The reference model tier is the full tensorflow/serving:2.3.0 binary
(reference tf-serving.dockerfile:2), whose PredictionService exposes these
RPCs alongside Predict; the reference's own client uses only Predict
(reference model_server.py:55), so these exist for third-party TF-Serving
clients.  Each test marshals the Example-list Input envelope exactly as
tf.make_example-style clients would (hand-written wire-compatible protos in
serving/tfs_protos) and reads the response through the public field numbers.
"""

from __future__ import annotations

import io

import grpc
import numpy as np
import pytest

from kubernetes_deep_learning_tpu.export import export_model
from kubernetes_deep_learning_tpu.models import init_variables
from kubernetes_deep_learning_tpu.modelspec import ModelSpec, register_spec
from kubernetes_deep_learning_tpu.serving.grpc_predict import (
    SERVICE_NAME,
    serve_grpc,
)
from kubernetes_deep_learning_tpu.serving.model_server import ModelServer
from kubernetes_deep_learning_tpu.serving.tfs_gen.tensorflow_serving.apis import (
    classification_pb2,
    inference_pb2,
    predict_pb2,
    regression_pb2,
)


@pytest.fixture(scope="module")
def classify_stack(tmp_path_factory):
    spec = register_spec(
        ModelSpec(
            name="classify-xception",
            family="xception",
            input_shape=(64, 64, 3),
            labels=("dress", "hat", "pants"),
            preprocessing="tf",
        )
    )
    root = tmp_path_factory.mktemp("models")
    export_model(spec, init_variables(spec, seed=3), str(root), dtype=np.float32)
    server = ModelServer(str(root), port=0, buckets=(1, 2, 4), max_delay_ms=1.0)
    server.warmup()
    grpc_server, port = serve_grpc(server, 0, host="127.0.0.1")
    channel = grpc.insecure_channel(f"127.0.0.1:{port}")

    def method(name, req_cls, resp_cls):
        return channel.unary_unary(
            f"/{SERVICE_NAME}/{name}",
            request_serializer=req_cls.SerializeToString,
            response_deserializer=resp_cls.FromString,
        )

    calls = {
        "classify": method(
            "Classify",
            classification_pb2.ClassificationRequest,
            classification_pb2.ClassificationResponse,
        ),
        "regress": method(
            "Regress",
            regression_pb2.RegressionRequest,
            regression_pb2.RegressionResponse,
        ),
        "multi": method(
            "MultiInference",
            inference_pb2.MultiInferenceRequest,
            inference_pb2.MultiInferenceResponse,
        ),
        "predict": method(
            "Predict", predict_pb2.PredictRequest, predict_pb2.PredictResponse
        ),
    }
    yield spec, server, calls
    channel.close()
    grpc_server.stop(grace=None)
    server.shutdown()


def _pixel_request(spec, images):
    """uint8 (N,H,W,C) -> ClassificationRequest with int64 pixel features."""
    req = classification_pb2.ClassificationRequest()
    req.model_spec.name = spec.name
    for img in images:
        ex = req.input.example_list.examples.add()
        ex.features.feature[spec.input_name].int64_list.value.extend(
            int(v) for v in img.reshape(-1)
        )
    return req


def _predict_logits(spec, calls, images):
    from kubernetes_deep_learning_tpu.serving.grpc_predict import (
        tensor_proto_from_array,
    )

    req = predict_pb2.PredictRequest()
    req.model_spec.name = spec.name
    req.inputs[spec.input_name].CopyFrom(tensor_proto_from_array(images))
    resp = calls["predict"](req, timeout=60)
    out = np.array(resp.outputs[spec.output_name].float_val, np.float32)
    return out.reshape(images.shape[0], spec.num_classes)


def test_classify_matches_predict_logits(classify_stack):
    spec, _, calls = classify_stack
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (2, *spec.input_shape), np.uint8)
    resp = calls["classify"](_pixel_request(spec, images), timeout=60)
    assert resp.model_spec.name == spec.name
    assert resp.model_spec.version.value == 1
    assert len(resp.result.classifications) == 2
    logits = _predict_logits(spec, calls, images)
    for row, cl in zip(logits, resp.result.classifications):
        # All classes present, descending by score, scores == Predict logits.
        assert [c.label for c in cl.classes] == [
            spec.labels[j] for j in np.argsort(-row)
        ]
        got = {c.label: c.score for c in cl.classes}
        want = dict(zip(spec.labels, row))
        for label in spec.labels:
            assert got[label] == pytest.approx(want[label], rel=1e-5)


def test_classify_accepts_encoded_and_float_features(classify_stack):
    spec, _, calls = classify_stack
    from PIL import Image

    rng = np.random.default_rng(1)
    img = rng.integers(0, 256, spec.input_shape, np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")

    req = classification_pb2.ClassificationRequest()
    req.model_spec.name = spec.name
    ex = req.input.example_list.examples.add()
    ex.features.feature["image/encoded"].bytes_list.value.append(buf.getvalue())
    resp = calls["classify"](req, timeout=60)
    # PNG is lossless and already at spec size: scores must match the same
    # pixels sent as a Predict tensor.
    logits = _predict_logits(spec, calls, img[None])
    got = {c.label: c.score for c in resp.result.classifications[0].classes}
    for j, label in enumerate(spec.labels):
        assert got[label] == pytest.approx(logits[0, j], rel=1e-5)

    # Float features ride the pre-normalized path end to end.
    req2 = classification_pb2.ClassificationRequest()
    req2.model_spec.name = spec.name
    ex2 = req2.input.example_list.examples.add()
    ex2.features.feature["x"].float_list.value.extend(
        np.zeros(int(np.prod(spec.input_shape)), np.float32)
    )
    resp2 = calls["classify"](req2, timeout=60)
    assert len(resp2.result.classifications[0].classes) == spec.num_classes


def test_classify_error_statuses(classify_stack):
    spec, _, calls = classify_stack
    # Unknown servable -> NOT_FOUND with TF-Serving's wording.
    req = classification_pb2.ClassificationRequest()
    req.model_spec.name = "no-such-model"
    req.input.example_list.examples.add().features.feature["x"].float_list.value.append(0.0)
    with pytest.raises(grpc.RpcError) as err:
        calls["classify"](req, timeout=30)
    assert err.value.code() == grpc.StatusCode.NOT_FOUND
    assert "Servable not found" in err.value.details()

    # Empty input -> INVALID_ARGUMENT.
    req2 = classification_pb2.ClassificationRequest()
    req2.model_spec.name = spec.name
    with pytest.raises(grpc.RpcError) as err2:
        calls["classify"](req2, timeout=30)
    assert err2.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    # Wrong-size float feature -> INVALID_ARGUMENT naming the expectation.
    req3 = classification_pb2.ClassificationRequest()
    req3.model_spec.name = spec.name
    ex = req3.input.example_list.examples.add()
    ex.features.feature["x"].float_list.value.extend([1.0, 2.0])
    with pytest.raises(grpc.RpcError) as err3:
        calls["classify"](req3, timeout=30)
    assert err3.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    assert "expected" in err3.value.details()


def test_regress_rejected_on_classifier(classify_stack):
    spec, _, calls = classify_stack
    req = regression_pb2.RegressionRequest()
    req.model_spec.name = spec.name
    ex = req.input.example_list.examples.add()
    ex.features.feature["x"].float_list.value.extend(
        np.zeros(int(np.prod(spec.input_shape)), np.float32)
    )
    with pytest.raises(grpc.RpcError) as err:
        calls["regress"](req, timeout=60)
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    assert "regression signature" in err.value.details()


def test_multi_inference_classify_task(classify_stack):
    spec, _, calls = classify_stack
    rng = np.random.default_rng(2)
    images = rng.integers(0, 256, (1, *spec.input_shape), np.uint8)
    req = inference_pb2.MultiInferenceRequest()
    task = req.tasks.add()
    task.model_spec.name = spec.name
    task.method_name = "tensorflow/serving/classify"
    for img in images:
        ex = req.input.example_list.examples.add()
        ex.features.feature[spec.input_name].int64_list.value.extend(
            int(v) for v in img.reshape(-1)
        )
    resp = calls["multi"](req, timeout=60)
    assert len(resp.results) == 1
    r = resp.results[0]
    assert r.WhichOneof("result") == "classification_result"
    assert len(r.classification_result.classifications) == 1
    logits = _predict_logits(spec, calls, images)
    got = {c.label: c.score for c in r.classification_result.classifications[0].classes}
    for j, label in enumerate(spec.labels):
        assert got[label] == pytest.approx(logits[0, j], rel=1e-5)

    # Unsupported method name -> INVALID_ARGUMENT.
    req.tasks[0].method_name = "tensorflow/serving/rank"
    with pytest.raises(grpc.RpcError) as err:
        calls["multi"](req, timeout=30)
    assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
